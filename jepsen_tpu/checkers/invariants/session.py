"""Session guarantees as vectorized per-process passes.

Monotonic reads / monotonic writes / read-your-writes /
writes-follow-reads over rw-register-shaped histories, checked against
the per-key version orders the shared packed core derives
(:func:`packed.infer_rw`): every committed external read / write
becomes one event row ``(process, key, seq, is_write, rank)`` where
``rank`` is the version's position in its key's chain, and each
guarantee is a segmented comparison against the LAST prior event of
the relevant type in the same ``(process, key)`` segment —

    monotonic-reads      read rank  < last prior read rank
    read-your-writes     read rank  < last prior write rank
    monotonic-writes     write rank < last prior write rank
    writes-follow-reads  write rank < last prior read rank

"last prior X" is one encoded cumulative max (position-dominant
encoding, the `_seg_inclusive_max` trick), so the whole pass is a
handful of array ops: sort, cummax, compare.  The **device path** runs
the cummax + comparisons on jnp (``jax.lax.cummax``) behind
`resilience.device_call` (site ``invariants.session``); the **host
oracle twin** is the identical numpy, pinned equal verdict-for-verdict.

Cross-key obligation propagation (the walker's pass A/B, ROADMAP 5c)
is ALSO vectorized here (:func:`_cross_key_violations`): dep
registration is a writes x same-session-group array join, activation a
deps x reader-group join over per-group prefix-max / suffix-min rank
scans — multi-key writer sessions stay on the array path.

Exactness first: rank comparison is only definite on keys whose
version graph is a simple chain (`RwInference.chain_ok`).  Histories
with branched/cyclic keys fall back to the exact DAG walker
(`checkers.elle.sessions.check`), the same degradation rule the elle
family uses (an oracle that cannot look must say so, never silently
validate)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.checkers.elle import consistency
from jepsen_tpu.checkers.elle.sessions import GUARANTEES
from jepsen_tpu.checkers.invariants import packed as packed_mod
from jepsen_tpu.checkers.invariants.packed import RwInference
from jepsen_tpu.history.soa import MOP_APPEND, TXN_OK, PackedTxns

SITE = "invariants.session"

_SUFFIX = "-violation"


def _session_events(p: PackedTxns, inf: RwInference):
    """Flatten committed reads/writes to (proc, key, seq, is_write,
    rank) rows sorted session-major.  Returns None when any event's
    rank is unknown or its key is not chain-shaped — the walker owns
    those histories."""
    ok = p.txn_type == TXN_OK
    V = p.n_vals
    # writes: committed append mops, in mop order
    kind = p.mop_kind.astype(np.int64)
    mtxn = p.mop_txn.astype(np.int64)
    w_sel = np.nonzero((kind == MOP_APPEND) & ok[mtxn])[0]
    # reads: the inference's external reads from committed txns
    r_txn = inf.ext_read_txn
    r_val = inf.ext_read_val
    r_mop = inf.ext_read_mop

    ev_txn = np.concatenate([mtxn[w_sel], r_txn]).astype(np.int64)
    ev_mop = np.concatenate([w_sel, r_mop]).astype(np.int64)
    ev_val = np.concatenate([p.mop_val.astype(np.int64)[w_sel],
                             r_val]).astype(np.int64)
    ev_write = np.concatenate([np.ones(len(w_sel), bool),
                               np.zeros(len(r_txn), bool)])
    if not len(ev_txn):
        return (np.zeros(0, np.int64),) * 5
    ev_key = p.mop_key.astype(np.int64)[ev_mop]
    if not inf.chain_ok[np.unique(ev_key)].all():
        return None
    rank = inf.chain_rank[ev_val]
    if (rank < 0).any():
        return None
    proc = p.txn_process.astype(np.int64)[ev_txn]
    inv = p.txn_invoke_pos.astype(np.int64)[ev_txn]
    # session order: invoke position, then mop order within the txn
    order = np.lexsort((ev_mop, inv, ev_key, proc))
    return (proc[order], ev_key[order], ev_write[order], rank[order],
            ev_txn[order])


def _chron_events(p: PackedTxns, inf: RwInference):
    """Committed writes + external reads in SESSION-CHRONOLOGICAL
    order (proc, invoke, mop) — the event stream the cross-key
    obligation pass walks.  Same event set as `_session_events`, whose
    key-major sort serves the same-key masks instead."""
    ok = p.txn_type == TXN_OK
    kind = p.mop_kind.astype(np.int64)
    mtxn = p.mop_txn.astype(np.int64)
    w_sel = np.nonzero((kind == MOP_APPEND) & ok[mtxn])[0]
    ev_mop = np.concatenate([w_sel, inf.ext_read_mop]).astype(np.int64)
    ev_txn = np.concatenate([mtxn[w_sel], inf.ext_read_txn])
    ev_val = np.concatenate([p.mop_val.astype(np.int64)[w_sel],
                             inf.ext_read_val])
    ev_w = np.concatenate([np.ones(len(w_sel), bool),
                           np.zeros(len(inf.ext_read_txn), bool)])
    if not len(ev_txn):
        return None
    ev_key = p.mop_key.astype(np.int64)[ev_mop]
    rank = inf.chain_rank[ev_val]
    proc = p.txn_process.astype(np.int64)[ev_txn]
    inv = p.txn_invoke_pos.astype(np.int64)[ev_txn]
    order = np.lexsort((ev_mop, inv, proc))
    return (proc[order], ev_key[order], ev_w[order], rank[order],
            ev_txn[order])


def _seg_cummax(vals: np.ndarray, start: np.ndarray,
                minimum: bool = False) -> np.ndarray:
    """Segmented inclusive prefix max (or min) over CONTIGUOUS
    segments: encode (segment, value) into one int so a plain
    `np.maximum.accumulate` can never carry a previous segment's value
    across a boundary (every element of segment s encodes above all of
    segment s-1)."""
    if not len(vals):
        return vals
    seg = np.cumsum(start) - 1
    lo = int(vals.min())
    span = int(vals.max()) - lo + 1
    base = vals - lo
    enc = seg * span + (span - 1 - base if minimum else base)
    dec = np.maximum.accumulate(enc) - seg * span
    return (span - 1 - dec if minimum else dec) + lo


def _cross_key_violations(p: PackedTxns, inf: RwInference, want,
                          max_reported: int = 8) -> Dict[str, List[dict]]:
    """Cross-key obligation propagation, vectorized (ISSUE 12 / ROADMAP
    5c — the last host-only hot path in this family).

    Walker semantics (`elle/sessions.check`), restated over chain
    ranks (valid here because every touched key is chain-shaped, the
    same gate the same-key pass uses):

    - pass A: a session that last read u(k1) [WFR] / last wrote w1(k1)
      [MW] and then writes w(k) registers a dep (k, rank(w), k1,
      rank(u|w1)).
    - pass B: any session whose read of k observes rank >= rank(w)
      activates the dep; a LATER read of k1 with rank < rank(u|w1) is
      a definite violation.

    Both passes are array joins: deps come from a writes x same-session
    (proc, key) group product with a composite-key searchsorted for
    "last prior event"; activations from a deps x reader-group product
    over per-group prefix-max / suffix-min rank scans.  The work is
    bounded by the same sums the walker's dict copies pay."""
    ev = _chron_events(p, inf)
    out: Dict[str, List[dict]] = {}
    if ev is None:
        return out
    proc, key, is_w, rank, ev_txn = ev
    n = len(proc)
    orig = p.txn_orig_index

    def grouped(sel):
        """(proc, key)-grouped view of selected rows: proc-major.
        Returns (rows_sorted, group_starts, group_ends, gid_of_row)."""
        idx = np.nonzero(sel)[0]
        o = np.lexsort((idx, key[idx], proc[idx]))
        ri = idx[o]
        if not len(ri):
            return ri, np.zeros(0, np.int64), np.zeros(0, np.int64), ri
        pi, ki = proc[ri], key[ri]
        start = np.concatenate(
            [[True], (pi[1:] != pi[:-1]) | (ki[1:] != ki[:-1])])
        gs = np.nonzero(start)[0]
        ge = np.concatenate([gs[1:], [len(ri)]])
        return ri, gs, ge, np.cumsum(start) - 1

    w_rows = np.nonzero(is_w)[0]
    if not len(w_rows):
        return out

    # per-observer-group read scans, shared by both dep kinds
    r_ri, r_gs, r_ge, r_gid = grouped(~is_w)
    if not len(r_ri):
        return out
    r_rank = rank[r_ri]
    r_start = np.zeros(len(r_ri), bool)
    r_start[r_gs] = True
    pmax = _seg_cummax(r_rank, r_start)
    smin = _seg_cummax(r_rank[::-1],
                       np.concatenate([r_start[1:], [True]])[::-1],
                       minimum=True)[::-1]
    r_gkey = key[r_ri][r_gs]
    r_key_ord = np.argsort(r_gkey, kind="stable")
    r_gkey_s = r_gkey[r_key_ord]
    rmax = int(rank.max()) + 2

    for name, prior_is_write in (("writes-follow-reads", False),
                                 ("monotonic-writes", True)):
        if name not in want:
            continue
        # ---- pass A: deps from writes x same-session prior groups ----
        pi_, gs_, ge_, gid_ = grouped(is_w if prior_is_write else ~is_w)
        if not len(gs_):
            continue
        g_proc = proc[pi_][gs_]
        g_key = key[pi_][gs_]
        wp = proc[w_rows]
        lo = np.searchsorted(g_proc, wp, side="left")
        hi = np.searchsorted(g_proc, wp, side="right")
        cnt = hi - lo
        tot = int(cnt.sum())
        if not tot:
            continue
        w_e = np.repeat(w_rows, cnt)
        g_e = np.repeat(lo, cnt) + (
            np.arange(tot) - np.repeat(np.cumsum(cnt) - cnt, cnt))
        keep = g_key[g_e] != key[w_e]
        w_e, g_e = w_e[keep], g_e[keep]
        if not len(w_e):
            continue
        # last prior event of that group strictly before the write row:
        # rows ascend within each contiguous group, so (gid, row) pairs
        # encode into one ascending key
        comp = gid_ * (n + 1) + pi_
        pos = np.searchsorted(comp, g_e * (n + 1) + w_e, side="left")
        has = pos > gs_[g_e]
        w_e, g_e, pos = w_e[has], g_e[has], pos[has]
        if not len(w_e):
            continue
        dep_kw = key[w_e]
        dep_wrank = rank[w_e]
        dep_k1 = g_key[g_e]
        dep_urank = rank[pi_[pos - 1]]

        # ---- pass B: activation x observer read groups ---------------
        dlo = np.searchsorted(r_gkey_s, dep_kw, side="left")
        dhi = np.searchsorted(r_gkey_s, dep_kw, side="right")
        dcnt = dhi - dlo
        dtot = int(dcnt.sum())
        if not dtot:
            continue
        d_e = np.repeat(np.arange(len(dep_kw)), dcnt)
        og = r_key_ord[np.repeat(dlo, dcnt) + (
            np.arange(dtot) -
            np.repeat(np.cumsum(dcnt) - dcnt, dcnt))]
        # first read position in the observer group whose prefix-max
        # rank reaches the dep's write rank (prefix-max ascends within
        # a group, so (gid, pmax) encodes into one ascending key)
        pm_comp = r_gid * rmax + pmax
        act = np.searchsorted(pm_comp, og * rmax + dep_wrank[d_e],
                              side="left")
        ok_act = act < r_ge[og]
        d_e, og, act = d_e[ok_act], og[ok_act], act[ok_act]
        if not len(d_e):
            continue
        # a later read of k1 below the dep threshold = violation; the
        # observer group here is the k-group — now check the SAME
        # session's k1 group after the activation row
        act_row = r_ri[act]
        # k1 group of the observer's session: composite (proc, key)
        gp_comp = proc[r_ri][r_gs] * (int(key.max()) + 2) + r_gkey
        obs_proc = proc[r_ri][r_gs][og]
        k1g = np.searchsorted(
            gp_comp, obs_proc * (int(key.max()) + 2) + dep_k1[d_e])
        in_range = (k1g < len(r_gs)) & \
            (gp_comp[np.clip(k1g, 0, max(len(r_gs) - 1, 0))] ==
             obs_proc * (int(key.max()) + 2) + dep_k1[d_e])
        d_e, og, act_row, k1g = (d_e[in_range], og[in_range],
                                 act_row[in_range], k1g[in_range])
        if not len(d_e):
            continue
        # first k1-group position strictly after the activation row
        comp_r = r_gid * (n + 1) + r_ri
        p1 = np.searchsorted(comp_r, k1g * (n + 1) + act_row,
                             side="right")
        ok_pos = p1 < r_ge[k1g]
        viol = np.zeros(len(d_e), bool)
        viol[ok_pos] = smin[p1[ok_pos]] < dep_urank[d_e[ok_pos]]
        hits = np.nonzero(viol)[0]
        if not len(hits):
            continue
        items: List[dict] = []
        seen_pairs = set()
        for hidx in hits.tolist():
            if len(items) >= max_reported:
                break
            d = int(d_e[hidx])
            # first violating read in the k1 group after activation
            sl = slice(int(p1[hidx]), int(r_ge[k1g[hidx]]))
            rel = np.nonzero(r_rank[sl] < dep_urank[d])[0]
            if not len(rel):
                continue
            j = int(p1[hidx]) + int(rel[0])
            t = int(ev_txn[r_ri[j]])
            pair = (int(proc[r_ri[j]]), t, int(dep_k1[d]))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            items.append({
                "process": int(proc[r_ri[j]]),
                "op": int(orig[t]),
                "key": p.key_names[int(dep_k1[d])],
                "rank": int(r_rank[j]),
                "kind": "read",
                "cross-key": {
                    "via-key": p.key_names[int(dep_kw[d])],
                    "required-rank": int(dep_urank[d]),
                },
            })
        if items:
            out[name + _SUFFIX] = items
    return out


def _viol_masks(seg_id: np.ndarray, is_write: np.ndarray,
                rank: np.ndarray):
    """Backend-generic violation masks.  Returns run(xp) computing the
    four masks via a 1-based-position cummax ("latest matching event so
    far") plus a segment-start comparison — the encoding stays within
    the event count, so jax's default int32 can't overflow even on
    million-event histories."""
    n = len(seg_id)
    # per-row first index of its own (process, key) segment
    new = np.concatenate([[True], seg_id[1:] != seg_id[:-1]]) \
        if n else np.zeros(0, bool)
    seg_start_np = np.maximum.accumulate(
        np.where(new, np.arange(n), 0)) if n else np.zeros(0, np.int64)

    def run(xp):
        if xp is np:
            asa = np.asarray
        else:
            # sharded-by-default: event rows split over the active
            # mesh's "batch" axis (GSPMD partitions the cummax)
            from jepsen_tpu.parallel.slots import place_sharded as asa
        w = asa(is_write)
        r = asa(rank)
        pos1 = xp.arange(1, n + 1)
        seg_start = asa(seg_start_np)

        def last_prior(of_write):
            # cummax of (1-based position where the event matches)
            # gives the latest matching event at-or-before each row;
            # the exclusive shift makes it strictly prior, and a match
            # from an earlier (process, key) segment is rejected by
            # the segment-start comparison
            match = w if of_write else ~w
            enc = xp.where(match, pos1, 0)
            cm = _cummax(xp, enc)
            prior = xp.concatenate([cm[:1] * 0, cm[:-1]])
            has = (prior > 0) & ((prior - 1) >= seg_start)
            prank = r[xp.clip(prior - 1, 0, max(n - 1, 0))]
            return has, prank

        has_r, last_r = last_prior(False)
        has_w, last_w = last_prior(True)
        # mask order == sessions.GUARANTEES order
        return (
            (~w) & has_r & (r < last_r),   # monotonic-reads
            w & has_w & (r < last_w),      # monotonic-writes
            (~w) & has_w & (r < last_w),   # read-your-writes
            w & has_r & (r < last_r),      # writes-follow-reads
        )

    return run


def _cummax(xp, a):
    if xp is np:
        return np.maximum.accumulate(a)
    from jax import lax

    return lax.cummax(a, axis=0)


def check(history, guarantees: Sequence[str] = GUARANTEES,
          use_device: bool = True, max_reported: int = 8,
          deadline=None, plan=None, policy=None,
          test: Optional[dict] = None) -> Dict[str, Any]:
    """Check session guarantees.  Accepts a History / op list /
    PackedTxns (rw-register packing).  Result shape matches the elle
    checkers; anomalies use the lattice's ``<guarantee>-violation``
    tokens."""
    from jepsen_tpu import resilience

    from jepsen_tpu.history.ir import HistoryIR

    ph = telemetry.phases()
    ir = history if isinstance(history, HistoryIR) else None
    op_level = None if (isinstance(history, PackedTxns)
                        or (ir is not None and ir.packed_only)) \
        else history
    if op_level is None:
        p = ir.packed("rw-register") if ir is not None else history
    else:
        ph.start("invariants.pack", device=False)
        p = ir.packed("rw-register") if ir is not None \
            else packed_mod.pack_rw(history)
    if p.n_txns == 0 or not (p.txn_type == TXN_OK).any():
        ph.end()
        return {"valid?": "unknown", "anomaly-types": [], "anomalies": {},
                "not": [], "also-not": []}

    ph.start("invariants.infer", device=False, txns=p.n_txns)
    inf = ir.rw_inference() if ir is not None else packed_mod.infer_rw(p)
    ev = _session_events(p, inf)
    want = set(guarantees)

    if ev is None:
        # branched/cyclic version graphs: only the ancestor-definite
        # DAG walker can compare versions soundly (op-level input
        # required).  Cross-key writer sessions no longer route here —
        # the vectorized obligation pass below covers them (ISSUE 12)
        ph.end()
        return _walker_fallback(op_level, want)

    proc, key, is_write, rank, ev_txn = ev
    seg = np.zeros(len(proc), np.int64)
    if len(proc):
        new = np.concatenate([[True], (proc[1:] != proc[:-1]) |
                              (key[1:] != key[:-1])])
        seg = np.cumsum(new) - 1
    run = _viol_masks(seg, is_write, rank)
    ph.start("invariants.check", device=use_device, events=len(proc))
    degraded = None
    try:
        if use_device and len(proc):
            def dev():
                import jax.numpy as jnp

                return tuple(np.asarray(m) for m in run(jnp))

            masks, degraded = resilience.with_fallback(
                SITE, dev, lambda: run(np), deadline=deadline,
                plan=plan, policy=policy, test=test)
        else:
            masks = run(np) if len(proc) else (np.zeros(0, bool),) * 4
    except resilience.DeadlineExceeded:
        ph.end()
        return resilience.deadline_result(checker="session")
    ph.end()

    found: Dict[str, List[dict]] = {}
    orig = p.txn_orig_index
    for g, mask in zip(GUARANTEES, masks):
        if g not in want:
            continue
        hits = np.nonzero(np.asarray(mask))[0]
        if not len(hits):
            continue
        lst = found.setdefault(g + _SUFFIX, [])
        for i in hits[:max_reported]:
            lst.append({
                "process": int(proc[i]),
                "op": int(orig[ev_txn[i]]),
                "key": p.key_names[int(key[i])],
                "rank": int(rank[i]),
                "kind": "write" if is_write[i] else "read",
            })

    # cross-key obligation propagation (vectorized; walker-equivalent
    # on chain-shaped keys — differential-pinned in test_invariants)
    if "writes-follow-reads" in want or "monotonic-writes" in want:
        ph.start("invariants.cross-key", device=False)
        cross = _cross_key_violations(p, inf, want, max_reported)
        ph.end()
        for nm, items in cross.items():
            lst = found.setdefault(nm, [])
            lst.extend(items[:max(0, max_reported - len(lst))])

    anomaly_types = sorted(found)
    boundary = consistency.friendly_boundary(anomaly_types)
    res: Dict[str, Any] = {
        "valid?": not found,
        "anomaly-types": anomaly_types,
        "anomalies": found,
        "not": boundary["not"],
        "also-not": boundary["also-not"],
        "events": int(len(proc)),
    }
    if degraded:
        res["degraded"] = degraded
    return res


def _walker_fallback(op_level, want) -> Dict[str, Any]:
    from jepsen_tpu.checkers.elle import coverage, sessions

    if op_level is None:
        # packed-only input: the walker needs the op-level view —
        # degrade rather than silently validate
        return coverage.apply_unchecked(
            {"valid?": True, "anomaly-types": [], "anomalies": {},
             "not": [], "also-not": [],
             "fallback": "walker-needs-op-history"},
            sorted(g + _SUFFIX for g in want))
    res = sessions.check(op_level, guarantees=sorted(want))
    res["fallback"] = "dag-walker"
    return res
