"""HTML timeline of operations per process.

Equivalent of the reference's `jepsen/src/jepsen/checker/timeline.clj`
(SURVEY.md §2.1): one column per process, one bar per op spanning
invoke→completion, colored by outcome, with the op's details in a hover
tooltip; written as a standalone ``timeline.html`` into the store dir.
"""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional

from ..history.ops import FAIL, INFO, INVOKE, OK
from .api import Checker, output_path

_COLOR = {OK: "#6DB6FE", FAIL: "#FEB5DA", INFO: "#FFAA26",
          INVOKE: "#C9C9C9"}
_NS = 1e9
_PX_PER_S = 100.0
_MIN_PX = 2.0
_COL_W = 120


def _bars(history) -> List[dict]:
    bars = []
    for op in history:
        if op.type != INVOKE:
            continue
        comp = history.completion(op) if hasattr(history, "completion") \
            else None
        t0 = op.time / _NS
        if comp is not None:
            t1 = comp.time / _NS
            outcome = comp.type
            detail = comp
        else:
            t1 = history[len(history) - 1].time / _NS if len(history) else t0
            outcome = INFO
            detail = op
        bars.append({
            "process": op.process, "t0": t0, "t1": t1, "outcome": outcome,
            "title": (f"{op.process} {op.f} {op.value!r} -> "
                      f"{outcome} {detail.value!r}"
                      + (f" err={detail.error!r}" if detail.error else "")),
            "label": f"{op.f}",
            "index": op.index,
        })
    return bars


class Timeline(Checker):
    """Writes timeline.html (reference `timeline/html`); always valid."""

    def __init__(self, filename: str = "timeline.html"):
        self.filename = filename

    def check(self, test, history, opts=None):
        bars = _bars(history)
        processes = sorted({b["process"] for b in bars}, key=repr)
        col_of = {p: i for i, p in enumerate(processes)}
        t_max = max((b["t1"] for b in bars), default=0.0)

        divs = []
        for b in bars:
            top = b["t0"] * _PX_PER_S
            height = max((b["t1"] - b["t0"]) * _PX_PER_S, _MIN_PX)
            left = col_of[b["process"]] * _COL_W
            divs.append(
                f'<div class="op" style="top:{top:.1f}px;'
                f'left:{left}px;height:{height:.1f}px;'
                f'background:{_COLOR[b["outcome"]]}" '
                f'title="{html.escape(b["title"])}">'
                f'{html.escape(str(b["label"]))}'
                f'<span class="idx">{b["index"]}</span></div>')
        heads = "".join(
            f'<div class="head" style="left:{col_of[p] * _COL_W}px">'
            f'{html.escape(str(p))}</div>' for p in processes)
        doc = f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{html.escape(str(test.get("name", "test")))} timeline</title>
<style>
body {{ font-family: sans-serif; margin: 0; }}
.lane {{ position: relative; margin-top: 30px;
        height: {t_max * _PX_PER_S + 40:.0f}px; }}
.head {{ position: fixed; top: 0; width: {_COL_W - 4}px; text-align: center;
        background: #eee; font-weight: bold; padding: 2px 0; }}
.op {{ position: absolute; width: {_COL_W - 8}px; font-size: 9px;
      overflow: hidden; border-radius: 2px; padding-left: 2px;
      box-sizing: border-box; border: 1px solid rgba(0,0,0,.25); }}
.idx {{ float: right; color: rgba(0,0,0,.45); padding-right: 2px; }}
</style></head>
<body><div class="lane">{heads}{"".join(divs)}</div></body></html>"""

        path = output_path(test, opts, self.filename)
        with open(path, "w") as f:
            f.write(doc)
        return {"valid?": True, "op-count": len(bars), "file": path}


def html_timeline(**kw) -> Timeline:
    return Timeline(**kw)
