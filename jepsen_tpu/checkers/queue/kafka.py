"""Kafka anomaly taxonomy as whole-history vectorized reductions.

Every pass `workloads.kafka.KafkaChecker` runs as a python scan over
(send, poll) tuples becomes an array reduction over the
:class:`~jepsen_tpu.checkers.queue.packed.PackedKafka` columns —
adjacency compares over pack-time sorted orders, searchsorted
membership against the per-key offset ladder, and one segment
reduction (the stale-group run lengths):

- **lost-write** — send rows below their key's max polled offset whose
  ``key*off_base+off`` code is absent from the unique polled table;
- **duplicate** — adjacent same-``(key, value)`` rows in the unique
  polled ``(key, value, offset)`` table (two offsets for one value);
- **inconsistent-offsets** — adjacent same-``(key, offset)`` rows in
  the unique observed ``(key, offset, value)`` table;
- **nonmonotonic-poll / poll-skip** — adjacent batch rows in
  ``(process, key, seq)`` order, gated on equal assignment epochs (the
  pack-time ``(reassign-bisect, rebalance-generation)`` code), with
  the skip's "an offset in between was actually polled" test a
  searchsorted interval count;
- **int-nonmonotonic-poll / int-poll-skip** — the same on adjacent
  message rows within one batch;
- **nonmonotonic-send / int-send-skip** — adjacent send rows in
  ``(process, key, seq)`` / ``(op, key, seq)`` order;
- **precommitted-read** — message rows observed at an op index before
  their value's send was invoked;
- **stale-consumer-group** — ≥3 subscribe-mode batches of one
  ``(key, generation)`` re-reading the same start offset while the
  key's log extends past them: the group's committed offset stopped
  advancing (run detection over the ``(key, gen, start)`` sort, run
  lengths via one bincount);
- **unseen** — informational, as in the host scan.

The device path runs the fused mask kernel behind
``resilience.with_fallback(site="queue.check")`` with compile-cache
routing (`compilecache.call`, pow2-padded columns, validity sentinels
``key == -1`` instead of static lengths so nearby history sizes share
one executable); the host path is the SAME arithmetic in numpy
(:func:`host_verdict` — the oracle twin the device path is
differentially pinned against, while `KafkaChecker` itself stays the
independent scan twin).  Verdict-for-verdict parity with the scan is
pinned by tests/test_queue_checkers.py.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.checkers.queue import packed as packed_mod
from jepsen_tpu.checkers.queue.packed import SENTINEL, PackedKafka

SITE = "queue.check"

#: anomaly keys, the host scan's names (KafkaChecker) + stale-group
ANOMALIES = ("lost-write", "duplicate", "inconsistent-offsets",
             "nonmonotonic-poll", "poll-skip", "int-nonmonotonic-poll",
             "int-poll-skip", "nonmonotonic-send", "int-send-skip",
             "precommitted-read", "stale-consumer-group")

#: minimum same-start batches before a frozen committed offset counts
#: as a stale consumer group (1–2 re-reads happen benignly around
#: rebalances; 3 with the log moving on do not)
STALE_MIN_POLLS = 3


def _bincount(xp, x, n: int, weights=None):
    if xp is np:
        return np.bincount(x, weights=weights, minlength=n)
    return xp.bincount(x, weights=weights, length=n)


def _cummax(xp, x):
    if xp is np:
        return np.maximum.accumulate(x)
    import jax.lax as lax

    return lax.cummax(x)


def _later(xp, pair, n: int):
    """Lift a length-``n-1`` adjacent-pair mask to length ``n``,
    marking the LATER row of each flagged pair."""
    if n == 0:
        return xp.zeros(0, bool)
    return xp.concatenate([xp.zeros(1, bool), pair])


def _both(xp, pair, n: int):
    """Lift a pair mask to length ``n`` marking BOTH rows (group
    membership: every row adjacent to a same-group neighbour)."""
    if n == 0:
        return xp.zeros(0, bool)
    z = xp.zeros(1, bool)
    return xp.concatenate([pair, z]) | xp.concatenate([z, pair])


def _math(xp, off_base: int,
          s_key, s_off, s_op, s_proc,
          b_key, b_proc, b_start, b_last, b_ep, b_gen,
          m_batch, m_key, m_off, m_op, m_sendinv,
          u_comp, polled_max, key_max,
          dv_key, dv_val, av_key, av_off,
          s_by_pk, s_by_ok, b_by_pk, b_by_kg):
    """The one reduction both paths implement.  Returns the 13 masks of
    :data:`MASKS` (padding rows, ``key == -1``, never flag)."""
    S, B, M = s_key.shape[0], b_key.shape[0], m_key.shape[0]

    def member(codes):
        if u_comp.shape[0] == 0:
            return xp.zeros(codes.shape, bool)
        idx = xp.clip(xp.searchsorted(u_comp, codes),
                      0, u_comp.shape[0] - 1)
        return u_comp[idx] == codes

    def polled_between(keys, lo, hi):
        """Any polled offset o of `keys` with lo < o < hi?"""
        if u_comp.shape[0] == 0:
            return xp.zeros(keys.shape, bool)
        base = keys * off_base
        return (xp.searchsorted(u_comp, base + hi)
                > xp.searchsorted(u_comp, base + lo + 1))

    # ---- send rows: lost / unseen -----------------------------------
    s_ok = s_key >= 0
    ks = xp.where(s_ok, s_key, 0)
    seen = member(xp.where(s_ok, s_key * off_base + s_off,
                           xp.int64(-1)))
    pm = polled_max[ks]
    lost = s_ok & (pm >= 0) & (s_off < pm) & ~seen
    unseen = s_ok & ~seen

    # ---- sends by (proc, key): nonmonotonic-send --------------------
    k = s_key[s_by_pk]
    p = s_proc[s_by_pk]
    o = s_off[s_by_pk]
    pair = (k[1:] == k[:-1]) & (p[1:] == p[:-1]) & (k[1:] >= 0) \
        & (k[:-1] >= 0)
    nm_send = _later(xp, pair & (o[1:] <= o[:-1]), S)

    # ---- sends by (op, key): int-send-skip --------------------------
    k = s_key[s_by_ok]
    i = s_op[s_by_ok]
    o = s_off[s_by_ok]
    pair = (k[1:] == k[:-1]) & (i[1:] == i[:-1]) & (k[1:] >= 0) \
        & (i[1:] >= 0)
    sk_send = _later(xp, pair & (o[1:] != o[:-1] + 1), S)

    # ---- batches by (proc, key): cross-poll order, epoch-gated ------
    k = b_key[b_by_pk]
    p = b_proc[b_by_pk]
    e = b_ep[b_by_pk]
    st = b_start[b_by_pk]
    la = b_last[b_by_pk]
    pair = (k[1:] == k[:-1]) & (p[1:] == p[:-1]) & (k[1:] >= 0) \
        & (k[:-1] >= 0) & (e[1:] == e[:-1])
    nm_poll = _later(xp, pair & (st[1:] <= la[:-1]), B)
    gap = pair & (st[1:] > la[:-1] + 1)
    skip_poll = _later(
        xp, gap & polled_between(k[1:], la[:-1], st[1:]), B)

    # ---- messages within one batch: int order -----------------------
    mb = (m_batch[1:] == m_batch[:-1]) & (m_key[1:] >= 0) \
        & (m_key[:-1] >= 0)
    a, b = m_off[:-1], m_off[1:]
    inm = _later(xp, mb & (b <= a), M)
    iskip = _later(xp, mb & (b > a) & (b != a + 1)
                   & polled_between(m_key[1:], a, b), M)

    # ---- precommitted-read ------------------------------------------
    precommit = (m_key >= 0) & (m_sendinv >= 0) & (m_op < m_sendinv)

    # ---- duplicate: unique polled (key, value, offset) --------------
    pair = (dv_key[1:] == dv_key[:-1]) & (dv_val[1:] == dv_val[:-1]) \
        & (dv_key[1:] >= 0)
    dup = _both(xp, pair, dv_key.shape[0])

    # ---- inconsistent-offsets: unique (key, offset, value) ----------
    pair = (av_key[1:] == av_key[:-1]) & (av_off[1:] == av_off[:-1]) \
        & (av_key[1:] >= 0)
    incon = _both(xp, pair, av_key.shape[0])

    # ---- stale-consumer-group: (key, gen, start) runs ---------------
    k = b_key[b_by_kg]
    g = b_gen[b_by_kg]
    st = b_start[b_by_kg]
    la = b_last[b_by_kg]
    ok = (k >= 0) & (g >= 0)
    if B:
        diff = (k[1:] != k[:-1]) | (g[1:] != g[:-1]) \
            | (st[1:] != st[:-1]) | ~ok[1:] | ~ok[:-1]
        new_run = xp.concatenate([xp.ones(1, bool), diff])
        run_id = xp.cumsum(new_run.astype(xp.int64)) - 1
        run_len = _bincount(xp, run_id, B)[run_id]
        kk = xp.where(ok, k, 0)
        evid = ok & (key_max[kk] > la)
        evid_n = _bincount(xp, run_id, B,
                           weights=evid.astype(xp.int64))[run_id]
        in_group = ok & (run_len >= STALE_MIN_POLLS) & (evid_n > 0)
        stale, stale_evid = in_group, in_group & evid
    else:
        stale = stale_evid = xp.zeros(0, bool)

    return (lost, unseen, nm_send, sk_send, nm_poll, skip_poll,
            inm, iskip, precommit, dup, incon, stale, stale_evid)


#: kernel output order; pair masks are in their sort-order coordinates
MASKS = ("lost", "unseen", "nm_send", "sk_send", "nm_poll",
         "skip_poll", "inm", "iskip", "precommit", "dup", "incon",
         "stale", "stale_evid")

_KERNEL = None


def _kernel():
    """The fused jit kernel, built once (so the in-process jit cache
    and the AOT compile-cache both key one function)."""
    global _KERNEL
    if _KERNEL is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("off_base",))
        def queue_kafka_core(*cols, off_base):
            return _math(jnp, off_base, *cols)

        _KERNEL = queue_kafka_core
    return _KERNEL


def _cols(pk: PackedKafka) -> Tuple[np.ndarray, ...]:
    return (pk.s_key, pk.s_off, pk.s_op, pk.s_proc,
            pk.b_key, pk.b_proc, pk.b_start, pk.b_last, pk.b_ep,
            pk.b_gen,
            pk.m_batch, pk.m_key, pk.m_off, pk.m_op, pk.m_sendinv,
            pk.u_comp, pk.polled_max, pk.key_max,
            pk.dv_key, pk.dv_val, pk.av_key, pk.av_off,
            pk.s_by_pk, pk.s_by_ok, pk.b_by_pk, pk.b_by_kg)


def _pad_to(a: np.ndarray, n: int, fill) -> np.ndarray:
    out = np.full(n, fill, np.int64)
    out[:len(a)] = a
    return out


def _pad_perm(perm: np.ndarray, n: int) -> np.ndarray:
    """Extend a permutation over the real rows with the padding rows'
    own indices — pads sort to the tail and never pair (key == -1)."""
    return np.concatenate(
        [perm, np.arange(len(perm), n, dtype=np.int64)])


def _padded_cols(pk: PackedKafka) -> Tuple[np.ndarray, ...]:
    """Bucket-pad every column to its pow2 capacity with validity
    sentinels, so nearby history sizes share one executable
    (`compilecache.bucket`)."""
    from jepsen_tpu.compilecache import bucket

    S = bucket.pow2_at_least(max(len(pk.s_key), 1))
    B = bucket.pow2_at_least(max(len(pk.b_key), 1))
    M = bucket.pow2_at_least(max(len(pk.m_key), 1))
    U = bucket.pow2_at_least(max(len(pk.u_comp), 1))
    DV = bucket.pow2_at_least(max(len(pk.dv_key), 1))
    AV = bucket.pow2_at_least(max(len(pk.av_key), 1))
    K = bucket.pow2_at_least(max(len(pk.polled_max), 1))
    return (
        _pad_to(pk.s_key, S, -1), _pad_to(pk.s_off, S, 0),
        _pad_to(pk.s_op, S, -1), _pad_to(pk.s_proc, S, -1),
        _pad_to(pk.b_key, B, -1), _pad_to(pk.b_proc, B, -1),
        _pad_to(pk.b_start, B, 0), _pad_to(pk.b_last, B, -1),
        _pad_to(pk.b_ep, B, -1), _pad_to(pk.b_gen, B, -1),
        _pad_to(pk.m_batch, M, -1), _pad_to(pk.m_key, M, -1),
        _pad_to(pk.m_off, M, 0), _pad_to(pk.m_op, M, -1),
        _pad_to(pk.m_sendinv, M, -1),
        _pad_to(pk.u_comp, U, SENTINEL),
        _pad_to(pk.polled_max, K, -1), _pad_to(pk.key_max, K, -1),
        _pad_to(pk.dv_key, DV, -1), _pad_to(pk.dv_val, DV, 0),
        _pad_to(pk.av_key, AV, -1), _pad_to(pk.av_off, AV, 0),
        _pad_perm(pk.s_by_pk, S), _pad_perm(pk.s_by_ok, S),
        _pad_perm(pk.b_by_pk, B), _pad_perm(pk.b_by_kg, B),
    )


def _reduce_host(pk: PackedKafka):
    return _math(np, pk.off_base, *_cols(pk))


def _reduce_device(pk: PackedKafka):
    from jepsen_tpu import compilecache

    out = compilecache.call(SITE, _kernel(), *_padded_cols(pk),
                            off_base=pk.off_base)
    lens = dict(zip(MASKS, (
        len(pk.s_key), len(pk.s_key), len(pk.s_key), len(pk.s_key),
        len(pk.b_key), len(pk.b_key),
        len(pk.m_key), len(pk.m_key), len(pk.m_key),
        len(pk.dv_key), len(pk.av_key),
        len(pk.b_key), len(pk.b_key))))
    return tuple(np.asarray(m)[:lens[nm]]
                 for m, nm in zip(out, MASKS))


def host_verdict(pk: PackedKafka,
                 max_reported: int = 16) -> Dict[str, Any]:
    """The exact host oracle twin — numpy only, no jax import."""
    return _render(pk, _reduce_host(pk), max_reported)


def _render(pk: PackedKafka, masks, max_reported: int) -> Dict[str, Any]:
    """Map mask indices back through the id tables into the host
    scan's exact entry shapes and iteration order (KafkaChecker —
    entry-for-entry equality is what the differential tests pin)."""
    m = dict(zip(MASKS, masks))
    K, V, P = pk.keys, pk.values, pk.procs

    lost = sorted({(K[pk.s_key[i]], int(pk.s_off[i]), V[pk.s_val[i]])
                   for i in np.nonzero(m["lost"])[0]})

    unseen: Dict[Any, int] = {}
    for i in np.nonzero(m["unseen"])[0]:
        kk = K[pk.s_key[i]]
        unseen[kk] = unseen.get(kk, 0) + 1

    by_kv: Dict[Tuple[Any, Any], List[int]] = {}
    for j in np.nonzero(m["dup"])[0]:
        by_kv.setdefault((K[pk.dv_key[j]], V[pk.dv_val[j]]),
                         []).append(int(pk.dv_off[j]))
    duplicates = sorted((k, v, sorted(offs))
                        for (k, v), offs in by_kv.items())

    by_ko: Dict[Tuple[Any, int], List[Any]] = {}
    for j in np.nonzero(m["incon"])[0]:
        by_ko.setdefault((K[pk.av_key[j]], int(pk.av_off[j])),
                         []).append(V[pk.av_val[j]])
    inconsistent = sorted((k, off, sorted(vs, key=repr))
                          for (k, off), vs in by_ko.items())

    def batch_pairs(mask, perm, shape):
        out = []
        for j in np.nonzero(mask)[0]:
            cur, prv = int(perm[j]), int(perm[j - 1])
            out.append((cur, shape(cur, prv)))
        return [e for _, e in sorted(out, key=lambda t: t[0])]

    nonmonotonic = batch_pairs(
        m["nm_poll"], pk.b_by_pk,
        lambda cur, prv: {"process": P[pk.b_proc[cur]],
                          "key": K[pk.b_key[cur]],
                          "prev": int(pk.b_last[prv]),
                          "next": int(pk.b_start[cur]),
                          "op-index": int(pk.b_op[cur])})
    skipped = batch_pairs(
        m["skip_poll"], pk.b_by_pk,
        lambda cur, prv: {"key": K[pk.b_key[cur]],
                          "from": int(pk.b_last[prv]),
                          "to": int(pk.b_start[cur]),
                          "process": P[pk.b_proc[cur]],
                          "op-index": int(pk.b_op[cur])})
    int_nonmono = [{"key": K[pk.m_key[j]],
                    "prev": int(pk.m_off[j - 1]),
                    "next": int(pk.m_off[j]),
                    "op-index": int(pk.m_op[j])}
                   for j in np.nonzero(m["inm"])[0]]
    int_skipped = [{"key": K[pk.m_key[j]],
                    "from": int(pk.m_off[j - 1]),
                    "to": int(pk.m_off[j]),
                    "op-index": int(pk.m_op[j])}
                   for j in np.nonzero(m["iskip"])[0]]
    nonmono_send = batch_pairs(
        m["nm_send"], pk.s_by_pk,
        lambda cur, prv: {"process": P[pk.s_proc[cur]],
                          "key": K[pk.s_key[cur]],
                          "prev": int(pk.s_off[prv]),
                          "next": int(pk.s_off[cur]),
                          "op-index": int(pk.s_op[cur])})
    int_send_skip = batch_pairs(
        m["sk_send"], pk.s_by_ok,
        lambda cur, prv: {"key": K[pk.s_key[cur]],
                          "from": int(pk.s_off[prv]),
                          "to": int(pk.s_off[cur]),
                          "op-index": int(pk.s_op[cur])})
    precommitted = [{"key": K[pk.m_key[j]], "value": V[pk.m_val[j]],
                     "poll-op": int(pk.m_op[j]),
                     "send-op": int(pk.m_sendinv[j])}
                    for j in np.nonzero(m["precommit"])[0]]

    groups: Dict[Tuple[Any, int, int], List[bool]] = {}
    for j in np.nonzero(m["stale"])[0]:
        row = int(pk.b_by_kg[j])
        g = (K[pk.b_key[row]], int(pk.b_gen[row]),
             int(pk.b_start[row]))
        groups.setdefault(g, []).append(bool(m["stale_evid"][j]))
    stale = [{"key": k, "generation": gen, "start": start,
              "polls": len(evs), "behind": sum(evs)}
             for (k, gen, start), evs in groups.items()]
    stale.sort(key=lambda e: (repr(e["key"]), e["generation"],
                              e["start"]))

    anomalies = {
        "lost-write": lost[:max_reported],
        "duplicate": duplicates[:max_reported],
        "inconsistent-offsets": inconsistent[:max_reported],
        "nonmonotonic-poll": nonmonotonic[:max_reported],
        "poll-skip": skipped[:max_reported],
        "int-nonmonotonic-poll": int_nonmono[:max_reported],
        "int-poll-skip": int_skipped[:max_reported],
        "nonmonotonic-send": nonmono_send[:max_reported],
        "int-send-skip": int_send_skip[:max_reported],
        "precommitted-read": precommitted[:max_reported],
        "stale-consumer-group": stale[:max_reported],
    }
    found = {k: v for k, v in anomalies.items() if v}
    out = {
        "valid?": not found,
        "anomaly-types": sorted(found),
        "anomalies": found,
        "send-count": pk.n_sends,
        "poll-count": pk.n_polls,
    }
    if unseen:
        out["unseen"] = dict(
            sorted(unseen.items(), key=repr)[:max_reported])
    for name, entries in found.items():
        telemetry.registry().counter(
            "queue-anomalies-found", anomaly=name).inc(len(entries))
    return out


def check(history, test: Optional[dict] = None, *,
          use_device: bool = True, max_reported: int = 16,
          deadline=None, plan=None, policy=None) -> Dict[str, Any]:
    """Check a kafka history.  Accepts a History / op list /
    PackedKafka.  Device path first (guarded, retried,
    deadline-polled); persistent failure degrades to the host twin
    with the standard stamp.  ``use_device=False`` IS the host twin."""
    from jepsen_tpu import resilience

    ph = telemetry.phases()
    pk = history if isinstance(history, PackedKafka) else None
    if pk is None:
        from jepsen_tpu.history.ir import HistoryIR

        ph.start("queue.pack", device=False)
        pk = (history.queue("kafka")
              if isinstance(history, HistoryIR)
              else packed_mod.pack_kafka(history))
    if pk.empty:
        ph.end()
        return {"valid?": "unknown"}
    if deadline is not None:
        deadline.check(SITE)
    use_device = use_device and pk.device_safe
    if not use_device:
        ph.start("queue.check", device=False,
                 sends=pk.n_sends, polls=pk.n_polls)
        res = host_verdict(pk, max_reported)
        ph.end()
        return res
    ph.start("queue.check", device=True,
             sends=pk.n_sends, polls=pk.n_polls)
    try:
        masks, degraded = resilience.with_fallback(
            SITE,
            lambda: _reduce_device(pk),
            lambda: _reduce_host(pk),
            deadline=deadline, plan=plan, policy=policy, test=test)
    except resilience.DeadlineExceeded:
        ph.end()
        return resilience.deadline_result(checker="kafka")
    res = _render(pk, masks, max_reported)
    if degraded:
        res["degraded"] = degraded
    ph.end()
    return res


class PackedKafkaChecker(checker_api.Checker):
    """The canonical kafka checker: packed anomaly passes on the
    HistoryIR, device path + host twin, `KafkaChecker` scan parity
    pinned differentially."""

    def name(self) -> str:
        return "kafka"

    def check(self, test, history, opts=None):
        return check(history, test,
                     deadline=(opts or {}).get("deadline"))
