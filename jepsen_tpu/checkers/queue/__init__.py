"""Packed queue/kafka checker family (ROADMAP item 4).

The last scenario frontier rebuilt the ISSUE-9/-11 way: queue and
kafka semantics — previously host-only scans (`workloads/kafka.py`'s
`KafkaChecker`, `checker_api.TotalQueueChecker`) — as whole-history
vectorized reductions over SoA columns on the HistoryIR, with a device
path behind ``resilience.with_fallback(site="queue.check")``,
compile-cache routing, and the original scans pinned as differential
twins (verdict-for-verdict on seeded corpora; tests/
test_queue_checkers.py).

- :mod:`.packed` — pack send/poll/assign/offset-commit histories into
  per-key offset ladders, per-consumer observation rows, and pack-time
  derived orders (``HistoryIR.queue(kind)`` memoizes both views);
- :mod:`.kafka` — the kafka anomaly taxonomy (lost-write, duplicate,
  inconsistent-offsets, poll/send order, precommitted-read,
  stale-consumer-group) as one fused mask kernel;
- :mod:`.fifo` — the total-queue counting model + the opt-in
  per-consumer FIFO pass.

Registry: :data:`MODELS` follows `checkers.invariants.MODELS` — model
name -> flywheel metadata (workload, device classification, anomaly
vocabulary) so campaign specs, shrink probe twins, and witness
renderers agree on one table.
"""

from __future__ import annotations

from jepsen_tpu.checkers.queue import fifo, kafka, packed

__all__ = ["packed", "kafka", "fifo", "MODELS"]

#: model name -> flywheel metadata (same shape as invariants.MODELS)
MODELS = {
    "kafka": {
        "workload": "kafka",
        "device": True,
        "anomalies": kafka.ANOMALIES,
    },
    "total-queue": {
        "workload": "queue",
        "device": True,
        "anomalies": (fifo.LOST, fifo.PHANTOM, fifo.FIFO),
    },
}
