"""SoA packing for the queue/kafka checker family (ROADMAP item 4).

Flattens send/poll/assign/offset-commit histories into the columnar
views the vectorized anomaly passes (:mod:`.kafka`, :mod:`.fifo`)
reduce over, the same treatment `history/soa.py` gives transactions and
`checkers/invariants/packed.py` gives bank reads:

- :class:`PackedKafka` — per-key **offset ladders** (send columns +
  the unique observed ``(key, offset)`` table), flattened poll-message
  columns, and per-consumer **observation rows** (one per ``(poll op,
  key)`` batch, carrying the assignment epoch the host scan checker
  computes via bisect);
- :class:`PackedFifo` — per-value enqueue/dequeue count columns plus
  the per-consumer dequeue order (the FIFO pass's input).

The facts are extracted by the SAME traversal the host scan twins use
(`workloads.kafka._observations`, `TotalQueueChecker`'s counting
model), so the packed columns cannot drift from the oracle semantics.
All derived ORDERS (lexsort permutations, unique tables, epoch codes)
are computed here at pack time on the host — the device reduction then
needs only adjacency compares, searchsorted membership tests, and
segment reductions over already-sorted columns (the PR 11 derived-order
idiom; see docs/QUEUE.md for the exact column set).

Composite codes: offsets/values/keys are small non-negative ints after
interning, so ``(key, offset)`` packs into one int64 as ``key *
off_base + offset`` (bases are pow2, ``class_label``-stable), which is
what makes the membership tests single searchsorted calls.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PackedKafka", "PackedFifo", "pack_kafka", "pack_fifo",
           "SENTINEL"]

#: searchsorted padding sentinel: larger than any real composite code,
#: so padded table rows can never test as members.  2**30 keeps the
#: device path int32-exact (the repo's device dtype convention — see
#: `device_infer.BIG`); histories whose codes would exceed it report
#: ``device_safe == False`` and stay on the int64 host path.
SENTINEL = np.int64(2 ** 30)


def _pow2(n: int, floor: int = 2) -> int:
    x = floor
    while x < n:
        x *= 2
    return x


def _intern(table: Dict[Any, int], order: List[Any], v: Any) -> int:
    i = table.get(v)
    if i is None:
        i = table[v] = len(order)
        order.append(v)
    return i


@dataclass
class PackedKafka:
    """Columnar kafka history: send rows, poll-batch rows (one per
    ``(poll op, key)`` entry, empty batches included for poll-count
    parity but flagged), flattened poll-message rows, the unique
    observed-offset tables, and the pack-time sort permutations the
    reductions consume.  All columns int64; id tables map back."""

    keys: List[Any]                 # key id -> source key
    values: List[Any]               # value id -> source value
    procs: List[Any]                # proc id -> process
    off_base: int                   # pow2 composite base for offsets
    n_sends: int
    n_polls: int                    # batches INCLUDING empty ones
    # send columns (history order)
    s_key: np.ndarray = field(default=None)
    s_off: np.ndarray = field(default=None)
    s_val: np.ndarray = field(default=None)
    s_op: np.ndarray = field(default=None)
    s_proc: np.ndarray = field(default=None)
    # poll-batch columns (non-empty batches, _observations order)
    b_key: np.ndarray = field(default=None)
    b_proc: np.ndarray = field(default=None)
    b_op: np.ndarray = field(default=None)
    b_start: np.ndarray = field(default=None)   # first polled offset
    b_last: np.ndarray = field(default=None)    # last polled offset
    b_ep: np.ndarray = field(default=None)      # epoch code (see pack)
    b_gen: np.ndarray = field(default=None)     # broker gen, -1 = none
    # poll-message columns (batch-major, batch order)
    m_batch: np.ndarray = field(default=None)   # row into b_*
    m_key: np.ndarray = field(default=None)
    m_off: np.ndarray = field(default=None)
    m_val: np.ndarray = field(default=None)
    m_op: np.ndarray = field(default=None)
    m_sendinv: np.ndarray = field(default=None)  # send INVOKE idx, -1
    # derived tables (pack-time sorted/unique)
    u_comp: np.ndarray = field(default=None)    # unique polled k*B+off
    polled_max: np.ndarray = field(default=None)  # per key id, -1=none
    key_max: np.ndarray = field(default=None)   # max SENT|polled, -1
    dv_key: np.ndarray = field(default=None)    # unique polled (k,v,o)
    dv_val: np.ndarray = field(default=None)
    dv_off: np.ndarray = field(default=None)
    av_key: np.ndarray = field(default=None)    # unique seen (k,o,v)
    av_off: np.ndarray = field(default=None)
    av_val: np.ndarray = field(default=None)
    # derived orders (pack-time lexsort permutations)
    s_by_pk: np.ndarray = field(default=None)   # sends by (proc,key,seq)
    s_by_ok: np.ndarray = field(default=None)   # sends by (op,key,seq)
    b_by_pk: np.ndarray = field(default=None)   # batches by (proc,key,seq)
    b_by_kg: np.ndarray = field(default=None)   # batches by (key,gen,start,seq)

    @property
    def empty(self) -> bool:
        return self.n_sends == 0 and self.n_polls == 0

    @property
    def device_safe(self) -> bool:
        """Every composite code / index the kernel computes fits below
        :data:`SENTINEL` (int32-exact on device).  False forces the
        int64 host path."""
        m = self.off_base * max(len(self.keys), 1)
        for a in (self.b_ep, self.s_op, self.m_op, self.m_sendinv):
            if len(a):
                m = max(m, int(a.max()) + 1)
        return m < int(SENTINEL)


def pack_kafka(history) -> PackedKafka:
    """Pack a kafka history.  Facts come from the host twin's own
    traversal (`workloads.kafka._observations`) — identical send/poll/
    reassign extraction, then columnized with epochs precomputed the
    way the twin computes them (per-process reassign bisect + the
    broker rebalance generation riding on subscribe-mode
    completions)."""
    from jepsen_tpu.workloads.kafka import _observations

    sends, polls, reassigns, send_invoked = _observations(history)

    ktab: Dict[Any, int] = {}
    korder: List[Any] = []
    vtab: Dict[Any, int] = {}
    vorder: List[Any] = []
    ptab: Dict[Any, int] = {}
    porder: List[Any] = []

    max_off = 0
    for (_k, off, _v, _i, _p) in sends:
        max_off = max(max_off, off)
    for (_k, msgs, _p, _i, _s, _g) in polls:
        for (off, _v) in msgs:
            max_off = max(max_off, off)

    reassign_by_proc: Dict[Any, List[int]] = {}
    for (p, i) in reassigns:
        reassign_by_proc.setdefault(p, []).append(i)
    max_gen = max([g for (_k, _m, _p, _i, _s, g) in polls
                   if g is not None] or [0])
    gen_base = _pow2(int(max_gen) + 2)

    # -- send columns -------------------------------------------------
    s_key = np.empty(len(sends), np.int64)
    s_off = np.empty(len(sends), np.int64)
    s_val = np.empty(len(sends), np.int64)
    s_op = np.empty(len(sends), np.int64)
    s_proc = np.empty(len(sends), np.int64)
    for n, (k, off, v, i, p) in enumerate(sends):
        s_key[n] = _intern(ktab, korder, k)
        s_off[n] = int(off)
        s_val[n] = _intern(vtab, vorder, v)
        s_op[n] = int(i)
        s_proc[n] = _intern(ptab, porder, p)

    # -- poll batches + messages --------------------------------------
    # the twin iterates sorted(polls, key=(op, slot)) — the polls list
    # is already in that order (one ordered history pass), so the list
    # index IS the batch sequence number
    bk: List[int] = []
    bp: List[int] = []
    bo: List[int] = []
    bstart: List[int] = []
    blast: List[int] = []
    bep: List[int] = []
    bgen: List[int] = []
    mb: List[int] = []
    mk: List[int] = []
    mo: List[int] = []
    mv: List[int] = []
    mop: List[int] = []
    msi: List[int] = []
    n_polls = len(polls)
    for (k, msgs, p, i, _slot, gen) in polls:
        if not msgs:
            continue  # counted in n_polls; excluded from order passes
        kid = _intern(ktab, korder, k)
        pid = _intern(ptab, porder, p)
        # the twin's epoch: (count of p's reassigns before this op,
        # broker generation) — encode the tuple as one comparable code
        epc = bisect.bisect_left(reassign_by_proc.get(p, ()), i)
        gcode = 0 if gen is None else int(gen) + 1
        row = len(bk)
        bk.append(kid)
        bp.append(pid)
        bo.append(int(i))
        bstart.append(int(msgs[0][0]))
        blast.append(int(msgs[-1][0]))
        bep.append(epc * gen_base + gcode)
        bgen.append(-1 if gen is None else int(gen))
        for (off, v) in msgs:
            mb.append(row)
            mk.append(kid)
            mo.append(int(off))
            mv.append(_intern(vtab, vorder, v))
            mop.append(int(i))
            j = send_invoked.get((k, v))
            msi.append(-1 if j is None else int(j))

    b_key = np.asarray(bk, np.int64)
    b_proc = np.asarray(bp, np.int64)
    b_op = np.asarray(bo, np.int64)
    b_start = np.asarray(bstart, np.int64)
    b_last = np.asarray(blast, np.int64)
    b_ep = np.asarray(bep, np.int64)
    b_gen = np.asarray(bgen, np.int64)
    m_batch = np.asarray(mb, np.int64)
    m_key = np.asarray(mk, np.int64)
    m_off = np.asarray(mo, np.int64)
    m_val = np.asarray(mv, np.int64)
    m_op = np.asarray(mop, np.int64)
    m_sendinv = np.asarray(msi, np.int64)

    n_keys = max(len(korder), 1)
    off_base = _pow2(max_off + 2)
    val_base = _pow2(len(vorder) + 1)

    # -- derived tables -----------------------------------------------
    # unique polled (key, offset): the ladder the membership tests
    # (lost-write, poll-skip intervening-offset) searchsorted against
    u_comp = np.unique(m_key * off_base + m_off) if len(m_key) \
        else np.zeros(0, np.int64)
    polled_max = np.full(n_keys, -1, np.int64)
    if len(m_key):
        np.maximum.at(polled_max, m_key, m_off)
    key_max = polled_max.copy()
    if len(s_key):
        np.maximum.at(key_max, s_key, s_off)
    # unique polled (key, value, offset): the duplicate pass's rows
    if len(m_key):
        dvc = np.unique((m_key * val_base + m_val) * off_base + m_off)
        dv_off = dvc % off_base
        dv_val = (dvc // off_base) % val_base
        dv_key = dvc // (off_base * val_base)
    else:
        dv_key = dv_val = dv_off = np.zeros(0, np.int64)
    # unique observed (key, offset, value) over sends AND polls: the
    # inconsistent-offsets pass's version map
    all_k = np.concatenate([s_key, m_key])
    all_o = np.concatenate([s_off, m_off])
    all_v = np.concatenate([s_val, m_val])
    if len(all_k):
        avc = np.unique((all_k * off_base + all_o) * val_base + all_v)
        av_val = avc % val_base
        av_off = (avc // val_base) % off_base
        av_key = avc // (val_base * off_base)
    else:
        av_key = av_off = av_val = np.zeros(0, np.int64)

    # -- derived orders -----------------------------------------------
    seq_s = np.arange(len(s_key), dtype=np.int64)
    seq_b = np.arange(len(b_key), dtype=np.int64)
    return PackedKafka(
        keys=korder, values=vorder, procs=porder, off_base=off_base,
        n_sends=len(sends), n_polls=n_polls,
        s_key=s_key, s_off=s_off, s_val=s_val, s_op=s_op,
        s_proc=s_proc,
        b_key=b_key, b_proc=b_proc, b_op=b_op, b_start=b_start,
        b_last=b_last, b_ep=b_ep, b_gen=b_gen,
        m_batch=m_batch, m_key=m_key, m_off=m_off, m_val=m_val,
        m_op=m_op, m_sendinv=m_sendinv,
        u_comp=u_comp, polled_max=polled_max, key_max=key_max,
        dv_key=dv_key, dv_val=dv_val, dv_off=dv_off,
        av_key=av_key, av_off=av_off, av_val=av_val,
        s_by_pk=np.lexsort((seq_s, s_key, s_proc)),
        s_by_ok=np.lexsort((seq_s, s_key, s_op)),
        b_by_pk=np.lexsort((seq_b, b_key, b_proc)),
        b_by_kg=np.lexsort((seq_b, b_start, b_gen, b_key)),
    )


@dataclass
class PackedFifo:
    """Columnar queue history: per-value enqueue/dequeue counts (the
    total-queue counting model) plus the per-consumer dequeue order
    with each value's enqueue invoke/complete indices (the FIFO
    pass's input)."""

    values: List[Any]               # value id -> source value
    procs: List[Any]
    enqueue_count: int              # total enqueue ATTEMPTS (invokes)
    dequeue_count: int
    # per-value-id count columns
    e_ok: np.ndarray = field(default=None)
    e_maybe: np.ndarray = field(default=None)
    d_cnt: np.ndarray = field(default=None)
    v_inv: np.ndarray = field(default=None)    # earliest enq INVOKE, -1
    v_done: np.ndarray = field(default=None)   # earliest enq OK idx, -1
    v_first_ok: np.ndarray = field(default=None)  # order for rendering
    # ok-dequeue rows (history order)
    q_val: np.ndarray = field(default=None)
    q_op: np.ndarray = field(default=None)
    q_proc: np.ndarray = field(default=None)
    q_by_proc: np.ndarray = field(default=None)  # rows by (proc, seq)

    @property
    def empty(self) -> bool:
        return self.enqueue_count == 0 and self.dequeue_count == 0


def pack_fifo(history) -> PackedFifo:
    """Pack an enqueue/dequeue history under the `TotalQueueChecker`
    counting model: OK enqueues are definite, INFO enqueues possible,
    FAIL enqueues absent; OK dequeues count."""
    from jepsen_tpu.history.ops import INFO, INVOKE, OK

    vtab: Dict[Any, int] = {}
    vorder: List[Any] = []
    ptab: Dict[Any, int] = {}
    porder: List[Any] = []
    eok: List[int] = []
    emaybe: List[int] = []
    dcnt: List[int] = []
    vinv: List[int] = []
    vdone: List[int] = []
    vfirst: List[int] = []
    qv: List[int] = []
    qo: List[int] = []
    qp: List[int] = []
    n_att = 0
    n_deq = 0

    def vid(v: Any) -> int:
        i = vtab.get(v)
        if i is None:
            i = vtab[v] = len(vorder)
            vorder.append(v)
            eok.append(0)
            emaybe.append(0)
            dcnt.append(0)
            vinv.append(-1)
            vdone.append(-1)
            vfirst.append(-1)
        return i

    for op in history:
        if not op.is_client_op():
            continue
        if op.f == "enqueue":
            i = vid(op.value)
            if op.type == INVOKE:
                n_att += 1
                if vinv[i] < 0:
                    vinv[i] = op.index
            elif op.type == OK:
                eok[i] += 1
                if vdone[i] < 0:
                    vdone[i] = op.index
                if vfirst[i] < 0:
                    vfirst[i] = op.index
            elif op.type == INFO:
                emaybe[i] += 1
        elif op.f == "dequeue" and op.type == OK:
            i = vid(op.value)
            dcnt[i] += 1
            n_deq += 1
            qv.append(i)
            qo.append(op.index)
            qp.append(_intern(ptab, porder, op.process))

    q_val = np.asarray(qv, np.int64)
    q_op = np.asarray(qo, np.int64)
    q_proc = np.asarray(qp, np.int64)
    seq = np.arange(len(q_val), dtype=np.int64)
    return PackedFifo(
        values=vorder, procs=porder,
        enqueue_count=n_att, dequeue_count=n_deq,
        e_ok=np.asarray(eok, np.int64),
        e_maybe=np.asarray(emaybe, np.int64),
        d_cnt=np.asarray(dcnt, np.int64),
        v_inv=np.asarray(vinv, np.int64),
        v_done=np.asarray(vdone, np.int64),
        v_first_ok=np.asarray(vfirst, np.int64),
        q_val=q_val, q_op=q_op, q_proc=q_proc,
        q_by_proc=np.lexsort((seq, q_proc)),
    )
