"""Total-queue + FIFO passes as vectorized reductions.

The `checker_api.TotalQueueChecker` counting model over the
:class:`~jepsen_tpu.checkers.queue.packed.PackedFifo` columns:

- **queue-lost** — per-value ``enq_ok > deq`` (definitely enqueued
  more times than ever dequeued);
- **queue-phantom** — per-value ``deq > enq_ok + enq_maybe``
  (dequeued more than it could possibly have been enqueued; the
  twin's "unexpected");
- **queue-fifo-violation** (additive, ``fifo=True``) — one consumer
  dequeues *b* then *a* although *a*'s enqueue OK-completed before
  *b*'s enqueue was even invoked: a sound single-consumer FIFO
  violation no interleaving explains.  Runs as a segmented running
  max of enqueue-invoke indices over the per-process dequeue order
  (``idx + seg*BIG`` cummax — no segment primitives needed), so the
  whole pass is one scan.  It is OFF by default: the canonical
  total-queue verdict stays verdict-for-verdict with the host scan
  twin, and FIFO attribution is an opt-in stricter mode (mem-store
  queues are FIFO, so the reorder adversarial knob is what trips it).

Device path behind ``resilience.with_fallback(site="queue.check")``
with compile-cache routing and pow2 padding, host path the same
arithmetic in numpy; result keeps every legacy `TotalQueueChecker`
key (lost / lost-count / unexpected / unexpected-count /
enqueue-count / dequeue-count) and adds the elle-style
``anomaly-types`` / ``anomalies`` the witness pages render.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from jepsen_tpu import telemetry
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.checkers.queue import packed as packed_mod
from jepsen_tpu.checkers.queue.packed import PackedFifo

SITE = "queue.check"

LOST = "queue-lost"
PHANTOM = "queue-phantom"
FIFO = "queue-fifo-violation"


def _cummax(xp, x):
    if xp is np:
        return np.maximum.accumulate(x)
    import jax.lax as lax

    return lax.cummax(x)


def _math(xp, big: int, e_ok, e_maybe, d_cnt, v_inv, v_done,
          q_val, q_proc, q_by_proc):
    """(lost mask [V], phantom mask [V], fifo mask [Q], prior-invoke
    index per dequeue row [Q] in q_by_proc coords, -1 none)."""
    lost = (d_cnt < e_ok)
    phantom = d_cnt > e_ok + e_maybe
    Q = q_val.shape[0]
    if Q == 0:
        z = xp.zeros(0, bool)
        return lost, phantom, z, xp.zeros(0, xp.int64)
    o = q_by_proc
    p = q_proc[o]
    valid = q_val[o] >= 0
    vs = xp.where(valid, q_val[o], 0)
    inv = xp.where(valid, v_inv[vs], -1)
    done = xp.where(valid, v_done[vs], -1)
    seg = xp.concatenate(
        [xp.zeros(1, bool), (p[1:] != p[:-1]) | ~valid[1:]])
    seg_id = xp.cumsum(seg.astype(xp.int64))
    run = _cummax(xp, xp.where(inv >= 0, inv, -1) + seg_id * big)
    prev = xp.concatenate([xp.full(1, -1, xp.int64), run[:-1]])
    in_seg = prev >= seg_id * big
    prev_inv = xp.where(in_seg, prev - seg_id * big, -1)
    fifo = valid & (done >= 0) & (prev_inv >= 0) & (done < prev_inv)
    return lost, phantom, fifo, prev_inv


_KERNEL = None


def _kernel():
    global _KERNEL
    if _KERNEL is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("big",))
        def queue_fifo_core(*cols, big):
            return _math(jnp, big, *cols)

        _KERNEL = queue_fifo_core
    return _KERNEL


def _big(pf: PackedFifo) -> int:
    from jepsen_tpu.compilecache import bucket

    top = int(max(pf.v_inv.max() if len(pf.v_inv) else 0,
                  pf.q_op.max() if len(pf.q_op) else 0, 0))
    return bucket.pow2_at_least(top + 2)


def _cols(pf: PackedFifo) -> Tuple[np.ndarray, ...]:
    return (pf.e_ok, pf.e_maybe, pf.d_cnt, pf.v_inv, pf.v_done,
            pf.q_val, pf.q_proc, pf.q_by_proc)


def _reduce_host(pf: PackedFifo):
    return _math(np, _big(pf), *_cols(pf))


def _reduce_device(pf: PackedFifo):
    from jepsen_tpu import compilecache
    from jepsen_tpu.compilecache import bucket

    V = bucket.pow2_at_least(max(len(pf.e_ok), 1))
    Q = bucket.pow2_at_least(max(len(pf.q_val), 1))

    def pad(a, n, fill):
        out = np.full(n, fill, np.int64)
        out[:len(a)] = a
        return out

    cols = (pad(pf.e_ok, V, 0), pad(pf.e_maybe, V, 0),
            pad(pf.d_cnt, V, 0), pad(pf.v_inv, V, -1),
            pad(pf.v_done, V, -1),
            pad(pf.q_val, Q, -1), pad(pf.q_proc, Q, -1),
            np.concatenate([pf.q_by_proc,
                            np.arange(len(pf.q_by_proc), Q,
                                      dtype=np.int64)]))
    out = compilecache.call(SITE, _kernel(), *cols, big=_big(pf))
    lost, phantom, fifo, prev_inv = (np.asarray(x) for x in out)
    n_v, n_q = len(pf.e_ok), len(pf.q_val)
    return (lost[:n_v], phantom[:n_v], fifo[:n_q], prev_inv[:n_q])


def host_verdict(pf: PackedFifo, fifo: bool = False,
                 max_reported: int = 32) -> Dict[str, Any]:
    """The exact host oracle twin — numpy only, no jax import."""
    return _render(pf, _reduce_host(pf), fifo, max_reported)


def _render(pf: PackedFifo, reduced, fifo: bool,
            max_reported: int) -> Dict[str, Any]:
    lost_m, phantom_m, fifo_m, prev_inv = reduced
    V = pf.values
    lost = {V[i]: int(pf.e_ok[i] - pf.d_cnt[i])
            for i in np.nonzero(lost_m)[0]}
    unexpected = {V[i]: int(pf.d_cnt[i] - pf.e_ok[i] - pf.e_maybe[i])
                  for i in np.nonzero(phantom_m)[0]}
    found: Dict[str, list] = {}
    if lost:
        found[LOST] = [
            {"value": v, "times": n,
             "why": f"value {v!r} was enqueued {n} more time(s) than "
                    f"it was ever dequeued"}
            for v, n in list(lost.items())[:max_reported]]
    if unexpected:
        found[PHANTOM] = [
            {"value": v, "times": n,
             "why": f"value {v!r} was dequeued {n} more time(s) than "
                    f"it could possibly have been enqueued"}
            for v, n in list(unexpected.items())[:max_reported]]
    if fifo:
        ent = []
        for j in np.nonzero(fifo_m)[0]:
            row = int(pf.q_by_proc[j])
            v = V[pf.q_val[row]]
            ent.append({
                "process": pf.procs[pf.q_proc[row]],
                "value": v, "op-index": int(pf.q_op[row]),
                "enq-completed": int(pf.v_done[pf.q_val[row]]),
                "prior-enq-invoked": int(prev_inv[j]),
                "why": f"value {v!r} (enqueue completed at op "
                       f"{int(pf.v_done[pf.q_val[row]])}) was dequeued "
                       f"after a value whose enqueue was only invoked "
                       f"at op {int(prev_inv[j])}"})
        if ent:
            found[FIFO] = sorted(ent, key=lambda e: e["op-index"]
                                 )[:max_reported]
    out = {
        "valid?": not found,
        "anomaly-types": sorted(found),
        "anomalies": found,
        # the TotalQueueChecker legacy keys, bit-for-bit
        "lost": dict(list(lost.items())[:32]),
        "lost-count": len(lost),
        "unexpected": dict(list(unexpected.items())[:32]),
        "unexpected-count": len(unexpected),
        "enqueue-count": pf.enqueue_count,
        "dequeue-count": pf.dequeue_count,
    }
    for name, entries in found.items():
        telemetry.registry().counter(
            "queue-anomalies-found", anomaly=name).inc(len(entries))
    return out


def check(history, test: Optional[dict] = None, *,
          fifo: bool = False, use_device: bool = True,
          max_reported: int = 32,
          deadline=None, plan=None, policy=None) -> Dict[str, Any]:
    """Check an enqueue/dequeue history.  Accepts a History / op list
    / PackedFifo.  ``fifo=True`` additionally runs the per-consumer
    FIFO pass (stricter than the host scan twin — leave off for
    twin-parity contexts)."""
    from jepsen_tpu import resilience

    ph = telemetry.phases()
    pf = history if isinstance(history, PackedFifo) else None
    if pf is None:
        from jepsen_tpu.history.ir import HistoryIR

        ph.start("queue.pack", device=False)
        pf = (history.queue("fifo")
              if isinstance(history, HistoryIR)
              else packed_mod.pack_fifo(history))
    if pf.empty:
        ph.end()
        return {"valid?": "unknown"}
    if deadline is not None:
        deadline.check(SITE)
    # int32-exactness bound for the segmented cummax (seg*big offsets)
    use_device = use_device and \
        _big(pf) * (len(pf.q_val) + 2) < 2 ** 31
    if not use_device:
        ph.start("queue.check", device=False,
                 values=len(pf.values), dequeues=pf.dequeue_count)
        res = host_verdict(pf, fifo, max_reported)
        ph.end()
        return res
    ph.start("queue.check", device=True,
             values=len(pf.values), dequeues=pf.dequeue_count)
    try:
        reduced, degraded = resilience.with_fallback(
            SITE,
            lambda: _reduce_device(pf),
            lambda: _reduce_host(pf),
            deadline=deadline, plan=plan, policy=policy, test=test)
    except resilience.DeadlineExceeded:
        ph.end()
        return resilience.deadline_result(checker="total-queue")
    res = _render(pf, reduced, fifo, max_reported)
    if degraded:
        res["degraded"] = degraded
    ph.end()
    return res


class PackedQueueChecker(checker_api.Checker):
    """The canonical total-queue checker: packed counting passes,
    device path + host twin, `TotalQueueChecker` scan parity pinned
    differentially.  ``fifo=True`` opts into the per-consumer FIFO
    pass on top."""

    def __init__(self, *, fifo: bool = False):
        self.fifo = fifo

    def name(self) -> str:
        return "total-queue"

    def check(self, test, history, opts=None):
        return check(history, test, fifo=self.fifo,
                     deadline=(opts or {}).get("deadline"))
