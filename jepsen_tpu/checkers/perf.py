"""Performance graphs: latency and rate over time.

Equivalent of the reference's `jepsen/src/jepsen/checker/perf.clj`
(SURVEY.md §2.1): extracts latency/rate point series from the history with
vectorised folds (numpy — the same SoA shape the device folds use) and
renders PNGs with matplotlib (replacing the reference's external gnuplot,
§2.5 #8), with nemesis activity windows shaded behind the series.

Checkers: :class:`LatencyGraph`, :class:`RateGraph`, and :func:`perf`
composing both — always valid; their value is the artifacts written into
the store directory.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..history.ops import FAIL, INFO, INVOKE, OK
from .api import Checker, output_path as _output_path

logger = logging.getLogger("jepsen.checker.perf")

_TYPE_COLOR = {OK: "#81F749", FAIL: "#E9A4A0", INFO: "#FFAA26"}
_NS = 1e9


def latency_points(history) -> Dict[str, np.ndarray]:
    """Per completed client op: invoke time (s), latency (ms), completion
    type code, and an interned :f id.  One pass, SoA output."""
    t_inv: List[float] = []
    lat: List[float] = []
    typ: List[str] = []
    fs: List[Any] = []
    for op in history:
        if not op.is_client_op() or op.type == INVOKE:
            continue
        inv = history.invocation(op) if hasattr(history, "invocation") else None
        if inv is None:
            continue
        t_inv.append(inv.time / _NS)
        lat.append(max(op.time - inv.time, 0) / 1e6)
        typ.append(op.type)
        fs.append(op.f)
    return {"time": np.asarray(t_inv), "latency_ms": np.asarray(lat),
            "type": np.asarray(typ, dtype=object),
            "f": np.asarray(fs, dtype=object)}


def rate_points(history, dt: float = 1.0) -> Dict[Tuple[Any, str], Tuple[np.ndarray, np.ndarray]]:
    """Ops/sec per (f, completion-type), bucketed into dt-second windows."""
    pts = latency_points(history)
    out: Dict[Tuple[Any, str], Tuple[np.ndarray, np.ndarray]] = {}
    if len(pts["time"]) == 0:
        return out
    t_end = float(pts["time"].max()) + dt
    edges = np.arange(0.0, t_end + dt, dt)
    for f in sorted(set(pts["f"]), key=repr):
        for typ in (OK, FAIL, INFO):
            sel = (pts["f"] == f) & (pts["type"] == typ)
            if not sel.any():
                continue
            counts, _ = np.histogram(pts["time"][sel], bins=edges)
            out[(f, typ)] = (edges[:-1], counts / dt)
    return out


# Fallback name heuristics for tests without perf metadata.  Note the
# exact f="start" is a *start* here (the conventional start/stop nemesis);
# the kill package, whose recovery op is f="start", supplies metadata.
_DEFAULT_STARTS = frozenset({"partition", "kill", "pause", "bump-clock",
                             "strobe-clock"})
_DEFAULT_STOPS = frozenset({"resume", "restart", "reset-clock"})


def _perf_specs(test: Optional[dict]) -> List[Tuple[frozenset, frozenset]]:
    """(start-fs, stop-fs) pairs.  Prefers the nemesis packages' exact perf
    metadata on the test map (`nemesis/combined.py` exports
    {"perf": {"start": {...}, "stop": {...}}}); falls back to name
    heuristics.  Note the kill package's *recovery* op is f="start", which
    is why metadata beats heuristics."""
    t = test or {}
    metas = list((t.get("plot") or {}).get("nemeses") or ())
    for pkg in t.get("nemesis-packages", ()) or ():
        perf_val = (pkg or {}).get("perf")
        if isinstance(perf_val, list):  # composed package: list of metas
            metas.extend(m for m in perf_val if m)
        elif perf_val:
            metas.append(perf_val)
    specs = []
    for perf_meta in metas:
        if perf_meta.get("start") or perf_meta.get("stop"):
            specs.append((frozenset(perf_meta.get("start", ())),
                          frozenset(perf_meta.get("stop", ()))))
    if not specs:
        specs.append((_DEFAULT_STARTS, _DEFAULT_STOPS))
    return specs


def nemesis_intervals(history, test: Optional[dict] = None
                      ) -> List[Tuple[float, float, Any]]:
    """(start, end, f) windows of nemesis activity, for plot shading
    (reference `util/nemesis-intervals` + perf's shaded regions).  Windows
    open/close on completions, when the fault has actually taken effect."""
    out = []
    specs = _perf_specs(test)
    open_at: List[Optional[float]] = [None] * len(specs)
    open_f: List[Any] = [None] * len(specs)
    for op in history:
        if op.process != "nemesis" or op.type == INVOKE:
            continue
        f = str(op.f or "")
        t = op.time / _NS
        for si, (starts, stops) in enumerate(specs):
            generic = starts is _DEFAULT_STARTS
            is_start = f in starts or (generic and f.startswith("start"))
            is_stop = f in stops or (generic and (f.startswith("stop")
                                                  or f.startswith("heal")))
            if generic and f == "start" and open_at[si] is not None:
                # heuristic mode: a bare "start" while a window is open is
                # the kill nemesis's recovery — close, don't open
                is_start, is_stop = False, True
            if is_start and open_at[si] is None:
                open_at[si], open_f[si] = t, op.f
            elif is_stop and open_at[si] is not None:
                out.append((open_at[si], t, open_f[si]))
                open_at[si], open_f[si] = None, None
    for si in range(len(specs)):
        if open_at[si] is not None:
            end = (history[len(history) - 1].time / _NS
                   if len(history) else open_at[si])
            out.append((open_at[si], end, open_f[si]))
    return sorted(out)


def _shade(ax, history, test: Optional[dict] = None):
    for (t0, t1, f) in nemesis_intervals(history, test):
        ax.axvspan(t0, t1, color="#FF8B8B", alpha=0.2, lw=0)


def _matplotlib():
    """pyplot with the Agg backend, or None when matplotlib is absent —
    the graphs then degrade to returning their computed counts instead
    of raising into `check_safe` (a missing plotting dep must never
    turn a run's results "unknown")."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        logger.warning("matplotlib unavailable; perf graphs skipped")
        return None


class LatencyGraph(Checker):
    """Scatter of op latencies over time, colored by completion type,
    one marker style per :f; nemesis windows shaded (reference
    `latency-graph`, rendered with matplotlib instead of gnuplot)."""

    def __init__(self, filename: str = "latency-raw.png"):
        self.filename = filename

    def check(self, test, history, opts=None):
        pts = latency_points(history)
        if len(pts["time"]) == 0:
            return {"valid?": True, "points": 0}
        plt = _matplotlib()
        if plt is None:
            return {"valid?": True, "points": int(len(pts["time"])),
                    "plot": "skipped (no matplotlib)"}

        fig, ax = plt.subplots(figsize=(10, 5))
        _shade(ax, history, test)
        markers = "ox+sd^v*"
        for i, f in enumerate(sorted(set(pts["f"]), key=repr)):
            for typ in (OK, FAIL, INFO):
                sel = (pts["f"] == f) & (pts["type"] == typ)
                if not sel.any():
                    continue
                ax.scatter(pts["time"][sel], pts["latency_ms"][sel],
                           s=8, marker=markers[i % len(markers)],
                           c=_TYPE_COLOR[typ], label=f"{f} {typ}",
                           alpha=0.7, linewidths=0.5, edgecolors="none")
        ax.set_yscale("log")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("latency (ms)")
        ax.set_title(test.get("name", "test"))
        ax.legend(fontsize=6, loc="upper right", ncol=2)
        path = _output_path(test, opts, self.filename)
        fig.savefig(path, dpi=110)
        plt.close(fig)
        return {"valid?": True, "points": int(len(pts["time"])),
                "file": path}


class RateGraph(Checker):
    """Throughput (ops/sec per :f × outcome) over time (reference
    `rate-graph`)."""

    def __init__(self, filename: str = "rate.png", dt: float = 1.0):
        self.filename = filename
        self.dt = dt

    def check(self, test, history, opts=None):
        series = rate_points(history, self.dt)
        if not series:
            return {"valid?": True, "points": 0}
        plt = _matplotlib()
        if plt is None:
            return {"valid?": True,
                    "points": sum(len(t) for t, _ in series.values()),
                    "series": len(series),
                    "plot": "skipped (no matplotlib)"}

        fig, ax = plt.subplots(figsize=(10, 5))
        _shade(ax, history, test)
        for (f, typ), (t, rate) in sorted(series.items(),
                                          key=lambda kv: repr(kv[0])):
            ax.plot(t, rate, drawstyle="steps-post",
                    color=_TYPE_COLOR[typ], alpha=0.8, lw=1.2,
                    label=f"{f} {typ}")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("ops / s")
        ax.set_title(test.get("name", "test"))
        ax.legend(fontsize=6, loc="upper right", ncol=2)
        path = _output_path(test, opts, self.filename)
        fig.savefig(path, dpi=110)
        plt.close(fig)
        return {"valid?": True, "points": sum(len(t) for t, _ in
                                              series.values()),
                "file": path}


def perf() -> Checker:
    """Both graphs (reference `checker/perf`)."""
    from .api import compose
    return compose({"latency-graph": LatencyGraph(),
                    "rate-graph": RateGraph()})
