"""Performance graphs: latency and rate over time.

Equivalent of the reference's `jepsen/src/jepsen/checker/perf.clj`
(SURVEY.md §2.1): extracts latency/rate point series from the history with
vectorised folds (numpy — the same SoA shape the device folds use) and
renders PNGs with matplotlib (replacing the reference's external gnuplot,
§2.5 #8), with nemesis activity windows shaded behind the series.

Checkers: :class:`LatencyGraph`, :class:`RateGraph`, and :func:`perf`
composing both — always valid; their value is the artifacts written into
the store directory.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..history.ops import FAIL, INFO, INVOKE, OK
from .api import Checker, output_path as _output_path_shared

logger = logging.getLogger("jepsen.checker.perf")

_TYPE_COLOR = {OK: "#81F749", FAIL: "#E9A4A0", INFO: "#FFAA26"}
_NS = 1e9


def latency_points(history) -> Dict[str, np.ndarray]:
    """Per completed client op: invoke time (s), latency (ms), completion
    type code, and an interned :f id.  One pass, SoA output."""
    t_inv: List[float] = []
    lat: List[float] = []
    typ: List[str] = []
    fs: List[Any] = []
    for op in history:
        if not op.is_client_op() or op.type == INVOKE:
            continue
        inv = history.invocation(op) if hasattr(history, "invocation") else None
        if inv is None:
            continue
        t_inv.append(inv.time / _NS)
        lat.append(max(op.time - inv.time, 0) / 1e6)
        typ.append(op.type)
        fs.append(op.f)
    return {"time": np.asarray(t_inv), "latency_ms": np.asarray(lat),
            "type": np.asarray(typ, dtype=object),
            "f": np.asarray(fs, dtype=object)}


def rate_points(history, dt: float = 1.0) -> Dict[Tuple[Any, str], Tuple[np.ndarray, np.ndarray]]:
    """Ops/sec per (f, completion-type), bucketed into dt-second windows."""
    pts = latency_points(history)
    out: Dict[Tuple[Any, str], Tuple[np.ndarray, np.ndarray]] = {}
    if len(pts["time"]) == 0:
        return out
    t_end = float(pts["time"].max()) + dt
    edges = np.arange(0.0, t_end + dt, dt)
    for f in sorted(set(pts["f"]), key=repr):
        for typ in (OK, FAIL, INFO):
            sel = (pts["f"] == f) & (pts["type"] == typ)
            if not sel.any():
                continue
            counts, _ = np.histogram(pts["time"][sel], bins=edges)
            out[(f, typ)] = (edges[:-1], counts / dt)
    return out


def nemesis_intervals(history) -> List[Tuple[float, float, Any]]:
    """(start, end, f) windows of nemesis activity, for plot shading
    (reference `util/nemesis-intervals` + perf's shaded regions)."""
    out = []
    open_at: Optional[float] = None
    open_f = None
    for op in history:
        if op.process != "nemesis" or op.type == INVOKE:
            # windows open/close on completions, when the fault has
            # actually taken effect
            continue
        f = str(op.f or "")
        is_start = f.startswith("start") or f in ("partition", "kill", "pause")
        is_stop = f.startswith("stop") or f.startswith("heal") \
            or f in ("resume", "restart")
        t = op.time / _NS
        if is_start and open_at is None:
            open_at, open_f = t, op.f
        elif is_stop and open_at is not None:
            out.append((open_at, t, open_f))
            open_at, open_f = None, None
    if open_at is not None:
        last = history[len(history) - 1].time / _NS if len(history) else open_at
        out.append((open_at, last, open_f))
    return out


_output_path = _output_path_shared


def _shade(ax, history):
    for (t0, t1, f) in nemesis_intervals(history):
        ax.axvspan(t0, t1, color="#FF8B8B", alpha=0.2, lw=0)


class LatencyGraph(Checker):
    """Scatter of op latencies over time, colored by completion type,
    one marker style per :f; nemesis windows shaded (reference
    `latency-graph`, rendered with matplotlib instead of gnuplot)."""

    def __init__(self, filename: str = "latency-raw.png"):
        self.filename = filename

    def check(self, test, history, opts=None):
        pts = latency_points(history)
        if len(pts["time"]) == 0:
            return {"valid?": True, "points": 0}
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(10, 5))
        _shade(ax, history)
        markers = "ox+sd^v*"
        for i, f in enumerate(sorted(set(pts["f"]), key=repr)):
            for typ in (OK, FAIL, INFO):
                sel = (pts["f"] == f) & (pts["type"] == typ)
                if not sel.any():
                    continue
                ax.scatter(pts["time"][sel], pts["latency_ms"][sel],
                           s=8, marker=markers[i % len(markers)],
                           c=_TYPE_COLOR[typ], label=f"{f} {typ}",
                           alpha=0.7, linewidths=0.5, edgecolors="none")
        ax.set_yscale("log")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("latency (ms)")
        ax.set_title(test.get("name", "test"))
        ax.legend(fontsize=6, loc="upper right", ncol=2)
        path = _output_path(test, opts, self.filename)
        fig.savefig(path, dpi=110)
        plt.close(fig)
        return {"valid?": True, "points": int(len(pts["time"])),
                "file": path}


class RateGraph(Checker):
    """Throughput (ops/sec per :f × outcome) over time (reference
    `rate-graph`)."""

    def __init__(self, filename: str = "rate.png", dt: float = 1.0):
        self.filename = filename
        self.dt = dt

    def check(self, test, history, opts=None):
        series = rate_points(history, self.dt)
        if not series:
            return {"valid?": True, "points": 0}
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(10, 5))
        _shade(ax, history)
        for (f, typ), (t, rate) in sorted(series.items(),
                                          key=lambda kv: repr(kv[0])):
            ax.plot(t, rate, drawstyle="steps-post",
                    color=_TYPE_COLOR[typ], alpha=0.8, lw=1.2,
                    label=f"{f} {typ}")
        ax.set_xlabel("time (s)")
        ax.set_ylabel("ops / s")
        ax.set_title(test.get("name", "test"))
        ax.legend(fontsize=6, loc="upper right", ncol=2)
        path = _output_path(test, opts, self.filename)
        fig.savefig(path, dpi=110)
        plt.close(fig)
        return {"valid?": True, "points": sum(len(t) for t, _ in
                                              series.values()),
                "file": path}


def perf() -> Checker:
    """Both graphs (reference `checker/perf`)."""
    from .api import compose
    return compose({"latency-graph": LatencyGraph(),
                    "rate-graph": RateGraph()})
