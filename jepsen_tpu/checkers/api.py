"""Checker protocol + built-in checkers.

Equivalent of the reference's `jepsen/checker.clj` (SURVEY.md §2.1): the
`Checker` protocol — `check(test, history, opts) -> {"valid?": ...}` — plus
`check_safe` (exception -> invalid), `compose` (map of named checkers), and
the built-in history checkers (stats, set, counter, unique-ids, queues,
unhandled exceptions, log-file-pattern).

Valid? values follow the reference: True, False, or "unknown" (e.g. an empty
history).  `compose` is valid iff every sub-checker is, unknown if any is
unknown and none is false.
"""

from __future__ import annotations

import re
import time
import traceback
from collections import Counter as _Counter
from typing import Any, Callable, Dict, Iterable, Optional

from jepsen_tpu import telemetry
from jepsen_tpu.history.ops import FAIL, INFO, INVOKE, OK, History, Op
from jepsen_tpu.resilience import DEADLINE_ERROR, Deadline, DeadlineExceeded


class Checker:
    """Base checker protocol.  Subclasses implement `check`."""

    def check(self, test: dict, history: History, opts: Optional[dict] = None
              ) -> Dict[str, Any]:
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__


class FnChecker(Checker):
    def __init__(self, fn: Callable, nm: str = "fn"):
        self.fn = fn
        self._name = nm

    def check(self, test, history, opts=None):
        return self.fn(test, history, opts or {})

    def name(self):
        return self._name


def checker(fn: Callable, name: str = "fn") -> Checker:
    return FnChecker(fn, name)


def check_safe(chk: Checker, test: dict, history: History,
               opts: Optional[dict] = None) -> Dict[str, Any]:
    """Run a checker, converting exceptions into an invalid result
    (reference: `jepsen.checker/check-safe`).  The failing checker's
    `name()` rides along in the error result so composed-checker
    failures stay attributable in stored results.  Telemetric runs get
    one ``check:<name>`` span per (composed) checker, carrying the
    history length, verdict, and throughput.

    Deadlines: ``opts["time-limit"]`` (seconds) or the test map's
    ``"checker-time-limit"`` bound the check — a cooperative
    :class:`Deadline` is placed in ``opts["deadline"]`` for checkers
    that poll it (the elle and knossos pipelines do), and any
    :class:`DeadlineExceeded` escaping a checker becomes
    ``{"valid?": "unknown", "error": "deadline-exceeded"}`` rather
    than a crash dump.  Composed checkers share ONE deadline: the
    outermost `check_safe` creates it, the nested calls find it
    already present in opts."""
    try:
        name = chk.name()
    except Exception:  # noqa: BLE001 — a broken name() must not mask check()
        name = type(chk).__name__
    dl = Deadline.resolve(opts, test)
    if dl is not None:
        opts = dict(opts or {}, deadline=dl)

    def deadline_res() -> Dict[str, Any]:
        telemetry.registry().counter("checker-deadline-exceeded",
                                     checker=name).inc()
        return {"valid?": "unknown", "checker": name,
                "error": DEADLINE_ERROR}

    tel = telemetry.active()
    if not tel.enabled:
        try:
            return chk.check(test, history, opts)
        except DeadlineExceeded:
            return deadline_res()
        except Exception:
            return {"valid?": "unknown", "checker": name,
                    "error": traceback.format_exc()}
    with tel.span(f"check:{name}", checker=name) as sp:
        try:
            n = len(history)
        except TypeError:
            n = None
        t0 = time.perf_counter()
        try:
            res = chk.check(test, history, opts)
        except DeadlineExceeded:
            sp.set_attr(ops=n, valid="unknown", error=DEADLINE_ERROR)
            return deadline_res()
        except Exception:
            sp.set_attr(ops=n, valid="unknown", crashed=True)
            return {"valid?": "unknown", "checker": name,
                    "error": traceback.format_exc()}
        dt = time.perf_counter() - t0
        sp.set_attr(ops=n, valid=res.get("valid?")
                    if isinstance(res, dict) else None)
        if n and dt > 0:
            telemetry.registry().gauge(
                "checker-ops-per-s", checker=name).set(round(n / dt, 1))
        return res


def _merge_valid(vs: Iterable[Any]) -> Any:
    vs = list(vs)
    if any(v is False for v in vs):
        return False
    if any(v == "unknown" for v in vs):
        return "unknown"
    return True


class Compose(Checker):
    """A map of named checkers run over the same history.

    The history is wrapped in ONE :class:`~jepsen_tpu.history.ir.
    HistoryIR` (a History subclass sharing the same op list), so every
    IR-aware sub-checker reuses the same packed columns / inference
    instead of re-deriving per family (docs/IR.md)."""

    def __init__(self, checkers: Dict[str, Checker]):
        self.checkers = checkers

    def check(self, test, history, opts=None):
        if isinstance(history, History):
            from jepsen_tpu.history.ir import HistoryIR

            history = HistoryIR.of(history)
        results = {name: check_safe(c, test, history, opts)
                   for name, c in self.checkers.items()}
        return {"valid?": _merge_valid(r.get("valid?") for r in results.values()),
                **results}


def compose(checkers: Dict[str, Checker]) -> Checker:
    return Compose(checkers)


def output_path(test: dict, opts: Optional[dict], filename: str) -> str:
    """Resolve (and create) the artifact path for a checker's output file
    in the store dir, honoring opts["subdirectory"] (reference checkers'
    :subdirectory opt).  Shared by perf/timeline/clock."""
    import os

    from .. import store

    d = store.test_dir(test)
    sub = (opts or {}).get("subdirectory")
    if sub:
        d = os.path.join(d, str(sub))
        os.makedirs(d, exist_ok=True)
    return os.path.join(d, filename)


class NoopChecker(Checker):
    def check(self, test, history, opts=None):
        return {"valid?": True}


noop = NoopChecker


class Stats(Checker):
    """Op counts by :f and overall ok/fail/info rates (reference `stats`).

    Valid iff every :f has at least one ok (unknown on empty).  Large
    histories take the columnar fold path (numpy group counts over column
    chunks, chunk-parallel) instead of per-op Python — the vectorized
    built-in fold the reference gets from fold.clj fusion."""

    COLUMNAR_MIN = 65536

    def check(self, test, history, opts=None):
        try:
            n = len(history)
        except TypeError:
            n = 0
        if n >= self.COLUMNAR_MIN:
            by_f, total = self._columnar_counts(history)
        else:
            by_f, total = self._loop_counts(history)
        if not total:
            return {"valid?": "unknown", "count": 0}
        valid = all(c[OK] > 0 for c in by_f.values())
        return {
            "valid?": valid,
            "count": sum(total.values()),
            "ok-count": total[OK],
            "fail-count": total[FAIL],
            "info-count": total[INFO],
            "by-f": {f: {"count": sum(c.values()), "ok-count": c[OK],
                         "fail-count": c[FAIL], "info-count": c[INFO]}
                     for f, c in by_f.items()},
        }

    @staticmethod
    def _loop_counts(history):
        by_f: Dict[Any, _Counter] = {}
        total = _Counter()
        for op in history:
            if op.type == INVOKE or not op.is_client_op():
                continue
            total[op.type] += 1
            by_f.setdefault(op.f, _Counter())[op.type] += 1
        return by_f, total

    @staticmethod
    def _columnar_counts(history):
        import numpy as np

        from ..history.fold import Folder, fold_spec

        def col(cols):
            m = cols["client?"] & (cols["type"] != INVOKE)
            fs = cols["f"][m]
            ts = cols["type"][m]
            pairs: Dict[Any, _Counter] = {}
            # group by f via sort-unique, then bincount types inside
            for fv in set(fs.tolist()):
                sel = fs == fv
                vals, counts = np.unique(ts[sel], return_counts=True)
                c = _Counter({str(t): int(n) for t, n in
                              zip(vals, counts)})
                pairs[fv] = c
            return pairs

        def comb(a, b):
            for k, c in b.items():
                if k in a:
                    a[k].update(c)
                else:
                    a[k] = c
            return a

        f = fold_spec(name="stats", reducer_identity=dict,
                      reducer=lambda acc, op: acc,  # unused on column path
                      combiner_identity=dict, combiner=comb, columnar=col)
        with Folder(history, columnar=True) as folder:
            by_f = folder.fold(f)
        total = _Counter()
        for c in by_f.values():
            total.update(c)
        return by_f, total


class UnhandledExceptions(Checker):
    """Collects ops with :error / exception classes (reference
    `unhandled-exceptions`).  Always valid; informational."""

    def check(self, test, history, opts=None):
        by_err: Dict[str, int] = {}
        for op in history:
            if op.type in (INFO, FAIL) and op.error is not None:
                key = str(op.error)
                by_err[key] = by_err.get(key, 0) + 1
        return {"valid?": True, "exceptions": by_err}


class UniqueIds(Checker):
    """Checks that all ok op values are distinct (reference `unique-ids`)."""

    def check(self, test, history, opts=None):
        seen: Dict[Any, int] = {}
        dups: Dict[Any, int] = {}
        attempted = 0
        for op in history:
            if op.type == OK and op.is_client_op():
                attempted += 1
                v = op.value
                try:
                    hash(v)
                except TypeError:
                    v = repr(v)
                seen[v] = seen.get(v, 0) + 1
                if seen[v] > 1:
                    dups[v] = seen[v]
        if attempted == 0:
            return {"valid?": "unknown", "attempted-count": 0}
        return {"valid?": not dups,
                "attempted-count": attempted,
                "acknowledged-count": len(seen),
                "duplicated-count": len(dups),
                "duplicated": dict(list(dups.items())[:32])}


class SetChecker(Checker):
    """Add-then-read set (reference `set`): elements added via :add ops, one
    final :read op; lost = acknowledged adds missing from the read."""

    def check(self, test, history, opts=None):
        attempts, adds = set(), set()
        final_read = None
        for op in history:
            if not op.is_client_op():
                continue
            if op.f == "add":
                if op.type == INVOKE:
                    attempts.add(op.value)
                elif op.type == OK:
                    adds.add(op.value)
            elif op.f == "read" and op.type == OK:
                final_read = set(op.value or [])
        if final_read is None:
            return {"valid?": "unknown", "error": "no read found"}
        lost = adds - final_read
        unexpected = final_read - attempts
        return {
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(final_read & adds),
            "lost-count": len(lost),
            "lost": sorted(lost)[:32],
            "unexpected-count": len(unexpected),
            "unexpected": sorted(unexpected)[:32],
            "recovered-count": len(final_read - adds & attempts),
        }


class SetFullChecker(Checker):
    """Reference `set-full`: every add should eventually be readable; computes
    stale-read windows.  For each acknowledged add, finds reads invoked after
    the add completed that omit the element (stale reads), and whether the
    element was ever lost (absent from all subsequent reads after appearing).
    """

    def check(self, test, history, opts=None):
        # collect reads (invoke time, completion value) and adds
        adds = {}  # value -> completion index of ok add
        add_invokes = {}
        reads = []  # (invoke_idx, ok_idx, set(value))
        for op in history:
            if not op.is_client_op():
                continue
            if op.f == "add":
                if op.type == INVOKE:
                    add_invokes[op.value] = op.index
                elif op.type == OK:
                    adds[op.value] = op.index
            elif op.f == "read" and op.type == OK:
                inv = history.invocation(op)
                reads.append((inv.index if inv else op.index, op.index,
                              set(op.value or [])))
        if not reads:
            return {"valid?": "unknown", "error": "no reads"}
        reads.sort()
        lost = []
        stale = []
        for v, ok_idx in adds.items():
            later = [r for r in reads if r[0] > ok_idx]
            if not later:
                continue
            missing = [r for r in later if v not in r[2]]
            if missing and all(v not in r[2] for r in later):
                lost.append(v)
            elif missing:
                stale.append(v)
        return {"valid?": not lost,
                "lost": sorted(lost)[:32], "lost-count": len(lost),
                "stale-count": len(stale), "stale": sorted(stale)[:32],
                "read-count": len(reads), "add-count": len(adds)}


class CounterChecker(Checker):
    """Reference `counter`: :add ops with deltas, :read ops; each read must
    lie within [sum of definite adds, sum of possible adds] at that point."""

    def check(self, test, history, opts=None):
        lower = 0          # definite adds completed
        pending: Dict[int, int] = {}  # invoke index -> delta in flight
        errs = []
        reads = 0
        for op in history:
            if not op.is_client_op():
                continue
            if op.f == "add":
                if op.type == INVOKE:
                    pending[op.index] = op.value
                elif op.type == OK:
                    j = history.pair_index(op.index)
                    pending.pop(j, None)
                    lower += op.value
                elif op.type == FAIL:
                    pending.pop(history.pair_index(op.index), None)
                # info: stays possibly-applied forever
            elif op.f == "read" and op.type == OK:
                reads += 1
                hi = lower + sum(d for d in pending.values() if d > 0)
                lo = lower + sum(d for d in pending.values() if d < 0)
                if not (lo <= op.value <= hi):
                    errs.append({"op": op.index, "value": op.value,
                                 "expected": [lo, hi]})
        if reads == 0:
            return {"valid?": "unknown", "error": "no reads"}
        return {"valid?": not errs, "reads": reads,
                "errors": errs[:32], "error-count": len(errs)}


class TotalQueueChecker(Checker):
    """Reference `total-queue`: every successful enqueue should be dequeued
    exactly once; dequeues must have been enqueued (possibly by an :info)."""

    def check(self, test, history, opts=None):
        enq_attempt, enq_ok, enq_maybe, deq = (
            _Counter(), _Counter(), _Counter(), _Counter())
        for op in history:
            if not op.is_client_op():
                continue
            if op.f == "enqueue":
                if op.type == INVOKE:
                    enq_attempt[op.value] += 1
                elif op.type == OK:
                    enq_ok[op.value] += 1
                elif op.type == INFO:
                    enq_maybe[op.value] += 1  # possibly enqueued, not required
            elif op.f == "dequeue" and op.type == OK:
                deq[op.value] += 1
        # lost: definitely enqueued more times than ever dequeued
        lost = {v: c - deq[v] for v, c in enq_ok.items() if deq[v] < c}
        # unexpected: dequeued more times than it could possibly be enqueued
        unexpected = {v: c - (enq_ok[v] + enq_maybe[v]) for v, c in deq.items()
                      if c > enq_ok[v] + enq_maybe[v]}
        if not enq_attempt and not deq:
            return {"valid?": "unknown"}
        return {"valid?": not lost and not unexpected,
                "lost": dict(list(lost.items())[:32]), "lost-count": len(lost),
                "unexpected": dict(list(unexpected.items())[:32]),
                "unexpected-count": len(unexpected),
                "enqueue-count": sum(enq_attempt.values()),
                "dequeue-count": sum(deq.values())}


class QueueChecker(Checker):
    """Reference `queue`: dequeues must be consistent with *some*
    linearization of a FIFO queue — delegated to the Knossos-equivalent
    search over the fifo-queue model."""

    def check(self, test, history, opts=None):
        from jepsen_tpu.checkers.knossos import analysis
        from jepsen_tpu.models import unordered_queue

        # Concurrent dequeues make strict FIFO order unobservable; the
        # reference's queue checker likewise accepts any order but requires
        # dequeues to return enqueued-and-undelivered items.
        return analysis(history, unordered_queue(),
                        deadline=(opts or {}).get("deadline"))


class LogFilePattern(Checker):
    """Reference `log-file-pattern`: greps downloaded node logs for a
    pattern; invalid if found."""

    def __init__(self, pattern: str, filename: str):
        self.pattern = pattern
        self.filename = filename

    def check(self, test, history, opts=None):
        import glob
        import os
        dirpath = (test or {}).get("store-dir")
        matches = []
        if dirpath:
            for path in glob.glob(os.path.join(dirpath, "*", self.filename)):
                node = os.path.basename(os.path.dirname(path))
                try:
                    with open(path, "r", errors="replace") as f:
                        for line in f:
                            if re.search(self.pattern, line):
                                matches.append({"node": node,
                                                "line": line.strip()[:200]})
                except OSError:
                    pass
        return {"valid?": not matches, "count": len(matches),
                "matches": matches[:32]}


class Linearizable(Checker):
    """Linearizability via the Knossos-equivalent competition search
    (reference `checker/linearizable` -> knossos, SURVEY.md §2.1/§2.4)."""

    def __init__(self, model=None, algorithm: str = "auto"):
        self.model = model
        self.algorithm = algorithm

    def check(self, test, history, opts=None):
        from jepsen_tpu.checkers.knossos import analysis
        from jepsen_tpu.models import cas_register

        model = self.model or (test or {}).get("model") or cas_register()
        return analysis(history, model, algorithm=self.algorithm,
                        deadline=(opts or {}).get("deadline"))


class ConcurrencyLimit(Checker):
    """Reference `concurrency-limit`: no more than n concurrent invocations
    (sanity check on the generator/interpreter)."""

    def __init__(self, limit: int):
        self.limit = limit

    def check(self, test, history, opts=None):
        open_ops = 0
        worst = 0
        for op in history:
            if not op.is_client_op():
                continue
            if op.type == INVOKE:
                open_ops += 1
                worst = max(worst, open_ops)
            else:
                open_ops = max(0, open_ops - 1)
        return {"valid?": worst <= self.limit, "max-concurrency": worst}
