"""Text rendering of test results.

Equivalent of the reference's `jepsen/src/jepsen/report.clj` (SURVEY.md
§2.1): a compact human-readable summary of a completed test's results
map, for terminals and logs.
"""

from __future__ import annotations

from typing import Any, List


def _fmt(v: Any, indent: int = 0, depth: int = 0) -> List[str]:
    pad = "  " * indent
    if isinstance(v, dict):
        lines = []
        for k in sorted(v, key=str):
            val = v[k]
            if isinstance(val, (dict, list)) and val and depth < 4:
                lines.append(f"{pad}{k}:")
                lines.extend(_fmt(val, indent + 1, depth + 1))
            else:
                sval = repr(val)
                if len(sval) > 120:
                    sval = sval[:117] + "..."
                lines.append(f"{pad}{k}: {sval}")
        return lines
    if isinstance(v, list):
        if len(v) > 8:
            shown = v[:8]
            rest = f"{pad}... ({len(v) - 8} more)"
            return [x for item in shown for x in _fmt(item, indent, depth + 1)] + [rest]
        return [x for item in v for x in _fmt(item, indent, depth + 1)]
    return [f"{pad}{v!r}"]


def render(test: dict) -> str:
    """Render a completed test's verdict + results (reference's textual
    report)."""
    results = test.get("results", {}) or {}
    valid = results.get("valid?")
    mark = {True: "✓", False: "✗"}.get(valid, "?")
    header = (f"{mark} {test.get('name', 'test')} — valid? = {valid}"
              f" ({len(test.get('history') or [])} ops)")
    body = _fmt({k: v for k, v in results.items() if k != "valid?"})
    return "\n".join([header] + body)


def print_report(test: dict) -> None:
    print(render(test))
