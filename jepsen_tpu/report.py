"""Text rendering of test results.

Equivalent of the reference's `jepsen/src/jepsen/report.clj` (SURVEY.md
§2.1): a compact human-readable summary of a completed test's results
map, for terminals and logs.
"""

from __future__ import annotations

from typing import Any, List


def _fmt(v: Any, indent: int = 0, depth: int = 0) -> List[str]:
    pad = "  " * indent
    if isinstance(v, dict):
        lines = []
        for k in sorted(v, key=str):
            val = v[k]
            if isinstance(val, (dict, list)) and val and depth < 4:
                lines.append(f"{pad}{k}:")
                lines.extend(_fmt(val, indent + 1, depth + 1))
            else:
                sval = repr(val)
                if len(sval) > 120:
                    sval = sval[:117] + "..."
                lines.append(f"{pad}{k}: {sval}")
        return lines
    if isinstance(v, list):
        if len(v) > 8:
            shown = v[:8]
            rest = f"{pad}... ({len(v) - 8} more)"
            return [x for item in shown for x in _fmt(item, indent, depth + 1)] + [rest]
        return [x for item in v for x in _fmt(item, indent, depth + 1)]
    return [f"{pad}{v!r}"]


def render(test: dict) -> str:
    """Render a completed test's verdict + results (reference's textual
    report)."""
    results = test.get("results", {}) or {}
    valid = results.get("valid?")
    mark = {True: "✓", False: "✗"}.get(valid, "?")
    header = (f"{mark} {test.get('name', 'test')} — valid? = {valid}"
              f" ({len(test.get('history') or [])} ops)")
    body = _fmt({k: v for k, v in results.items() if k != "valid?"})
    return "\n".join([header] + body)


def print_report(test: dict) -> None:
    print(render(test))


# -- campaign rollups --------------------------------------------------------

_CELL = {True: "ok", False: "FAIL", "unknown": "?", None: "-"}


def _campaign_cell(row: dict) -> str:
    """One grid cell: verdict mark + attribution flags (t = deadline
    budget expired, h = degraded to the host oracle)."""
    mark = _CELL.get(row.get("valid?"), "?")
    flags = ("t" if row.get("deadline") else "") + \
            ("h" if row.get("degraded") else "")
    return mark + ("·" + flags if flags else "")


def render_campaign(summary: dict) -> str:
    """Suite-level rollup of a campaign summary (see
    `campaign.core.summarize`): verdict counts, the workload × fault ×
    seed grid, span-duration aggregates, and regressions."""
    c = summary.get("counts", {})
    lines: List[str] = [
        f"campaign {summary.get('campaign')} — "
        f"{summary.get('total', 0)} runs: "
        f"{c.get('true', 0)} ok, {c.get('false', 0)} invalid, "
        f"{c.get('unknown', 0)} unknown "
        f"({c.get('degraded', 0)} degraded, "
        f"{c.get('deadline', 0)} deadline-expired, "
        f"{summary.get('pending', 0)} pending)",
        f"index: {summary.get('index')}",
    ]
    if summary.get("executed") or summary.get("skipped"):
        lines.append(f"this invocation: {summary.get('executed', 0)} "
                     f"executed, {summary.get('skipped', 0)} resumed "
                     f"(skipped), {summary.get('wall_s', 0)}s")
    seeds = summary.get("seeds") or []
    rows = summary.get("rows") or []
    if rows:
        by_rf: dict = {}
        for r in rows:
            by_rf.setdefault((r["workload"], r["fault"]), {})[r["seed"]] = r
        w0 = max([len(w) for w, _ in by_rf] + [8])
        w1 = max([len(f) for _, f in by_rf] + [5])
        head = (f"  {'workload':<{w0}} {'fault':<{w1}} "
                + " ".join(f"s{s:<5}" for s in seeds))
        lines += ["", head, "  " + "-" * (len(head) - 2)]
        for (wl, fl), cells in sorted(by_rf.items()):
            marks = " ".join(
                f"{_campaign_cell(cells[s]) if s in cells else '-':<6}"
                for s in seeds)
            lines.append(f"  {wl:<{w0}} {fl:<{w1}} {marks}")
        lines.append("  (ok/FAIL/?  ·t = checker deadline expired, "
                     "·h = degraded to host oracle)")
    stats = summary.get("span-stats") or {}
    if stats:
        lines += ["", "  checker span durations (s, across all indexed "
                      "runs):"]
        lines.append(f"  {'span':<32} {'n':>4} {'p50':>10} {'p95':>10} "
                     f"{'max':>10}")
        for name, st in stats.items():
            lines.append(f"  {name:<32} {st['count']:>4} {st['p50']:>10.4f}"
                         f" {st['p95']:>10.4f} {st['max']:>10.4f}")
    regs = summary.get("regressions") or []
    if regs:
        lines += ["", "  REGRESSIONS (valid? moved away from True):"]
        for r in regs:
            lines.append(f"  {r['key']}: {r['from']} -> {r['to']} "
                         f"({r.get('when') or r.get('gen') or '?'})")
    else:
        lines += ["", "  no regressions"]
    return "\n".join(lines)


def print_campaign(summary: dict) -> None:
    print(render_campaign(summary))
