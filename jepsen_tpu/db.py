"""DB protocol: how to set up and tear down the system under test.

Equivalent of the reference's `jepsen/db.clj` (SURVEY.md §2.1): the core
`DB` lifecycle (`setup`/`teardown`) plus optional facets — `LogFiles`,
`Primary` (`primaries`/`setup_primary`), `Process` (`start`/`kill`) and
`Pause` (`pause`/`resume`).  The reference models facets as separate
protocols satisfied ad hoc; here they are mixin base classes and
capability checks via `supports()`.

All methods run with a control session bound for `node` (they are invoked
from `control.on_nodes`), so implementations use `control.exec_` freely.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from jepsen_tpu import control
from jepsen_tpu.control import util as cu


class DB:
    """Base DB. Subclasses override lifecycle methods as needed."""

    def setup(self, test: dict, node: str) -> None:
        """Install and start the db on `node`."""

    def teardown(self, test: dict, node: str) -> None:
        """Stop the db and wipe its state on `node`."""


class LogFiles:
    """Facet: which files to download from nodes after a run
    (reference: `db/LogFiles`)."""

    def log_files(self, test: dict, node: str) -> Sequence[str]:
        return []


class Primary:
    """Facet: primary/leader discovery and initial placement
    (reference: `db/Primary`)."""

    def primaries(self, test: dict) -> List[str]:
        """Nodes currently believed to be primaries."""
        return []

    def setup_primary(self, test: dict, node: str) -> None:
        """One-time setup performed on the first node before others."""


class Process:
    """Facet: start/kill the db process (reference: `db/Process`/`Kill`)."""

    def start(self, test: dict, node: str) -> None:
        raise NotImplementedError

    def kill(self, test: dict, node: str) -> None:
        raise NotImplementedError


class Pause:
    """Facet: pause/resume (SIGSTOP/SIGCONT) the db process
    (reference: `db/Pause`)."""

    def pause(self, test: dict, node: str) -> None:
        raise NotImplementedError

    def resume(self, test: dict, node: str) -> None:
        raise NotImplementedError


def supports(db: Any, facet: type) -> bool:
    return isinstance(db, facet)


class Noop(DB):
    """A db that does nothing (reference: `db/noop`) — for tests whose
    clients talk to an external or in-process system."""


noop = Noop()


class ProcessDB(DB, Process, Pause, LogFiles):
    """A db managed as a single daemon process per node: start with a
    pidfile, kill/pause via signals.  Convenience base covering the common
    shape of real Jepsen db implementations (reference idiom:
    `control/util start-daemon!` + `db/Process` facet).
    """

    def __init__(self, bin_: str, args: Sequence[Any] = (), *,
                 logfile: str = "db.log", pidfile: str = "db.pid",
                 dir: Optional[str] = None, env: Optional[dict] = None):
        self.bin = bin_
        self.args = list(args)
        self.logfile = logfile
        self.pidfile = pidfile
        self.dir = dir
        self.env = env

    def setup(self, test, node):
        self.start(test, node)

    def teardown(self, test, node):
        self.kill(test, node)
        control.exec_result("rm", "-f", self.logfile, self.pidfile)

    def start(self, test, node):
        if cu.daemon_running(self.pidfile):
            return
        cu.start_daemon(self.bin, *self.args, logfile=self.logfile,
                        pidfile=self.pidfile, chdir=self.dir, env=self.env)

    def kill(self, test, node):
        cu.stop_daemon(self.pidfile, signal="KILL", wait_s=1.0)
        cu.grepkill(self.bin)

    def pause(self, test, node):
        control.exec_("bash", "-c",
                      f"kill -STOP $(cat {control.escape(self.pidfile)})")

    def resume(self, test, node):
        control.exec_("bash", "-c",
                      f"kill -CONT $(cat {control.escape(self.pidfile)})")

    def log_files(self, test, node):
        return [self.logfile]


class TcpdumpDB(DB, LogFiles):
    """Wraps another db, running tcpdump on each node during the test
    (reference: `db/tcpdump`)."""

    def __init__(self, db: DB, *, ports: Sequence[int] = (),
                 pcap: str = "trace.pcap", filter_: str = ""):
        self.db = db
        self.ports = list(ports)
        self.pcap = pcap
        self.filter = filter_ or " or ".join(f"port {p}" for p in self.ports)

    def setup(self, test, node):
        cu.start_daemon("tcpdump", "-w", self.pcap, *(
            ["-i", "any"] + ([self.filter] if self.filter else [])),
            logfile="tcpdump.log", pidfile="tcpdump.pid")
        self.db.setup(test, node)

    def teardown(self, test, node):
        self.db.teardown(test, node)
        cu.stop_daemon("tcpdump.pid", wait_s=1.0)

    def log_files(self, test, node):
        inner = (self.db.log_files(test, node)
                 if supports(self.db, LogFiles) else [])
        return [*inner, self.pcap]


def cycle_db(db: DB, test: dict, node: str) -> None:
    """teardown! then setup! on one node (reference: `db/cycle!`)."""
    db.teardown(test, node)
    db.setup(test, node)
