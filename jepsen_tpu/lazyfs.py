"""lazyfs integration: lose un-fsynced writes.

Equivalent of the reference's `jepsen/src/jepsen/lazyfs.clj` (SURVEY.md
§2.1, §2.5 #6): installs/builds the external lazyfs FUSE filesystem on db
nodes, mounts the db's data dir through it, and injects "lose un-fsynced
writes" faults through lazyfs's command FIFO.  lazyfs itself is an
external C++ project (out of rewrite scope per §2.5); this is the
integration layer that drives it over the control plane.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from . import control
from . import db as db_proto
from .control.core import RemoteError

logger = logging.getLogger("jepsen.lazyfs")

REPO_URL = "https://github.com/dsrhaslab/lazyfs.git"
DIR = "/opt/jepsen/lazyfs"
BIN = DIR + "/lazyfs/build/lazyfs"


@dataclasses.dataclass
class LazyFS:
    """One lazyfs mount on a node: `dir` is what the db sees; writes pass
    through to `data_dir` and live in page cache until fsync."""

    dir: str
    data_dir: Optional[str] = None
    fifo: Optional[str] = None
    config: Optional[str] = None

    def __post_init__(self):
        base = self.dir.rstrip("/")
        if self.data_dir is None:
            self.data_dir = base + ".data"
        if self.fifo is None:
            self.fifo = base + ".fifo"
        if self.config is None:
            self.config = base + ".lazyfs.toml"


def install() -> None:
    """Clone and build lazyfs on the current node (reference
    `lazyfs/install!`).  Needs git, cmake, g++, libfuse3-dev."""
    control.exec_("mkdir", "-p", DIR)
    if not _exists(BIN):
        control.exec_("sh", "-c",
                      f"test -d {DIR}/.git || "
                      f"git clone {REPO_URL} {DIR}")
        for sub in ("libs/libpcache", "lazyfs"):
            control.exec_("sh", "-c",
                          f"cd {DIR}/{sub} && ./build.sh")


def _exists(path: str) -> bool:
    try:
        control.exec_("test", "-e", path)
        return True
    except RemoteError:
        return False


def config_toml(fs: LazyFS) -> str:
    """The lazyfs config enabling the faults FIFO."""
    return (f'[faults]\nfifo_path="{fs.fifo}"\n'
            f'[cache]\napply_lru_eviction=false\n'
            f'[cache.simple]\ncustom_size="0.5GB"\nblocks_per_page=1\n')


def mount(fs: LazyFS) -> None:
    """Mount fs.dir through lazyfs, backed by fs.data_dir (reference
    `lazyfs/mount!`)."""
    control.exec_("mkdir", "-p", fs.dir, fs.data_dir)
    control.exec_("sh", "-c",
                  f"echo {control.core.escape(config_toml(fs))} "
                  f"> {fs.config}")
    control.exec_(BIN, fs.dir,
                  "--config-path", fs.config,
                  "-o", "allow_other",
                  "-o", "modules=subdir",
                  "-o", f"subdir={fs.data_dir}")


def umount(fs: LazyFS) -> None:
    try:
        control.exec_("fusermount", "-u", fs.dir)
    except RemoteError as e:
        logger.warning("lazyfs umount failed: %s", e)


def _fifo_cmd(fs: LazyFS, cmd: str) -> None:
    control.exec_("sh", "-c",
                  f"echo {control.core.escape(cmd)} > {fs.fifo}")


def lose_unfsynced_writes(fs: LazyFS) -> None:
    """Drop every write that was never fsynced (the signature fault)."""
    _fifo_cmd(fs, "lazyfs::clear-cache")


def checkpoint(fs: LazyFS) -> None:
    """Persist current cache state (used between fault rounds)."""
    _fifo_cmd(fs, "lazyfs::cache-checkpoint")


class DB(db_proto.DB):
    """Wraps a db so its data dir lives on lazyfs (reference `lazyfs/db`):
    install+mount before inner setup, unmount after inner teardown."""

    def __init__(self, db, fs: LazyFS):
        self.db = db
        self.fs = fs

    def setup(self, test, node):
        install()
        mount(self.fs)
        self.db.setup(test, node)

    def teardown(self, test, node):
        self.db.teardown(test, node)
        umount(self.fs)

    def __getattr__(self, name):
        # forward facet methods (log_files, kill, ...) to the inner db
        return getattr(self.db, name)
