"""Prestaged bench inputs: pay generation cost once, outside the tunnel
window.

VERDICT r04 item 1a: at 10M txns the synthetic generator alone costs
~153 s — more than the only tunnel window round 4 saw.  The campaign
pre-generates every ladder input to disk while the tunnel is down
(`scripts/prestage_inputs.py`); in-window, bench.py and the ladder
scripts load the .npz in seconds instead.

Filenames are keyed by every generator parameter, so a generator change
that alters kwargs can never silently reuse stale inputs.  (A change to
generator *internals* must bump `synth.PACKED_GEN_VERSION`.)
"""

from __future__ import annotations

import os
import time

from jepsen_tpu.history.soa import PackedTxns, load_packed, save_packed


def prestage_dir() -> str:
    d = os.environ.get("JT_PRESTAGE_DIR")
    if d:
        return d
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "scripts", "prestaged")


def _path(kind: str, **kw) -> str:
    from jepsen_tpu.workloads.synth import PACKED_GEN_VERSION

    name = f"{kind}_v{PACKED_GEN_VERSION}_" + "_".join(
        f"{k}{kw[k]}" for k in sorted(kw)) + ".npz"
    return os.path.join(prestage_dir(), name)


def _get(kind: str, gen, save: bool, verbose: bool, **kw) -> PackedTxns:
    path = _path(kind, **kw)
    if os.path.exists(path):
        t0 = time.perf_counter()
        p = load_packed(path)
        if verbose:
            print(f"prestaged load {os.path.basename(path)} "
                  f"{time.perf_counter() - t0:.1f}s", flush=True)
        return p
    p = gen(**kw)
    if save or os.environ.get("JT_PRESTAGE_SAVE"):
        os.makedirs(prestage_dir(), exist_ok=True)
        # pid-unique tmp: prestage_inputs.py and aot_warm.py may both
        # save the same input concurrently (np.savez appends .npz)
        tmp = path[:-len(".npz")] + f".tmp{os.getpid()}.npz"
        save_packed(tmp, p)
        os.replace(tmp, path)
    return p


def la_history(n_txns: int, n_keys: int, concurrency: int = 10,
               mops_per_txn: int = 4, read_frac: float = 0.25,
               seed: int = 7, save: bool = False,
               verbose: bool = True) -> PackedTxns:
    """Bench list-append input: prestaged if on disk, else generated."""
    from jepsen_tpu.workloads import synth

    return _get("la", synth.packed_la_history, save, verbose,
                n_txns=n_txns, n_keys=n_keys, concurrency=concurrency,
                mops_per_txn=mops_per_txn, read_frac=read_frac, seed=seed)


def rw_history(n_txns: int, n_keys: int, concurrency: int = 10,
               mops_per_txn: int = 3, read_frac: float = 0.5,
               seed: int = 11, save: bool = False,
               verbose: bool = True) -> PackedTxns:
    """Bench rw-register input: prestaged if on disk, else generated."""
    from jepsen_tpu.workloads import synth

    return _get("rw", synth.packed_rw_history, save, verbose,
                n_txns=n_txns, n_keys=n_keys, concurrency=concurrency,
                mops_per_txn=mops_per_txn, read_frac=read_frac, seed=seed)
