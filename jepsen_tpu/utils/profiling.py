"""JAX profiler integration (SURVEY.md §5 "Tracing / profiling").

The reference's post-hoc story is perf plots from history folds
(`checker/perf.clj`); the TPU-native framework adds kernel-level
tracing: wrap any checking call in :func:`trace` to capture an XLA/TPU
profile viewable in TensorBoard or Perfetto (`xprof`), e.g.

    with profiling.trace("/tmp/jax-trace"):
        core_check(h, n_keys)

The bench honors ``BENCH_PROFILE_DIR`` and wraps its timed repeats, so
`BENCH_PROFILE_DIR=/tmp/tr python bench.py` yields the trace behind
PROFILE.md's numbers.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

logger = logging.getLogger("jepsen.profiling")


@contextlib.contextmanager
def trace(out_dir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace into `out_dir` (no-op when None or
    when the profiler is unavailable — tracing must never break a
    check).  Only profiler SETUP/TEARDOWN failures are swallowed; body
    exceptions propagate untouched (a single yield outside any except —
    re-yielding after a throw would mask the real error with
    contextlib's "generator didn't stop" RuntimeError)."""
    if not out_dir:
        yield
        return
    started = False
    try:
        import jax

        os.makedirs(out_dir, exist_ok=True)
        prof = jax.profiler.trace(out_dir)
        prof.__enter__()
        started = True
    except Exception:  # noqa: BLE001 — profiling is best-effort
        logger.warning("jax profiler unavailable; continuing untraced",
                       exc_info=True)
    try:
        yield
    finally:
        if started:
            try:
                prof.__exit__(None, None, None)
                logger.info("jax profiler trace written to %s", out_dir)
            except Exception:  # noqa: BLE001
                logger.warning("jax profiler teardown failed",
                               exc_info=True)


def annotate(name: str):
    """Named span inside a trace (TraceAnnotation), safe no-op without
    a profiler."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001
        return contextlib.nullcontext()
