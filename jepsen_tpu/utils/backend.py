"""Backend selection workarounds — single canonical copy.

The axon (TPU tunnel) PJRT plugin is registered at interpreter startup by
sitecustomize.  Backend *initialization* dials the TPU relay even under
``JAX_PLATFORMS=cpu``, so any process that wants the CPU backend must drop
the axon/tpu backend factories before the first jax backend init.  Used by
tests/conftest.py, bench.py, and __graft_entry__.py — keep the knowledge
here, in one place (it touches the private jax._src.xla_bridge API).
"""

from __future__ import annotations

import os


def force_cpu_backend(n_devices: int | None = None) -> None:
    """Make jax use the CPU backend, optionally with ``n_devices`` virtual
    host devices.  Must run before the first jax backend initialization;
    safe to call again after (no-op beyond config updates).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    try:
        import jax
        import jax._src.xla_bridge as _xb

        for name in ("axon", "tpu"):
            getattr(_xb, "_backend_factories", {}).pop(name, None)
        # a caller (or pytest plugin) may have imported jax before us,
        # binding jax_platforms to the outer env — override the config too
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
