"""Backend selection workarounds — single canonical copy.

The axon (TPU tunnel) PJRT plugin is registered at interpreter startup by
sitecustomize.  Backend *initialization* dials the TPU relay even under
``JAX_PLATFORMS=cpu``, so any process that wants the CPU backend must drop
the axon/tpu backend factories before the first jax backend init.  Used by
tests/conftest.py, bench.py, and __graft_entry__.py — keep the knowledge
here, in one place (it touches the private jax._src.xla_bridge API).
"""

from __future__ import annotations

import os


def force_cpu_backend(n_devices: int | None = None) -> None:
    """Make jax use the CPU backend, optionally with ``n_devices`` virtual
    host devices.  Must run before the first jax backend initialization;
    safe to call again after (no-op beyond config updates).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    try:
        import jax
        import jax._src.xla_bridge as _xb

        for name in ("axon", "tpu"):
            getattr(_xb, "_backend_factories", {}).pop(name, None)
        # a caller (or pytest plugin) may have imported jax before us,
        # binding jax_platforms to the outer env — override the config too
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def get_shard_map():
    """Version-portable ``shard_map``: newer jax exposes it as
    ``jax.shard_map``; this box's 0.4.x only has
    ``jax.experimental.shard_map.shard_map``.  Resolve whichever
    exists (preferring the public one) — the `parallel/` modules bind
    it once at import instead of touching `jax.shard_map` directly.

    The legacy experimental API defaults ``check_rep=True``, whose
    replication checker has no rule for ``while_loop`` (the cycle-sweep
    fixpoint) and rejects our kernels; the wrapper defaults it off,
    matching the public API's behavior."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    import functools

    from jax.experimental.shard_map import shard_map as legacy

    @functools.wraps(legacy)
    def sm(f, *args, **kw):
        kw.setdefault("check_rep", False)
        return legacy(f, *args, **kw)

    return sm


def pcast_varying(x, axis_name):
    """Version-portable ``jax.lax.pcast(x, axis, to="varying")``: jax
    versions without the varying-axis type system (no ``lax.pcast``)
    don't track replication in manual-mesh code either, so the cast is
    simply unnecessary there — return the operand unchanged."""
    import jax

    pcast = getattr(jax.lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axis_name, to="varying")


def enable_compile_cache(cache_dir: str | None = None) -> str:
    """Point jax at a persistent XLA compilation cache (honors the
    BENCH_CACHE_DIR env knob; defaults to <repo>/.jax_cache).  Driver
    reruns and same-shape recompiles then skip XLA compile entirely —
    round 2 measured 125.8 s of compile at 100k-txn shapes.  Returns the
    cache dir in use."""
    import jax

    if cache_dir is None:
        cache_dir = os.environ.get("BENCH_CACHE_DIR") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return cache_dir
