"""General helpers.

Equivalent of the reference's `jepsen/src/jepsen/util.clj` (SURVEY.md §2.1):
the monotonic relative test clock, `timeout`, `majority`, random
distributions for generators, retry-with-backoff, `fcatch`, and
`nemesis-intervals` (pairing nemesis start/stop ops into shaded windows for
perf plots).
"""

from __future__ import annotations

import math
import random
import threading
import time as _time
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Relative test clock (reference: `util/relative-time-nanos` — a monotonic
# clock whose origin is the start of the test, so op :time fields are small
# and comparable across processes).

_origin_lock = threading.Lock()
_origin_ns: Optional[int] = None


def init_time_origin() -> None:
    """Reset the relative clock origin to now (called at test start)."""
    global _origin_ns
    with _origin_lock:
        _origin_ns = _time.monotonic_ns()


def relative_time_nanos() -> int:
    """Nanoseconds since the test clock origin (auto-initializes)."""
    global _origin_ns
    if _origin_ns is None:
        init_time_origin()
    return _time.monotonic_ns() - _origin_ns


# ---------------------------------------------------------------------------
# Timeouts


class TimeoutError_(Exception):
    pass


def timeout(seconds: float, fn: Callable[[], Any], *,
            on_timeout: Any = TimeoutError_) -> Any:
    """Run `fn` with a wall-clock timeout (reference `util/timeout` macro).

    Python threads can't be safely killed, so like the JVM original (which
    interrupts), the worker may linger; we abandon it.  If `on_timeout` is an
    exception class it is raised; otherwise it is returned as the value.
    """
    done = threading.Event()
    result: list = [None]
    error: list = [None]

    def run():
        try:
            result[0] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            error[0] = e
        finally:
            done.set()

    # daemon thread: an abandoned (hung) worker must not block process exit
    t = threading.Thread(target=run, daemon=True)
    t.start()
    if not done.wait(timeout=seconds):
        if isinstance(on_timeout, type) and issubclass(on_timeout, BaseException):
            raise on_timeout(f"timed out after {seconds}s")
        return on_timeout
    if error[0] is not None:
        raise error[0]
    return result[0]


# ---------------------------------------------------------------------------
# Small numeric helpers


def majority(n: int) -> int:
    """Smallest majority of n nodes: majority(5) == 3 (reference
    `util/majority`)."""
    return n // 2 + 1


def minority(n: int) -> int:
    """Largest minority: minority(5) == 2."""
    return (n - 1) // 2


def fcatch(fn: Callable) -> Callable:
    """Wrap fn so thrown exceptions are returned instead (reference
    `util/fcatch`)."""

    def wrapper(*args, **kw):
        try:
            return fn(*args, **kw)
        except Exception as e:  # noqa: BLE001 — mirror of fcatch semantics
            return e

    return wrapper


# ---------------------------------------------------------------------------
# Random distributions (reference `util/rand-distribution`, used by
# generators and nemesis interval schedules).


def rand_distribution(spec: dict, rng: Optional[random.Random] = None) -> float:
    """Draw from a distribution spec.

    Specs (mirroring the reference's map flavor):
      {"distribution": "constant", "value": x}
      {"distribution": "uniform",  "min": a, "max": b}
      {"distribution": "exponential", "mean": m}
      {"distribution": "zipf", "n": n, "skew": s}  -> int in [0, n)
    """
    rng = rng or random
    kind = spec.get("distribution", "uniform")
    if kind == "constant":
        return spec["value"]
    if kind == "uniform":
        return rng.uniform(spec["min"], spec["max"])
    if kind == "exponential":
        return rng.expovariate(1.0 / spec["mean"])
    if kind == "zipf":
        n, s = spec["n"], spec.get("skew", 1.0001)
        # inverse-CDF draw over the finite zipf pmf
        weights = [1.0 / (i + 1) ** s for i in range(n)]
        total = sum(weights)
        u = rng.random() * total
        acc = 0.0
        for i, w in enumerate(weights):
            acc += w
            if u <= acc:
                return i
        return n - 1
    raise ValueError(f"unknown distribution {kind!r}")


# ---------------------------------------------------------------------------
# Retry with backoff (reference `util/with-retry` idiom + `control/retry.clj`
# policies).


def with_retry(fn: Callable[[], Any], *, retries: int = 5,
               backoff: float = 0.2, max_backoff: float = 5.0,
               retry_on: type = Exception,
               log: Optional[Callable[[str], None]] = None) -> Any:
    """Call fn, retrying on exception with exponential backoff."""
    delay = backoff
    for attempt in range(retries + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == retries:
                raise
            if log:
                log(f"retry {attempt + 1}/{retries} after {type(e).__name__}: {e}")
            _time.sleep(delay)
            delay = min(max_backoff, delay * 2)


# ---------------------------------------------------------------------------
# Nemesis intervals (reference `util/nemesis-intervals`: pair nemesis ops
# into [start, stop] windows — used by perf plots for activity shading).

# f names conventionally marking window starts/stops
_DEFAULT_START_FS = {"start", "start!", "start-partition", "kill", "pause",
                     "corrupt", "bump-clock", "strobe-clock"}
_DEFAULT_STOP_FS = {"stop", "stop!", "stop-partition", "restart", "resume",
                    "heal", "reset-clock"}


def nemesis_intervals(ops: Sequence, *, start_fs: Optional[set] = None,
                      stop_fs: Optional[set] = None
                      ) -> List[Tuple[Any, Any]]:
    """Pair nemesis ops into (start-op, stop-op-or-None) intervals.

    Each start op opens a window closed by the next stop op; unclosed
    windows get None (open until end of test)."""
    start_fs = _DEFAULT_START_FS if start_fs is None else start_fs
    stop_fs = _DEFAULT_STOP_FS if stop_fs is None else stop_fs
    intervals: List[Tuple[Any, Any]] = []
    open_starts: List[Any] = []
    for op in ops:
        f = getattr(op, "f", None)
        if f in start_fs:
            open_starts.append(op)
        elif f in stop_fs:
            for s in open_starts:
                intervals.append((s, op))
            open_starts = []
    for s in open_starts:
        intervals.append((s, None))
    return intervals


# ---------------------------------------------------------------------------
# Misc


def coll(x: Any) -> list:
    """Coerce scalar-or-sequence to a list (reference `util/coll`)."""
    if x is None:
        return []
    if isinstance(x, (list, tuple, set)):
        return list(x)
    return [x]


def seconds_to_nanos(s: float) -> int:
    return int(s * 1e9)


def nanos_to_seconds(ns: int) -> float:
    return ns / 1e9
