from jepsen_tpu.utils.core import (
    fcatch,
    majority,
    minority,
    nemesis_intervals,
    rand_distribution,
    relative_time_nanos,
    timeout,
    with_retry,
)

__all__ = [
    "fcatch",
    "majority",
    "minority",
    "nemesis_intervals",
    "rand_distribution",
    "relative_time_nanos",
    "timeout",
    "with_retry",
]
