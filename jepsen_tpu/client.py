"""Client protocol.

Equivalent of the reference's `jepsen/client.clj` (SURVEY.md §2.1): a
`Client` owns one connection to one db node on behalf of one logical
process.  Lifecycle: `open` (per-process connection) -> `setup` (once) ->
`invoke` (op -> completed op) -> `teardown` -> `close`.

`invoke` receives an invoke op dict and must return its completion: the
same op with type "ok" / "fail" / "info" (info = indeterminate — the op
may or may not have taken effect; the process is considered crashed and
its thread is given a fresh process id by the interpreter, exactly the
reference's semantics).
"""

from __future__ import annotations

import traceback
from typing import Any, Optional

from jepsen_tpu.utils.core import TimeoutError_, timeout


class Client:
    """Base client.  Subclasses override what they need."""

    def open(self, test: dict, node: str) -> "Client":
        """Return a client bound to `node` for a new process.  May return
        self for connectionless clients."""
        return self

    def setup(self, test: dict) -> None:
        """One-time data setup (e.g. create tables)."""

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply op; return the completion op (type ok/fail/info)."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        """One-time cleanup."""

    def close(self, test: dict) -> None:
        """Release this connection."""


def closable(client: Any) -> bool:
    return hasattr(client, "close")


class Validate(Client):
    """Wraps a client, checking invoke returns a legal completion
    (reference `client/validate`)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        return Validate(self.client.open(test, node))

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        res = self.client.invoke(test, op)
        if not isinstance(res, dict):
            raise ValueError(f"client returned non-op {res!r} for {op!r}")
        if res.get("type") not in ("ok", "fail", "info"):
            raise ValueError(f"client completion has bad type: {res!r}")
        if res.get("process") != op.get("process"):
            raise ValueError(
                f"client changed op process {op.get('process')!r} -> "
                f"{res.get('process')!r}")
        return res

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)


class WithTimeout(Client):
    """Wraps a client so invokes time out with an :info completion
    (reference `client/timeout` idiom)."""

    def __init__(self, client: Client, seconds: float):
        self.client = client
        self.seconds = seconds

    def open(self, test, node):
        return WithTimeout(self.client.open(test, node), self.seconds)

    def setup(self, test):
        self.client.setup(test)

    def invoke(self, test, op):
        try:
            return timeout(self.seconds, lambda: self.client.invoke(test, op))
        except TimeoutError_:
            return dict(op, type="info", error="timeout")

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)


def invoke_with_errors(client: Client, test: dict, op: dict) -> dict:
    """Run client.invoke, converting exceptions into :info completions (the
    interpreter's safety net; reference interpreter behavior — a client
    exception means the op's effect is unknown)."""
    try:
        return client.invoke(test, op)
    except Exception as e:  # noqa: BLE001 — any client error = indeterminate
        return dict(op, type="info",
                    error=f"{type(e).__name__}: {e}",
                    ext={"traceback": traceback.format_exc()})
