"""jepsen_tpu — a TPU-native framework for black-box safety testing of
distributed systems.

Re-architecture of the Jepsen ecosystem (jepsen-io/jepsen and its satellite
libraries elle, knossos, io.jepsen/history — see SURVEY.md) designed for
TPUs from scratch:

- **History substrate** (`jepsen_tpu.history`): histories as structure-of-array
  device tensors (mirrors `jepsen.history`'s dense Op vectors + pair index).
- **Checkers** (`jepsen_tpu.checkers`): Elle-style transactional isolation
  checking (dependency-edge inference under vmap + cycle detection as a
  blocked-scan label-propagation kernel feeding the MXU) and Knossos-style
  linearizability checking (memoized model + batched frontier search).
- **Generator DSL + interpreter** (`jepsen_tpu.generator`): pure generators,
  threaded workers (mirrors `jepsen.generator` / `generator/interpreter.clj`).
- **Fault injection** (`jepsen_tpu.nemesis`): partitions, kill/pause, clock
  skew, file corruption (mirrors `jepsen.nemesis`, `jepsen.net`).
- **Control plane** (`jepsen_tpu.control`): pluggable Remote protocol
  (mirrors `jepsen.control`).
- **Store** (`jepsen_tpu.store`): two-phase persistent runs with chunked
  binary histories (mirrors `jepsen.store` / `store/format.clj`).

The checkers are the TPU-resident heart; everything else is host-side
orchestration, as in the reference (SURVEY.md §1: L2-L3 are pure).
"""

__version__ = "0.1.0"
