"""libfaketime integration: run a db process on a skewed/accelerated clock.

Equivalent of the reference's `jepsen/src/jepsen/faketime.clj` (SURVEY.md
§2.1, §2.5 #9): LD_PRELOAD wrappers around libfaketime (external C
library) so one node's process experiences a shifted or rate-scaled
clock without touching the system clock.
"""

from __future__ import annotations

import random
from typing import Optional, Sequence

from . import control
from .control.core import Lit, RemoteError

SO_PATHS = (
    "/usr/lib/x86_64-linux-gnu/faketime/libfaketime.so.1",
    "/usr/lib/faketime/libfaketime.so.1",
    "/usr/local/lib/faketime/libfaketime.so.1",
)


def install() -> None:
    """Install libfaketime on the current node (best effort)."""
    if libfaketime_path() is None:
        control.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                      "apt-get", "install", "-y", "libfaketime")


def libfaketime_path() -> Optional[str]:
    for p in SO_PATHS:
        try:
            control.exec_("test", "-e", p)
            return p
        except RemoteError:
            continue
    return None


def faketime_spec(offset_s: float = 0.0, rate: float = 1.0) -> str:
    """libfaketime FAKETIME spec: '+<offset>s x<rate>'."""
    sign = "+" if offset_s >= 0 else "-"
    return f"{sign}{abs(offset_s)}s x{rate:g}"


def wrap_cmd(cmd: Sequence, offset_s: float = 0.0, rate: float = 1.0,
             so_path: Optional[str] = None) -> list:
    """Prefix a command so it runs under libfaketime (reference
    `faketime/wrap!` mechanism): env LD_PRELOAD + FAKETIME."""
    so = so_path or libfaketime_path()
    if so is None:
        raise RuntimeError("libfaketime not installed on this node")
    return ["env", Lit(f"LD_PRELOAD={so}"),
            Lit(f'FAKETIME="{faketime_spec(offset_s, rate)}"'),
            Lit("FAKETIME_NO_CACHE=1"), *cmd]


def rand_factor(rng: Optional[random.Random] = None,
                max_skew: float = 5.0) -> float:
    """A random clock rate in [1/max_skew, max_skew], log-uniform
    (reference `faketime/rand-factor`)."""
    import math
    rng = rng or random.Random()
    return math.exp(rng.uniform(-math.log(max_skew), math.log(max_skew)))
