"""Command-line entry points.

Equivalent of the reference's `jepsen/src/jepsen/cli.clj` (SURVEY.md §2.1):
argparse option specs (``--nodes``, ``--concurrency 10n``, ``--time-limit``,
``--test-count``, ``--username/--password``, ``--leave-db-running``), the
`single_test_cmd` / `test_all_cmd` / `serve_cmd` scaffolding, and the merge
of parsed options into the test map.

A db suite calls::

    from jepsen_tpu import cli

    def my_test(opts):        # opts dict -> test map
        return {**opts, "name": "etcd", "db": Etcd(), ...}

    if __name__ == "__main__":
        cli.run(cli.single_test_cmd(my_test))
"""

from __future__ import annotations

import argparse
import logging
import os
import re
import sys
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import core, store

logger = logging.getLogger("jepsen.cli")


def parse_concurrency(spec: str, n_nodes: int) -> int:
    """"30" -> 30; "10n" -> 10 * n_nodes (reference `--concurrency`)."""
    m = re.fullmatch(r"(\d+)(n?)", str(spec).strip())
    if not m:
        raise ValueError(f"bad concurrency {spec!r} (want e.g. 30 or 3n)")
    n = int(m.group(1))
    return n * max(n_nodes, 1) if m.group(2) else n


def parse_nodes(values: Optional[Sequence[str]],
                nodes_file: Optional[str]) -> List[str]:
    nodes: List[str] = []
    for v in values or []:
        nodes.extend(x for x in v.split(",") if x)
    if nodes_file:
        with open(nodes_file) as f:
            nodes.extend(line.strip() for line in f if line.strip())
    return nodes


def base_parser(prog: str = "jepsen-tpu") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=prog)
    p.add_argument("--store-dir", default=store.BASE,
                   help="store directory (default ./store)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU jax backend (skip TPU dial; also "
                        "honored via JT_FORCE_CPU=1). On a machine whose "
                        "TPU tunnel is down, backend init HANGS rather "
                        "than raising — this flag is the way out.")
    return p


def add_test_opts(p: argparse.ArgumentParser) -> None:
    """The standard test flags (reference `test-opt-spec`)."""
    p.add_argument("-n", "--node", action="append", dest="nodes",
                   metavar="HOST", help="node to test; repeatable, or "
                   "comma-separated")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("-c", "--concurrency", default="1n",
                   help='number of workers, e.g. "30" or "10n" (per node)')
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="seconds to run the workload")
    p.add_argument("--ops", type=int, default=None,
                   help="cap on generated operations; with --time-limit, "
                        "whichever bound hits first ends the workload. "
                        "Without it workload size — and checker cost — "
                        "scales with host speed")
    p.add_argument("--checker-time-limit", type=float, default=None,
                   help="seconds of analysis budget per check; past it "
                        "checkers return valid? = unknown with "
                        "error = deadline-exceeded instead of running "
                        "unbounded (see docs/RESILIENCE.md)")
    p.add_argument("--test-count", type=int, default=1,
                   help="how many times to run the test")
    p.add_argument("--username", default="root", help="ssh user")
    p.add_argument("--password", help="ssh password")
    p.add_argument("--private-key-path", dest="private_key_path",
                   help="ssh identity file")
    p.add_argument("--leave-db-running", action="store_true",
                   help="skip db teardown for post-mortem inspection")
    p.add_argument("--logging-json", action="store_true",
                   help="JSON log lines")
    p.add_argument("--telemetry", action="store_true",
                   help="collect span tracing + metrics; writes "
                        "telemetry.json and Chrome trace.json into the "
                        "store dir (view with `trace <dir>` or Perfetto), "
                        "and streams events.jsonl live (follow with "
                        "`tail <dir> -f` or the web /live page)")
    p.add_argument("--profile-dir", dest="profile_dir", default=None,
                   help="capture a JAX profiler trace into this dir; "
                        "implies telemetry, and every telemetry span is "
                        "bridged to a TraceAnnotation so host spans and "
                        "XLA kernels share one Perfetto timeline")


def opts_to_test_map(opts: argparse.Namespace) -> Dict[str, Any]:
    """Merge parsed options into test-map keys (reference's opt merge).
    Every parsed flag passes through (so extra_opts reach test_fn);
    the standard ones are normalized on top."""
    nodes = parse_nodes(opts.nodes, opts.nodes_file)
    out: Dict[str, Any] = {k: v for k, v in vars(opts).items()
                           if k not in ("cmd", "nodes", "nodes_file")}
    out.update({
        "nodes": nodes,
        "concurrency": parse_concurrency(opts.concurrency, len(nodes)),
        "concurrency-spec": opts.concurrency,
        "time-limit": opts.time_limit,
        "checker-time-limit": getattr(opts, "checker_time_limit", None),
        "leave-db-running": opts.leave_db_running,
        "store-dir": opts.store_dir,
        "profile-dir": getattr(opts, "profile_dir", None),
    })
    return out


def _apply_time_limit(test: Dict[str, Any]) -> Dict[str, Any]:
    if test.get("generator") is None:
        return test
    from .generator import core as g
    tl = test.get("time-limit")
    if tl:
        test["generator"] = g.time_limit(float(tl), test["generator"])
    n = test.get("ops")
    if n:
        test["generator"] = g.limit(int(n), test["generator"])
    return test


def run_test_cmd(test_fn: Callable[[Dict[str, Any]], Dict[str, Any]],
                 opts: argparse.Namespace) -> int:
    """Run test_fn --test-count times; exit 0 iff all valid (reference
    `single-test-cmd`'s run action)."""
    failures = 0
    for i in range(opts.test_count):
        test = test_fn(opts_to_test_map(opts))
        test = _apply_time_limit(test)
        done = core.run(test)
        valid = done.get("results", {}).get("valid?")
        print(f"run {i + 1}/{opts.test_count}: "
              f"{done.get('name')} valid? = {valid} "
              f"({store.test_dir(done)})")
        if valid is not True:
            failures += 1
    if failures:
        print(f"{failures} failing run(s)", file=sys.stderr)
    return 1 if failures else 0


def serve_cmd(opts: argparse.Namespace) -> int:
    from . import web

    verifier = None
    if getattr(opts, "ingest", False):
        from .verifier import VerifierService

        cfg = {}
        if getattr(opts, "compact_bytes", None):
            cfg["compact-bytes"] = int(opts.compact_bytes)
        if getattr(opts, "gc_idle", None):
            cfg["gc-idle-s"] = float(opts.gc_idle)
        if getattr(opts, "archive_sealed", None):
            cfg["archive-sealed-s"] = float(opts.archive_sealed)
        verifier = VerifierService(opts.store_dir, default_config=cfg)
        # the production-service loop (ISSUE 13): batched multi-tenant
        # sweeps + GC/retention on a maintenance thread
        verifier.start_maintenance(
            float(getattr(opts, "maintain_interval", 5.0) or 5.0))
    try:
        web.serve(port=opts.port, base=opts.store_dir,
                  host=getattr(opts, "host", "127.0.0.1"),
                  verifier=verifier)
    finally:
        if verifier is not None:
            verifier.close()
    return 0


def trace_cmd(opts: argparse.Namespace) -> int:
    """Summarize a stored run's telemetry (span tree + metrics); with
    ``--top N``, append the slowest-spans-by-self-time table."""
    import json

    from .telemetry import export as tel_export
    d = opts.dir
    if not os.path.isdir(d):
        print(f"trace: no such directory {d!r}", file=sys.stderr)
        return 2
    try:
        with open(os.path.join(d, tel_export.TELEMETRY_FILE)) as f:
            doc = json.load(f)
        print(tel_export.summarize(d, doc=doc))
    except FileNotFoundError:
        print(f"trace: {d} has no telemetry.json (run the test with "
              "--telemetry or JEPSEN_TELEMETRY=1)", file=sys.stderr)
        return 2
    top = getattr(opts, "top", None)
    if top:
        print(f"\ntop {top} spans by self time:")
        print(tel_export.render_top_spans(tel_export.top_spans(doc, top)))
    return 0


def parse_since(spec: str, now: Optional[float] = None) -> float:
    """``--since`` argument → epoch seconds: a duration back from now
    (``90s``, ``5m``, ``2h``, ``1d``, bare seconds), a large bare
    number taken as an epoch timestamp, or a UTC ISO timestamp."""
    import time as _time

    s = str(spec).strip()
    now = _time.time() if now is None else now
    m = re.fullmatch(r"(\d+(?:\.\d+)?)([smhd]?)", s)
    if m:
        mult = {"": 1.0, "s": 1.0, "m": 60.0, "h": 3600.0,
                "d": 86400.0}[m.group(2)]
        v = float(m.group(1)) * mult
        if m.group(2) == "" and v > 1e9:
            return v  # an epoch timestamp, not a duration
        return now - v
    for fmt in ("%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            import calendar
            import time as _t

            return float(calendar.timegm(_t.strptime(s, fmt)))
        except ValueError:
            continue
    raise ValueError(f"bad --since {spec!r} (want e.g. 5m, 2h, 1d, "
                     "epoch seconds, or YYYY-MM-DDTHH:MM:SS UTC)")


def _warehouse_events(d: str, since: Optional[float]):
    """The ``tail --since`` warehouse fast path: when a warehouse
    exists two levels up (the store base) and fully covers this dir's
    event stream, answer from the indexed event table.  None -> the
    caller falls back to the stream scan."""
    from .telemetry import warehouse as wmod

    base = os.path.dirname(os.path.dirname(os.path.abspath(d)))
    try:
        wh = wmod.open_if_exists(base)
        if wh is None or not wh.events_fresh(d, base):
            return None
        return wh.events_since(d, base, since)
    except Exception:  # noqa: BLE001 — fast path only
        return None


def tail_cmd(opts: argparse.Namespace) -> int:
    """`tail <run-dir>` — render a run's streamed events.jsonl as
    human-readable progress lines; ``-f`` follows a live run; `--since
    <ts|duration>` filters to recent events (served from the warehouse
    event table when one covers the run, stream scan otherwise).  The
    footer names the still-open span chain and the final counter
    values — the post-mortem view for killed/wedged runs."""
    import time as _time

    from .telemetry import stream as tel_stream

    since = None
    if getattr(opts, "since", None):
        try:
            since = parse_since(opts.since)
        except ValueError as e:
            print(f"tail: {e}", file=sys.stderr)
            return 2
    path = opts.dir
    if os.path.isdir(path):
        path = (tel_stream.events_path(path)
                or os.path.join(path, tel_stream.EVENTS_FILE))
    if not os.path.exists(path):
        print(f"tail: {opts.dir} has no events.jsonl (run with "
              "--telemetry or JEPSEN_TELEMETRY=1 to stream)",
              file=sys.stderr)
        return 2

    def since_filter(evs):
        if since is None:
            return evs
        return [e for e in evs
                if isinstance(e.get("t"), (int, float))
                and e["t"] >= since]

    if not getattr(opts, "follow", False):
        evs = None
        if since is not None and os.path.isdir(opts.dir) and \
                os.path.basename(path) == tel_stream.EVENTS_FILE:
            evs = _warehouse_events(opts.dir, since)
        if evs is None:
            evs = since_filter(tel_stream.read_events(path))
        print(tel_stream.render_tail(evs, limit=opts.lines))
        return 0
    cursor = None
    t0 = None
    first = True
    try:
        while True:
            # rotation-proof byte cursor, not a re-parse: a multi-hour
            # soak's events.jsonl is unbounded (and may size-rotate any
            # number of times between polls) and a full-file read per
            # poll is O(n^2) over the run
            evs, cursor = tel_stream.follow_events(path, cursor)
            if evs:
                # "end" can be followed by a straggler (e.g. a sampler
                # tick racing close) — scan the batch, not just its tail
                ended = any(e.get("ev") == "end" for e in evs)
                evs = since_filter(evs)
                if t0 is None and evs:
                    t0 = evs[0].get("t")
                if first and opts.lines is not None \
                        and len(evs) > opts.lines:
                    print(f"... ({len(evs) - opts.lines} earlier events)",
                          flush=True)
                    evs = evs[-opts.lines:] if opts.lines else []
                first = False
                for e in evs:
                    if e.get("ev") == "start":
                        t0 = e.get("t")  # new session replaced the file
                    print(tel_stream.render_line(e, t0), flush=True)
                if ended:
                    return 0
            _time.sleep(0.5)
    except KeyboardInterrupt:
        return 0


def campaign_cmd(opts: argparse.Namespace) -> int:
    """`campaign run|status|report <spec.json>` — drive a whole fleet
    of tests through `jepsen_tpu.campaign` (see docs/CAMPAIGN.md)."""
    from . import campaign, report

    try:
        spec = campaign.load_spec(opts.spec)
        campaign.expand(spec)  # plan-time validation: an unknown
        # workload fails HERE with the registered list, not mid-fleet
    except (OSError, ValueError) as e:
        print(f"campaign: bad spec {opts.spec!r}: {e}", file=sys.stderr)
        return 2
    base = opts.store_dir
    if opts.action == "run":
        summary = campaign.run_campaign(
            spec, base, workers=opts.workers,
            device_slots=opts.device_slots, executor=opts.executor,
            rerun=opts.rerun, run_deadline_s=opts.run_deadline)
        print(report.render_campaign(summary))
        bad = summary["counts"]["false"]
        if bad:
            print(f"{bad} invalid run(s)", file=sys.stderr)
        return 1 if bad else 0
    if opts.action == "status":
        s = campaign.status_campaign(spec, base)
        c = s["counts"]
        print(f"campaign {s['campaign']}: {s['total']} runs, "
              f"{s['pending']} pending — {c['true']} ok, "
              f"{c['false']} invalid, {c['unknown']} unknown "
              f"({c['degraded']} degraded, {c['deadline']} "
              f"deadline-expired)\nindex: {s['index']}")
        return 0
    if opts.action == "report":
        print(campaign.report_campaign(spec, base))
        return 0
    print(f"campaign: unknown action {opts.action!r}", file=sys.stderr)
    return 2


def fleet_cmd(opts: argparse.Namespace) -> int:
    """`fleet serve|work|status|autopilot` — the distributed campaign
    control plane (docs/FLEET.md): a coordinator serves a spec as a
    leased work queue over HTTP; remote workers claim, execute, and
    upload verdicts; every cell lands exactly one attributable record.
    `autopilot` (docs/AUTOPILOT.md) is the continuous driver on top:
    stream template generations forever, gate each one, quarantine +
    auto-shrink regressions, scale the worker pool."""
    import json
    import signal
    import time as _time
    import urllib.request

    from . import report, web
    from .fleet import Autopilot, FleetCoordinator, FleetWorker

    base = opts.store_dir
    if getattr(opts, "cache_warm", False) and opts.action in (
            "serve", "work", "autopilot"):
        # before the service loop starts: a coordinator warms the
        # store its claim adverts ship from; a worker warms the store
        # its own dispatches hit
        _fleet_cache_warm(base)
    if opts.action == "autopilot":
        if not opts.spec:
            print("fleet autopilot needs a campaign spec template",
                  file=sys.stderr)
            return 2
        url = f"http://{opts.host}:{opts.port}"
        mutate = None
        if getattr(opts, "rotate", 0):
            from jepsen_tpu.fleet import scenario_rotation
            mutate = scenario_rotation(
                pivot=tuple(getattr(opts, "pivot", None) or ()),
                slots=opts.rotate)
        try:
            ap = Autopilot(
                opts.spec, base, lease_s=opts.lease,
                run_deadline_s=opts.run_deadline,
                generations=getattr(opts, "generations", None),
                spans=tuple(getattr(opts, "gate_span", None)
                            or ("workload", "check:*")),
                parole_after=getattr(opts, "parole_after", None),
                mutate=mutate,
                coordinator_url=url,
                min_workers=getattr(opts, "workers_min", 0),
                max_workers=getattr(opts, "workers_max", 0),
                worker_version=getattr(opts, "worker_version", None)
                or "dev")
        except (OSError, ValueError) as e:
            print(f"fleet: bad spec {opts.spec!r}: {e}",
                  file=sys.stderr)
            return 2
        try:
            signal.signal(signal.SIGTERM, lambda *_: ap.stop.set())
        except ValueError:
            pass  # not the main thread (embedded use)
        srv = web.serve(port=opts.port, base=base, host=opts.host,
                        fleet=ap.coordinator, background=True)
        print(f"autopilot {ap.name}: serving {url}, journal digest "
              f"{ap.journal.digest()}, {len(ap.journal.order)} "
              f"generation(s) journaled, "
              f"{len(ap.journal.quarantined)} quarantined", flush=True)
        try:
            out = ap.run()
        except KeyboardInterrupt:
            ap.close()
            return 1
        finally:
            srv.server_close()
            ap.coordinator.close()
        print(f"autopilot {ap.name}: {out['generations']} "
              f"generation(s) closed, quarantined="
              f"{out['quarantined'] or '[]'}, digest {out['digest']}")
        return 0
    if opts.action == "serve":
        if not opts.spec:
            print("fleet serve needs a campaign spec", file=sys.stderr)
            return 2
        try:
            retention = getattr(opts, "staging_retention", None)
            coord = FleetCoordinator(
                opts.spec, base, lease_s=opts.lease,
                run_deadline_s=opts.run_deadline,
                staging_retention_s=(retention if retention is not None
                                     else 24 * 3600.0))
        except (OSError, ValueError) as e:
            print(f"fleet: bad spec {opts.spec!r}: {e}", file=sys.stderr)
            return 2
        verifier = None
        if getattr(opts, "ingest", False):
            # live verification at fleet scale (ISSUE 13): the
            # coordinator also serves the verifier, so workers' cells
            # with "live-check" opts stream here — no shared
            # filesystem, one control-plane URL
            from .verifier import VerifierService

            verifier = VerifierService(base)
            verifier.start_maintenance()
        print(f"fleet {coord.name}: {len(coord.specs)} cells, "
              f"{len(coord._done_ids)} already indexed, lease "
              f"{coord.lease_s}s, boot digest {coord.boot_digest}",
              flush=True)
        if not getattr(opts, "until_done", False):
            try:
                web.serve(port=opts.port, base=base, host=opts.host,
                          fleet=coord, verifier=verifier)
            finally:
                coord.close()
                if verifier is not None:
                    verifier.close()
            return 0
        srv = web.serve(port=opts.port, base=base, host=opts.host,
                        fleet=coord, verifier=verifier,
                        background=True)
        try:
            while not coord.finished:
                _time.sleep(0.2)
        except KeyboardInterrupt:
            return 1
        finally:
            coord.close()
            if verifier is not None:
                verifier.close()
            srv.server_close()
        summary = coord.summary()
        print(report.render_campaign(summary))
        bad = summary["counts"]["false"]
        if bad:
            print(f"{bad} invalid run(s)", file=sys.stderr)
        return 1 if bad else 0
    if opts.action == "work":
        if not opts.coordinator:
            print("fleet work needs --coordinator URL", file=sys.stderr)
            return 2
        worker = FleetWorker(opts.coordinator, base, name=opts.name,
                             device_slots=opts.device_slots,
                             backend=opts.backend, mesh=opts.mesh,
                             poll_s=opts.poll,
                             claim_budget_s=opts.claim_budget,
                             upload=getattr(opts, "upload", False),
                             version=getattr(opts, "worker_version",
                                             None))
        # SIGTERM drains gracefully: finish the in-flight cell, release
        # unstarted claims, exit — the lease protocol covers kill -9
        try:
            signal.signal(signal.SIGTERM,
                          lambda *_: worker.stop.set())
        except ValueError:
            pass  # not the main thread (embedded use)
        try:
            n = worker.run()
        except KeyboardInterrupt:
            return 1
        print(f"worker {worker.name}: {n} cells completed")
        return 0
    if opts.action == "status":
        if not opts.coordinator:
            print("fleet status needs --coordinator URL",
                  file=sys.stderr)
            return 2
        url = opts.coordinator.rstrip("/") + "/fleet/status"
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                s = json.loads(r.read().decode())
        except Exception as e:  # noqa: BLE001 — network errors surfaced
            print(f"fleet: status fetch failed: {e}", file=sys.stderr)
            return 2
        c = s.get("counts") or {}
        print(f"fleet {s.get('campaign')}: {s.get('done')}/"
              f"{s.get('total')} cells done "
              f"({'finished' if s.get('finished') else 'running'}) — "
              f"{c.get('queued')} queued, {c.get('claimed')} claimed, "
              f"{c.get('requeues')} requeues, {c.get('duplicates')} "
              f"duplicates discarded")
        # the scaler's two inputs (ISSUE 17 satellite)
        p95 = s.get("claim-latency-p95-s")
        print(f"queue depth: {s.get('queue-depth')}  "
              f"claim-latency p95: "
              f"{'-' if p95 is None else f'{p95:.3f}s'}")
        print(f"digest: {s.get('digest')}  boot: {s.get('boot-digest')}")
        for w, d in sorted((s.get("workers") or {}).items()):
            line = (f"  worker {w}: host={d.get('host')} "
                    f"slots={d.get('device-slots')} "
                    f"version={d.get('version') or '-'} "
                    f"seen {d.get('age-s')}s ago "
                    f"({'alive' if d.get('alive') else 'silent'})")
            wd = d.get("windows")
            if wd:
                open_ = ",".join(str(o.get("pos"))
                                 for o in wd.get("open") or ()) or "-"
                line += (f" windows[gen {wd.get('gen')}] "
                         f"{wd.get('digest')} open={open_}"
                         f"{'' if wd.get('synced') else ' DESYNCED'}")
                if wd.get("t0-skew") is not None:
                    line += (f" t0-skew={wd['t0-skew']}s"
                             f"{'' if wd.get('clock-synced') else ' CLOCK-DESYNCED'}")
            print(line)
        sched = s.get("nemesis-schedule")
        if sched:
            print(f"nemesis schedule: {sched.get('windows')} "
                  f"window(s)/gen over {'|'.join(sched.get('faults'))}")
            gens = sched.get("gens") or {}
            digests = sched.get("digest-by-gen") or {}
            t0s = sched.get("t0-by-gen") or {}
            for g in sorted(gens, key=lambda x: int(x)):
                wins = " ".join(
                    f"[{w.get('pos')}:{w.get('fault')}@"
                    f"{w.get('at_s')}s+{w.get('dur_s')}s]"
                    for w in gens[g])
                anchor = (f" t0={t0s[g]}" if g in t0s else "")
                print(f"  gen {g}: {digests.get(g)}{anchor} {wins}")
        ap = s.get("autopilot")
        if ap:
            print(f"autopilot: generation {ap.get('generation')} "
                  f"({ap.get('generations-closed')} closed), "
                  f"{len(ap.get('quarantined') or {})} quarantined, "
                  f"worker version {ap.get('worker-version')}, "
                  f"journal {ap.get('journal-digest')}")
            for k, q in sorted((ap.get("quarantined") or {}).items()):
                print(f"  quarantined {k}: {q.get('span')} "
                      f"{q.get('rel-delta')} at {q.get('gen')}")
            for v in ap.get("last-verdicts") or []:
                print(f"  gate[{v.get('to-gen')}] "
                      f"{v.get('span')}: {v.get('status')} "
                      f"(rc {v.get('rc')})")
        return 0
    print(f"fleet: unknown action {opts.action!r}", file=sys.stderr)
    return 2


def cache_cmd(opts: argparse.Namespace) -> int:
    """`cache warm|ls|stats|clear` — the shape-bucketed AOT compile
    cache (docs/COMPILECACHE.md): pre-warm the bucket ladder into
    ``<store>/compilecache/``, list/inspect the entry store, or drop
    it.  ``warm`` is what a fleet service runs at start (``fleet ...
    --cache-warm``) so every worker's first claim of a known shape
    class pays dispatch, not compile."""
    import json as _json

    from jepsen_tpu import compilecache
    from jepsen_tpu.compilecache import store as cc_store

    d = compilecache.adopt_base(opts.store_dir)
    if opts.action == "warm":
        from jepsen_tpu.compilecache import warm as cc_warm

        sizes = ([int(s) for s in opts.sizes.split(",") if s]
                 if opts.sizes else None)
        fams = tuple(f for f in (opts.families or "la,rw").split(",")
                     if f)
        recs = cc_warm.warm_ladder(
            sizes=sizes, max_txns=opts.max_txns, families=fams,
            max_k=opts.max_k, verbose=not opts.json)
        st = compilecache.stats()
        if opts.json:
            print(_json.dumps({"dir": d, "rungs": recs, "stats": st},
                              indent=1))
        else:
            ok = sum(1 for r in recs if r.get("ok"))
            print(f"cache warm: {ok}/{len(recs)} rungs ok, "
                  f"{st['entries']} entries "
                  f"({d or 'memory-only'})")
        return 0 if all(r.get("ok") for r in recs) else 1
    if opts.action == "ls":
        rows = cc_store.entries(d) if d else []
        for e in rows:
            meta = {}
            try:
                with open(os.path.join(d, e["name"]), "rb") as f:
                    doc = cc_store.unpack_entry(f.read())
                meta = (doc or {}).get("meta") or {}
            except OSError:
                pass
            print(f"{e['name']}  {e['size']:>9}  "
                  f"{meta.get('site', '?')}  {meta.get('class', '?')}")
        print(f"{len(rows)} entries, "
              f"{cc_store.total_bytes(d) if d else 0} bytes "
              f"({d or 'memory-only'})")
        return 0
    if opts.action == "stats":
        print(_json.dumps(dict(compilecache.stats(), dir=d), indent=1))
        return 0
    if opts.action == "clear":
        n = 0
        for e in (cc_store.entries(d) if d else []):
            if cc_store.delete(d, e["name"][:-len(cc_store.SUFFIX)]):
                n += 1
        compilecache.clear()
        print(f"cache clear: {n} entries removed ({d or 'memory-only'})")
        return 0
    print(f"cache: unknown action {opts.action!r}", file=sys.stderr)
    return 2


def _fleet_cache_warm(base: str) -> None:
    """The ``fleet --cache-warm`` service-start hook: point the AOT
    store at this service's base and walk the bucket ladder, so the
    coordinator's claim adverts (or this worker's own dispatches) are
    warm from the first cell.  Failures are logged, never fatal — a
    cold cache only costs compile time."""
    try:
        from jepsen_tpu import compilecache
        from jepsen_tpu.compilecache import warm as cc_warm

        d = compilecache.adopt_base(base)
        recs = cc_warm.warm_ladder(verbose=True)
        ok = sum(1 for r in recs if r.get("ok"))
        print(f"cache warm: {ok}/{len(recs)} rungs ok "
              f"({d or 'memory-only'})", flush=True)
    except Exception as e:  # noqa: BLE001 — warm is an optimization
        print(f"cache warm failed (continuing cold): {e}",
              file=sys.stderr)


def _render_timeline(tl: Dict[str, Any]) -> str:
    """One stitched cross-host trace as a text waterfall (ISSUE 14):
    ordered, host-attributed segments with offsets from the trace's
    first event and proportional duration bars.  Geometry comes from
    the shared `Warehouse.timeline_layout` (one layout, two
    renderers), which is empty-safe for the only-orphans case."""
    from .telemetry.warehouse import Warehouse

    lay = Warehouse.timeline_layout(tl)
    spans, hosts, wall = lay["spans"], lay["hosts"], lay["wall"]
    lines = [f"trace {tl['trace-id']} — run {tl.get('run') or '?'} "
             f"({len(spans)} spans, {len(hosts) or 1} host(s), "
             f"{wall:.3f}s wall)"]
    if spans:
        lines.append(f"{'host':<14} {'segment':<28} {'start':>9} "
                     f"{'dur':>9}  timeline")
    width = 32
    for s in spans:
        left = int(round(s["frac_left"] * width))
        bar = " " * min(left, width - 1) + "#" * max(
            1, int(round(s["frac_width"] * width)))
        lines.append(
            f"{str(s.get('host') or '-'):<14} "
            f"{str(s.get('name')):<28} {s['off']:>8.3f}s "
            f"{s.get('dur_s') or 0.0:>8.3f}s  "
            f"|{bar[:width]:<{width}}|")
    orphans = tl.get("orphans") or []
    if orphans:
        lines.append("")
        lines.append(f"ORPHAN spans ({len(orphans)} recorded against "
                     "this run under a DIFFERENT trace id):")
        for s in orphans:
            lines.append(f"  {s.get('trace_id')} {s.get('name')} "
                         f"host={s.get('host')}")
    return "\n".join(lines)


def obs_cmd(opts: argparse.Namespace) -> int:
    """`obs ingest|rebuild|gate|sql|bench|timeline|profile|diff` — the
    sqlite telemetry warehouse over the store dir (docs/TELEMETRY.md):
    build/refresh it, query it, gate span regressions statistically,
    render stitched cross-host run timelines, and run the performance
    observatory (device-call profiles, cross-generation forensics)."""
    import glob as _glob

    from .telemetry import warehouse as wmod

    base = opts.store_dir
    if opts.action in ("ingest", "rebuild"):
        wh = wmod.open_or_create(base)
        stats = (wh.rebuild(base) if opts.action == "rebuild"
                 else wh.ingest_store(base))
        for pat in opts.bench or []:
            paths = sorted(_glob.glob(pat)) or [pat]
            for p in paths:
                if wh.ingest_bench_file(p):
                    stats["bench"] = stats.get("bench", 0) + 1
                else:
                    print(f"obs: bench file skipped: {p}",
                          file=sys.stderr)
        counts = wh.counts()
        print(f"warehouse: {wmod.warehouse_path(base)}")
        print("ingested: " + ", ".join(
            f"{v} {k}" for k, v in sorted(stats.items())))
        print("tables: " + ", ".join(
            f"{k}={v}" for k, v in sorted(counts.items()) if v))
        if opts.bench and not stats.get("bench"):
            # an explicitly requested bench ingest that landed nothing
            # (typo'd glob, unparsable files) must not leave CI green
            # while the bench series silently stops updating
            print("obs: --bench matched/ingested no files",
                  file=sys.stderr)
            return 2
        return 0
    if opts.action == "gc":
        # store retention (ISSUE 17 satellite / ROADMAP 5c): archive
        # landed run dirs past --retention to _archive/ — needs no
        # warehouse (it operates on the store itself; the next ingest
        # simply no longer sees the archived dirs)
        from . import store as store_mod

        retention = getattr(opts, "retention", None)
        if retention is None:
            print("obs: gc needs --retention <seconds>",
                  file=sys.stderr)
            return 2
        stats = store_mod.gc_runs(base, retention_s=float(retention))
        print(f"obs gc: archived {stats['archived']} run dir(s) to "
              f"{store_mod.archive_dir(base)} "
              f"({stats['kept']} kept within retention, "
              f"{stats['skipped']} unlanded skipped)")
        return 0
    if opts.action in ("gate", "profile", "diff"):
        # campaign analytics: Index answers from the warehouse when it
        # is fresh and falls back to the jsonl scan otherwise, so these
        # work (identically) with or without an ingested warehouse
        return _obs_campaign_cmd(opts, base)
    if opts.action == "alerts":
        # the watchtower (ISSUE 20): warehouse signals are best-effort,
        # so this works on a store with no warehouse at all
        return _obs_alerts_cmd(opts, base)
    wh = wmod.open_if_exists(base)
    if wh is None:
        print(f"obs: no warehouse at {wmod.warehouse_path(base)} "
              "(run `obs ingest` first)", file=sys.stderr)
        return 2
    if opts.action == "compact":
        cdir = os.path.join(base, "campaigns")
        want = opts.campaign or opts.query
        names = ([want] if want else sorted(
            fn[:-len(".jsonl")] for fn in (
                os.listdir(cdir) if os.path.isdir(cdir) else ())
            if fn.endswith(".jsonl")))
        if not names:
            print("obs: no campaign ledgers to compact", file=sys.stderr)
            return 2
        total = {"gens-compacted": 0, "dropped-records": 0,
                 "dropped-spans": 0, "kept-witnesses": 0}
        for name in names:
            path = os.path.join(cdir, f"{name}.jsonl")
            if not os.path.exists(path):
                print(f"obs: no ledger for campaign {name!r}",
                      file=sys.stderr)
                return 2
            stats = wh.compact_ledger(path, base,
                                      keep_gens=opts.keep_gens)
            print(f"compact {name}: " + ", ".join(
                f"{v} {k}" for k, v in sorted(stats.items())))
            for k, v in stats.items():
                total[k] = total.get(k, 0) + v
        if len(names) > 1:
            print("total: " + ", ".join(
                f"{v} {k}" for k, v in sorted(total.items())))
        return 0
    if opts.action == "bench":
        rows = wh.bench_series()
        if not rows:
            print("obs: no bench results ingested (try `obs ingest "
                  "--bench 'BENCH_r0*.json'`)", file=sys.stderr)
            return 2
        print(f"{'source':<24} {'value':>12} {'unit':<10} "
              f"{'vs_baseline':>11} {'n_txns':>9} backend")
        for r in rows:
            print(f"{str(r['source']):<24} {r['value'] or 0:>12.1f} "
                  f"{str(r['unit']):<10} {r['vs_baseline'] or 0:>11.3f} "
                  f"{r['n_txns'] or 0:>9} {r['backend']}")
        return 0
    if opts.action == "timeline":
        if not opts.query:
            print("obs: timeline needs a run id (or 32-hex trace id)",
                  file=sys.stderr)
            return 2
        tl = wh.trace_timeline(opts.query)
        if not tl["spans"] and not tl["orphans"]:
            print(f"obs: no trace spans for {opts.query!r} (run "
                  "`obs ingest` after the run lands; traced runs need "
                  "telemetry or a fleet ledger)", file=sys.stderr)
            return 2
        print(_render_timeline(tl))
        # orphans are a stitching failure worth a red exit: the run's
        # artifacts disagree about which trace they belong to
        return 1 if tl["orphans"] else 0
    if opts.action == "sql":
        if not opts.query:
            print("obs: sql needs a query argument", file=sys.stderr)
            return 2
        try:
            cols, rows = wh.query(opts.query)
        except Exception as e:  # noqa: BLE001 — sqlite/read-only errors
            print(f"obs: sql failed: {e}", file=sys.stderr)
            return 2
        print("\t".join(cols))
        for r in rows:
            print("\t".join(str(v) for v in r))
        return 0
    print(f"obs: unknown action {opts.action!r}", file=sys.stderr)
    return 2


def _obs_alerts_cmd(opts: argparse.Namespace, base: str) -> int:
    """`obs alerts` — render the watchtower's durable alert state
    (docs/ALERTS.md).  Plain: replay <store>/alerts.jsonl read-only.
    With --eval: run one engine tick against the live registry,
    campaign heartbeats, store counters, and warehouse rollups first
    (journaling transitions + notifying sinks — the headless cron
    form of the autopilot's alert tick).  Exit 1 while anything is
    firing, so CI and cron wrappers get the red exit for free."""
    import json as _json

    from .telemetry import alerts as alerts_mod

    if opts.alerts_eval:
        from .telemetry import warehouse as wmod

        eng = alerts_mod.AlertEngine(base)
        eng.evaluate(warehouse=wmod.open_if_exists(base))
        jr = eng.journal
    else:
        path = alerts_mod.alerts_path(base)
        if not os.path.exists(path):
            print(f"obs: no alert journal at {path} (the autopilot's "
                  "alert tick or `obs alerts --eval` creates it)",
                  file=sys.stderr)
            return 2
        jr = alerts_mod.AlertJournal(path)
    order = {"firing": 0, "pending": 1, "resolved": 2}
    rows = sorted(jr.states.items(),
                  key=lambda kv: (order.get(kv[1].get("state"), 3),
                                  kv[0]))
    if opts.json_out:
        doc = {"digest": jr.digest(),
               "sends-ok": jr.sends_ok,
               "sends-failed": jr.sends_failed,
               "states": {r: dict(d) for r, d in rows}}
        if opts.json_out == "-":
            _json.dump(doc, sys.stdout, indent=2, sort_keys=True)
            print()
        else:
            with open(opts.json_out, "w") as f:
                _json.dump(doc, f, indent=2, sort_keys=True)
            print(f"report written: {opts.json_out}")
    firing = [r for r, d in rows if d.get("state") == "firing"]
    if opts.json_out != "-":
        print(f"alerts: {len(firing)} firing, "
              f"{sum(1 for _r, d in rows if d.get('state') == 'pending')} "
              f"pending ({len(rows)} rule(s) journaled) · digest "
              f"{jr.digest()} · notifications {jr.sends_ok} ok / "
              f"{jr.sends_failed} failed")
        if rows:
            w = max(len(r) for r, _d in rows)
            print(f"{'rule':<{w}} {'severity':<8} {'state':<8} "
                  f"{'value':>12} since")
            for r, d in rows:
                v = d.get("value")
                print(f"{r:<{w}} {str(d.get('severity')):<8} "
                      f"{str(d.get('state')):<8} "
                      f"{(f'{v:.4g}' if isinstance(v, (int, float)) else '-'):>12} "
                      f"{d.get('since')}")
    return 1 if firing else 0


def _obs_campaign_cmd(opts: argparse.Namespace, base: str) -> int:
    """`obs gate|profile|diff` — the campaign-scoped observatory
    queries (docs/TELEMETRY.md "Performance observatory").  Exit codes:
    0 pass / rendered, 1 regression, 2 cannot evaluate; for a multi-
    span gate the rc is the WORST single-span verdict (regression >
    insufficient-data > pass)."""
    import json as _json

    from .campaign.core import index_path
    from .campaign.index import Index
    from .telemetry import forensics
    from .telemetry import gate as gate_mod

    campaign = opts.campaign or opts.query
    if not campaign:
        print(f"obs: {opts.action} needs a campaign (positional or "
              "--campaign)", file=sys.stderr)
        return 2
    if opts.action == "profile":
        rows = Index(index_path(campaign, base)).profile()
        if not rows:
            print(f"obs: no device-call profile for campaign "
                  f"{campaign!r} (profiles come from runs recorded "
                  "with telemetry; re-run `obs ingest` after runs "
                  "land)", file=sys.stderr)
            return 2
        print(f"obs profile: campaign {campaign} "
              f"({len(rows)} site/shape cells)")
        print(forensics.render_profile(rows))
        return 0
    if opts.action == "diff":
        report = forensics.run_diff(
            base, campaign, from_gen=opts.from_gen, to_gen=opts.to_gen,
            spans=opts.span or None, alpha=opts.alpha,
            threshold=opts.threshold, min_runs=opts.min_runs)
        if opts.json_out == "-":
            # machine form on stdout (ISSUE 20 satellite): the human
            # rendering moves to stderr so `obs diff --json - | jq`
            # sees pure JSON
            print(forensics.render_diff(report), file=sys.stderr)
            _json.dump(report, sys.stdout, indent=2, sort_keys=True)
            print()
        else:
            print(forensics.render_diff(report))
            if opts.json_out:
                with open(opts.json_out, "w") as f:
                    _json.dump(report, f, indent=2, sort_keys=True)
                print(f"report written: {opts.json_out}")
        return {"pass": 0, "regression": 1}.get(report.get("status"), 2)
    # gate: repeated --span flags, each an exact name or a * glob
    if not opts.span:
        print("obs: gate needs --campaign and --span", file=sys.stderr)
        return 2
    idx = Index(index_path(campaign, base))
    records = idx.forensic_records()
    known = {n for _g, sp, _p, _c in records for n in sp}
    wanted = forensics.resolve_spans(known, opts.span)
    if not wanted:
        print(f"obs: --span {opts.span} matched no recorded span of "
              f"campaign {campaign!r} (known: "
              f"{', '.join(sorted(known)) or 'none'})", file=sys.stderr)
        return 2
    statuses = []
    results = []
    out = sys.stderr if opts.json_out == "-" else sys.stdout
    for i, span in enumerate(wanted):
        res = gate_mod.run_gate(
            base, campaign, span,
            from_gen=opts.from_gen, to_gen=opts.to_gen,
            alpha=opts.alpha, threshold=opts.threshold,
            min_runs=opts.min_runs)
        statuses.append(res.get("status"))
        if i:
            print(file=out)
        print(gate_mod.render_gate(res), file=out)
        entry = None
        if opts.explain and res.get("status") == "regression":
            entry = forensics.attribute_span(
                span, records, res["from-gen"], res["to-gen"])
            for line in forensics.render_attribution(entry):
                print("  " + line, file=out)
        results.append({"span": span, **res,
                        **({"attribution": entry} if entry else {})})
    if opts.json_out:
        # machine form (ISSUE 20 satellite): '-' puts pure JSON on
        # stdout for webhook payloads / CI without a tempfile
        report = {"campaign": campaign,
                  "status": ("regression" if "regression" in statuses
                             else "pass" if all(s == "pass"
                                                for s in statuses)
                             else "insufficient-data"),
                  "gates": results}
        if opts.json_out == "-":
            _json.dump(report, sys.stdout, indent=2, sort_keys=True)
            print()
        else:
            with open(opts.json_out, "w") as f:
                _json.dump(report, f, indent=2, sort_keys=True)
            print(f"report written: {opts.json_out}")
    if "regression" in statuses:
        return 1
    return 0 if all(s == "pass" for s in statuses) else 2


def shrink_cmd(opts: argparse.Namespace,
               checker_fn: Optional[Callable[[], Any]] = None) -> int:
    """`shrink <run-dir>` — delta-debug an invalid run's history to a
    minimal failing witness (see docs/MINIMIZE.md)."""
    from . import minimize

    chk = checker_fn() if checker_fn else None
    try:
        s = minimize.shrink(
            opts.dir, checker=chk, rounds=opts.rounds,
            probe_deadline_s=opts.probe_deadline,
            workers=opts.workers, device_slots=opts.device_slots,
            host_oracle=opts.host_oracle, anomalies=opts.anomaly,
            force=opts.force)
    except (ValueError, FileNotFoundError) as e:
        print(f"shrink: {e}", file=sys.stderr)
        return 2
    if s.get("error") == "not-invalid":
        print(f"shrink: run is valid? = {s.get('valid?')}; nothing to "
              "shrink", file=sys.stderr)
        return 1
    if s.get("error") == "target-absent":
        print(f"shrink: requested anomaly {s.get('requested')} not in "
              f"this run's set {s.get('anomaly-types')}", file=sys.stderr)
        return 1
    kinds = ",".join(s.get("anomaly-types") or ()) or "?"
    src = s.get("source-ops", "?")
    print(f"witness: {s['ops']} ops (from {src}) — {kinds}"
          f"{' [cached]' if s.get('cached') else ''}")
    print(f"rounds: {s.get('rounds', 0)}  probes: {s.get('probes', 0)}"
          f"  digest: {s.get('digest')}")
    print(f"written: {s['paths']['ops']}")
    return 0 if s.get("valid?") is False else 1


def analyze_cmd(opts: argparse.Namespace,
                checker_fn: Optional[Callable[[], Any]] = None) -> int:
    """Re-check a stored run (reference: store/load + re-check path)."""
    chk = checker_fn() if checker_fn else None
    try:
        t = core.analyze(opts.dir, checker=chk)
    except (ValueError, FileNotFoundError) as e:
        print(f"analyze: {e}", file=sys.stderr)
        return 2
    valid = t.get("results", {}).get("valid?")
    print(f"re-analysis: valid? = {valid}")
    return 0 if valid is True else 1


def single_test_cmd(test_fn, *, extra_opts: Optional[Callable] = None,
                    checker_fn: Optional[Callable] = None,
                    prog: str = "jepsen-tpu"):
    """Build the standard CLI: `test`, `serve`, `analyze` subcommands.
    Returns (parser, dispatch)."""
    p = base_parser(prog)
    sub = p.add_subparsers(dest="cmd", required=True)

    pt = sub.add_parser("test", help="run the test")
    add_test_opts(pt)
    if extra_opts:
        extra_opts(pt)

    ps = sub.add_parser("serve", help="serve the store web UI")
    ps.add_argument("-p", "--port", type=int, default=8080)
    ps.add_argument("--host", default="127.0.0.1",
                    help='bind address (use "0.0.0.0" to expose)')
    ps.add_argument("--ingest", action="store_true",
                    help="run the always-on verifier service: accept "
                         "streamed history segments on POST "
                         "/ingest/<session> and publish rolling "
                         "verdicts on GET /verdict/<session> "
                         "(docs/VERIFIER.md)")
    ps.add_argument("--compact-bytes", type=int, default=None,
                    help="auto-compact a session's journal once it "
                         "exceeds this many bytes (checkpoint + "
                         "truncate; docs/VERIFIER.md)")
    ps.add_argument("--gc-idle", type=float, default=None,
                    help="expire open sessions idle for this many "
                         "seconds (journal stays; a later touch "
                         "recovers them)")
    ps.add_argument("--archive-sealed", type=float, default=None,
                    help="archive sealed sessions idle for this many "
                         "seconds under <store>/verifier/_archive/")
    ps.add_argument("--maintain-interval", type=float, default=5.0,
                    help="seconds between maintenance ticks (batched "
                         "sweep + gc)")

    pa = sub.add_parser("analyze", help="re-check a stored run")
    pa.add_argument("dir", help="store run directory")

    ptr = sub.add_parser("trace",
                         help="summarize a stored run's telemetry")
    ptr.add_argument("dir", help="store run directory")
    ptr.add_argument("--top", type=int, default=None, metavar="N",
                     help="also print the N slowest spans by self-time "
                          "(name, count, total/p95) — span regressions "
                          "quotable without opening Perfetto")

    ptl = sub.add_parser("tail",
                         help="render a run's streamed events.jsonl "
                              "(the flight recorder; docs/TELEMETRY.md)")
    ptl.add_argument("dir", help="store run directory (or events.jsonl)")
    ptl.add_argument("-f", "--follow", action="store_true",
                     help="poll for new events until the run ends")
    ptl.add_argument("-n", "--lines", type=int, default=None,
                     help="only show the last N event lines")
    ptl.add_argument("--since", default=None, metavar="TS|DUR",
                     help="only events at/after this time: a duration "
                          "back from now (90s, 5m, 2h, 1d), epoch "
                          "seconds, or a UTC timestamp "
                          "(YYYY-MM-DDTHH:MM:SS); answered from the "
                          "warehouse event table when one covers the "
                          "run (cli obs ingest), stream scan otherwise")

    psh = sub.add_parser("shrink",
                         help="delta-debug an invalid run to a minimal "
                              "failing witness (docs/MINIMIZE.md)")
    psh.add_argument("dir", help="store run directory")
    psh.add_argument("--rounds", type=int, default=None,
                     help="cap on probe rounds (default: run to "
                          "1-minimality)")
    psh.add_argument("--probe-deadline", type=float, default=30.0,
                     help="seconds of checker budget per candidate "
                          "probe (expired probes count as "
                          "non-reproducing)")
    psh.add_argument("--workers", type=int, default=2,
                     help="concurrent probe workers (host probes run "
                          "wide; device probes serialize through "
                          "--device-slots)")
    psh.add_argument("--device-slots", type=int, default=1,
                     help="concurrent device-pipeline probes")
    psh.add_argument("--host-oracle", action="store_true",
                     help="probe through the exact host reference "
                          "checker where one exists (much cheaper for "
                          "the many small candidates)")
    psh.add_argument("--anomaly", action="append", default=None,
                     help="pin the shrink target to this anomaly type "
                          "(repeatable; default: any of the run's)")
    psh.add_argument("--force", action="store_true",
                     help="re-shrink even when a cached witness "
                          "matches the history digest")

    po = sub.add_parser("obs",
                        help="telemetry warehouse: ingest/rebuild the "
                             "sqlite index over the store, query it, "
                             "gate span regressions, and render "
                             "stitched cross-host run timelines "
                             "(docs/TELEMETRY.md)")
    po.add_argument("action",
                    choices=("ingest", "rebuild", "gate", "sql",
                             "bench", "timeline", "profile", "diff",
                             "gc", "alerts", "compact"))
    po.add_argument("query", nargs="?",
                    help="SQL for the sql action (read-only); run id "
                         "or 32-hex trace id for the timeline action; "
                         "campaign name for profile/diff")
    po.add_argument("--bench", action="append", metavar="GLOB",
                    help="BENCH json file(s) to ingest alongside the "
                         "store (repeatable; glob ok)")
    po.add_argument("--campaign", help="gate/profile/diff: campaign "
                                       "name")
    po.add_argument("--span", action="append",
                    help="gate/diff: span site(s) to compare "
                         "(repeatable; * globs match known spans, "
                         "e.g. --span 'check:*')")
    po.add_argument("--explain", action="store_true",
                    help="gate: on regression, attribute the delta "
                         "across phase buckets and forensic counters")
    po.add_argument("--json", dest="json_out", metavar="PATH",
                    help="gate/diff/alerts: also write the full "
                         "report as a JSON artifact; '-' writes it to "
                         "stdout (webhook payloads / CI embedding "
                         "without a tempfile round-trip)")
    po.add_argument("--eval", dest="alerts_eval", action="store_true",
                    help="alerts: run one evaluation tick (registry + "
                         "heartbeats + warehouse rollups) against the "
                         "store's rule pack, journaling transitions "
                         "and notifying sinks, before rendering")
    po.add_argument("--keep-gens", dest="keep_gens", type=int,
                    default=2,
                    help="compact: generations of raw rows to keep "
                         "live per ledger (default 2); older fold "
                         "into bounded summary rows")
    po.add_argument("--from-gen", dest="from_gen", default=None,
                    help="gate: baseline generation (default: "
                         "second-latest)")
    po.add_argument("--to-gen", dest="to_gen", default=None,
                    help="gate: candidate generation (default: latest)")
    po.add_argument("--alpha", type=float, default=0.05,
                    help="gate: Mann-Whitney one-sided significance "
                         "level (default 0.05)")
    po.add_argument("--threshold", type=float, default=0.25,
                    help="gate: hard relative p95 regression bound "
                         "(default 0.25 = +25%%)")
    po.add_argument("--min-runs", dest="min_runs", type=int, default=3,
                    help="gate: minimum runs per generation; fewer "
                         "exits 2 (cannot evaluate), never a silent "
                         "pass/fail")
    po.add_argument("--retention", type=float, default=None,
                    metavar="SECONDS",
                    help="gc: archive landed run dirs older than this "
                         "to <store>/_archive/ (they leave store "
                         "scans and future warehouse ingests)")

    pc = sub.add_parser("campaign",
                        help="run/inspect a fleet of tests from a "
                             "campaign spec (docs/CAMPAIGN.md)")
    pc.add_argument("action", choices=("run", "status", "report"))
    pc.add_argument("spec", help="campaign spec JSON file")
    pc.add_argument("--workers", type=int, default=2,
                    help="concurrent campaign workers")
    pc.add_argument("--device-slots", type=int, default=1,
                    help="concurrent device-pipeline runs (host-only "
                         "runs are unthrottled)")
    pc.add_argument("--executor", choices=("thread", "subprocess"),
                    default="thread",
                    help="per-run isolation: in-process threads (warm "
                         "jit cache) or one subprocess per run "
                         "(crash/hang isolation)")
    pc.add_argument("--rerun", action="store_true",
                    help="re-execute runs already in the index "
                         "(appends fresh records; this is what makes "
                         "verdict flips observable)")
    pc.add_argument("--run-deadline", type=float, default=None,
                    help="per-run budget in seconds (hard kill under "
                         "the subprocess executor; cooperative checker "
                         "deadline otherwise)")

    pfl = sub.add_parser("fleet",
                         help="distributed campaign execution: a "
                              "leased work queue served over HTTP + "
                              "remote workers (docs/FLEET.md)")
    pfl.add_argument("action", choices=("serve", "work", "status",
                                        "autopilot"))
    pfl.add_argument("spec", nargs="?",
                     help="campaign spec JSON file (serve), or spec "
                          "TEMPLATE (autopilot: expanded into "
                          "generations forever)")
    pfl.add_argument("-p", "--port", type=int, default=8080)
    pfl.add_argument("--host", default="127.0.0.1",
                     help='bind address (use "0.0.0.0" so remote '
                          "workers can reach the control plane)")
    pfl.add_argument("--coordinator", default=None, metavar="URL",
                     help="coordinator base URL (work/status), e.g. "
                          "http://host:8080")
    pfl.add_argument("--lease", type=float, default=15.0,
                     help="claim lease seconds; a worker that stops "
                          "renewing for this long loses the cell, "
                          "which requeues (serve)")
    pfl.add_argument("--run-deadline", type=float, default=None,
                     help="per-cell checker budget in seconds, merged "
                          "into cells without their own (serve)")
    pfl.add_argument("--until-done", action="store_true",
                     help="serve: exit with the campaign summary once "
                          "every cell has a verdict (default: keep "
                          "serving)")
    pfl.add_argument("--name", default=None,
                     help="worker name (default: host-pid)")
    pfl.add_argument("--device-slots", type=int, default=1,
                     help="device pipelines this worker can run; 0 "
                          "claims host-only cells")
    pfl.add_argument("--poll", type=float, default=0.5,
                     help="idle claim poll interval seconds (work)")
    pfl.add_argument("--backend", default=None,
                     help="advertised device backend capability "
                          "(work): device cells whose opts pin a "
                          '"backend" land only on matching workers')
    pfl.add_argument("--mesh", default=None,
                     help='advertised mesh shape, e.g. "2x2" (work)')
    pfl.add_argument("--claim-budget", type=float, default=120.0,
                     help="seconds of seeded-jittered backoff a worker "
                          "spends riding out claim outages before "
                          "giving up (work)")
    pfl.add_argument("--upload", action="store_true",
                     help="work: upload each cell's run dir to the "
                          "coordinator's artifact endpoint — no "
                          "shared store filesystem needed "
                          "(docs/FLEET.md federation)")
    pfl.add_argument("--ingest", action="store_true",
                     help="serve: also run the verifier service on "
                          "the same port, so cells with "
                          '"live-check" opts stream here '
                          "(docs/VERIFIER.md)")
    pfl.add_argument("--generations", type=int, default=None,
                     help="autopilot: stop after this many gated "
                          "generations (default: stream forever)")
    pfl.add_argument("--gate-span", dest="gate_span", action="append",
                     help="autopilot: span site(s) gated per "
                          "generation (repeatable, * globs; default "
                          "workload + check:*)")
    pfl.add_argument("--workers-min", dest="workers_min", type=int,
                     default=0,
                     help="autopilot: scaler lower bound on managed "
                          "local workers (0 = bring your own workers)")
    pfl.add_argument("--workers-max", dest="workers_max", type=int,
                     default=0,
                     help="autopilot: scaler upper bound; 0 disables "
                          "the scaler entirely")
    pfl.add_argument("--worker-version", dest="worker_version",
                     default=None,
                     help="work: advertised build version (default "
                          "$JEPSEN_WORKER_VERSION or 'dev'); "
                          "autopilot: target version — changing it on "
                          "a live loop rolls the pool one worker at "
                          "a time")
    pfl.add_argument("--parole-after", dest="parole_after",
                     type=int, default=None, metavar="N",
                     help="autopilot: re-admit a quarantined cell "
                          "after N closed generations with no "
                          "regression since its quarantine — a "
                          "re-offender is re-quarantined "
                          "(docs/AUTOPILOT.md; default: quarantine "
                          "is forever)")
    pfl.add_argument("--rotate", dest="rotate", type=int, default=0,
                     metavar="N",
                     help="autopilot: rotate scenarios, not just "
                          "seeds — each generation keeps the pivot "
                          "cells and fills N slots by walking the "
                          "template's remaining cells in order "
                          "(docs/AUTOPILOT.md; 0 = run the full "
                          "template every generation)")
    pfl.add_argument("--pivot", dest="pivot", action="append",
                     metavar="LABEL",
                     help="autopilot --rotate: cell label/workload "
                          "kept in EVERY generation so its span "
                          "stays gate-comparable (repeatable; "
                          "default: the template's first cell)")
    pfl.add_argument("--staging-retention", dest="staging_retention",
                     type=float, default=None,
                     help="serve: expire abandoned artifact-upload "
                          "partials under <store>/fleet/staging/ "
                          "after this many seconds (default 86400); "
                          "staged bytes are visible either way as "
                          "jepsen_fleet_artifact_staging_bytes on "
                          "/metrics")
    pfl.add_argument("--cache-warm", dest="cache_warm",
                     action="store_true",
                     help="pre-warm the AOT compile cache's bucket "
                          "ladder at service start (serve/work/"
                          "autopilot), so first claims pay dispatch, "
                          "not compile (docs/COMPILECACHE.md)")

    pcc = sub.add_parser("cache",
                         help="shape-bucketed AOT compile cache: "
                              "pre-warm the bucket ladder, list/"
                              "inspect the entry store, or clear it "
                              "(docs/COMPILECACHE.md)")
    pcc.add_argument("action", choices=("warm", "ls", "stats", "clear"))
    pcc.add_argument("--sizes", default=None,
                     help="comma-separated txn-count rungs to warm "
                          "(default: the pow2 bucket ladder "
                          "64..1024)")
    pcc.add_argument("--max-txns", dest="max_txns", type=int,
                     default=None,
                     help="cap the default ladder at this txn "
                          "count's pow2 bucket (rungs above it are "
                          "dropped; a bucket past 1024 extends the "
                          "ladder to it by doubling)")
    pcc.add_argument("--families", default="la,rw",
                     help="workload families to warm (la = "
                          "list-append infer + core check, rw = "
                          "rw-register core check)")
    pcc.add_argument("--max-k", dest="max_k", type=int, default=128,
                     help="key-space ceiling fed to the warm "
                          "generators")
    pcc.add_argument("--json", action="store_true",
                     help="machine-readable output (warm/stats)")

    def dispatch(opts: argparse.Namespace) -> int:
        if opts.cmd == "test":
            return run_test_cmd(test_fn, opts)
        if opts.cmd == "serve":
            return serve_cmd(opts)
        if opts.cmd == "analyze":
            return analyze_cmd(opts, checker_fn)
        if opts.cmd == "trace":
            return trace_cmd(opts)
        if opts.cmd == "tail":
            return tail_cmd(opts)
        if opts.cmd == "shrink":
            return shrink_cmd(opts, checker_fn)
        if opts.cmd == "campaign":
            return campaign_cmd(opts)
        if opts.cmd == "fleet":
            return fleet_cmd(opts)
        if opts.cmd == "cache":
            return cache_cmd(opts)
        if opts.cmd == "obs":
            return obs_cmd(opts)
        p.error(f"unknown command {opts.cmd}")
        return 2

    return p, dispatch


def test_all_cmd(test_fns: Dict[str, Callable], **kw):
    """Like single_test_cmd but runs a whole named suite via
    `test-all [names...]` (reference `test-all-cmd`)."""

    def all_fn(topts: Dict[str, Any]) -> Dict[str, Any]:
        raise RuntimeError("use dispatch, not all_fn")

    p, base_dispatch = single_test_cmd(all_fn, **kw)
    sub = next(a for a in p._actions
               if isinstance(a, argparse._SubParsersAction))
    pall = sub.add_parser("test-all", help="run every named test")
    add_test_opts(pall)
    pall.add_argument("--only", action="append",
                      help="subset of test names to run")

    def dispatch(opts: argparse.Namespace) -> int:
        if opts.cmd == "test-all":
            rc = 0
            names = opts.only or list(test_fns)
            unknown = [n for n in names if n not in test_fns]
            if unknown:
                print(f"unknown test(s): {', '.join(unknown)} "
                      f"(have: {', '.join(test_fns)})", file=sys.stderr)
                return 2
            for name in names:
                logger.info("test-all: %s", name)
                rc |= run_test_cmd(test_fns[name], opts)
            return rc
        if opts.cmd == "test":
            if len(test_fns) != 1:
                print("multiple tests defined; use test-all "
                      f"(have: {', '.join(test_fns)})", file=sys.stderr)
                return 2
            return run_test_cmd(next(iter(test_fns.values())), opts)
        return base_dispatch(opts)

    return p, dispatch


class _JsonFormatter(logging.Formatter):
    """JSON log lines with properly escaped messages (--logging-json)."""

    def format(self, record: logging.LogRecord) -> str:
        import json
        return json.dumps({
            "t": self.formatTime(record),
            "lvl": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        })


def run(parser_dispatch, argv: Optional[Sequence[str]] = None) -> int:
    """-main scaffold: parse, set up logging, dispatch, exit code."""
    p, dispatch = parser_dispatch
    opts = p.parse_args(argv)
    # truthy ALLOWlist: unrecognized spellings (off/none/disabled) must
    # not silently downgrade a TPU box to CPU — but warn, because an
    # IGNORED truthy-intent spelling means the process will go on to
    # dial the TPU, which HANGS when the tunnel is down
    env_cpu = os.environ.get("JT_FORCE_CPU", "").strip().lower()
    if env_cpu and env_cpu not in ("1", "true", "yes", "on",
                                   "0", "false", "no", "off"):
        print(f"warning: ignoring unrecognized JT_FORCE_CPU={env_cpu!r} "
              "(use 1/true/yes/on)", file=sys.stderr)
    if getattr(opts, "cpu", False) or env_cpu in ("1", "true", "yes",
                                                  "on"):
        # must happen before the first jax backend init (checkers);
        # see utils.backend for why JAX_PLATFORMS=cpu alone is not enough
        from jepsen_tpu.utils.backend import force_cpu_backend

        force_cpu_backend()
    if getattr(opts, "logging_json", False):
        h = logging.StreamHandler()
        h.setFormatter(_JsonFormatter())
        logging.basicConfig(level=logging.INFO, handlers=[h])
    else:
        logging.basicConfig(
            level=logging.INFO,
            format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    return dispatch(opts)


def main(parser_dispatch, argv: Optional[Sequence[str]] = None) -> None:
    sys.exit(run(parser_dispatch, argv))
