"""Clock-skew nemesis.

Equivalent of the reference's `jepsen/nemesis/time.clj` + the compiled C
helper (SURVEY.md §2.1): uploads `bump_time.c` to each node, compiles it
with the node's `cc`, then serves ops:

- ``bump-clock``   value = ms offset, or {node: ms} — jump clocks
- ``strobe-clock`` value = {"delta_ms", "period_ms", "duration_ms"}
- ``reset-clock``  re-sync node clocks to the control host's time
- ``check-clock-offsets`` sample each node's offset (for the clock plot)

Requires the OS layer to have disabled NTP (os_setup.Debian does).
"""

from __future__ import annotations

import os
import time as _time
from typing import Dict, Optional

from jepsen_tpu import control
from jepsen_tpu.control import on_nodes
from jepsen_tpu.nemesis.core import Nemesis

HELPER_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "resources", "bump_time.c")
REMOTE_SRC = "/tmp/jepsen/bump_time.c"
REMOTE_BIN = "/tmp/jepsen/bump_time"


def install(test: dict) -> None:
    """Upload and compile the helper on every node (reference:
    `nemesis.time/install!`)."""

    def fn(t, node):
        control.exec_("mkdir", "-p", "/tmp/jepsen")
        control.upload(HELPER_SRC, REMOTE_SRC)
        control.exec_("cc", "-O2", "-o", REMOTE_BIN, REMOTE_SRC)
    on_nodes(test, fn)


def bump_time(ms: float) -> None:
    """Jump the current node's clock by ms (run within a session)."""
    control.exec_(REMOTE_BIN, "bump", str(int(ms)))


def strobe_time(delta_ms: float, period_ms: float, duration_ms: float
                ) -> None:
    control.exec_(REMOTE_BIN, "strobe", str(int(delta_ms)),
                  str(int(period_ms)), str(int(duration_ms)))


def reset_time() -> None:
    """Set the current node's clock to the control host's time (reference
    resets via ntpdate; we write the coordinator's clock directly so no
    NTP server is needed)."""
    control.exec_("date", "-u", "-s", "@" + str(_time.time()))


def clock_offset_ms() -> float:
    """Node wall clock minus control wall clock, in ms (sampled; includes
    command latency — fine for plots, not for science)."""
    t0 = _time.time()
    node_s = float(control.exec_("date", "+%s.%N"))
    t1 = _time.time()
    return (node_s - (t0 + t1) / 2.0) * 1000.0


class ClockNemesis(Nemesis):
    """The clock nemesis (reference `nemesis.time/clock-nemesis`)."""

    def setup(self, test):
        install(test)
        # stop ntp daemons in case the OS layer didn't
        on_nodes(test, lambda t, n: control.exec_result(
            "bash", "-c",
            "systemctl stop ntp systemd-timesyncd chrony 2>/dev/null; true"))
        return self

    def invoke(self, test, op):
        f, v = op["f"], op.get("value")
        if f == "bump-clock":
            # value: ms, or {node: ms}
            bumps: Dict[str, float]
            if isinstance(v, dict):
                bumps = v
            else:
                bumps = {n: float(v or 0) for n in test["nodes"]}
            res = on_nodes(test,
                           lambda t, n: bump_time(bumps[n]),
                           nodes=list(bumps))
            return dict(op, type="info", value=bumps)
        if f == "strobe-clock":
            v = v or {}
            on_nodes(test, lambda t, n: strobe_time(
                v.get("delta_ms", 200), v.get("period_ms", 10),
                v.get("duration_ms", 1000)),
                nodes=v.get("nodes") or test["nodes"])
            return dict(op, type="info")
        if f == "reset-clock":
            on_nodes(test, lambda t, n: reset_time(),
                     nodes=(v if isinstance(v, list) else None)
                     or test["nodes"])
            return dict(op, type="info")
        if f == "check-clock-offsets":
            offs = on_nodes(test, lambda t, n: clock_offset_ms())
            return dict(op, type="info", value=offs)
        raise ValueError(f"clock nemesis can't handle f={f!r}")

    def teardown(self, test):
        try:
            on_nodes(test, lambda t, n: reset_time())
        except Exception:
            pass
