"""Composable fault packages: nemesis + generator + perf metadata.

Equivalent of the reference's `jepsen/nemesis/combined.clj` (SURVEY.md
§2.1): `nemesis_package(opts)` assembles fault packages — partition,
kill, pause, clock, file corruption, custom — each a dict

    {"nemesis":  Nemesis,
     "generator": fault-op generator (already nemesis-thread scoped),
     "final_generator": heal/recover ops run at test end,
     "perf": {"name", "start", "stop", "fs"}}  # for plot shading

and composes the requested ones into a single package whose nemesis is a
`compose` over sub-nemeses and whose generator interleaves fault
schedules (interval-driven: sleep -> start -> sleep -> stop -> ...).

`opts["faults"]` picks packages: any of {"partition", "kill", "pause",
"clock", "file", "traffic"}; `opts["interval"]` (seconds, default 10)
spaces fault start/stop pairs; `opts["db"]` supplies Process/Pause
facets for kill/pause; `opts["file"]` the corruption target.
"""

from __future__ import annotations

import random as _random
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu import db as db_
from jepsen_tpu import generator as gen
from jepsen_tpu.control import on_nodes
from jepsen_tpu.nemesis import core as nc
from jepsen_tpu.nemesis.file import FileCorruptionNemesis
from jepsen_tpu.nemesis.time import ClockNemesis

DEFAULT_INTERVAL = 10.0


# ---------------------------------------------------------------- partition

def partition_package(opts: dict) -> Optional[dict]:
    if "partition" not in opts.get("faults", ()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    targets = opts.get("partition_targets") or [
        nc.partition_random_halves, nc.partition_random_node,
        nc.partition_majorities_ring]

    def start(test, ctx):
        grudge_fn = rng.choice(targets)
        return {"f": "start-partition",
                "value": grudge_fn(test["nodes"])}

    return {
        "nemesis": nc.partitioner(),
        "generator": gen.cycle([gen.sleep(interval), gen.once(start),
                                gen.sleep(interval),
                                {"f": "stop-partition", "value": None}]),
        "final_generator": {"f": "stop-partition", "value": None},
        "perf": {"name": "partition", "start": {"start-partition"},
                 "stop": {"stop-partition"}, "fs": set()},
    }


# ---------------------------------------------------------------- kill/pause

def _db_nodes_targeter(rng, targeting: str = "one"):
    def targeter(test, nodes):
        if targeting == "all":
            return list(nodes)
        if targeting == "majority":
            from jepsen_tpu.utils.core import majority
            k = majority(len(nodes))
            return rng.sample(list(nodes), k)
        if targeting == "minority":
            from jepsen_tpu.utils.core import minority
            k = max(1, minority(len(nodes)))
            return rng.sample(list(nodes), k)
        return [rng.choice(list(nodes))]
    return targeter


class DBNemesis(nc.Nemesis):
    """Kill/restart or pause/resume the db via its Process/Pause facets
    (reference `nemesis.combined/db-nemesis`)."""

    def __init__(self, db, targeter, *, mode: str = "kill"):
        self.db = db
        self.targeter = targeter
        self.mode = mode
        self.affected: List[str] = []

    def invoke(self, test, op):
        f = op["f"]
        db = self.db
        if f in ("kill", "pause"):
            targets = list(op.get("value") or
                           self.targeter(test, test["nodes"]))
            fn = db.kill if f == "kill" else db.pause
            res = on_nodes(test, lambda t, n: fn(t, n), nodes=targets)
            self.affected = targets
            return dict(op, type="info", value=targets)
        if f in ("start", "resume"):
            targets = self.affected or test["nodes"]
            fn = db.start if f == "start" else db.resume
            res = on_nodes(test, lambda t, n: fn(t, n), nodes=targets)
            self.affected = []
            return dict(op, type="info", value=targets)
        raise ValueError(f"db nemesis can't handle f={f!r}")

    def teardown(self, test):
        if self.affected:
            fn = self.db.start if self.mode == "kill" else self.db.resume
            try:
                on_nodes(test, lambda t, n: fn(t, n), nodes=self.affected)
            except Exception:
                pass
            self.affected = []


def kill_package(opts: dict) -> Optional[dict]:
    if "kill" not in opts.get("faults", ()):
        return None
    db = opts.get("db")
    if not db_.supports(db, db_.Process):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    targeter = _db_nodes_targeter(rng, opts.get("kill_targeting", "one"))
    return {
        "nemesis": DBNemesis(db, targeter, mode="kill"),
        "generator": gen.cycle([gen.sleep(interval),
                                {"f": "kill", "value": None},
                                gen.sleep(interval),
                                {"f": "start", "value": None}]),
        "final_generator": {"f": "start", "value": None},
        "perf": {"name": "kill", "start": {"kill"}, "stop": {"start"},
                 "fs": set()},
    }


def pause_package(opts: dict) -> Optional[dict]:
    if "pause" not in opts.get("faults", ()):
        return None
    db = opts.get("db")
    if not db_.supports(db, db_.Pause):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    targeter = _db_nodes_targeter(rng, opts.get("pause_targeting", "one"))
    return {
        "nemesis": DBNemesis(db, targeter, mode="pause"),
        "generator": gen.cycle([gen.sleep(interval),
                                {"f": "pause", "value": None},
                                gen.sleep(interval),
                                {"f": "resume", "value": None}]),
        "final_generator": {"f": "resume", "value": None},
        "perf": {"name": "pause", "start": {"pause"}, "stop": {"resume"},
                 "fs": set()},
    }


# ---------------------------------------------------------------- clock

def clock_package(opts: dict) -> Optional[dict]:
    if "clock" not in opts.get("faults", ()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random

    def bump(test, ctx):
        node = rng.choice(test["nodes"])
        ms = rng.choice([-1, 1]) * rng.choice([100, 1000, 10_000, 60_000])
        return {"f": "bump-clock", "value": {node: ms}}

    def strobe(test, ctx):
        return {"f": "strobe-clock",
                "value": {"delta_ms": rng.choice([50, 200, 1000]),
                          "period_ms": rng.choice([2, 10, 50]),
                          "duration_ms": 1000,
                          "nodes": [rng.choice(test["nodes"])]}}

    return {
        "nemesis": ClockNemesis(),
        "generator": gen.cycle([gen.sleep(interval),
                                gen.once(gen.mix([bump, strobe], rng=rng)),
                                gen.sleep(interval),
                                {"f": "reset-clock", "value": None}]),
        "final_generator": {"f": "reset-clock", "value": None},
        "perf": {"name": "clock", "start": {"bump-clock", "strobe-clock"},
                 "stop": {"reset-clock"}, "fs": set()},
    }


# ---------------------------------------------------------------- file

def file_package(opts: dict) -> Optional[dict]:
    if "file" not in opts.get("faults", ()):
        return None
    path = opts.get("file")
    if not path:
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random

    def corrupt(test, ctx):
        node = rng.choice(test["nodes"])
        f = rng.choice(["bitflip-file", "truncate-file"])
        return {"f": f, "value": {"file": path, "nodes": [node]}}

    return {
        "nemesis": FileCorruptionNemesis(path),
        "generator": gen.cycle([gen.sleep(interval), gen.once(corrupt)]),
        "final_generator": None,
        "perf": {"name": "file",
                 "start": {"bitflip-file", "truncate-file"},
                 "stop": set(), "fs": set()},
    }


# ---------------------------------------------------------------- traffic

def traffic_package(opts: dict) -> Optional[dict]:
    """Traffic-shaping fault package: drives the `Net.slow/flaky/shape`
    protocol (which no package exercised before) through a
    :class:`~jepsen_tpu.nemesis.core.TrafficShaper`.  Each cycle picks
    one shaping mode at random, holds it for `interval`, then heals
    with ``fast``."""
    if "traffic" not in opts.get("faults", ()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random

    def degrade(test, ctx):
        f = rng.choice(["slow", "flaky", "shape"])
        value = {
            "slow": {"mean_ms": float(rng.choice([20, 50, 200])),
                     "variance_ms": float(rng.choice([5, 10, 50]))},
            "flaky": {"loss_pct": float(rng.choice([5, 20, 45])),
                      "correlation_pct": 75.0},
            "shape": ["delay", f"{rng.choice([10, 100, 500])}ms",
                      "loss", f"{rng.choice([1, 5])}%"],
        }[f]
        return {"f": f, "value": value}

    return {
        "nemesis": nc.traffic_shaper(),
        "generator": gen.cycle([gen.sleep(interval), gen.once(degrade),
                                gen.sleep(interval),
                                {"f": "fast", "value": None}]),
        "final_generator": {"f": "fast", "value": None},
        "perf": {"name": "traffic", "start": {"slow", "flaky", "shape"},
                 "stop": {"fast"}, "fs": set()},
    }


# ------------------------------------------------------------- sim skew

def skew_package(opts: dict) -> Optional[dict]:
    """Clock-skew package for the in-process sim cluster: drives
    :class:`~jepsen_tpu.nemesis.sim.SimClockSkewNemesis` on an
    interval schedule (skew -> hold -> heal), FAKETIME-spec'd offsets
    in the op values.  Fault key ``"skew"`` (the real-cluster
    ``"clock"`` package stays separate — it needs nodes)."""
    if "skew" not in opts.get("faults", ()):
        return None
    from jepsen_tpu.nemesis.sim import SimClockSkewNemesis

    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    return {
        "nemesis": SimClockSkewNemesis(
            rng if isinstance(rng, _random.Random) else None),
        "generator": gen.cycle([gen.sleep(interval),
                                {"f": "start-skew", "value": None},
                                gen.sleep(interval),
                                {"f": "stop-skew", "value": None}]),
        "final_generator": {"f": "stop-skew", "value": None},
        "perf": {"name": "skew", "start": {"start-skew"},
                 "stop": {"stop-skew"}, "fs": set()},
    }


# ------------------------------------------------------- sim membership

def membership_package(opts: dict) -> Optional[dict]:
    """Membership-change package for the sim cluster: a
    :class:`~jepsen_tpu.nemesis.membership.MembershipNemesis` over
    :class:`~jepsen_tpu.nemesis.sim.SimMembershipState` (join/leave
    against the store's member set).  Fault key ``"membership"``.
    A db suite supplies its own state via ``opts["membership_state"]``."""
    if "membership" not in opts.get("faults", ()):
        return None
    from jepsen_tpu.nemesis.membership import (MembershipNemesis,
                                               possible_op)
    from jepsen_tpu.nemesis.sim import SimMembershipState

    interval = opts.get("interval", DEFAULT_INTERVAL)
    state = opts.get("membership_state") or SimMembershipState(
        opts.get("nodes") or ["n1", "n2", "n3"])
    nem = MembershipNemesis(
        state,
        converge_timeout_s=opts.get("membership_timeout_s", 5.0),
        poll_interval_s=opts.get("membership_poll_s", 0.05))

    def next_change(test, ctx):
        op = possible_op(state, test)
        return op or {"f": "membership-view", "value": None}

    return {
        "nemesis": nem,
        "generator": gen.cycle([gen.sleep(interval),
                                gen.once(next_change)]),
        "final_generator": None,
        "perf": {"name": "membership",
                 "start": {"leave-node", "join-node"},
                 "stop": set(), "fs": {"membership-view"}},
    }


# ---------------------------------------------------------------- compose

PACKAGE_FNS = [partition_package, kill_package, pause_package,
               clock_package, file_package, traffic_package,
               skew_package, membership_package]


def _fs_of(pkg: dict) -> set:
    perf = pkg.get("perf") or {}
    return (set(perf.get("start", ())) | set(perf.get("stop", ()))
            | set(perf.get("fs", ())))


def compose_packages(pkgs: Sequence[dict]) -> dict:
    """Combine packages: compose nemeses by their op fs; interleave
    generators with `any_gen`; chain final generators
    (reference `nemesis.combined/compose-packages`)."""
    pkgs = [p for p in pkgs if p]
    if not pkgs:
        return {"nemesis": nc.Noop(), "generator": None,
                "final_generator": None, "perf": []}
    dispatch = {}
    for p in pkgs:
        fs = _fs_of(p)
        if not fs:
            continue
        dispatch[tuple(sorted(fs))] = p["nemesis"]
    gens = [p["generator"] for p in pkgs if p.get("generator")]
    finals = [p["final_generator"] for p in pkgs
              if p.get("final_generator")]
    return {
        "nemesis": nc.compose(dispatch),
        "generator": gen.any_gen(*gens) if gens else None,
        "final_generator": finals or None,
        "perf": [p.get("perf") for p in pkgs if p.get("perf")],
    }


def nemesis_package(opts: dict) -> dict:
    """Build the combined fault package for `opts` (reference
    `nemesis.combined/nemesis-package`).

    The returned package's generator is nemesis-thread scoped — drop it
    into `test["nemesis_generator"]` or `gen.nemesis(...)` yourself.
    """
    extra = opts.get("extra_packages") or []
    pkgs = [fn(opts) for fn in PACKAGE_FNS] + list(extra)
    return compose_packages(pkgs)
