"""Composable fault packages: nemesis + generator + perf metadata.

Equivalent of the reference's `jepsen/nemesis/combined.clj` (SURVEY.md
§2.1): `nemesis_package(opts)` assembles fault packages — partition,
kill, pause, clock, file corruption, custom — each a dict

    {"nemesis":  Nemesis,
     "generator": fault-op generator (already nemesis-thread scoped),
     "final_generator": heal/recover ops run at test end,
     "perf": {"name", "start", "stop", "fs"}}  # for plot shading

and composes the requested ones into a single package whose nemesis is a
`compose` over sub-nemeses and whose generator interleaves fault
schedules (interval-driven: sleep -> start -> sleep -> stop -> ...).

`opts["faults"]` picks packages: any of {"partition", "kill", "pause",
"clock", "file", "traffic"}; `opts["interval"]` (seconds, default 10)
spaces fault start/stop pairs; `opts["db"]` supplies Process/Pause
facets for kill/pause; `opts["file"]` the corruption target.
"""

from __future__ import annotations

import random as _random
import time as _time
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu import db as db_
from jepsen_tpu import generator as gen
from jepsen_tpu.control import on_nodes
from jepsen_tpu.nemesis import core as nc
from jepsen_tpu.nemesis.file import FileCorruptionNemesis
from jepsen_tpu.nemesis.time import ClockNemesis

DEFAULT_INTERVAL = 10.0


# ---------------------------------------------------------------- partition

def _partition_start(opts: dict):
    """The start-partition op factory (shared between the interval
    package and the window schedule): rng-chosen grudge over the
    test's nodes at emit time."""
    rng = opts.get("rng") or _random
    targets = opts.get("partition_targets") or [
        nc.partition_random_halves, nc.partition_random_node,
        nc.partition_majorities_ring]

    def start(test, ctx):
        grudge_fn = rng.choice(targets)
        return {"f": "start-partition",
                "value": grudge_fn(test["nodes"])}

    return start


def partition_package(opts: dict) -> Optional[dict]:
    if "partition" not in opts.get("faults", ()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    start = _partition_start(opts)

    return {
        "nemesis": nc.partitioner(),
        "generator": gen.cycle([gen.sleep(interval), gen.once(start),
                                gen.sleep(interval),
                                {"f": "stop-partition", "value": None}]),
        "final_generator": {"f": "stop-partition", "value": None},
        "perf": {"name": "partition", "start": {"start-partition"},
                 "stop": {"stop-partition"}, "fs": set()},
    }


# ---------------------------------------------------------------- kill/pause

def _db_nodes_targeter(rng, targeting: str = "one"):
    def targeter(test, nodes):
        if targeting == "all":
            return list(nodes)
        if targeting == "majority":
            from jepsen_tpu.utils.core import majority
            k = majority(len(nodes))
            return rng.sample(list(nodes), k)
        if targeting == "minority":
            from jepsen_tpu.utils.core import minority
            k = max(1, minority(len(nodes)))
            return rng.sample(list(nodes), k)
        return [rng.choice(list(nodes))]
    return targeter


class DBNemesis(nc.Nemesis):
    """Kill/restart or pause/resume the db via its Process/Pause facets
    (reference `nemesis.combined/db-nemesis`)."""

    def __init__(self, db, targeter, *, mode: str = "kill"):
        self.db = db
        self.targeter = targeter
        self.mode = mode
        self.affected: List[str] = []

    def invoke(self, test, op):
        f = op["f"]
        db = self.db
        if f in ("kill", "pause"):
            targets = list(op.get("value") or
                           self.targeter(test, test["nodes"]))
            fn = db.kill if f == "kill" else db.pause
            res = on_nodes(test, lambda t, n: fn(t, n), nodes=targets)
            self.affected = targets
            return dict(op, type="info", value=targets)
        if f in ("start", "resume"):
            targets = self.affected or test["nodes"]
            fn = db.start if f == "start" else db.resume
            res = on_nodes(test, lambda t, n: fn(t, n), nodes=targets)
            self.affected = []
            return dict(op, type="info", value=targets)
        raise ValueError(f"db nemesis can't handle f={f!r}")

    def teardown(self, test):
        if self.affected:
            fn = self.db.start if self.mode == "kill" else self.db.resume
            try:
                on_nodes(test, lambda t, n: fn(t, n), nodes=self.affected)
            except Exception:
                pass
            self.affected = []


def kill_package(opts: dict) -> Optional[dict]:
    if "kill" not in opts.get("faults", ()):
        return None
    db = opts.get("db")
    if not db_.supports(db, db_.Process):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    targeter = _db_nodes_targeter(rng, opts.get("kill_targeting", "one"))
    return {
        "nemesis": DBNemesis(db, targeter, mode="kill"),
        "generator": gen.cycle([gen.sleep(interval),
                                {"f": "kill", "value": None},
                                gen.sleep(interval),
                                {"f": "start", "value": None}]),
        "final_generator": {"f": "start", "value": None},
        "perf": {"name": "kill", "start": {"kill"}, "stop": {"start"},
                 "fs": set()},
    }


def pause_package(opts: dict) -> Optional[dict]:
    if "pause" not in opts.get("faults", ()):
        return None
    db = opts.get("db")
    if not db_.supports(db, db_.Pause):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    targeter = _db_nodes_targeter(rng, opts.get("pause_targeting", "one"))
    return {
        "nemesis": DBNemesis(db, targeter, mode="pause"),
        "generator": gen.cycle([gen.sleep(interval),
                                {"f": "pause", "value": None},
                                gen.sleep(interval),
                                {"f": "resume", "value": None}]),
        "final_generator": {"f": "resume", "value": None},
        "perf": {"name": "pause", "start": {"pause"}, "stop": {"resume"},
                 "fs": set()},
    }


# ---------------------------------------------------------------- clock

def _clock_bump(opts: dict):
    """The bump-clock op factory (shared with the window schedule)."""
    rng = opts.get("rng") or _random

    def bump(test, ctx):
        node = rng.choice(test["nodes"])
        ms = rng.choice([-1, 1]) * rng.choice([100, 1000, 10_000, 60_000])
        return {"f": "bump-clock", "value": {node: ms}}

    return bump


def clock_package(opts: dict) -> Optional[dict]:
    if "clock" not in opts.get("faults", ()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    bump = _clock_bump(opts)

    def strobe(test, ctx):
        return {"f": "strobe-clock",
                "value": {"delta_ms": rng.choice([50, 200, 1000]),
                          "period_ms": rng.choice([2, 10, 50]),
                          "duration_ms": 1000,
                          "nodes": [rng.choice(test["nodes"])]}}

    return {
        "nemesis": ClockNemesis(),
        "generator": gen.cycle([gen.sleep(interval),
                                gen.once(gen.mix([bump, strobe], rng=rng)),
                                gen.sleep(interval),
                                {"f": "reset-clock", "value": None}]),
        "final_generator": {"f": "reset-clock", "value": None},
        "perf": {"name": "clock", "start": {"bump-clock", "strobe-clock"},
                 "stop": {"reset-clock"}, "fs": set()},
    }


# ---------------------------------------------------------------- file

def file_package(opts: dict) -> Optional[dict]:
    if "file" not in opts.get("faults", ()):
        return None
    path = opts.get("file")
    if not path:
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random

    def corrupt(test, ctx):
        node = rng.choice(test["nodes"])
        f = rng.choice(["bitflip-file", "truncate-file"])
        return {"f": f, "value": {"file": path, "nodes": [node]}}

    return {
        "nemesis": FileCorruptionNemesis(path),
        "generator": gen.cycle([gen.sleep(interval), gen.once(corrupt)]),
        "final_generator": None,
        "perf": {"name": "file",
                 "start": {"bitflip-file", "truncate-file"},
                 "stop": set(), "fs": set()},
    }


# ---------------------------------------------------------------- traffic

def _traffic_degrade(opts: dict):
    """The traffic-degrade op factory (shared with the window
    schedule): one rng-chosen shaping mode per emit."""
    rng = opts.get("rng") or _random

    def degrade(test, ctx):
        f = rng.choice(["slow", "flaky", "shape"])
        value = {
            "slow": {"mean_ms": float(rng.choice([20, 50, 200])),
                     "variance_ms": float(rng.choice([5, 10, 50]))},
            "flaky": {"loss_pct": float(rng.choice([5, 20, 45])),
                      "correlation_pct": 75.0},
            "shape": ["delay", f"{rng.choice([10, 100, 500])}ms",
                      "loss", f"{rng.choice([1, 5])}%"],
        }[f]
        return {"f": f, "value": value}

    return degrade


def traffic_package(opts: dict) -> Optional[dict]:
    """Traffic-shaping fault package: drives the `Net.slow/flaky/shape`
    protocol (which no package exercised before) through a
    :class:`~jepsen_tpu.nemesis.core.TrafficShaper`.  Each cycle picks
    one shaping mode at random, holds it for `interval`, then heals
    with ``fast``."""
    if "traffic" not in opts.get("faults", ()):
        return None
    interval = opts.get("interval", DEFAULT_INTERVAL)
    degrade = _traffic_degrade(opts)

    return {
        "nemesis": nc.traffic_shaper(),
        "generator": gen.cycle([gen.sleep(interval), gen.once(degrade),
                                gen.sleep(interval),
                                {"f": "fast", "value": None}]),
        "final_generator": {"f": "fast", "value": None},
        "perf": {"name": "traffic", "start": {"slow", "flaky", "shape"},
                 "stop": {"fast"}, "fs": set()},
    }


# ------------------------------------------------------------- sim skew

def skew_package(opts: dict) -> Optional[dict]:
    """Clock-skew package for the in-process sim cluster: drives
    :class:`~jepsen_tpu.nemesis.sim.SimClockSkewNemesis` on an
    interval schedule (skew -> hold -> heal), FAKETIME-spec'd offsets
    in the op values.  Fault key ``"skew"`` (the real-cluster
    ``"clock"`` package stays separate — it needs nodes)."""
    if "skew" not in opts.get("faults", ()):
        return None
    from jepsen_tpu.nemesis.sim import SimClockSkewNemesis

    interval = opts.get("interval", DEFAULT_INTERVAL)
    rng = opts.get("rng") or _random
    return {
        "nemesis": SimClockSkewNemesis(
            rng if isinstance(rng, _random.Random) else None),
        "generator": gen.cycle([gen.sleep(interval),
                                {"f": "start-skew", "value": None},
                                gen.sleep(interval),
                                {"f": "stop-skew", "value": None}]),
        "final_generator": {"f": "stop-skew", "value": None},
        "perf": {"name": "skew", "start": {"start-skew"},
                 "stop": {"stop-skew"}, "fs": set()},
    }


# ------------------------------------------------------- sim membership

def membership_package(opts: dict) -> Optional[dict]:
    """Membership-change package for the sim cluster: a
    :class:`~jepsen_tpu.nemesis.membership.MembershipNemesis` over
    :class:`~jepsen_tpu.nemesis.sim.SimMembershipState` (join/leave
    against the store's member set).  Fault key ``"membership"``.
    A db suite supplies its own state via ``opts["membership_state"]``."""
    if "membership" not in opts.get("faults", ()):
        return None
    from jepsen_tpu.nemesis.membership import (MembershipNemesis,
                                               possible_op)
    from jepsen_tpu.nemesis.sim import SimMembershipState

    interval = opts.get("interval", DEFAULT_INTERVAL)
    state = opts.get("membership_state") or SimMembershipState(
        opts.get("nodes") or ["n1", "n2", "n3"])
    nem = MembershipNemesis(
        state,
        converge_timeout_s=opts.get("membership_timeout_s", 5.0),
        poll_interval_s=opts.get("membership_poll_s", 0.05))

    def next_change(test, ctx):
        op = possible_op(state, test)
        return op or {"f": "membership-view", "value": None}

    return {
        "nemesis": nem,
        "generator": gen.cycle([gen.sleep(interval),
                                gen.once(next_change)]),
        "final_generator": None,
        "perf": {"name": "membership",
                 "start": {"leave-node", "join-node"},
                 "stop": set(), "fs": {"membership-view"}},
    }


# ------------------------------------------------------- window schedule

#: fault families a campaign-level nemesis schedule can window
#: (ISSUE 11): family -> its package fn.  Window-shaped families emit a
#: start op at window open and a heal op at close; one-shot families
#: (file, membership) emit their single op at open.
WINDOW_FAULTS = {
    "partition": partition_package,
    "kill": kill_package,
    "pause": pause_package,
    "clock": clock_package,
    "file": file_package,
    "traffic": traffic_package,
    "skew": skew_package,
    "membership": membership_package,
}

#: families whose window has no closing op
_ONE_SHOT_FAULTS = frozenset({"file", "membership"})


def _window_events(fault: str, opts: dict):
    """(start, stop) event specs for one window of `fault` — the same
    op shapes the interval packages emit, minus the cycling.  `stop` is
    None for one-shot families."""
    if fault == "partition":
        return _partition_start(opts), {"f": "stop-partition",
                                        "value": None}
    if fault == "skew":
        return ({"f": "start-skew", "value": None},
                {"f": "stop-skew", "value": None})
    if fault == "kill":
        return ({"f": "kill", "value": None},
                {"f": "start", "value": None})
    if fault == "pause":
        return ({"f": "pause", "value": None},
                {"f": "resume", "value": None})
    if fault == "clock":
        return _clock_bump(opts), {"f": "reset-clock", "value": None}
    if fault == "traffic":
        return _traffic_degrade(opts), {"f": "fast", "value": None}
    if fault == "file":
        path = opts.get("file")
        rng = opts.get("rng") or _random

        def corrupt(test, ctx):
            node = rng.choice(test["nodes"])
            f = rng.choice(["bitflip-file", "truncate-file"])
            return {"f": f, "value": {"file": path, "nodes": [node]}}

        return corrupt, None
    if fault == "membership":
        from jepsen_tpu.nemesis.membership import possible_op

        state = opts["membership_state"]

        def next_change(test, ctx):
            op = possible_op(state, test)
            return op or {"f": "membership-view", "value": None}

        return next_change, None
    raise ValueError(f"unknown window fault family {fault!r} "
                     f"(have {sorted(WINDOW_FAULTS)})")


def _stamp_event(evt, stamp: dict):
    """Attach the window identity (pos/digest/fault/host) to an event's
    emitted op — it rides the op dict into `Op.ext`, survives store
    round-trips, and is what the cross-host fault-window ddmin groups
    on."""
    if callable(evt):
        def fn(test, ctx):
            op = evt(test, ctx)
            return dict(op, window=dict(stamp)) if op else op

        return fn
    return dict(evt, window=dict(stamp))


def schedule_package(opts: dict) -> dict:
    """Build a nemesis package from EXPLICIT window descriptors instead
    of interval cycling (the campaign-level nemesis schedule, ISSUE
    11): ``opts["windows"]`` is a list of ``{"pos", "fault", "at_s",
    "dur_s", "digest"}`` (see `campaign.plan.schedule_windows`); the
    generator emits each window's start op at its offset and its heal
    op at close, every op stamped with the window identity plus
    ``opts["host"]`` (the executing host, for cross-host witness
    attribution).  Families whose package is unavailable in this run
    (e.g. ``kill`` without a Process-capable db) have their windows
    skipped.

    Sub-nemeses, final heal ops, and perf metadata come from the
    ordinary interval packages (`compose_packages` shape), so
    downstream consumers cannot tell a scheduled window from an
    interval one — except by the window stamp."""
    windows = [w for w in (opts.get("windows") or ())
               if w.get("fault") in WINDOW_FAULTS]
    host = str(opts.get("host") or "")
    fams = []
    for w in windows:
        if w["fault"] not in fams:
            fams.append(w["fault"])
    if "membership" in fams and not opts.get("membership_state"):
        from jepsen_tpu.nemesis.sim import SimMembershipState

        opts = dict(opts, membership_state=SimMembershipState(
            opts.get("nodes") or ["n1", "n2", "n3"]))
    pkgs, alive = [], []
    for fam in fams:
        p = WINDOW_FAULTS[fam](dict(opts, faults={fam}))
        if p is not None:
            pkgs.append(p)
            alive.append(fam)
    base = compose_packages(pkgs)
    # wall-clock t0 alignment (ISSUE 13): when the campaign carries an
    # absolute anchor (opts["t0"], epoch seconds — a fleet worker's
    # claim-derived, clock-offset-corrected value), every window shifts
    # by (t0 - now) so its ABSOLUTE fire time matches the other hosts'
    # regardless of when each host's workload started.  An anchor in
    # the past clamps to 0 — relative semantics, the single-process
    # behavior, and window digests are anchor-free either way.
    shift = 0.0
    t0 = opts.get("t0")
    if isinstance(t0, (int, float)):
        shift = max(0.0, float(t0) - _time.time())
    timeline = []  # (time_s, order, event)
    for w in windows:
        if w["fault"] not in alive:
            continue
        start, stop = _window_events(w["fault"], opts)
        stamp = {"pos": w.get("pos"), "digest": w.get("digest"),
                 "fault": w["fault"], "host": host}
        timeline.append((shift + float(w["at_s"]), len(timeline),
                         _stamp_event(start, stamp)))
        if stop is not None and w["fault"] not in _ONE_SHOT_FAULTS:
            timeline.append((shift + float(w["at_s"]) + float(w["dur_s"]),
                             len(timeline), _stamp_event(stop, stamp)))
    timeline.sort(key=lambda t: (t[0], t[1]))
    seq, t_prev = [], 0.0
    for t, _, evt in timeline:
        if t > t_prev:
            seq.append(gen.sleep(t - t_prev))
            t_prev = t
        seq.append(gen.once(evt) if callable(evt) else evt)
    base["generator"] = seq or None
    return base


# ---------------------------------------------------------------- compose

PACKAGE_FNS = [partition_package, kill_package, pause_package,
               clock_package, file_package, traffic_package,
               skew_package, membership_package]


def _perf_list(pkg: dict) -> List[dict]:
    """A package's perf entries as a flat list — base packages carry
    one dict, COMPOSED packages a list (so composition must accept
    both to be closed under itself)."""
    perf = pkg.get("perf")
    if not perf:
        return []
    return [p for p in perf if p] if isinstance(perf, list) else [perf]


def _fs_of(pkg: dict) -> set:
    out: set = set()
    for perf in _perf_list(pkg):
        out |= (set(perf.get("start", ())) | set(perf.get("stop", ()))
                | set(perf.get("fs", ())))
    return out


def compose_packages(pkgs: Sequence[dict]) -> dict:
    """Combine packages: compose nemeses by their op fs; interleave
    generators with `any_gen`; chain final generators
    (reference `nemesis.combined/compose-packages`).  Closed under
    itself: an already-composed package (perf list, compose nemesis)
    composes again — its fs is the union of its entries', and its
    nested compose nemesis routes ops on — which is what lets a cell's
    own nemesis package stack with a campaign-level window schedule."""
    pkgs = [p for p in pkgs if p]
    if not pkgs:
        return {"nemesis": nc.Noop(), "generator": None,
                "final_generator": None, "perf": []}
    dispatch = {}
    for p in pkgs:
        fs = _fs_of(p)
        if not fs:
            continue
        dispatch[tuple(sorted(fs))] = p["nemesis"]
    gens = [p["generator"] for p in pkgs if p.get("generator")]
    finals = [p["final_generator"] for p in pkgs
              if p.get("final_generator")]
    return {
        "nemesis": nc.compose(dispatch),
        "generator": gen.any_gen(*gens) if gens else None,
        "final_generator": finals or None,
        "perf": [q for p in pkgs for q in _perf_list(p)],
    }


def nemesis_package(opts: dict) -> dict:
    """Build the combined fault package for `opts` (reference
    `nemesis.combined/nemesis-package`).

    The returned package's generator is nemesis-thread scoped — drop it
    into `test["nemesis_generator"]` or `gen.nemesis(...)` yourself.
    """
    extra = opts.get("extra_packages") or []
    pkgs = [fn(opts) for fn in PACKAGE_FNS] + list(extra)
    return compose_packages(pkgs)
