"""Membership-change nemesis: grow/shrink the cluster during a test.

Equivalent of the reference's `jepsen/nemesis/membership.clj` (SURVEY.md
§2.1): a *staged state-machine* nemesis.  The db-specific logic lives in
a `MembershipState` — how to read one node's view, how to merge the
per-node views into a cluster view, which ops are possible, how to apply
one, and when a pending op has taken effect ("resolved") in a view.

The nemesis keeps:
- the merged **view** and a **view log** (every distinct view observed,
  with its index and wall time — the reference's view history);
- a **pending set** of applied-but-unresolved ops.  After applying an op
  it polls the per-node views; when the op resolves against a merged
  view it completes **ok** (with the resolving view index).  On timeout
  the op completes **info** and *stays pending*: later invocations keep
  resolving it against newer views and report it in ``also-resolved`` —
  the synchronous-client rendering of the reference's async resolution.
"""

from __future__ import annotations

import time as _time
from typing import Any, Dict, List, Optional

from jepsen_tpu.nemesis.core import Nemesis


class MembershipState:
    """Db-specific membership protocol (reference: the `State` protocol).

    New implementations override the staged protocol (`node_view` /
    `merge_views` / `possible_ops` / `apply_op` / `resolve_op`); the
    legacy single-view protocol (`view` + `converged`) keeps working via
    the defaults.
    """

    # ---- lifecycle -------------------------------------------------------
    def setup(self, test: dict) -> None:
        pass

    def teardown(self, test: dict) -> None:
        pass

    # ---- staged protocol -------------------------------------------------
    def node_view(self, test: dict, node: Optional[str]) -> Any:
        """The cluster view from one node's perspective.  Default:
        delegate to the legacy whole-cluster `view`."""
        return self.view(test)

    def merge_views(self, test: dict, views: List[Any]) -> Any:
        """Combine per-node views into the canonical cluster view.
        Default: the first non-None view (single-source states)."""
        for v in views:
            if v is not None:
                return v
        return None

    def possible_ops(self, test: dict, view: Any) -> List[dict]:
        """Ops applicable now, e.g. [{"f": "leave-node", "value": "n3"}]."""
        raise NotImplementedError

    def apply_op(self, test: dict, op: dict) -> Any:
        """Start the change; return a result for the completion value.
        Returning a dict with ``{"status": "fail"}`` means the change
        definitely did NOT start (nothing entered any log): the nemesis
        completes the op ``fail`` and does not track it as pending."""
        raise NotImplementedError

    def resolve_op(self, test: dict, op: dict, result: Any,
                   view: Any) -> bool:
        """Has the change from `op` (with apply result `result`) taken
        effect in `view`?  Default: the legacy `converged`."""
        return self.converged(test, view, op)

    # ---- legacy protocol (still honored) ---------------------------------
    def view(self, test: dict) -> Any:
        """Current cluster view (e.g. member list), from the db's pov."""
        raise NotImplementedError

    def converged(self, test: dict, view: Any, op: dict) -> bool:
        """Has the change from `op` taken effect in `view`?"""
        return True


class MembershipNemesis(Nemesis):
    """Drives a MembershipState (reference
    `nemesis.membership/nemesis-for-state`).

    Ops:
    - any f the state's possible_ops produce (join/leave/grow/shrink...)
    - ``membership-view``: report the current merged view + log index
    """

    def __init__(self, state: MembershipState, *,
                 converge_timeout_s: float = 60.0,
                 poll_interval_s: float = 0.5):
        self.state = state
        self.converge_timeout_s = converge_timeout_s
        self.poll_interval_s = poll_interval_s
        self.view: Any = None
        self.view_log: List[Dict[str, Any]] = []
        self.pending: List[Dict[str, Any]] = []

    def setup(self, test):
        self.state.setup(test)
        self._refresh(test)
        return self

    # ---- view plumbing ---------------------------------------------------
    def _refresh(self, test) -> Any:
        v = merged_view(self.state, test)
        if not self.view_log or v != self.view_log[-1]["view"]:
            self.view_log.append({"i": len(self.view_log),
                                  "time": _time.time(), "view": v})
        self.view = v
        return v

    def _resolve_pending(self, test, view) -> List[Dict[str, Any]]:
        resolved, still = [], []
        for p in self.pending:
            try:
                done = self.state.resolve_op(test, p["op"], p["result"],
                                             view)
            except Exception:
                done = False
            (resolved if done else still).append(p)
        self.pending = still
        for p in resolved:
            p["view-index"] = self.view_log[-1]["i"] if self.view_log \
                else None
        return resolved

    # ---- nemesis protocol ------------------------------------------------
    def invoke(self, test, op):
        if op["f"] == "membership-view":
            v = self._refresh(test)
            return dict(op, type="info",
                        value={"view": v,
                               "view-index": self.view_log[-1]["i"]})

        result = self.state.apply_op(test, op)
        if isinstance(result, dict) and result.get("status") == "fail":
            # the state reports the change definitely did NOT start
            # (e.g. no quorum, nothing entered any log): a clean :fail —
            # it must not join the pending set, or an unrelated later
            # change could "resolve" it into a fault that never happened
            return dict(op, type="fail",
                        value={"result": result, "converged": False})
        entry = {"op": op, "result": result, "since": _time.time()}
        self.pending.append(entry)
        also: List[dict] = []
        deadline = _time.monotonic() + self.converge_timeout_s
        while _time.monotonic() < deadline:
            view = self._refresh(test)
            for p in self._resolve_pending(test, view):
                if p is entry:
                    # the change took effect: a definite ok completion
                    return dict(op, type="ok",
                                value={"result": result, "converged": True,
                                       "view-index": p["view-index"],
                                       "also-resolved": also})
                also.append({"f": p["op"]["f"], "value": p["op"]["value"],
                             "view-index": p["view-index"]})
            _time.sleep(self.poll_interval_s)
        # indeterminate: the op stays pending and may resolve during a
        # later invocation (reported there via also-resolved)
        return dict(op, type="info",
                    value={"result": result, "converged": False,
                           "pending": True, "also-resolved": also})

    def teardown(self, test):
        self.state.teardown(test)


def merged_view(state: MembershipState, test: dict) -> Any:
    """Gather per-node views (a dead/partitioned node yields None rather
    than crashing the caller — it's exactly the fault window membership
    tests create) and merge them."""
    if type(state).node_view is MembershipState.node_view:
        # legacy single-view state: node_view ignores the node, so N
        # polls would be N identical (possibly expensive) cluster fetches
        try:
            return state.view(test)
        except Exception:
            return None
    views = []
    for node in (test.get("nodes") or [None]):
        try:
            views.append(state.node_view(test, node))
        except Exception:
            views.append(None)
    return state.merge_views(test, views)


def possible_op(state: MembershipState, test: dict) -> Optional[dict]:
    """Generator helper: pick the next membership op, or None if the view
    offers nothing (used as `lambda t, ctx: possible_op(state, t)`)."""
    ops = state.possible_ops(test, merged_view(state, test))
    return ops[0] if ops else None
