"""Membership-change nemesis: grow/shrink the cluster during a test.

Equivalent of the reference's `jepsen/nemesis/membership.clj` (SURVEY.md
§2.1): a state-machine nemesis.  The db-specific logic lives in a
`MembershipState` — what the current view is, which ops are possible,
how to apply one, and when the cluster has converged after a change.
The nemesis polls the view, generates join/leave ops, applies them, and
blocks op completion until convergence (or times out to `info`).
"""

from __future__ import annotations

import time as _time
from typing import Any, List, Optional

from jepsen_tpu.nemesis.core import Nemesis


class MembershipState:
    """Db-specific membership protocol (reference: the `State` protocol)."""

    def view(self, test: dict) -> Any:
        """Current cluster view (e.g. member list), from the db's pov."""
        raise NotImplementedError

    def possible_ops(self, test: dict, view: Any) -> List[dict]:
        """Ops applicable now, e.g. [{"f": "leave-node", "value": "n3"}]."""
        raise NotImplementedError

    def apply_op(self, test: dict, op: dict) -> Any:
        """Perform the change; return a result for the completion value."""
        raise NotImplementedError

    def converged(self, test: dict, view: Any, op: dict) -> bool:
        """Has the change from `op` taken effect in `view`?"""
        return True


class MembershipNemesis(Nemesis):
    """Drives a MembershipState (reference
    `nemesis.membership/nemesis-for-state`).

    Ops:
    - any f the state's possible_ops produce (join/leave/grow/shrink...)
    - ``membership-view``: report the current view
    """

    def __init__(self, state: MembershipState, *,
                 converge_timeout_s: float = 60.0,
                 poll_interval_s: float = 0.5):
        self.state = state
        self.converge_timeout_s = converge_timeout_s
        self.poll_interval_s = poll_interval_s

    def setup(self, test):
        return self

    def invoke(self, test, op):
        if op["f"] == "membership-view":
            return dict(op, type="info", value=self.state.view(test))
        result = self.state.apply_op(test, op)
        deadline = _time.monotonic() + self.converge_timeout_s
        converged = False
        while _time.monotonic() < deadline:
            view = self.state.view(test)
            if self.state.converged(test, view, op):
                converged = True
                break
            _time.sleep(self.poll_interval_s)
        return dict(op, type="info",
                    value={"result": result, "converged": converged})

    def teardown(self, test):
        pass


def possible_op(state: MembershipState, test: dict) -> Optional[dict]:
    """Generator helper: pick the next membership op, or None if the view
    offers nothing (used as `lambda t, ctx: possible_op(state, t)`)."""
    ops = state.possible_ops(test, state.view(test))
    return ops[0] if ops else None
