"""File-corruption nemesis.

Equivalent of the reference's `jepsen/nemesis/file.clj` (SURVEY.md §2.1):
corrupt chunks of a db file on nodes — bitflip a random chunk, truncate
bytes off the end, or snapshot/restore chunks — implemented with `dd`
and `/dev/urandom` on the node (the (M)-confidence survey note says the
reference uses a deployed helper or dd; dd keeps us dependency-free).

Ops:
- ``bitflip-file``  value = {"file", "probability"? , "nodes"?}
- ``truncate-file`` value = {"file", "bytes"?, "nodes"?}
- ``snapshot-file`` value = {"file", "nodes"?}   (copy aside)
- ``restore-file``  value = {"file", "nodes"?}   (copy back)
"""

from __future__ import annotations

import random as _random
from typing import Optional, Sequence

from jepsen_tpu import control
from jepsen_tpu.control import on_nodes
from jepsen_tpu.control.core import escape
from jepsen_tpu.nemesis.core import Nemesis

SNAP_DIR = "/tmp/jepsen/snapshots"


def bitflip_chunk(path: str, *, chunk_size: int = 512,
                  rng: Optional[_random.Random] = None) -> str:
    """Overwrite one random chunk of `path` with urandom bytes, in place.
    Returns a description. Runs on the current node."""
    rng = rng or _random
    p = escape(path)
    script = (
        f"size=$(stat -c %s {p}); "
        f"if [ \"$size\" -lt {chunk_size} ]; then exit 0; fi; "
        f"chunks=$((size / {chunk_size})); "
        f"target=$((RANDOM * RANDOM % chunks)); "
        f"dd if=/dev/urandom of={p} bs={chunk_size} seek=$target count=1 "
        f"conv=notrunc 2>/dev/null; echo corrupted chunk $target of $chunks")
    return control.exec_("bash", "-c", script)


def truncate_file(path: str, bytes_: int = 64) -> str:
    """Chop `bytes_` off the end of path (reference truncation fault)."""
    p = escape(path)
    return control.exec_(
        "bash", "-c",
        f"size=$(stat -c %s {p}); "
        f"new=$((size > {bytes_} ? size - {bytes_} : 0)); "
        f"truncate -s $new {p}; echo truncated to $new")


def snapshot_file(path: str) -> None:
    p = escape(path)
    control.exec_("mkdir", "-p", SNAP_DIR)
    control.exec_("bash", "-c",
                  f"cp -p {p} {SNAP_DIR}/$(echo {p} | tr / _)")


def restore_file(path: str) -> None:
    p = escape(path)
    control.exec_("bash", "-c",
                  f"cp -p {SNAP_DIR}/$(echo {p} | tr / _) {p}")


class FileCorruptionNemesis(Nemesis):
    """Dispatches the corruption ops (reference
    `nemesis.file/corrupt-file-nemesis`)."""

    def __init__(self, default_file: Optional[str] = None):
        self.default_file = default_file

    def _targets(self, test, v) -> Sequence[str]:
        return (v or {}).get("nodes") or test["nodes"]

    def _file(self, v) -> str:
        f = (v or {}).get("file") or self.default_file
        if not f:
            raise ValueError("no file given for corruption op")
        return f

    def invoke(self, test, op):
        f, v = op["f"], op.get("value")
        path = self._file(v)
        nodes = self._targets(test, v)
        if f == "bitflip-file":
            res = on_nodes(test, lambda t, n: bitflip_chunk(path),
                           nodes=nodes)
        elif f == "truncate-file":
            res = on_nodes(test, lambda t, n: truncate_file(
                path, (v or {}).get("bytes", 64)), nodes=nodes)
        elif f == "snapshot-file":
            res = on_nodes(test, lambda t, n: snapshot_file(path),
                           nodes=nodes)
        elif f == "restore-file":
            res = on_nodes(test, lambda t, n: restore_file(path),
                           nodes=nodes)
        else:
            raise ValueError(f"file nemesis can't handle f={f!r}")
        return dict(op, type="info", value={"file": path, "nodes": res})
