"""Nemeses for the in-process sim cluster (`workloads.mem`).

The real-cluster nemeses (`nemesis/time.py` clock bumps via the
compiled helper, `nemesis/membership.py` over a db's views) need nodes;
these are their in-process twins, so campaign cells over the MemStore
sim can run the same fault *schedules* — and actually corrupt reads —
without SSH:

- :class:`SimClockSkewNemesis` — "clock skew" for a snapshot store:
  on ``start-skew`` it snapshots the store and puts it in *skewed read*
  mode, where whole-state reads observe a torn mix of the snapshot and
  the live state (exactly what a snapshot read built from per-node
  clocks that disagree looks like); ``stop-skew`` heals.  The skew
  magnitude is derived through `faketime.faketime_spec` /
  `faketime.rand_factor` so the op values carry the same FAKETIME
  offset strings a real libfaketime deployment would use.

- :class:`SimMembershipState` — a `MembershipState` over the sim
  cluster: views are the store's member set; ``leave-node`` /
  ``join-node`` converge after a configurable number of view polls,
  and clients bound to a removed node fail cleanly
  (``error="node-removed"``).  Drive it with the standard
  :class:`~jepsen_tpu.nemesis.membership.MembershipNemesis`.
"""

from __future__ import annotations

import random as _random
from typing import Any, List, Optional

from jepsen_tpu import faketime
from jepsen_tpu.nemesis.core import Nemesis
from jepsen_tpu.nemesis.membership import MembershipState

__all__ = ["SimClockSkewNemesis", "SimMembershipState", "store_of"]


def store_of(test: dict):
    """The sim cluster's MemStore, via the test's client."""
    client = test.get("client")
    store = getattr(client, "store", None)
    if store is None:
        raise ValueError("sim nemesis needs a MemClient-backed test "
                         "(no client.store found)")
    return store


class SimClockSkewNemesis(Nemesis):
    """Skew the sim store's read clock (reference: `nemesis/time.clj`'s
    role, realized for the in-process store).

    Ops:
    - ``start-skew`` value = {"offset_s", "rate", "faketime"} (filled
      in from the rng when absent) — snapshot the store and enter
      skewed-read mode;
    - ``stop-skew``  — heal (reads observe the live state again).
    """

    def __init__(self, rng: Optional[_random.Random] = None,
                 max_offset_s: float = 60.0):
        self.rng = rng or _random.Random()
        self.max_offset_s = max_offset_s

    def invoke(self, test, op):
        store = store_of(test)
        f = op["f"]
        if f == "start-skew":
            v = dict(op.get("value") or {})
            if "offset_s" not in v:
                v["offset_s"] = round(
                    self.rng.uniform(-self.max_offset_s,
                                     self.max_offset_s), 3)
            if "rate" not in v:
                v["rate"] = round(faketime.rand_factor(self.rng), 4)
            v["faketime"] = faketime.faketime_spec(v["offset_s"],
                                                   v.get("rate", 1.0))
            store.start_skew(self.rng.random())
            return dict(op, type="info", value=v)
        if f == "stop-skew":
            store.stop_skew()
            return dict(op, type="info")
        raise ValueError(f"sim clock-skew nemesis can't handle f={f!r}")

    def teardown(self, test):
        try:
            store_of(test).stop_skew()
        except Exception:
            pass


class SimMembershipState(MembershipState):
    """Membership over the sim store's member set.

    A change takes effect after `converge_polls` view polls (modelling
    config-propagation latency); the merged view is the member set.
    Clients whose node has left the view fail ops cleanly (the
    MemClient checks ``store.members``)."""

    def __init__(self, nodes: List[str], *, converge_polls: int = 1,
                 min_members: int = 1):
        self.initial = list(nodes)
        self.converge_polls = converge_polls
        self.min_members = min_members
        self._pending: Optional[tuple] = None
        self._store = None

    def setup(self, test):
        self._store = store_of(test)
        if getattr(self._store, "members", None) is None:
            self._store.members = set(self.initial)

    def view(self, test) -> Any:
        if self._pending is not None:
            op, polls = self._pending
            if polls <= 0:
                members = self._store.members
                if op["f"] == "leave-node":
                    members.discard(op["value"])
                else:
                    members.add(op["value"])
                self._pending = None
            else:
                self._pending = (op, polls - 1)
        return set(self._store.members)

    def possible_ops(self, test, view):
        out = []
        if view and len(view) > self.min_members:
            out.append({"f": "leave-node", "value": sorted(view)[-1],
                        "type": "invoke"})
        gone = [n for n in self.initial if n not in (view or ())]
        if gone:
            out.append({"f": "join-node", "value": gone[0],
                        "type": "invoke"})
        return out

    def apply_op(self, test, op):
        if self._pending is not None:
            return {"status": "fail", "reason": "change-in-flight"}
        self._pending = (op, self.converge_polls)
        return "requested"

    def converged(self, test, view, op):
        if op["f"] == "leave-node":
            return op["value"] not in view
        return op["value"] in view
