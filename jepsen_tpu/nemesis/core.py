"""Nemesis protocol, partitioners, and process-fault nemeses.

Equivalent of the reference's `jepsen/nemesis.clj` (SURVEY.md §2.1):
the `Nemesis` protocol (`setup`/`invoke`/`teardown`), the partitioner
nemesis with its grudge functions (`complete_grudge`, `bridge`,
`majorities_ring`, `partition_halves`, `partition_random_halves`,
`partition_random_node`), `compose` for routing ops to sub-nemeses,
`node_start_stopper` and `hammer_time` (SIGSTOP) process faults.

Grudges are maps {dst_node: set-of-src-nodes-to-block}, applied by
`net.drop_all`; a partition op's value carries the grudge, and `stop`
heals.
"""

from __future__ import annotations

import random as _random
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from jepsen_tpu import control
from jepsen_tpu import net as net_
from jepsen_tpu.control import on_nodes
from jepsen_tpu.utils.core import majority


def _net(test: dict) -> net_.Net:
    """The test's Net, defaulting to the noop net: nemeses must work on
    test maps without a ``"net"`` key (`core.noop_test` now carries
    one, but hand-built maps routinely don't — a KeyError here used to
    kill Partitioner.setup/invoke/teardown)."""
    return test.get("net") or net_.noop


class Nemesis:
    """Base nemesis: a single-threaded fault client
    (reference `nemesis/Nemesis`)."""

    def setup(self, test: dict) -> "Nemesis":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply a fault op; return its completion."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass


class Noop(Nemesis):
    """Does nothing (reference `nemesis/noop`)."""

    def invoke(self, test, op):
        return dict(op, type="info")


# ---------------------------------------------------------------------------
# Grudges: {dst: set(srcs blocked at dst)}

Grudge = Dict[str, Set[str]]


def complete_grudge(components: Sequence[Sequence[str]]) -> Grudge:
    """Each component can only see itself (reference
    `nemesis/complete-grudge`)."""
    grudge: Grudge = {}
    all_nodes = [n for comp in components for n in comp]
    for comp in components:
        others = set(all_nodes) - set(comp)
        for node in comp:
            grudge[node] = set(others)
    return grudge


def bridge(nodes: Sequence[str]) -> Grudge:
    """Splits nodes into two halves joined only by one bridge node
    (reference `nemesis/bridge`)."""
    nodes = list(nodes)
    mid = len(nodes) // 2
    b = nodes[mid]
    left, right = nodes[:mid], nodes[mid + 1:]
    grudge: Grudge = {b: set()}
    for n in left:
        grudge[n] = set(right)
    for n in right:
        grudge[n] = set(left)
    return grudge


def split_one(nodes: Sequence[str],
              node: Optional[str] = None,
              rng: Optional[_random.Random] = None) -> List[List[str]]:
    """Isolate one node (given or random) from the rest."""
    nodes = list(nodes)
    rng = rng or _random
    node = node if node is not None else rng.choice(nodes)
    return [[node], [n for n in nodes if n != node]]


def majorities_ring(nodes: Sequence[str],
                    rng: Optional[_random.Random] = None) -> Grudge:
    """Every node sees a majority, but no two majorities agree: node i
    sees itself and the (m-1)//2 neighbors on each side of a shuffled
    ring (reference `nemesis/majorities-ring`)."""
    nodes = list(nodes)
    rng = rng or _random
    ring = list(nodes)
    rng.shuffle(ring)
    n = len(ring)
    m = majority(n)
    half = (m - 1) // 2
    grudge: Grudge = {}
    for i, node in enumerate(ring):
        visible = {ring[(i + d) % n] for d in range(-half, half + 1)}
        grudge[node] = set(ring) - visible
    return grudge


def invert_grudge(nodes: Sequence[str], visible: Dict[str, Set[str]]
                  ) -> Grudge:
    """Turn a visibility map into a grudge."""
    return {n: set(nodes) - set(visible.get(n, ())) - {n} for n in nodes}


# Grudge-producing strategies for the partitioner.  Each takes the test's
# node list and returns a grudge.

def partition_halves(nodes: Sequence[str]) -> Grudge:
    """First half | second half (reference `nemesis/partition-halves`:
    used via `(partitioner (comp complete-grudge split-one ...))`)."""
    nodes = list(nodes)
    mid = (len(nodes) + 1) // 2
    return complete_grudge([nodes[:mid], nodes[mid:]])


def partition_random_halves(nodes: Sequence[str],
                            rng: Optional[_random.Random] = None) -> Grudge:
    nodes = list(nodes)
    rng = rng or _random
    shuffled = list(nodes)
    rng.shuffle(shuffled)
    mid = (len(shuffled) + 1) // 2
    return complete_grudge([shuffled[:mid], shuffled[mid:]])


def partition_random_node(nodes: Sequence[str],
                          rng: Optional[_random.Random] = None) -> Grudge:
    return complete_grudge(split_one(nodes, rng=rng))


def partition_majorities_ring(nodes: Sequence[str],
                              rng: Optional[_random.Random] = None
                              ) -> Grudge:
    return majorities_ring(nodes, rng=rng)


class Partitioner(Nemesis):
    """Applies partitions on `start-partition` ops and heals on
    `stop-partition` (reference `nemesis/partitioner`).

    `grudge_fn(nodes) -> grudge` picks the partition when the op's value
    doesn't already carry one.  The completion's value describes the
    applied grudge so the history records what actually happened.
    """

    def __init__(self, grudge_fn: Optional[Callable] = None, *,
                 start_f: str = "start-partition",
                 stop_f: str = "stop-partition"):
        self.grudge_fn = grudge_fn or partition_random_halves
        self.start_f = start_f
        self.stop_f = stop_f

    def setup(self, test):
        _net(test).heal(test)
        return self

    def invoke(self, test, op):
        if op["f"] == self.start_f:
            grudge = op.get("value") or self.grudge_fn(test["nodes"])
            net = _net(test)
            if hasattr(net, "drop_all"):
                net.drop_all(test, grudge)
            else:
                for dst, srcs in grudge.items():
                    for src in srcs:
                        net.drop_(test, src, dst)
            return dict(op, type="info",
                        value={d: sorted(s) for d, s in grudge.items()})
        elif op["f"] == self.stop_f:
            _net(test).heal(test)
            return dict(op, type="info", value="network healed")
        raise ValueError(f"partitioner can't handle op f={op['f']!r}")

    def teardown(self, test):
        _net(test).heal(test)


def partitioner(grudge_fn: Optional[Callable] = None, **kw) -> Nemesis:
    return Partitioner(grudge_fn, **kw)


class Compose(Nemesis):
    """Routes ops to sub-nemeses by an f-dispatch map (reference
    `nemesis/compose`).  Keys are sets/sequences of op :f values (or a
    predicate); values are nemeses."""

    def __init__(self, dispatch: Dict[Any, Nemesis]):
        self.dispatch = [(set(fs) if not callable(fs) else fs, nem)
                         for fs, nem in dispatch.items()]

    def _route(self, f) -> Nemesis:
        for fs, nem in self.dispatch:
            if (fs(f) if callable(fs) else f in fs):
                return nem
        raise ValueError(f"no nemesis handles op f={f!r}")

    def setup(self, test):
        self.dispatch = [(fs, nem.setup(test)) for fs, nem in self.dispatch]
        return self

    def invoke(self, test, op):
        return self._route(op["f"]).invoke(test, op)

    def teardown(self, test):
        for _, nem in self.dispatch:
            nem.teardown(test)


def compose(dispatch: Dict[Any, Nemesis]) -> Nemesis:
    # dict keys must be hashable: accept tuples/frozensets/callables
    return Compose(dispatch)


class NodeStartStopper(Nemesis):
    """On `start_f`, runs `stop_fn` on targeted nodes; on `stop_f`, runs
    `start_fn` on the affected ones (reference
    `nemesis/node-start-stopper`).  `targeter(test, nodes) -> nodes`."""

    def __init__(self, targeter: Callable, stop_fn: Callable,
                 start_fn: Callable, *, start_f: str = "start",
                 stop_f: str = "stop"):
        self.targeter = targeter
        self.stop_fn = stop_fn
        self.start_fn = start_fn
        self.start_f = start_f
        self.stop_f = stop_f
        self.affected: List[str] = []

    def invoke(self, test, op):
        if op["f"] == self.start_f:
            targets = list(self.targeter(test, test["nodes"]))
            res = on_nodes(test, self.stop_fn, nodes=targets)
            self.affected = targets
            return dict(op, type="info", value=res)
        elif op["f"] == self.stop_f:
            res = on_nodes(test, self.start_fn,
                           nodes=self.affected or test["nodes"])
            self.affected = []
            return dict(op, type="info", value=res)
        raise ValueError(f"can't handle op f={op['f']!r}")

    def teardown(self, test):
        if self.affected:
            on_nodes(test, self.start_fn, nodes=self.affected)
            self.affected = []


def node_start_stopper(targeter, stop_fn, start_fn, **kw) -> Nemesis:
    return NodeStartStopper(targeter, stop_fn, start_fn, **kw)


def hammer_time(process_pattern: str,
                targeter: Optional[Callable] = None) -> Nemesis:
    """SIGSTOP/SIGCONT a process by pgrep pattern on targeted nodes
    (reference `nemesis/hammer-time`)."""
    targeter = targeter or (lambda test, nodes: [_random.choice(nodes)])

    def _signal_all(sig: str) -> str:
        # not pkill: the invoking shell's cmdline contains the pattern
        p = control.escape(process_pattern)
        return (f"for p in $(pgrep -f -- {p}); do "
                f'[ "$p" != "$$" ] && [ "$p" != "$PPID" ] '
                f"&& kill -{sig} $p 2>/dev/null; done; true")

    def stop(test, node):
        control.exec_("bash", "-c", _signal_all("STOP"))
        return "paused"

    def start(test, node):
        control.exec_("bash", "-c", _signal_all("CONT"))
        return "resumed"

    return NodeStartStopper(targeter, stop, start,
                            start_f="start-pause", stop_f="stop-pause")


class TrafficShaper(Nemesis):
    """Drives the Net traffic-shaping protocol (the `net.py` methods
    nothing drove before this): ``slow``/``flaky``/``shape`` ops apply
    latency/loss/raw-netem behaviors cluster-wide; ``fast`` heals.

    Op values:
      slow  — kwargs dict for `Net.slow` (mean_ms, variance_ms,
              distribution); None for defaults
      flaky — kwargs dict for `Net.flaky` (loss_pct, correlation_pct)
      shape — raw netem behavior list, e.g. ["delay", "100ms",
              "loss", "5%"]
      fast  — ignored

    The completion's value echoes what was applied so the history
    records the actual shaping (same contract as the partitioner's
    grudge echo).
    """

    def __init__(self, *, fast_f: str = "fast"):
        self.fast_f = fast_f

    def setup(self, test):
        _net(test).fast(test)
        return self

    def invoke(self, test, op):
        net = _net(test)
        f = op["f"]
        if f == "slow":
            kw = dict(op.get("value") or {})
            net.slow(test, **kw)
            return dict(op, type="info", value=["slow", kw])
        if f == "flaky":
            kw = dict(op.get("value") or {})
            net.flaky(test, **kw)
            return dict(op, type="info", value=["flaky", kw])
        if f == "shape":
            behaviors = list(op.get("value") or ())
            if not behaviors:
                raise ValueError("shape op needs a netem behavior list")
            net.shape(test, behaviors)
            return dict(op, type="info", value=["shape", behaviors])
        if f == self.fast_f:
            net.fast(test)
            return dict(op, type="info", value="shaping removed")
        raise ValueError(f"traffic shaper can't handle op f={f!r}")

    def teardown(self, test):
        _net(test).fast(test)


def traffic_shaper(**kw) -> Nemesis:
    return TrafficShaper(**kw)
