"""Fault injection (reference: `jepsen/nemesis*.clj`, SURVEY.md §1 L4b).

The nemesis is a special single-threaded client driven by the generator's
nemesis thread: `invoke` receives fault ops (`start-partition`, `kill`,
`bump-clock`, ...) and performs them against the cluster via the control
plane.  Host-side only.
"""

from jepsen_tpu.nemesis.core import (Nemesis, Noop, bridge, complete_grudge,
                                     compose, hammer_time, invert_grudge,
                                     majorities_ring, node_start_stopper,
                                     partition_halves, partition_majorities_ring,
                                     partition_random_halves,
                                     partition_random_node, partitioner,
                                     split_one)

__all__ = [
    "Nemesis", "Noop", "bridge", "complete_grudge", "compose",
    "hammer_time", "invert_grudge", "majorities_ring", "node_start_stopper",
    "partition_halves", "partition_majorities_ring",
    "partition_random_halves", "partition_random_node", "partitioner",
    "split_one",
]
