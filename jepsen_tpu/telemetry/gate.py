"""Statistical span-regression gate (``cli obs gate``, ISSUE 6).

Turns ROADMAP's "quote span_trend deltas, not anecdotes" into an
enforceable CI check: compare one span site's per-run duration samples
across two campaign generations and exit nonzero on regression.

The decision combines two tests, BOTH of which must trip:

- a one-sided **Mann-Whitney U** (normal approximation with tie
  correction and continuity correction — stdlib only) that the new
  generation's durations are stochastically larger, at significance
  ``alpha``; and
- a **hard relative-delta threshold** on the group p95s
  (``(p95_new - p95_old) / p95_old > threshold``), so a statistically
  detectable but operationally irrelevant shift doesn't fail the build
  — and conversely a huge delta backed by too little evidence doesn't
  pass silently (it exits with the distinct "insufficient data" code).

Exit codes (``cli obs gate``): 0 pass, 1 regression, 2 cannot evaluate
(unknown campaign/span, or fewer than ``min_runs`` samples per side).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["mann_whitney_u", "gate_samples", "run_gate", "render_gate"]


def _rank(values: Sequence[float]) -> Tuple[List[float], float]:
    """Average ranks (1-based) and the tie-correction term
    ``sum(t^3 - t)`` over tie groups."""
    order = sorted(range(len(values)), key=lambda i: values[i])
    ranks = [0.0] * len(values)
    tie_term = 0.0
    i = 0
    while i < len(order):
        j = i
        while j + 1 < len(order) and \
                values[order[j + 1]] == values[order[i]]:
            j += 1
        avg = (i + j) / 2.0 + 1.0
        for k in range(i, j + 1):
            ranks[order[k]] = avg
        t = j - i + 1
        if t > 1:
            tie_term += t ** 3 - t
        i = j + 1
    return ranks, tie_term


def mann_whitney_u(a: Sequence[float], b: Sequence[float]
                   ) -> Dict[str, float]:
    """One-sided Mann-Whitney U test that ``b`` is stochastically
    LARGER than ``a`` (the regression direction for durations).
    Returns ``{"u": U_b, "z": ..., "p": one-sided p-value}`` using the
    normal approximation with tie correction and a 0.5 continuity
    correction.  Degenerate inputs (an empty side, or all values tied)
    return p = 1.0 — no evidence of regression."""
    n1, n2 = len(a), len(b)
    if not n1 or not n2:
        return {"u": 0.0, "z": 0.0, "p": 1.0}
    ranks, tie_term = _rank(list(a) + list(b))
    r2 = sum(ranks[n1:])
    u2 = r2 - n2 * (n2 + 1) / 2.0  # pairs where b > a (+ half-ties)
    mu = n1 * n2 / 2.0
    n = n1 + n2
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term / (n * (n - 1)))
    if var <= 0:
        return {"u": u2, "z": 0.0, "p": 1.0}
    z = (u2 - mu - 0.5) / math.sqrt(var)
    p = 0.5 * math.erfc(z / math.sqrt(2.0))
    return {"u": u2, "z": z, "p": p}


def _p95(vals: List[float]) -> float:
    from jepsen_tpu.campaign.index import _percentile

    return _percentile(vals, 95)


def gate_samples(old: List[float], new: List[float], *,
                 alpha: float = 0.05, threshold: float = 0.25,
                 min_runs: int = 3) -> Dict[str, Any]:
    """The gate decision over two sample groups.  Returns a result map
    with ``status`` in {"pass", "regression", "insufficient-data"} and
    the full evidence (n, p95s, relative delta, U, p-value)."""
    res: Dict[str, Any] = {
        "n_old": len(old), "n_new": len(new),
        "alpha": alpha, "threshold": threshold,
    }
    if len(old) < min_runs or len(new) < min_runs:
        res["status"] = "insufficient-data"
        res["reason"] = (f"need >= {min_runs} runs per generation "
                         f"(have {len(old)} vs {len(new)})")
        return res
    p95_old, p95_new = _p95(old), _p95(new)
    rel = ((p95_new - p95_old) / p95_old if p95_old > 0
           else (math.inf if p95_new > 0 else 0.0))
    mw = mann_whitney_u(old, new)
    res.update({
        "p95_old": round(p95_old, 6), "p95_new": round(p95_new, 6),
        "rel_delta": (round(rel, 4) if math.isfinite(rel) else rel),
        "u": mw["u"], "z": round(mw["z"], 4), "p_value": mw["p"],
    })
    significant = mw["p"] < alpha
    big = rel > threshold
    if significant and big:
        res["status"] = "regression"
        res["reason"] = (f"p95 +{rel * 100.0:.1f}% (> "
                         f"{threshold * 100.0:.0f}%) and Mann-Whitney "
                         f"p={mw['p']:.2g} < {alpha:g}")
    else:
        res["status"] = "pass"
        res["reason"] = ("shift not significant "
                         f"(p={mw['p']:.2g} >= {alpha:g})"
                         if big else
                         f"p95 delta {rel * 100.0:+.1f}% within "
                         f"{threshold * 100.0:.0f}% threshold")
    return res


def run_gate(base: str, campaign: str, span: str, *,
             from_gen: Optional[str] = None,
             to_gen: Optional[str] = None,
             alpha: float = 0.05, threshold: float = 0.25,
             min_runs: int = 3) -> Dict[str, Any]:
    """Gate one span site of one campaign: pull its (gen, duration)
    samples (warehouse-backed when fresh, jsonl scan otherwise), pick
    the generation pair (default: the two most recent), and decide.
    The result map carries ``status`` as in :func:`gate_samples`."""
    from jepsen_tpu.campaign.core import index_path
    from jepsen_tpu.campaign.index import Index

    path = index_path(campaign, base)
    samples = Index(path).span_samples(span)
    by_gen: Dict[str, List[float]] = {}
    order: List[str] = []
    for gen, dur in samples:
        g = str(gen or "?")
        if g not in by_gen:
            order.append(g)
        by_gen.setdefault(g, []).append(dur)
    res: Dict[str, Any] = {"campaign": campaign, "span": span,
                           "generations": order}
    if not order:
        res.update(status="insufficient-data",
                   reason=f"no samples for span {span!r} in campaign "
                          f"{campaign!r} (index: {path})",
                   n_old=0, n_new=0)
        return res
    if from_gen is None or to_gen is None:
        if len(order) < 2:
            res.update(status="insufficient-data",
                       reason="need two generations to compare "
                              f"(have {order})", n_old=0, n_new=0)
            return res
        from_gen = from_gen or order[-2]
        to_gen = to_gen or order[-1]
    if from_gen not in by_gen or to_gen not in by_gen:
        missing = [g for g in (from_gen, to_gen) if g not in by_gen]
        res.update(status="insufficient-data",
                   reason=f"generation(s) {missing} not in {order}",
                   n_old=0, n_new=0)
        return res
    if from_gen == to_gen:
        # a half-specified pair can resolve to the same generation
        # (e.g. --from-gen <latest> with --to-gen omitted): comparing a
        # group against itself always passes — refuse loudly (exit 2)
        # instead of letting a misconfigured gate pass forever
        res.update(status="insufficient-data",
                   reason=f"from-gen == to-gen ({from_gen!r}): nothing "
                          f"to compare (generations: {order})",
                   n_old=0, n_new=0)
        return res
    res.update({"from-gen": from_gen, "to-gen": to_gen})
    res.update(gate_samples(by_gen[from_gen], by_gen[to_gen],
                            alpha=alpha, threshold=threshold,
                            min_runs=min_runs))
    return res


def render_gate(res: Dict[str, Any]) -> str:
    """Human one-screen gate report."""
    lines = [f"obs gate: {res.get('campaign')} span={res.get('span')}"]
    if res.get("from-gen"):
        lines.append(f"  generations: {res['from-gen']} -> "
                     f"{res['to-gen']} "
                     f"({res.get('n_old')} vs {res.get('n_new')} runs)")
    if "p95_old" in res:
        lines.append(
            f"  p95: {res['p95_old']}s -> {res['p95_new']}s "
            f"({res['rel_delta'] * 100.0:+.1f}%), "
            f"Mann-Whitney U={res['u']:.1f} z={res['z']} "
            f"p={res['p_value']:.3g}")
    lines.append(f"  {res.get('status').upper()}: {res.get('reason')}")
    return "\n".join(lines)
