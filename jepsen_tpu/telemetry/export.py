"""Telemetry persistence: ``telemetry.json`` + Chrome ``trace.json``.

Two artifacts per run, written into the store dir during
``store.save_1`` (so a crashed checker still has the phase-0 history,
and the telemetry covers the checking phase itself):

- ``telemetry.json`` — the span forest (nested, durations in ns) plus a
  snapshot of the process-wide metrics registry.  Machine-readable; the
  CLI ``trace`` command and the web UI's telemetry page render it.
- ``trace.json`` — Chrome trace-event format (the ``{"traceEvents":
  [...]}`` object form), loadable in Perfetto / ``chrome://tracing``.
  Spans become ``"ph": "X"`` complete events with microsecond
  timestamps; each thread gets a named row via ``"M"`` metadata events.

Open spans (export runs inside the still-open ``run`` and
``store.save_1`` spans) get a provisional end stamped by
``Collector.close_open_spans`` and are marked ``"open": true``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import metrics as _metrics
from .spans import Collector, Span

__all__ = ["span_to_dict", "snapshot", "chrome_trace", "write_run",
           "summarize", "histogram_quantiles", "top_spans",
           "render_top_spans"]

TELEMETRY_FILE = "telemetry.json"
TRACE_FILE = "trace.json"


def span_to_dict(sp: Span) -> Dict[str, Any]:
    return {
        "name": sp.name,
        "t0_ns": sp.t0,
        "dur_ns": sp.duration_ns,
        "thread": sp.thread_name,
        "tid": sp.tid,
        "attrs": _jsonable(sp.attrs),
        "children": [span_to_dict(c) for c in sp.children],
    }


def _jsonable(v: Any) -> Any:
    """Best-effort JSON coercion: attrs may hold numpy scalars, sets,
    arbitrary objects — telemetry must never crash a run over one."""
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, bool)) or v is None:
        return v
    if isinstance(v, (int, float)):
        return v
    item = getattr(v, "item", None)  # numpy scalar
    if callable(item):
        try:
            return item()
        except Exception:  # noqa: BLE001
            pass
    return repr(v)


def snapshot(collector: Collector,
             registry: Optional[_metrics.Registry] = None,
             meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The full telemetry document: span forest + metric snapshot.
    Defaults to the collector's own registry (per-run isolation), then
    the process-wide default."""
    collector.close_open_spans()
    reg = (registry or getattr(collector, "registry", None)
           or _metrics.registry())
    doc = {
        "version": 1,
        "epoch_ns": collector.epoch_ns,
        "perf0_ns": collector.perf0_ns,
        "meta": _jsonable(meta or {}),
        "spans": [span_to_dict(r) for r in collector.roots],
        "metrics": reg.snapshot(),
    }
    trace = getattr(collector, "trace", None)
    if trace is not None:
        # the distributed trace triple (ISSUE 14): what the warehouse
        # stitches cross-host timelines on
        doc["trace"] = trace.to_dict()
    return doc


def chrome_trace(collector: Collector,
                 process_name: str = "jepsen-tpu") -> Dict[str, Any]:
    """Chrome trace-event document for the collector's span forest."""
    collector.close_open_spans()
    pid = os.getpid()
    events: List[Dict[str, Any]] = [{
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    seen_tids = set()

    def emit(sp: Span) -> None:
        if sp.tid not in seen_tids:
            seen_tids.add(sp.tid)
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": sp.tid,
                           "args": {"name": sp.thread_name}})
        t1 = sp.t1 if sp.t1 is not None else sp.t0
        events.append({
            "ph": "X",
            "name": sp.name,
            "pid": pid,
            "tid": sp.tid,
            # trace-event timestamps are microseconds; anchor at the
            # collector's perf origin so the run starts near t=0
            "ts": (sp.t0 - collector.perf0_ns) / 1e3,
            "dur": (t1 - sp.t0) / 1e3,
            "args": _jsonable(sp.attrs),
        })
        for c in sp.children:
            emit(c)

    for r in collector.roots:
        emit(r)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_run(dirpath: str, collector: Collector,
              registry: Optional[_metrics.Registry] = None,
              meta: Optional[Dict[str, Any]] = None,
              suffix: str = "") -> Dict[str, str]:
    """Persist both artifacts into `dirpath`; returns their paths.
    `suffix` distinguishes artifact sets (e.g. "-analyze" keeps a
    re-check from clobbering the original run's trace)."""
    doc = snapshot(collector, registry, meta)
    tel_path = os.path.join(
        dirpath, TELEMETRY_FILE.replace(".json", suffix + ".json"))
    with open(tel_path, "w") as f:
        json.dump(doc, f, indent=1)
    trace_path = os.path.join(
        dirpath, TRACE_FILE.replace(".json", suffix + ".json"))
    with open(trace_path, "w") as f:
        json.dump(chrome_trace(collector, meta.get("name", "jepsen-tpu")
                               if meta else "jepsen-tpu"), f)
    return {"telemetry": tel_path, "trace": trace_path}


def histogram_quantiles(bounds: List[Any], counts: List[int],
                        qs: List[float] = (0.50, 0.95, 0.99)
                        ) -> Dict[str, float]:
    """Quantile estimates from fixed-bucket counts — the
    histogram_quantile rule: find the bucket holding the target rank,
    linear-interpolate within its [lower, upper) bounds.  `bounds` is
    the snapshot's ``buckets`` list (finite upper bounds, possibly with
    a trailing ``"+inf"``); a rank landing in the +inf bucket clamps to
    the largest finite bound (no upper edge to interpolate toward).
    Returns {"p50": ..., "p95": ..., "p99": ...} (empty when count 0)."""
    finite = [float(b) for b in bounds if isinstance(b, (int, float))]
    total = sum(counts)
    if not total or not finite:
        return {}
    out: Dict[str, float] = {}
    for q in qs:
        rank = q * total
        cum = 0.0
        val = finite[-1]
        for i, c in enumerate(counts):
            prev = cum
            cum += c
            if cum >= rank and c:
                lo = finite[i - 1] if 0 < i <= len(finite) else 0.0
                if i < len(finite):
                    hi = finite[i]
                    val = lo + (hi - lo) * (rank - prev) / c
                else:  # +inf bucket: clamp to the last finite bound
                    val = finite[-1]
                break
        out[f"p{int(q * 100)}"] = round(val, 6)
    return out


def quantile(sorted_vals: List[float], p: float) -> float:
    """THE floor nearest-rank quantile rule, shared by every surface
    that quotes span/probe percentiles (``trace --top``, the shrink
    probe stats) — one formula, so two reports of the same samples
    can't disagree."""
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(p * (len(sorted_vals) - 1)))]


def top_spans(doc: Dict[str, Any], n: int = 10) -> List[Dict[str, Any]]:
    """The slowest-spans table (``cli trace --top N``): per span name,
    count / total self-time / p95 self-time, sorted by total self-time
    descending.  Self-time = a span's duration minus its children's
    (clamped at 0 — provisional closes can overlap), so a parent that
    merely *waits* on an expensive child doesn't crowd it out.  Makes a
    span regression quotable without opening Perfetto."""
    agg: Dict[str, List[float]] = {}

    def walk(sp: Dict[str, Any]) -> None:
        dur = sp.get("dur_ns")
        kids = sp.get("children") or []
        if isinstance(dur, (int, float)):
            child_ns = sum(c.get("dur_ns") or 0 for c in kids
                           if isinstance(c.get("dur_ns"), (int, float)))
            agg.setdefault(sp["name"], []).append(
                max(0.0, float(dur) - child_ns))
        for c in kids:
            walk(c)

    for r in doc.get("spans", []):
        walk(r)
    rows: List[Dict[str, Any]] = []
    for name, selfs in agg.items():
        s = sorted(selfs)
        p95 = quantile(s, 0.95)
        rows.append({"name": name, "count": len(s),
                     "total_self_s": round(sum(s) / 1e9, 6),
                     "p95_self_s": round(p95 / 1e9, 6)})
    rows.sort(key=lambda r: -r["total_self_s"])
    return rows[:max(1, int(n))]


def render_top_spans(rows: List[Dict[str, Any]]) -> str:
    lines = [f"{'span':<40} {'n':>6} {'total self':>12} {'p95 self':>12}"]
    for r in rows:
        lines.append(f"{r['name']:<40} {r['count']:>6} "
                     f"{r['total_self_s']:>11.4f}s {r['p95_self_s']:>11.4f}s")
    return "\n".join(lines)


# -- summaries (cli `trace` command) ---------------------------------------

def _fmt_dur(ns: Optional[float], fallback: str = "open") -> str:
    if not isinstance(ns, (int, float)):
        return fallback
    if ns >= 1e9:
        return f"{ns / 1e9:.2f}s"
    if ns >= 1e6:
        return f"{ns / 1e6:.1f}ms"
    return f"{ns / 1e3:.0f}us"


def _render_span(sp: Dict[str, Any], depth: int, lines: List[str],
                 max_depth: int = 6) -> None:
    attrs = {k: v for k, v in (sp.get("attrs") or {}).items()
             if k != "open"}
    extra = (" " + " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
             if attrs else "")
    lines.append(f"{'  ' * depth}{sp['name']:<{max(1, 40 - 2 * depth)}} "
                 f"{_fmt_dur(sp.get('dur_ns')):>10}{extra}")
    if depth < max_depth:
        for c in sp.get("children") or []:
            _render_span(c, depth + 1, lines, max_depth)


def summarize(dirpath: str, max_depth: int = 6,
              doc: Optional[Dict[str, Any]] = None) -> str:
    """Human summary of a stored run's telemetry.json: the span tree
    with durations, then non-zero counters and gauges.  Pass an
    already-parsed `doc` to skip the file read (the web handler loads
    the json once for both its percentile table and this summary)."""
    if doc is None:
        path = os.path.join(dirpath, TELEMETRY_FILE)
        with open(path) as f:
            doc = json.load(f)
    lines: List[str] = [f"telemetry for {dirpath}", ""]
    for root in doc.get("spans", []):
        _render_span(root, 0, lines, max_depth)
    m = doc.get("metrics", {})
    counters = [c for c in m.get("counters", []) if c.get("value")]
    gauges = [g for g in m.get("gauges", []) if g.get("value") is not None]
    if counters or gauges:
        lines.append("")
        lines.append("metrics:")
        for c in sorted(counters, key=lambda c: (c["name"],
                                                 str(c["labels"]))):
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(c["labels"].items()))
            lines.append(f"  {c['name']}{{{lbl}}} = {c['value']}")
        for g in sorted(gauges, key=lambda g: (g["name"],
                                               str(g["labels"]))):
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(g["labels"].items()))
            lines.append(f"  {g['name']}{{{lbl}}} = {g['value']}")
    for h in m.get("histograms", []):
        if h.get("count"):
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(h["labels"].items()))
            quant = histogram_quantiles(h.get("buckets") or [],
                                        h.get("counts") or [])
            qs = " ".join(f"{k}={v:.4g}" for k, v in quant.items())
            lines.append(f"  {h['name']}{{{lbl}}} count={h['count']} "
                         f"sum={h['sum']:.6g}"
                         + (f" {qs}" if qs else ""))
    return "\n".join(lines)
