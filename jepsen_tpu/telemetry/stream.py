"""The flight recorder: streaming telemetry for in-flight runs (ISSUE 5).

PR 1's telemetry is export-only — ``telemetry.json`` / ``trace.json``
appear at ``store.save_1``, so the runs this framework exists to study
(wedged checkers, crashed workers, deadline-killed campaign cells)
leave *no* observability artifact at all.  This module makes the active
collector *stream*: every span open/close, every metric delta, and
every resilience event (fault injected, retry, host fallback, deadline
expiry) is appended to an ``events.jsonl`` in the run dir **as it
happens**, fsync'd per event, so a SIGKILLed run still yields a
readable partial trace (tail-truncated at worst — the reader drops one
torn trailing line, exactly like the campaign ledger).

Pieces:

- :class:`EventStream` — the append-only fsync'd jsonl writer.  Never
  raises into the instrumented code: any IO failure marks the stream
  broken and subsequent emits are dropped.
- :class:`ResourceSampler` — a daemon thread sampling process RSS,
  thread count, and device memory (``device.memory_stats()`` when the
  jax backend is *already* initialized — the sampler must never be the
  thing that dials a TPU) into gauges + ``sample`` events.
- :func:`attach` — wire a stream + sampler onto a live
  :class:`~.spans.Collector`; ``core.run`` does this for every
  telemetric run, ``minimize.shrink`` for shrink sessions.
- :func:`read_events` / :func:`replay` / :func:`render_tail` — the
  torn-line-tolerant reader and the human renderer behind ``cli tail``
  and the web ``/live`` views.
- :class:`Heartbeat` — an atomically-replaced JSON state file for the
  campaign scheduler's per-worker in-flight heartbeats
  (``<store>/campaigns/<name>.live.json``), the data behind the live
  fleet dashboard.

Event shapes (one JSON object per line, ``t`` = epoch seconds)::

    {"t": ..., "ev": "start", ...meta}
    {"t": ..., "ev": "span-open", "name": "check:list-append", "tid": ...}
    {"t": ..., "ev": "span", "name": ..., "dur_ns": ..., "attrs": {...}}
    {"t": ..., "ev": "metrics", "counters": {"name{k=v}": value}, ...}
    {"t": ..., "ev": "sample", "rss_bytes": ..., "threads": ...}
    {"t": ..., "ev": "fault"|"retry"|"fallback"|"deadline", "site": ...}
    {"t": ..., "ev": "end", ...}

Metric events carry *changed instruments with their current values*
(incremental updates, not raw increments): replaying every metrics
event in order leaves the reader holding the final tallies, which is
what ``cli tail``'s footer prints for a killed run.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .export import _fmt_dur, _jsonable
from .metrics import Registry

__all__ = ["EventStream", "ResourceSampler", "Recorder", "Heartbeat",
           "HttpHeartbeat",
           "attach", "event", "read_events", "replay", "render_line",
           "render_tail", "segment_files", "follow_events",
           "EVENTS_FILE", "SHRINK_EVENTS_FILE", "events_path"]

EVENTS_FILE = "events.jsonl"
SHRINK_EVENTS_FILE = "events-shrink.jsonl"


def events_path(dirpath: str) -> Optional[str]:
    """The run dir's streamed-events file — whichever of the run's own
    stream and the shrink session's was written to most recently, so
    tailing a dir follows the LIVE activity (a `cli shrink` of an
    already-ended telemetric run streams events-shrink.jsonl next to
    the finished events.jsonl; preferring the run stream would replay
    the ended run and exit instead of following the shrink).  Ties go
    to the run's own stream.  THE lookup `cli tail` and the web
    `/live` + link surfaces share, so they can't disagree about which
    runs are followable."""
    best: Optional[str] = None
    best_mtime = float("-inf")
    for fn in (EVENTS_FILE, SHRINK_EVENTS_FILE):
        p = os.path.join(dirpath, fn)
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            continue
        if mtime > best_mtime:
            best, best_mtime = p, mtime
    return best


def segment_files(path: str) -> List[str]:
    """All on-disk files of one rotated stream, oldest first: the
    rotation segments ``<path>.N`` (largest N = oldest) then the live
    file.  The reader-side contract behind size-based rotation: every
    surface that replays a stream (``read_events``, the warehouse
    ingest, ``cli tail``) spans segments through this one lookup."""
    d = os.path.dirname(path) or "."
    bn = os.path.basename(path)
    segs: List[Tuple[int, str]] = []
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    pat = re.compile(re.escape(bn) + r"\.(\d+)$")
    for n in names:
        m = pat.match(n)
        if m:
            segs.append((int(m.group(1)), os.path.join(d, n)))
    out = [p for _, p in sorted(segs, reverse=True)]
    if os.path.exists(path):
        out.append(path)
    return out


def _remove_segments(path: str) -> None:
    for p in segment_files(path):
        if p != path:
            try:
                os.remove(p)
            except OSError:
                pass


def _label_key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    lbl = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{lbl}}}"


class _MetricsDelta:
    """Tracks last-streamed instrument values so each flush emits only
    what changed since the previous one (with current values)."""

    def __init__(self, registry: Registry):
        self.registry = registry
        self._last: Dict[Tuple[str, str], Any] = {}

    def changed(self) -> Optional[Dict[str, Dict[str, Any]]]:
        snap = self.registry.snapshot()
        out: Dict[str, Dict[str, Any]] = {}
        for c in snap["counters"]:
            k = ("c", _label_key(c["name"], c["labels"]))
            if self._last.get(k) != c["value"]:
                self._last[k] = c["value"]
                out.setdefault("counters", {})[k[1]] = c["value"]
        for g in snap["gauges"]:
            if g["value"] is None:
                continue
            k = ("g", _label_key(g["name"], g["labels"]))
            if self._last.get(k) != g["value"]:
                self._last[k] = g["value"]
                out.setdefault("gauges", {})[k[1]] = g["value"]
        for h in snap["histograms"]:
            k = ("h", _label_key(h["name"], h["labels"]))
            cur = (h["count"], h["sum"])
            if self._last.get(k) != cur:
                self._last[k] = cur
                out.setdefault("histograms", {})[k[1]] = {
                    "count": h["count"], "sum": round(h["sum"], 6)}
        return out or None


class EventStream:
    """Append-only fsync'd jsonl event sink.

    Crash-safety contract: each event is one ``write()`` of a complete
    line followed by ``fsync`` — a kill between the two leaves at most
    one torn trailing line, which :func:`read_events` drops.  Emits
    must NEVER raise into the instrumented run: any failure (disk full,
    closed fd) marks the stream broken and later emits are no-ops.

    Size-based rotation (``max_bytes``): when an append would push the
    live file past the bound, the stream records a ``rotate`` event
    in-stream, shifts ``events.jsonl`` → ``events.jsonl.1`` (… keep-N,
    the oldest segment dropped), and continues into a fresh live file
    opened with a ``rotate-cont`` marker — so soak/service runs never
    grow one unbounded file.  Readers span segments transparently via
    :func:`segment_files`."""

    def __init__(self, path: str, meta: Optional[Dict[str, Any]] = None,
                 *, max_bytes: Optional[int] = None, keep: int = 3):
        self.path = path
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.keep = max(1, int(keep))
        self._segment = 0
        self._bytes = 0
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self.broken = False
        self._metrics: Optional[_MetricsDelta] = None
        #: when a ResourceSampler is attached, its ``watermarks`` bound
        #: method — span closes stamp the current peaks into the span's
        #: attrs (so telemetry.json carries per-span high watermarks)
        self.watermarks: Optional[Any] = None
        # one session per file: truncate any previous stream (and drop
        # its rotation segments) — a --force re-shrink appending after
        # the old "end" event would make replay() render a killed
        # re-run as ended, with counters mixed across sessions
        _remove_segments(path)
        try:
            self._f = open(path, "wb", buffering=0)
        except OSError:
            self._f = None
            self.broken = True
        self.emit("start", **{k: v for k, v in (meta or {}).items()
                              if v is not None})

    def bind_registry(self, registry: Registry) -> None:
        """Attach the registry whose deltas :meth:`flush_metrics`
        streams (the collector's own, for per-run isolation)."""
        self._metrics = _MetricsDelta(registry)

    def emit(self, ev: str, **fields: Any) -> None:
        if self.broken:
            return
        rec: Dict[str, Any] = {"t": 0.0, "ev": ev}
        rec.update(fields)
        with self._lock:
            if self.broken or self._f is None:
                return
            # stamp under the lock so file order and timestamps agree
            rec["t"] = round(time.time(), 3)
            try:
                data = (json.dumps(_jsonable(rec), separators=(",", ":"))
                        + "\n").encode()
            except Exception:  # noqa: BLE001 — bad payload, stream fine
                return
            try:
                if self.max_bytes and self._bytes \
                        and self._bytes + len(data) > self.max_bytes:
                    self._rotate()
                self._f.write(data)
                self._bytes += len(data)
                os.fsync(self._f.fileno())
            except Exception:  # noqa: BLE001
                self.broken = True

    def _rotate(self) -> None:
        """Rotate the live file (caller holds the emit lock).  The old
        segment's LAST line is the ``rotate`` event and the new live
        file's FIRST line is ``rotate-cont`` — both in-stream, so a
        spanning replay sees an unbroken, self-describing sequence."""
        self._segment += 1

        def marker(ev: str) -> bytes:
            return (json.dumps({"t": round(time.time(), 3), "ev": ev,
                                "segment": self._segment},
                               separators=(",", ":")) + "\n").encode()

        self._f.write(marker("rotate"))
        os.fsync(self._f.fileno())
        self._f.close()
        oldest = f"{self.path}.{self.keep}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, self.path + ".1")
        self._f = open(self.path, "wb", buffering=0)
        cont = marker("rotate-cont")
        self._f.write(cont)
        self._bytes = len(cont)

    # -- collector-facing hooks (spans.Collector calls these) ---------------

    def span_open(self, sp: Any) -> None:
        self.emit("span-open", name=sp.name, tid=sp.tid,
                  thread=sp.thread_name)

    def span_close(self, sp: Any) -> None:
        if self.watermarks is not None:
            # stamp the enclosing span with the run's current memory
            # high watermarks at its close — this lands in the span
            # event AND (the attrs dict is the live span's) in the
            # telemetry.json export, so peak memory is attributable to
            # the phase that drove it
            try:
                wm = self.watermarks()
                if wm:
                    sp.attrs.update(wm)
            except Exception:  # noqa: BLE001 — stamping is best-effort
                pass
        self.emit("span", name=sp.name, tid=sp.tid, dur_ns=sp.duration_ns,
                  **({"attrs": _jsonable(sp.attrs)} if sp.attrs else {}))
        # a span boundary is the natural metrics flush point: low-rate,
        # and it lands the workload counters before the check phase — a
        # run killed mid-check still shows its final op tallies
        self.flush_metrics()

    def flush_metrics(self) -> None:
        if self._metrics is None or self.broken:
            return
        # compute-delta + emit must be one atomic step: two concurrent
        # span closes could otherwise stream a stale snapshot AFTER a
        # newer one, and replay() keeps the last value seen
        with self._flush_lock:
            try:
                delta = self._metrics.changed()
            except Exception:  # noqa: BLE001
                return
            if delta:
                self.emit("metrics", **delta)

    def close(self, **fields: Any) -> None:
        self.emit("end", **fields)
        with self._lock:
            try:
                if self._f is not None:
                    self._f.close()
            except Exception:  # noqa: BLE001
                pass
            self.broken = True


def event(ev: str, **fields: Any) -> None:
    """Emit one event onto the ACTIVE collector's stream, if any — the
    module-level hook resilience sites call (fault/retry/fallback/
    deadline); a no-op for unstreamed/disabled telemetry."""
    from . import spans

    s = getattr(spans.active(), "stream", None)
    if s is not None:
        s.emit(ev, **fields)


# ---------------------------------------------------------------------------
# Resource sampler
# ---------------------------------------------------------------------------

def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 — non-linux
        try:
            import resource

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is kilobytes on Linux/BSD but BYTES on macOS
            return rss if sys.platform == "darwin" else rss * 1024
        except Exception:  # noqa: BLE001
            return None


def _hwm_bytes() -> Optional[int]:
    """Kernel-tracked RSS high watermark (``VmHWM``) — catches a
    transient allocation spike even when every sampler tick missed it
    entirely, which is exactly what a watermark series is for."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmHWM:"):
                    return int(line.split()[1]) * 1024
    except Exception:  # noqa: BLE001 — non-linux
        pass
    return None


def _device_memory_stats() -> "Dict[str, Tuple[int, Optional[int]]]":
    """Per-device ``(bytes_in_use, peak_bytes_in_use-or-None)`` from
    ``device.memory_stats()``, with a live-buffer-bytes fallback.  Only
    consulted when jax is imported AND its backend is already
    initialized — ``jax.devices()`` on a cold process would *dial* the
    backend (which can hang on a downed TPU tunnel), and a sampler must
    never be the thing that does that."""
    jx = sys.modules.get("jax")
    if jx is None:
        return {}
    try:
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return {}
    except Exception:  # noqa: BLE001 — unknown jax layout: stay safe
        return {}
    out: Dict[str, Tuple[int, Optional[int]]] = {}
    try:
        for d in jx.devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001
                ms = None
            if ms and ms.get("bytes_in_use") is not None:
                pk = ms.get("peak_bytes_in_use")
                out[str(d)] = (int(ms["bytes_in_use"]),
                               int(pk) if pk is not None else None)
        if not out:
            out["live-buffers"] = (int(sum(
                int(getattr(a, "nbytes", 0))
                for a in jx.live_arrays())), None)
    except Exception:  # noqa: BLE001
        pass
    return out


def _device_memory() -> Dict[str, int]:
    return {dev: used
            for dev, (used, _pk) in _device_memory_stats().items()}


class ResourceSampler:
    """Daemon thread sampling process/device resources into gauges +
    ``sample`` events.  :meth:`start` samples once synchronously on the
    caller's thread (so even an instant run records one, and a short
    run never shares the GIL with a sampler tick — per-worker op-split
    tests stay deterministic), then the thread waits a full interval
    before its first tick; :meth:`stop` ALWAYS takes one final
    synchronous sample (marked ``"final": true``) on the caller's
    thread before detach — the state a post-mortem reads, and the
    guarantee that the peak gauges below reflect the whole run.

    Beyond instantaneous gauges the sampler maintains HIGH WATERMARKS
    (ISSUE 16 tentpole b): ``process-rss-peak-bytes`` (max of sampled
    RSS and the kernel's VmHWM, which catches spikes between ticks),
    ``device-memory-peak-bytes{device=}`` (``peak_bytes_in_use`` when
    the backend reports it, else the in-process max of bytes-in-use)
    and ``jit-cache-entries-peak``.  :meth:`watermarks` exposes them
    for span-close stamping (see :func:`attach`)."""

    def __init__(self, stream: EventStream, registry: Registry,
                 interval_s: float = 1.0):
        self.stream = stream
        self.registry = registry
        self.interval_s = max(0.02, float(interval_s))
        self.peak_rss = 0
        self.peak_dev: Dict[str, int] = {}
        self.peak_jit = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="telemetry-sampler")

    def start(self) -> None:
        try:
            self.sample()
        except Exception:  # noqa: BLE001 — sampling must never kill
            pass
        self._thread.start()

    def _run(self) -> None:
        while True:
            if self._stop.wait(self.interval_s):
                return
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — sampling must never kill
                pass

    def sample(self, final: bool = False) -> None:
        fields: Dict[str, Any] = {}
        rss = _rss_bytes()
        if rss is not None:
            self.registry.gauge("process-rss-bytes").set(rss)
            fields["rss_bytes"] = rss
            self.peak_rss = max(self.peak_rss, rss, _hwm_bytes() or 0)
            self.registry.gauge("process-rss-peak-bytes").set(
                self.peak_rss)
            fields["rss_peak_bytes"] = self.peak_rss
        n = threading.active_count()
        self.registry.gauge("process-threads").set(n)
        fields["threads"] = n
        for dev, (used, pk) in _device_memory_stats().items():
            self.registry.gauge("device-memory-bytes",
                                device=dev).set(used)
            fields.setdefault("device_bytes", {})[dev] = used
            peak = max(self.peak_dev.get(dev, 0), used, pk or 0)
            self.peak_dev[dev] = peak
            self.registry.gauge("device-memory-peak-bytes",
                                device=dev).set(peak)
            fields.setdefault("device_peak_bytes", {})[dev] = peak
        jit = self.registry.gauge("jit-cache-entries").value
        if jit:
            self.peak_jit = max(self.peak_jit, int(jit))
            self.registry.gauge("jit-cache-entries-peak").set(
                self.peak_jit)
        if final:
            fields["final"] = True
        self.stream.emit("sample", **fields)
        self.stream.flush_metrics()

    def watermarks(self) -> Dict[str, Any]:
        """The current high watermarks, in the shape span-close
        stamping writes into span attrs (empty until a sample has
        landed any)."""
        out: Dict[str, Any] = {}
        if self.peak_rss:
            out["rss_peak_bytes"] = self.peak_rss
        if self.peak_dev:
            out["device_peak_bytes"] = dict(self.peak_dev)
        if self.peak_jit:
            out["jit_cache_entries_peak"] = self.peak_jit
        return out

    def stop(self) -> None:
        self._stop.set()
        try:
            self.sample(final=True)
        except Exception:  # noqa: BLE001
            pass


class Recorder:
    """Handle returned by :func:`attach`: owns the stream + sampler
    lifetime; ``close()`` detaches and finalizes (idempotent)."""

    def __init__(self, collector: Any, stream: EventStream,
                 sampler: Optional[ResourceSampler]):
        self.collector = collector
        self.stream = stream
        self.sampler = sampler
        self._closed = False

    def close(self, **fields: Any) -> None:
        if self._closed:
            return
        self._closed = True
        if self.sampler is not None:
            self.sampler.stop()
        if getattr(self.collector, "stream", None) is self.stream:
            self.collector.stream = None
        self.stream.flush_metrics()
        self.stream.close(**fields)


def _env_int(name: str) -> Optional[int]:
    try:
        v = os.environ.get(name, "").strip()
        return int(v) if v else None
    except ValueError:
        return None


def attach(collector: Any, dirpath: str, *,
           meta: Optional[Dict[str, Any]] = None,
           interval_s: float = 1.0,
           filename: str = EVENTS_FILE,
           sampler: bool = True,
           max_bytes: Optional[int] = None,
           keep: Optional[int] = None) -> Recorder:
    """Attach a flight-recorder stream (and resource sampler) to a live
    collector; events land in ``<dirpath>/<filename>``.  Returns the
    :class:`Recorder` whose ``close()`` the activator must call.
    ``max_bytes``/``keep`` enable size-based rotation (soak runs);
    defaults come from ``JEPSEN_EVENTS_MAX_BYTES``/``JEPSEN_EVENTS_KEEP``
    when unset."""
    if max_bytes is None:
        max_bytes = _env_int("JEPSEN_EVENTS_MAX_BYTES")
    if keep is None:
        keep = _env_int("JEPSEN_EVENTS_KEEP") or 3
    s = EventStream(os.path.join(dirpath, filename), meta=meta,
                    max_bytes=max_bytes, keep=keep)
    reg = getattr(collector, "registry", None)
    if reg is not None:
        s.bind_registry(reg)
    smp = None
    if sampler and reg is not None:
        smp = ResourceSampler(s, reg, interval_s)
        s.watermarks = smp.watermarks
        smp.start()
    collector.stream = s
    return Recorder(collector, s, smp)


# ---------------------------------------------------------------------------
# Reading + rendering (cli tail, web /live)
# ---------------------------------------------------------------------------

def _read_one(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        f = open(path, "rb")
    except OSError:
        return out
    with f:
        for line in f:
            if not line.endswith(b"\n"):
                break  # torn tail: a kill raced the write
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if isinstance(rec, dict):
                out.append(rec)
    return out


def read_events(path: str, spanning: bool = True) -> List[Dict[str, Any]]:
    """Parse an events.jsonl, dropping a torn trailing line (crash
    mid-append) and everything after the first unparsable record — the
    same tolerance contract as the campaign ledger reader.  With
    ``spanning`` (the default) a size-rotated stream is read whole:
    rotated segments oldest-first, then the live file — callers tailing
    one physical file (the warehouse per-file ingest) pass False."""
    if spanning:
        out: List[Dict[str, Any]] = []
        for p in segment_files(path) or [path]:
            out.extend(_read_one(p))
        return out
    return _read_one(path)


def _rotated_catchup(path: str, offset: int) -> List[Dict[str, Any]]:
    """Events the follower missed across a rotation: the tail of the
    just-rotated segment (``<path>.1``) from the old cursor.  Empty
    when ``.1`` doesn't cover the cursor — that shrink was a new
    session truncating the stream, not a rotation."""
    p1 = path + ".1"
    out: List[Dict[str, Any]] = []
    try:
        if os.path.getsize(p1) < offset:
            return out
        f = open(p1, "rb")
    except OSError:
        return out
    with f:
        f.seek(offset)
        for line in f:
            if not line.endswith(b"\n"):
                break
            try:
                rec = json.loads(line) if line.strip() else None
            except ValueError:
                rec = None
            if isinstance(rec, dict):
                out.append(rec)
    return out


def read_events_incremental(
        path: str, offset: int = 0, follow_rotation: bool = True,
        stop_at_corrupt: bool = False
) -> "tuple[List[Dict[str, Any]], int]":
    """Parse complete event lines starting at byte ``offset``; returns
    ``(events, new_offset)`` with ``new_offset`` just past the last line
    consumed — the O(appended-bytes) cursor for following a live stream
    (``read_events`` re-parses the whole file each call).  A torn
    (unterminated) tail line is left unconsumed so the next poll retries
    it once the writer finishes the append; a complete-but-corrupt line
    is skipped — it will never heal, and a follower must stay live past
    it (with ``stop_at_corrupt`` it instead STOPS there, cursor before
    the bad line — the ``read_events`` scan semantics, used by the
    warehouse ingest so the two backends index the same prefix).  A
    shrunken file means either size rotation (the old bytes
    moved to ``<path>.1`` — with ``follow_rotation`` the segment's tail
    past the cursor is delivered first) or a new session truncating the
    stream; both reset the cursor to 0.  (A rotation the poll only
    sees after the NEW live file has already outgrown the old cursor
    is indistinguishable from plain growth, and two rotations between
    polls leave the cursor pointing at the wrong segment — a plain
    byte cursor cannot tell segments apart.  Followers that must
    survive arbitrary rotation cadence use :func:`follow_events`,
    whose cursor also carries the live file's first-line identity; the
    warehouse ingest re-reads segments by signature, so the durable
    record stays exact either way.)"""
    out: List[Dict[str, Any]] = []
    try:
        f = open(path, "rb")
    except OSError:
        return out, offset
    with f:
        f.seek(0, os.SEEK_END)
        if f.tell() < offset:
            if follow_rotation:
                out.extend(_rotated_catchup(path, offset))
            offset = 0
        f.seek(offset)
        for line in f:
            if not line.endswith(b"\n"):
                break  # torn tail: an append is in flight
            try:
                rec = json.loads(line) if line.strip() else None
            except ValueError:
                if stop_at_corrupt:
                    break  # scan semantics: cursor stays before it
                rec = None
            offset += len(line)
            if isinstance(rec, dict):
                out.append(rec)
    return out, offset


_FIRST_LINE_CAP = 1 << 20  # 1 MiB — no sane first event comes close


def _first_line(path: str) -> str:
    """A file's first COMPLETE line — the stream's segment/session
    identity: every live file opens with a unique first event (the
    session's attach meta, or a timestamped ``rotate-cont`` marker),
    and rotation renames preserve file content.  Shared by the
    :func:`follow_events` cursor and the warehouse event ingest, so
    the two can't disagree about what counts as the same session.
    ``""`` means no identity yet (file absent, or the first line still
    in flight).  A pathological first line longer than the cap yields
    the capped prefix once the file has grown past it — a stable
    identity rather than a permanent "" that would blind a follower
    forever."""
    try:
        with open(path, "rb") as f:
            first = f.readline(_FIRST_LINE_CAP)
            if len(first) >= _FIRST_LINE_CAP and \
                    not first.endswith(b"\n"):
                # over-cap line: identity = the capped prefix, stable
                # only once bytes BEYOND the cap exist (the prefix of a
                # still-growing line could change between polls)
                if f.read(1):
                    return first.decode("utf-8", "replace")
                return ""
    except OSError:
        return ""
    if not first.endswith(b"\n"):
        return ""
    return first.decode("utf-8", "replace")


def follow_events(path: str, cursor: Optional[Dict[str, Any]] = None
                  ) -> "tuple[List[Dict[str, Any]], Dict[str, Any]]":
    """The rotation-proof follower behind ``cli tail -f``: like
    :func:`read_events_incremental`, but the opaque ``cursor`` dict
    also carries the live file's first-line identity, so ANY number of
    rotations between polls is spanned losslessly — the follower's
    former live file is found among the rotated segments by first
    line, its tail past the old offset drained, every newer segment
    delivered whole, then the new live file read from byte 0.  A
    former segment that aged out of keep-N (or a new session, which
    removes old segments) delivers every surviving segment whole.
    Pass the returned cursor back on the next poll; start with None.
    The first poll spans existing rotated segments, matching
    :func:`read_events`."""
    cursor = cursor or {}
    offset = int(cursor.get("offset") or 0)
    head = cursor.get("head") or ""
    live_head = _first_line(path)
    out: List[Dict[str, Any]] = []
    segs = [p for p in segment_files(path) if p != path]
    # the resume anchor: identity + offset of the last position fully
    # delivered, valid even if the live-file read below can't complete
    # (rename race) — the next poll restarts the segment walk from it
    anchor_off, anchor_head = offset, head
    if not head or live_head != head:
        if head:
            # the live file was replaced since last poll (>=1
            # rotations, or a new session): locate the former live
            # file among the rotated segments by identity
            idx = next((i for i, p in enumerate(segs)
                        if _first_line(p) == head), None)
            if idx is not None:
                evs, new_off = read_events_incremental(
                    segs[idx], offset, follow_rotation=False)
                if _first_line(segs[idx]) != head:
                    # a rotation renamed another segment onto this
                    # path mid-read: the bytes may be the wrong
                    # file's — drop them, retry from the old cursor
                    return out, {"offset": anchor_off,
                                 "head": anchor_head}
                out.extend(evs)
                anchor_off = new_off
                segs = segs[idx + 1:]
            # else: former segment dropped (keep-N overrun / new
            # session, which removes old segments) — every surviving
            # segment is newer than the cursor, deliver them whole
        # fresh follower (no head): span already-rotated history,
        # matching read_events.  Fingerprint each segment BEFORE
        # reading and re-check after: a rotation racing the walk
        # renames other content onto these paths, and anchoring to a
        # fingerprint taken after such a rename would mark events as
        # delivered that never were.
        for p in segs:
            fl = _first_line(p)
            try:
                size = os.path.getsize(p)
            except OSError:
                fl = ""
            if not fl:
                continue  # segment dropped by keep-N mid-walk
            evs = read_events(p, spanning=False)
            if _first_line(p) != fl:
                # renamed under us: stop the walk; the next poll
                # resumes the chain from the last good anchor
                return out, {"offset": anchor_off, "head": anchor_head}
            out.extend(evs)
            anchor_off, anchor_head = size, fl
        offset = 0
    if not live_head:
        # live file absent or its first line still in flight (a poll
        # racing the rotation rename): deliver the segment catch-up
        # and retry the live file from the anchor next poll
        return out, {"offset": anchor_off, "head": anchor_head}
    evs, offset = read_events_incremental(path, offset,
                                          follow_rotation=False)
    if _first_line(path) != live_head:
        # a rotation raced the live read: the bytes parsed may belong
        # to a different file than live_head names — drop the live
        # batch (the next poll re-delivers it via the segment walk)
        # but keep the rename-stable segment catch-up
        return out, {"offset": anchor_off, "head": anchor_head}
    out.extend(evs)
    return out, {"offset": offset, "head": live_head}


def replay(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold an event sequence into its end state: which spans are still
    open (in open order), the final metric values, the last resource
    sample, and resilience tallies.  This is what a post-mortem of a
    killed run reads — and what the acceptance contract renders."""
    state: Dict[str, Any] = {
        "meta": {}, "open": [], "ended": False, "t0": None, "t_last": None,
        "counters": {}, "gauges": {}, "histograms": {}, "sample": {},
        "spans_closed": 0, "events": 0, "rotations": 0,
        "faults": 0, "retries": 0, "fallbacks": 0, "deadlines": 0,
        "env_anomalies": 0,
    }
    open_spans: List[Dict[str, Any]] = []
    for e in events:
        state["events"] += 1
        t = e.get("t")
        if t is not None:
            if state["t0"] is None:
                state["t0"] = t
            state["t_last"] = t
        ev = e.get("ev")
        if ev == "start":
            state["meta"] = {k: v for k, v in e.items()
                             if k not in ("t", "ev")}
        elif ev == "span-open":
            open_spans.append({"name": e.get("name"), "tid": e.get("tid"),
                               "t": t})
        elif ev == "span":
            state["spans_closed"] += 1
            for i in range(len(open_spans) - 1, -1, -1):
                if open_spans[i]["name"] == e.get("name") and \
                        open_spans[i]["tid"] == e.get("tid"):
                    del open_spans[i]
                    break
        elif ev == "metrics":
            for sect in ("counters", "gauges", "histograms"):
                state[sect].update(e.get(sect) or {})
        elif ev == "sample":
            state["sample"] = {k: v for k, v in e.items()
                               if k not in ("t", "ev")}
        elif ev in ("fault", "retry", "fallback", "deadline"):
            key = "retries" if ev == "retry" else ev + "s"
            state[key] += 1
        elif ev == "env-anomaly":
            state["env_anomalies"] += 1
        elif ev == "rotate":
            state["rotations"] += 1
        elif ev == "end":
            state["ended"] = True
    state["open"] = open_spans
    return state


def _fmt_bytes(n: Any) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _fmt_dur_ns(ns: Any) -> str:
    return _fmt_dur(ns, fallback="?")


def render_line(e: Dict[str, Any], t0: Optional[float] = None) -> str:
    """One human-readable progress line per event."""
    off = ""
    if t0 is not None and isinstance(e.get("t"), (int, float)):
        off = f"+{e['t'] - t0:8.3f}s "
    ev = e.get("ev", "?")
    if ev == "span-open":
        return f"{off}open  {e.get('name')}"
    if ev == "span":
        attrs = e.get("attrs") or {}
        extra = "".join(f" {k}={v}" for k, v in sorted(attrs.items())
                        if k not in ("open",))
        return (f"{off}span  {e.get('name')} "
                f"{_fmt_dur_ns(e.get('dur_ns'))}{extra}")
    if ev == "metrics":
        parts = []
        for sect in ("counters", "gauges"):
            for k, v in sorted((e.get(sect) or {}).items()):
                parts.append(f"{k}={v}")
        for k, v in sorted((e.get("histograms") or {}).items()):
            parts.append(f"{k}.count={v.get('count')}")
        return f"{off}metrics {' '.join(parts[:8])}" + \
            (" ..." if len(parts) > 8 else "")
    if ev == "sample":
        bits = []
        if "rss_bytes" in e:
            bits.append(f"rss={_fmt_bytes(e['rss_bytes'])}")
        if "threads" in e:
            bits.append(f"threads={e['threads']}")
        for dev, b in sorted((e.get("device_bytes") or {}).items()):
            bits.append(f"{dev}={_fmt_bytes(b)}")
        return f"{off}sample {' '.join(bits)}"
    if ev in ("fault", "retry", "fallback", "deadline"):
        extra = " ".join(f"{k}={v}" for k, v in sorted(e.items())
                         if k not in ("t", "ev"))
        return f"{off}{ev:<6}{extra}"
    extra = " ".join(f"{k}={v}" for k, v in sorted(e.items())
                     if k not in ("t", "ev"))
    return f"{off}{ev:<6}{extra}".rstrip()


def render_tail(events: List[Dict[str, Any]],
                limit: Optional[int] = None) -> str:
    """The full ``cli tail`` rendering: recent event lines, then the
    replayed end state — the still-open span chain (a killed run's
    "where was it?") and the final counter/gauge values."""
    st = replay(events)
    t0 = st["t0"]
    # limit=0 means "footer only" — lst[-0:] would be the whole list
    shown = (events if limit is None
             else events[-limit:] if limit > 0 else [])
    lines = [render_line(e, t0) for e in shown]
    if limit is not None and len(events) > limit:
        lines.insert(0, f"... ({len(events) - limit} earlier events)")
    lines.append("")
    if st["ended"]:
        lines.append("run ended cleanly")
    elif st["open"]:
        chain = " > ".join(str(s["name"]) for s in st["open"])
        lines.append(f"open spans: {chain}")
        last = st["open"][-1]
        age = ""
        if isinstance(st["t_last"], (int, float)) and \
                isinstance(last.get("t"), (int, float)):
            age = f" (open {st['t_last'] - last['t']:.1f}s at last event)"
        lines.append(f"last open span: {last['name']}{age}")
    else:
        lines.append("no open spans (stream truncated before close?)")
    if st["faults"] or st["retries"] or st["fallbacks"] or st["deadlines"] \
            or st["env_anomalies"]:
        env = (f", {st['env_anomalies']} env anomalies"
               if st["env_anomalies"] else "")
        lines.append(f"resilience: {st['faults']} faults, "
                     f"{st['retries']} retries, {st['fallbacks']} "
                     f"fallbacks, {st['deadlines']} deadline expiries"
                     f"{env}")
    if st["counters"]:
        lines.append("counters:")
        for k, v in sorted(st["counters"].items()):
            lines.append(f"  {k} = {v}")
    if st["gauges"]:
        lines.append("gauges:")
        for k, v in sorted(st["gauges"].items()):
            lines.append(f"  {k} = {v}")
    if st["histograms"]:
        lines.append("histograms:")
        for k, v in sorted(st["histograms"].items()):
            lines.append(f"  {k} count={v.get('count')} sum={v.get('sum')}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Heartbeat: atomic JSON state for live fleet dashboards
# ---------------------------------------------------------------------------

class Heartbeat:
    """Atomically-replaced JSON state file (`tmp` + ``os.replace``) the
    campaign scheduler updates as workers pick up / finish runs — the
    in-flight counterpart of the append-only ledger.  Readers (the web
    ``/campaign/<name>/live`` view, ``campaign status``) always see a
    complete document; a killed campaign leaves its last state behind,
    naming exactly the cells that were in flight.

    Writes are throttled to one per ``min_interval_s`` except when
    forced (close, and every worker-slot transition forces — those are
    the edges a dashboard cares about).

    No-raise guarantee: heartbeats are best-effort observability — the
    ledger is the record — so no public method ever raises; callers
    (the campaign scheduler's worker loop) rely on this and do not
    wrap their calls."""

    def __init__(self, path: str, *, campaign: Optional[str] = None,
                 total: int = 0, done: int = 0,
                 min_interval_s: float = 0.5):
        self.path = path
        self._lock = threading.Lock()
        self._last_write = 0.0
        self.min_interval_s = float(min_interval_s)
        self.state: Dict[str, Any] = {
            "campaign": campaign, "total": int(total), "done": int(done),
            "workers": {}, "updated": None, "finished": False,
        }
        self.write(force=True)

    def worker(self, worker_id: str,
               state: Optional[Dict[str, Any]]) -> None:
        """Set (or clear, with None) one worker's in-flight state."""
        with self._lock:
            if state is None:
                self.state["workers"].pop(str(worker_id), None)
            else:
                self.state["workers"][str(worker_id)] = dict(
                    state, since=state.get("since", round(time.time(), 3)))
        self.write(force=True)

    def record_done(self, run_id: str, valid: Any = None) -> None:
        with self._lock:
            self.state["done"] = int(self.state.get("done", 0)) + 1
            self.state["last"] = {"run": run_id, "valid?": valid}
        self.write()

    def write(self, force: bool = False) -> None:
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_write < self.min_interval_s:
                return
            self._last_write = now
            self.state["updated"] = round(time.time(), 3)
            # tmp write + replace stay under the lock: the tmp path is
            # shared, so an unlocked writer pair could publish the
            # other's half-written inode via os.replace
            tmp = self.path + ".tmp"
            try:
                doc = json.dumps(_jsonable(self.state), indent=1)
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                with open(tmp, "w") as f:
                    f.write(doc)
                os.replace(tmp, self.path)
            except Exception:  # noqa: BLE001 — see no-raise guarantee
                pass

    def close(self) -> None:
        with self._lock:
            self.state["workers"] = {}
            self.state["finished"] = True
        self.write(force=True)

    @staticmethod
    def load(path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None


class HttpHeartbeat:
    """:class:`Heartbeat` twin that PUSHES over HTTP to a fleet
    coordinator instead of writing ``live.json`` locally — the PR 5
    open item ("heartbeats pushed over HTTP"), closed by ISSUE 9.

    Same interface and the same no-raise guarantee as
    :class:`Heartbeat`; a `run_campaign` whose spec opts (or the
    ``JEPSEN_COORDINATOR`` env) name a coordinator URL uses this
    instead, and the coordinator's single `Heartbeat` writer merges
    the pushes into the exact ``live.json`` shape the file path
    writes — so ``/campaign/<name>/live`` renders both sources
    unchanged (pinned in tests/test_fleet.py).

    Best-effort by design: a dropped push loses a dashboard tick,
    never work — the ledger stays the record.  A FAILED push arms a
    cooldown (``backoff_s``) during which further pushes are skipped
    outright: heartbeats are called synchronously from the campaign
    scheduler's worker threads, and an unreachable coordinator —
    exactly the partition the fleet rides out elsewhere — must cost
    one timeout per cooldown window, not one per cell transition."""

    def __init__(self, url: str, *, campaign: Optional[str] = None,
                 total: int = 0, done: int = 0,
                 timeout_s: float = 2.0, backoff_s: float = 5.0):
        self.url = url.rstrip("/") + "/fleet/heartbeat"
        self.campaign = campaign
        self.timeout_s = float(timeout_s)
        self.backoff_s = float(backoff_s)
        self._down_until = 0.0
        self._post({"total": int(total), "init-done": int(done)})

    def _post(self, doc: Dict[str, Any]) -> None:
        import urllib.request

        if time.monotonic() < self._down_until:
            return  # coordinator recently unreachable: skip, don't stall
        body = dict(doc)
        if self.campaign:
            body["campaign"] = self.campaign
        try:
            req = urllib.request.Request(
                self.url, data=json.dumps(_jsonable(body)).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                pass
            self._down_until = 0.0
        except Exception:  # noqa: BLE001 — see no-raise guarantee
            self._down_until = time.monotonic() + self.backoff_s

    def worker(self, worker_id: str,
               state: Optional[Dict[str, Any]]) -> None:
        self._post({"worker": str(worker_id), "state": state})

    def record_done(self, run_id: str, valid: Any = None) -> None:
        self._post({"done": {"run": run_id, "valid?": valid}})

    def write(self, force: bool = False) -> None:
        pass  # every update is already pushed

    def close(self) -> None:
        self._post({"finished": True})
