"""The watchtower: a declarative SLO/alert engine (ISSUE 20 tentpole).

Every health signal the fleet already records — verdict freshness,
claim-latency p95, worker liveness, quarantine rate, journal growth,
RSS/device watermarks, compile-cache fallthrough — was only visible to
a human staring at ``/fleet`` or ``/metrics``.  This module makes the
store *watch itself*: a rule pack evaluated each autopilot/coordinator
tick against three signal sources, with Prometheus-style alert state
on the exposition and durable crash-safe notification bookkeeping.

Signal sources (cheap by construction — an evaluation tick must cost
O(rollup rows), never O(runs)):

- the **live registry** (``gauge:<name>`` / ``counter:<name>``,
  summed across label sets);
- **campaign heartbeat files** (``heartbeat:max-age-s`` and per-
  campaign ``heartbeat:<name>:age-s``/``done``/``total``);
- the **autopilot journal** (``autopilot:gate-regression``,
  ``autopilot:gate-rc2-streak``, ``autopilot:quarantined-active``);
- **store growth** (``store:fleet-bytes``);
- **warehouse rollups** (``warehouse:flip-regressions``,
  ``warehouse:span-p95-s:<name>`` — flip_rollup/span_rollup tables
  ONLY; the per-record tables are never touched).

Rule kinds:

``threshold``
    breach when the signal exists and ``value <op> rule.value``.
``absence``
    breach when the signal is missing from the snapshot.
``freshness``
    the signal is an age in seconds; breach when it is PRESENT and
    older than ``rule.value``.  A missing signal is quiet — an idle
    store with no campaigns must not page; pair with an ``absence``
    rule when the signal is required to exist.
``rate``
    breach when the signal's rate of change over ``window_s``
    satisfies ``<op> rule.value`` (per second).  The sample ring is
    in-memory derived state — never journaled.

State machine (per rule): ``inactive → pending → firing → resolved``.
A breach makes the rule pending; once it has held for ``for_s`` the
rule fires (``for_s == 0`` fires in the same tick — pending and firing
are both journaled, in order).  A clean tick resolves a pending or
firing rule; only resolve-from-firing notifies.

Durability is the ``AutopilotJournal`` discipline verbatim: an
append-only fsync'd jsonl ledger at ``<store>/alerts.jsonl``, torn
final line ignored on replay and healed before the first append,
``digest()`` pins the replayed state so kill -9 tests can compare
independent replays.  Notification is at-most-once: the ``notify``
INTENT is journaled *before* any sink send, so a crash between intent
and send loses at most one delivery and a replay never re-sends.
Sink results are a digest-excluded audit trail (same rule as the
autopilot's scale events).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["Rule", "AlertJournal", "AlertEngine", "FileSink",
           "WebhookSink", "alerts_path", "stock_rules", "load_rules",
           "load_config", "collect_signals", "STOCK_PACK"]

ALERTS_JSONL = "alerts.jsonl"

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}

_KINDS = ("threshold", "absence", "freshness", "rate")
_SEVERITIES = ("page", "warn", "info")


def alerts_path(base: str) -> str:
    """The journal lives at the store root — NOT under ``fleet/``,
    whose ``*.jsonl`` files the warehouse ingests as work ledgers."""
    return os.path.join(base, ALERTS_JSONL)


class Rule:
    """One declarative alert rule.  Plain data — ``from_dict`` /
    ``to_dict`` round-trip so packs load from JSON (specs/ ships an
    example)."""

    def __init__(self, name: str, *, kind: str = "threshold",
                 severity: str = "warn", signal: str = "",
                 op: str = ">", value: float = 0.0,
                 for_s: float = 0.0, window_s: float = 60.0,
                 description: str = ""):
        if kind not in _KINDS:
            raise ValueError(f"unknown rule kind {kind!r}")
        if severity not in _SEVERITIES:
            raise ValueError(f"unknown severity {severity!r}")
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r}")
        self.name = str(name)
        self.kind = kind
        self.severity = severity
        self.signal = str(signal)
        self.op = op
        self.value = float(value)
        self.for_s = float(for_s)
        self.window_s = float(window_s)
        self.description = str(description)

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "signal": self.signal,
                "op": self.op, "value": self.value,
                "for_s": self.for_s, "window_s": self.window_s,
                "description": self.description}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Rule":
        # `for:`/`window:` are the Prometheus-style spellings rule
        # files naturally use; the `_s`-suffixed forms are the
        # canonical to_dict() output — accept both
        return cls(d["name"],
                   kind=d.get("kind", "threshold"),
                   severity=d.get("severity", "warn"),
                   signal=d.get("signal", ""),
                   op=d.get("op", ">"),
                   value=d.get("value", 0.0),
                   for_s=d.get("for_s", d.get("for", 0.0)),
                   window_s=d.get("window_s", d.get("window", 60.0)),
                   description=d.get("description", ""))


#: The stock pack: the fleet's known failure smells, one rule each.
STOCK_PACK: Tuple[Dict[str, Any], ...] = (
    {"name": "campaign-heartbeat-stale", "kind": "freshness",
     "severity": "page", "signal": "heartbeat:max-age-s",
     "op": ">", "value": 300.0, "for_s": 0.0,
     "description": "verifier verdict freshness: a live campaign's "
                    "heartbeat has not been written for 5 minutes"},
    {"name": "fleet-claim-latency-p95-high", "kind": "threshold",
     "severity": "warn", "signal": "gauge:fleet-claim-latency-p95-s",
     "op": ">", "value": 5.0, "for_s": 10.0,
     "description": "workers wait too long between enqueue and claim"},
    {"name": "fleet-workers-alive-low", "kind": "threshold",
     "severity": "page", "signal": "gauge:fleet-workers-alive",
     "op": "<", "value": 1.0, "for_s": 5.0,
     "description": "worker liveness dropped to zero with work queued"},
    {"name": "quarantine-storm", "kind": "rate",
     "severity": "page", "signal": "gauge:fleet-quarantined-cells",
     "op": ">", "value": 0.2, "window_s": 60.0,
     "description": "quarantines accruing faster than one per 5s "
                    "sustained over a minute — gate or fleet sickness, "
                    "not a real regression"},
    {"name": "autopilot-gate-regression", "kind": "threshold",
     "severity": "page", "signal": "autopilot:gate-regression",
     "op": ">=", "value": 1.0, "for_s": 0.0,
     "description": "the latest closed generation's gate found a "
                    "perf regression (rc 1)"},
    {"name": "autopilot-gate-rc2-streak", "kind": "threshold",
     "severity": "warn", "signal": "autopilot:gate-rc2-streak",
     "op": ">=", "value": 3.0, "for_s": 0.0,
     "description": "three consecutive generations closed "
                    "inconclusive — the gate is starved of data"},
    {"name": "fleet-journal-bytes-growth", "kind": "rate",
     "severity": "warn", "signal": "store:fleet-bytes",
     "op": ">", "value": 1e6, "window_s": 60.0,
     "description": "fleet ledgers/journals growing >1MB/s sustained"},
    {"name": "worker-rss-watermark", "kind": "threshold",
     "severity": "warn", "signal": "gauge:worker-rss-peak-bytes",
     "op": ">", "value": 4e9, "for_s": 0.0,
     "description": "a worker's peak RSS crossed the 4GB watermark"},
    {"name": "compile-cache-fallthrough-rate", "kind": "rate",
     "severity": "warn", "signal": "counter:compile-cache-fallthrough",
     "op": ">", "value": 1.0, "window_s": 60.0,
     "description": "AOT cache misses falling through to online "
                    "compile faster than 1/s — pre-warm drifted from "
                    "the plan"},
)


def stock_rules() -> List[Rule]:
    return [Rule.from_dict(d) for d in STOCK_PACK]


def load_rules(doc: Any) -> List[Rule]:
    """Rules from a parsed JSON doc: either a bare list of rule dicts
    or ``{"rules": [...]}``."""
    rows = doc.get("rules") if isinstance(doc, dict) else doc
    return [Rule.from_dict(d) for d in (rows or [])]


def load_config(base: str) -> Tuple[List[Rule], List[Any]]:
    """Store-local config: ``<store>/alerts.json`` may override the
    rule pack and declare sinks (``{"rules": [...], "sinks":
    [{"file": path}, {"webhook": url}]}``).  Absent or unreadable →
    stock pack, no sinks."""
    path = os.path.join(base, "alerts.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return stock_rules(), []
    if isinstance(doc, list):
        return (load_rules(doc) or stock_rules()), []
    if not isinstance(doc, dict):
        return stock_rules(), []
    rules = load_rules(doc) if doc.get("rules") else stock_rules()
    sinks: List[Any] = []
    for s in doc.get("sinks") or []:
        if not isinstance(s, dict):
            continue
        if s.get("file"):
            p = s["file"]
            if not os.path.isabs(p):
                p = os.path.join(base, p)
            sinks.append(FileSink(p))
        elif s.get("webhook"):
            sinks.append(WebhookSink(s["webhook"],
                                     timeout=s.get("timeout", 3.0)))
    return rules, sinks


# -- sinks -------------------------------------------------------------------


class FileSink:
    """Append-one-json-line-per-notification sink — the soak test's
    duplicate counter and the zero-dep default."""

    def __init__(self, path: str):
        self.path = path

    def send(self, payload: Dict[str, Any]) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(payload, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def __repr__(self) -> str:
        return f"FileSink({self.path})"


class WebhookSink:
    """POST the notification JSON to a URL (stdlib urllib — zero
    deps).  Failures raise; the engine audits and moves on."""

    def __init__(self, url: str, timeout: float = 3.0):
        self.url = url
        self.timeout = float(timeout)

    def send(self, payload: Dict[str, Any]) -> None:
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            r.read()

    def __repr__(self) -> str:
        return f"WebhookSink({self.url})"


# -- journal -----------------------------------------------------------------


class AlertJournal:
    """Durable alert state: the exact ``AutopilotJournal`` /
    ``queue.WorkQueue`` discipline — in-memory state is a pure
    function of the event sequence, a torn final line (crash
    mid-append) is ignored on replay and healed by the writer before
    its first append, and ``digest`` pins the replayed state.

    Events: ``state`` (a rule's transition — pending/firing/resolved,
    each bumping the rule's transition ``seq``), ``notify`` (the
    at-most-once delivery INTENT, written before any sink send),
    ``notify-result`` (per-sink delivery audit — derived telemetry,
    digest-excluded, same rule as the autopilot's scale events)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.RLock()
        #: rule -> {state, since, value, severity, seq}
        self.states: Dict[str, Dict[str, Any]] = {}
        #: rule -> seq of the last journaled notify INTENT
        self.notified: Dict[str, int] = {}
        #: digest-excluded audit counters
        self.sends_ok = 0
        self.sends_failed = 0
        self._good_bytes = 0
        self._healed = False
        self._load()

    # -- replay --------------------------------------------------------------

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        good = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn tail: crash mid-append — ignore
            try:
                ev = json.loads(line.decode("utf-8"))
            except ValueError:
                break
            self._apply(ev)
            good += len(line)
        self._good_bytes = good

    def _apply(self, ev: Dict[str, Any]) -> None:
        kind = ev.get("ev")
        if kind == "state":
            rule = str(ev.get("rule"))
            st = self.states.get(rule) or {"seq": 0}
            st["state"] = ev.get("state")
            st["since"] = ev.get("at")
            st["value"] = ev.get("value")
            st["severity"] = ev.get("severity")
            st["seq"] = int(st.get("seq") or 0) + 1
            self.states[rule] = st
        elif kind == "notify":
            self.notified[str(ev.get("rule"))] = int(ev.get("seq") or 0)
        elif kind == "notify-result":
            if ev.get("ok"):
                self.sends_ok += 1
            else:
                self.sends_failed += 1

    # -- append --------------------------------------------------------------

    def _event(self, ev: Dict[str, Any]) -> Dict[str, Any]:
        ev = dict(ev)
        ev["ts"] = round(time.time(), 3)
        with self._lock:
            os.makedirs(os.path.dirname(self.path) or ".",
                        exist_ok=True)
            if not self._healed:
                # only the writer heals: truncate a torn tail right
                # before the first append so readers of a crashed
                # journal replay the same prefix we extend
                if os.path.exists(self.path) and \
                        os.path.getsize(self.path) > self._good_bytes:
                    with open(self.path, "rb+") as f:
                        f.truncate(self._good_bytes)
                self._healed = True
            with open(self.path, "ab") as f:
                f.write((json.dumps(ev, sort_keys=True) + "\n")
                        .encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            self._apply(ev)
        return ev

    def transition(self, rule: Rule, state: str, value: Any, *,
                   at: float) -> None:
        self._event({"ev": "state", "rule": rule.name, "state": state,
                     "value": value, "severity": rule.severity,
                     "at": round(float(at), 3)})

    def notify(self, rule: str, state: str, seq: int) -> None:
        """The at-most-once commit point: journaled BEFORE the send."""
        self._event({"ev": "notify", "rule": rule, "state": state,
                     "seq": int(seq)})

    def notify_result(self, rule: str, sink: str, ok: bool,
                      error: Optional[str] = None) -> None:
        self._event({"ev": "notify-result", "rule": rule,
                     "sink": sink, "ok": bool(ok), "error": error})

    # -- state ---------------------------------------------------------------

    def active(self) -> List[Dict[str, Any]]:
        """Pending + firing rules (the ``ALERTS`` exposition set)."""
        with self._lock:
            return sorted(
                (dict(v, rule=k) for k, v in self.states.items()
                 if v.get("state") in ("pending", "firing")),
                key=lambda d: d["rule"])

    def digest(self) -> str:
        """Replayed-state digest (notify-result audit counters
        excluded — they are derived telemetry, same rule as the
        autopilot's scale events)."""
        with self._lock:
            state = {
                "states": sorted(
                    (k, v.get("state"), v.get("since"), v.get("seq"),
                     v.get("severity"))
                    for k, v in self.states.items()),
                "notified": sorted(self.notified.items()),
            }
        blob = json.dumps(state, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


# -- signal collection -------------------------------------------------------


def collect_signals(base: Optional[str] = None, *,
                    registry: Any = None,
                    autopilot: Any = None,
                    warehouse: Any = None,
                    now: Optional[float] = None) -> Dict[str, float]:
    """One flat snapshot of every signal the rule selectors can
    reference.  Each source is best-effort and independently cheap;
    the warehouse leg reads ROLLUP tables only (``flip_rollup``,
    ``span_rollup``) so a 100k-run store costs the same tick as a
    100-run one."""
    now = time.time() if now is None else now
    out: Dict[str, float] = {}
    _registry_signals(out, registry)
    if base:
        _heartbeat_signals(out, base, now)
        _store_signals(out, base)
    _autopilot_signals(out, autopilot)
    _warehouse_signals(out, warehouse, base)
    return out


def _registry_signals(out: Dict[str, float], registry: Any) -> None:
    if registry is None:
        from jepsen_tpu import telemetry

        registry = telemetry.registry()
    try:
        snap = registry.snapshot()
    except Exception:  # noqa: BLE001 — a source never kills the tick
        return
    for g in snap.get("gauges") or []:
        v = g.get("value")
        if isinstance(v, (int, float)):
            key = f"gauge:{g['name']}"
            out[key] = out.get(key, 0.0) + float(v)
    for c in snap.get("counters") or []:
        v = c.get("value")
        if isinstance(v, (int, float)):
            key = f"counter:{c['name']}"
            out[key] = out.get(key, 0.0) + float(v)


def _heartbeat_signals(out: Dict[str, float], base: str,
                       now: float) -> None:
    cdir = os.path.join(base, "campaigns")
    if not os.path.isdir(cdir):
        return
    max_age = None
    try:
        names = sorted(os.listdir(cdir))
    except OSError:
        return
    for fn in names:
        if not fn.endswith(".live.json"):
            continue
        try:
            with open(os.path.join(cdir, fn)) as f:
                hb = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(hb, dict):
            continue
        name = hb.get("campaign") or fn[:-len(".live.json")]
        upd = hb.get("updated")
        if isinstance(upd, (int, float)):
            age = max(0.0, round(now - upd, 3))
            out[f"heartbeat:{name}:age-s"] = age
            if not hb.get("finished") and \
                    (max_age is None or age > max_age):
                max_age = age
        for k in ("done", "total"):
            v = hb.get(k)
            if isinstance(v, (int, float)):
                out[f"heartbeat:{name}:{k}"] = float(v)
        out[f"heartbeat:{name}:finished"] = \
            1.0 if hb.get("finished") else 0.0
    if max_age is not None:
        out["heartbeat:max-age-s"] = max_age


def _store_signals(out: Dict[str, float], base: str) -> None:
    """Growth watermarks: total bytes under ``<store>/fleet/`` (work
    ledgers, autopilot journals, staging) + the alerts journal."""
    total = 0
    fdir = os.path.join(base, "fleet")
    if os.path.isdir(fdir):
        for root, _dirs, files in os.walk(fdir):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(root, fn))
                except OSError:
                    pass
    out["store:fleet-bytes"] = float(total)


def _autopilot_signals(out: Dict[str, float], autopilot: Any) -> None:
    """Gate state straight off the (already in-memory) journal:
    regression in the latest closed generation, the trailing rc-2
    streak, and the active-quarantine census."""
    journal = getattr(autopilot, "journal", autopilot)
    if journal is None or not hasattr(journal, "gens"):
        return
    try:
        closed = [l for l in journal.order
                  if journal.gens[l].get("closed")]
        regression = 0.0
        streak = 0.0
        if closed:
            last = journal.gens[closed[-1]].get("verdicts") or []
            regression = 1.0 if any(
                v.get("rc") == 1 for v in last) else 0.0
            for l in reversed(closed):
                vs = journal.gens[l].get("verdicts") or []
                if vs and all(v.get("rc") == 2 for v in vs):
                    streak += 1
                else:
                    break
        out["autopilot:gate-regression"] = regression
        out["autopilot:gate-rc2-streak"] = streak
        out["autopilot:quarantined-active"] = float(sum(
            1 for v in journal.quarantined.values()
            if "paroled-gen" not in v))
    except Exception:  # noqa: BLE001 — a source never kills the tick
        pass


def _warehouse_signals(out: Dict[str, float], warehouse: Any,
                       base: Optional[str]) -> None:
    """Rollup-table-only aggregates.  ``warehouse`` may be a
    Warehouse instance; when None and a store warehouse exists it is
    opened read-only.  NEVER queries campaign_records/record_spans —
    the O(rollup rows) pin."""
    wh = warehouse
    if wh is None and base:
        try:
            from . import warehouse as wmod

            wh = wmod.open_if_exists(base)
        except Exception:  # noqa: BLE001
            return
    if wh is None:
        return
    try:
        sig = wh.alert_signals()
    except Exception:  # noqa: BLE001 — a source never kills the tick
        return
    for k, v in (sig or {}).items():
        if isinstance(v, (int, float)):
            out[f"warehouse:{k}"] = float(v)


# -- engine ------------------------------------------------------------------


class AlertEngine:
    """Evaluate the rule pack against a signal snapshot, drive the
    per-rule state machine through the journal, and deliver
    at-most-once notifications through ``device_call``-guarded
    sinks."""

    def __init__(self, base: str, *, rules: Optional[List[Rule]] = None,
                 sinks: Optional[List[Any]] = None,
                 journal: Optional[AlertJournal] = None):
        self.base = base
        if rules is None and sinks is None:
            rules, sinks = load_config(base)
        self.rules = list(rules) if rules is not None else stock_rules()
        self.sinks = list(sinks or [])
        self.journal = journal or AlertJournal(alerts_path(base))
        #: rule -> [(ts, value)] sample ring for rate rules — derived
        #: state, deliberately NOT journaled (a replay restarts the
        #: window; a rate alert needs window_s of post-restart data
        #: before it can re-breach, which is the conservative side)
        self._samples: Dict[str, List[Tuple[float, float]]] = {}

    # -- breach tests --------------------------------------------------------

    def _breach(self, rule: Rule, value: Optional[float],
                now: float) -> bool:
        if rule.kind == "absence":
            return value is None
        if rule.kind == "freshness":
            return value is not None and _OPS[">"](value, rule.value)
        if rule.kind == "rate":
            return self._rate_breach(rule, value, now)
        if value is None:
            return False
        return _OPS[rule.op](float(value), rule.value)

    def _rate_breach(self, rule: Rule, value: Optional[float],
                     now: float) -> bool:
        if value is None:
            return False
        buf = self._samples.setdefault(rule.name, [])
        buf.append((now, float(value)))
        horizon = now - max(rule.window_s, 1e-9)
        while len(buf) > 1 and buf[1][0] <= horizon:
            buf.pop(0)
        if buf[0][0] > horizon or len(buf) < 2:
            return False  # window not yet covered — no verdict
        dt = buf[-1][0] - buf[0][0]
        if dt <= 0:
            return False
        rate = (buf[-1][1] - buf[0][1]) / dt
        return _OPS[rule.op](rate, rule.value)

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, signals: Optional[Dict[str, float]] = None, *,
                 now: Optional[float] = None,
                 autopilot: Any = None,
                 warehouse: Any = None) -> Dict[str, Any]:
        """One tick: snapshot signals (unless given), run every rule
        through the state machine, notify transitions.  Returns the
        status doc."""
        now = time.time() if now is None else now
        if signals is None:
            signals = collect_signals(self.base, autopilot=autopilot,
                                      warehouse=warehouse, now=now)
        for rule in self.rules:
            value = signals.get(rule.signal)
            breach = self._breach(rule, value, now)
            st = self.journal.states.get(rule.name) or {}
            state = st.get("state") or "inactive"
            if breach:
                if state in ("inactive", "resolved"):
                    self.journal.transition(rule, "pending", value,
                                            at=now)
                    state = "pending"
                if state == "pending":
                    since = self.journal.states[rule.name].get("since")
                    if since is None or now - since >= rule.for_s:
                        self.journal.transition(rule, "firing", value,
                                                at=now)
                        self._notify(rule, "firing", value)
            elif state in ("pending", "firing"):
                self.journal.transition(rule, "resolved", value,
                                        at=now)
                if state == "firing":
                    self._notify(rule, "resolved", value)
        return self.status_doc()

    def _notify(self, rule: Rule, state: str,
                value: Optional[float]) -> None:
        """At-most-once delivery: the journal INTENT is the commit
        point (a crash after intent, before send, drops the delivery
        rather than ever duplicating it; replay sees the intent's seq
        and skips)."""
        seq = int(self.journal.states[rule.name].get("seq") or 0)
        if self.journal.notified.get(rule.name) == seq:
            return  # intent already journaled for this transition
        self.journal.notify(rule.name, state, seq)
        if not self.sinks:
            return
        payload = {"alertname": rule.name, "state": state,
                   "severity": rule.severity, "signal": rule.signal,
                   "value": value, "description": rule.description}
        from jepsen_tpu import resilience

        for sink in self.sinks:
            try:
                resilience.device_call("alerts.notify", sink.send,
                                       payload)
            except Exception as e:  # noqa: BLE001 — audit, move on
                self.journal.notify_result(
                    rule.name, repr(sink), False,
                    error=f"{type(e).__name__}: {e}")
            else:
                self.journal.notify_result(rule.name, repr(sink), True)

    # -- reporting -----------------------------------------------------------

    def status_doc(self) -> Dict[str, Any]:
        active = self.journal.active()
        return {
            "rules": len(self.rules),
            "firing": [d["rule"] for d in active
                       if d.get("state") == "firing"],
            "pending": [d["rule"] for d in active
                        if d.get("state") == "pending"],
            "active": active,
            "sends-ok": self.journal.sends_ok,
            "sends-failed": self.journal.sends_failed,
            "digest": self.journal.digest(),
        }
