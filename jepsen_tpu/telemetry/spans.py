"""Nestable timed spans — the tracing half of the telemetry layer.

A :class:`Collector` owns one span forest per run: every thread that
opens a span gets its own root chain (thread-local stacks), so the
interpreter's worker threads, the knossos race legs, and the main
orchestration loop each land on their own timeline row in the Chrome
trace export.  Spans nest via context managers (or the :func:`traced`
decorator) and carry free-form attributes (op counts, history length,
device vs host, jit compile vs execute ...).

Cost contract (ISSUE 1): telemetry must be off-by-default-cheap.  The
disabled path is the module-level :data:`NOOP` singleton whose
``span()`` returns one shared no-op context manager — no allocation, no
clock read, no locks.  Hot loops additionally guard per-op work with
``collector.enabled``.

Clocks: span timing uses ``time.perf_counter_ns()`` (monotonic,
comparable across threads in one process); the collector anchors that
to wall time once at construction so exports can place the run in
absolute time.
"""

from __future__ import annotations

import contextlib
import functools
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Collector", "NoopCollector", "NOOP", "active",
           "activate", "deactivate", "span", "traced", "enabled",
           "current", "TraceContext", "TRACE_HEADER", "mint_trace",
           "trace_id_for", "parse_trace_header", "current_trace",
           "set_trace", "trace_scope", "add_phase", "PHASE_BUCKETS"]

# ---------------------------------------------------------------------------
# Distributed trace context (ISSUE 14 tentpole a)
#
# One W3C-style (trace_id, span_id, parent_id) triple follows a run
# across every control-plane seam — coordinator claim/complete,
# verifier ingest/verdict/seal, artifact uploads — in a ``Jepsen-Trace``
# header.  The trace id is a PURE FUNCTION of the run id (minted at
# enqueue, stable across retries/resends and lease-lapse re-executions),
# so every process that knows which run it is working on derives the
# same id without coordination, and the warehouse can stitch a
# cross-host timeline from artifacts that never traveled together.
# ---------------------------------------------------------------------------

#: the HTTP header carrying the trace triple across control-plane seams
TRACE_HEADER = "Jepsen-Trace"


def trace_id_for(run_id: str) -> str:
    """The run's trace id: 32 hex chars, deterministically derived from
    the stable run id — NOT per-attempt, so a retried claim, a resent
    chunk, or a lease-lapse re-execution all land on ONE trace."""
    return hashlib.sha256(
        ("jepsen-trace:" + str(run_id)).encode()).hexdigest()[:32]


def _span_id(*parts: str) -> str:
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


class TraceContext:
    """One point on a distributed trace: ``trace_id`` names the run's
    whole cross-host story, ``span_id`` this segment, ``parent_id`` the
    segment that caused it (empty at the root)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def child(self, name: str) -> "TraceContext":
        """A deterministic child segment: same trace, a span id derived
        from (trace, parent, name) — two hosts naming the same segment
        of the same run agree on its identity."""
        return TraceContext(self.trace_id,
                            _span_id(self.trace_id, self.span_id, name),
                            self.span_id)

    def header(self) -> str:
        """``Jepsen-Trace`` header value (W3C traceparent-shaped):
        ``00-<trace_id>-<span_id>-01``."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_dict(self) -> Dict[str, str]:
        out = {"trace-id": self.trace_id, "span-id": self.span_id}
        if self.parent_id:
            out["parent-id"] = self.parent_id
        return out

    def __repr__(self) -> str:
        return (f"<TraceContext {self.trace_id[:8]}../{self.span_id}"
                f"{' <- ' + self.parent_id if self.parent_id else ''}>")


def mint_trace(run_id: str) -> TraceContext:
    """The run's ROOT trace context, minted at enqueue (or at
    single-process execute) — seeded from the run id, so every mint of
    the same run is the same trace."""
    tid = trace_id_for(run_id)
    return TraceContext(tid, _span_id(tid, "root"))


def trace_context(trace_id: str, segment: str = "run") -> TraceContext:
    """A named segment context on an EXISTING trace (the receiver side
    of a propagated trace id): deterministic span id from (trace,
    segment), parented on the trace root."""
    tid = str(trace_id)
    return TraceContext(tid, _span_id(tid, segment),
                        _span_id(tid, "root"))


def parse_trace_header(value: Optional[str]) -> Optional["TraceContext"]:
    """Parse a ``Jepsen-Trace`` header back into a context; the
    header's span id becomes the receiver's ``parent_id`` (the sender's
    segment caused whatever the receiver does next).  Malformed values
    parse to None — a bad header must never fail a control-plane
    request."""
    if not value:
        return None
    parts = str(value).strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return TraceContext(parts[1], parts[2], parts[2])


_trace_tls = threading.local()


def current_trace() -> Optional[TraceContext]:
    """The trace context installed on THIS thread (None outside any
    traced request/run)."""
    return getattr(_trace_tls, "ctx", None)


def set_trace(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Install `ctx` as this thread's trace context; returns the
    previous one (restore it when done, or use :func:`trace_scope`)."""
    prev = getattr(_trace_tls, "ctx", None)
    _trace_tls.ctx = ctx
    return prev


@contextlib.contextmanager
def trace_scope(ctx: Optional[TraceContext]):
    """``with trace_scope(ctx): ...`` — the handler-side seam: parse
    the incoming header, run the handler under it, restore."""
    prev = set_trace(ctx)
    try:
        yield ctx
    finally:
        set_trace(prev)


class Span:
    """One timed node in the span tree.  ``t0``/``t1`` are
    perf_counter_ns values; ``t1`` is None while the span is open."""

    __slots__ = ("name", "t0", "t1", "attrs", "children", "tid",
                 "thread_name", "ann")

    def __init__(self, name: str, tid: int, thread_name: str,
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t0 = time.perf_counter_ns()
        self.t1: Optional[int] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.children: List[Span] = []
        self.tid = tid
        self.thread_name = thread_name
        # profiler-bridge annotation ctx (Collector.annotate runs):
        # entered at push, exited at pop, same thread both times
        self.ann: Optional[Any] = None

    @property
    def duration_ns(self) -> Optional[int]:
        return None if self.t1 is None else self.t1 - self.t0

    def set_attr(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def __repr__(self) -> str:
        d = self.duration_ns
        return (f"<Span {self.name} "
                f"{'open' if d is None else f'{d / 1e6:.3f}ms'} "
                f"children={len(self.children)}>")


class _SpanCtx:
    """Context manager binding one Span to a collector's thread stack."""

    __slots__ = ("_collector", "_name", "_attrs", "span")

    def __init__(self, collector: "Collector", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._collector = collector
        self._name = name
        self._attrs = attrs
        self.span: Optional[Span] = None

    def __enter__(self) -> Span:
        self.span = self._collector._push(self._name, self._attrs)
        return self.span

    def __exit__(self, *exc) -> bool:
        self._collector._pop(self.span)
        return False


class _NoopSpan:
    """Shared stand-in for both the no-op context manager and the span
    it yields; every operation is a cheap no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attr(self, **attrs: Any) -> "_NoopSpan":
        return self

    attrs: Dict[str, Any] = {}
    duration_ns = None


_NOOP_SPAN = _NoopSpan()


class Collector:
    """Thread-safe span collector for one run (or one process session).

    Each thread keeps its own span stack; a span opened with an empty
    stack becomes a root.  ``roots`` and cross-thread registration are
    lock-protected; within a thread, push/pop touch only thread-local
    state.

    Each collector owns a fresh metrics registry: while it is active,
    ``telemetry.registry()`` resolves to it, so a run's exported
    counters cover exactly that run (a second telemetric run in one
    process does not inherit the first run's tallies).

    Streaming (ISSUE 5): ``stream`` is an attached flight-recorder
    ``EventStream`` (see :func:`stream.attach`) — span opens/closes
    are emitted as they happen so a killed run leaves a partial trace.
    ``annotate=True`` bridges every span to the JAX profiler: the span
    body runs inside a ``TraceAnnotation`` of the same name, so a
    ``--profile-dir`` run interleaves host spans with XLA kernels on
    one Perfetto timeline."""

    enabled = True
    stream: Optional[Any] = None
    annotate = False
    #: the run's distributed trace context (ISSUE 14): when set, root
    #: spans carry trace_id/span_id attrs and the export stamps the
    #: triple into telemetry.json for warehouse stitching
    trace: Optional[TraceContext] = None

    def __init__(self):
        from .metrics import Registry

        self._tls = threading.local()
        self._lock = threading.Lock()
        self.roots: List[Span] = []
        self.registry = Registry()
        # wall-clock anchor: epoch_ns + (t - perf0_ns) locates any span
        # in absolute time
        self.perf0_ns = time.perf_counter_ns()
        self.epoch_ns = time.time_ns()

    # -- span API ----------------------------------------------------------

    def span(self, name: str, /, **attrs: Any) -> _SpanCtx:
        return _SpanCtx(self, name, attrs or None)

    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    # -- internals ---------------------------------------------------------

    def _push(self, name: str, attrs: Optional[Dict[str, Any]]) -> Span:
        t = threading.current_thread()
        sp = Span(name, t.ident or 0, t.name, attrs)
        if self.annotate:
            try:
                from jepsen_tpu.utils.profiling import annotate

                sp.ann = annotate(name)
                sp.ann.__enter__()
            except Exception:  # noqa: BLE001 — bridging is best-effort
                sp.ann = None
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        if stack:
            stack[-1].children.append(sp)
        else:
            if self.trace is not None:
                # roots only: per-span stamping would bloat the export
                # for zero stitch value (children inherit by nesting)
                sp.attrs.setdefault("trace_id", self.trace.trace_id)
                sp.attrs.setdefault("span_id", self.trace.span_id)
            with self._lock:
                self.roots.append(sp)
        stack.append(sp)
        if self.stream is not None:
            self.stream.span_open(sp)
        return sp

    def _pop(self, sp: Optional[Span]) -> None:
        if sp is None:
            return
        sp.t1 = time.perf_counter_ns()
        stack = getattr(self._tls, "stack", None)
        # tolerate exits out of order (a crashed body that skipped
        # children's __exit__): unwind to and including sp
        while stack:
            top = stack.pop()
            if top.t1 is None:
                top.t1 = sp.t1
            ann, top.ann = top.ann, None
            if ann is not None:
                try:  # innermost-first pop order matches TraceAnnotation
                    ann.__exit__(None, None, None)
                except Exception:  # noqa: BLE001
                    pass
            if self.stream is not None:
                self.stream.span_close(top)
            if top is sp:
                break

    # -- finalization ------------------------------------------------------

    def close_open_spans(self) -> None:
        """Stamp a provisional end on every still-open span (export can
        run mid-span, e.g. from inside store.save_1's own span).  Open
        spans also get the current memory high watermarks (ISSUE 16):
        the root ``run`` span is still open when telemetry.json is
        written, and its real close stamps only the event stream."""
        now = time.perf_counter_ns()
        wm: Dict[str, Any] = {}
        st = self.stream
        if st is not None and getattr(st, "watermarks", None) is not None:
            try:
                wm = st.watermarks() or {}
            except Exception:  # noqa: BLE001 — stamping is best-effort
                wm = {}

        def walk(sp: Span) -> None:
            if sp.t1 is None:
                sp.attrs.setdefault("open", True)
                if wm:
                    sp.attrs.update(wm)
                sp.t1 = now
            for c in sp.children:
                walk(c)

        with self._lock:
            for r in self.roots:
                walk(r)


class NoopCollector:
    """The disabled collector: a no-op singleton.  ``span()`` hands back
    one shared object; nothing is recorded."""

    enabled = False
    roots: List[Span] = []
    registry = None  # telemetry.registry() falls back to the default
    stream = None
    annotate = False
    trace = None

    def span(self, name: str, /, **attrs: Any) -> _NoopSpan:
        return _NOOP_SPAN

    def current(self) -> None:
        return None

    def close_open_spans(self) -> None:
        pass


NOOP = NoopCollector()

# process-wide active collector; module-level so instrumentation sites
# (interpreter workers, checker internals) need no plumbing
_active: Any = NOOP
_active_lock = threading.Lock()


def active() -> Any:
    """The currently-active collector (NOOP when telemetry is off)."""
    return _active


def enabled() -> bool:
    return _active.enabled


def activate(collector: Optional[Collector] = None) -> Collector:
    """Install `collector` (a fresh one by default) as the process-wide
    active collector; returns it.  The previous collector is remembered
    so nested activations restore correctly via :func:`deactivate`."""
    global _active
    c = collector or Collector()
    with _active_lock:
        prev = _active
        c._prev = prev  # type: ignore[attr-defined]
        _active = c
    return c


def deactivate(collector: Optional[Collector] = None) -> None:
    """Remove `collector` (default: whatever is active), restoring its
    predecessor."""
    global _active
    with _active_lock:
        c = collector or _active
        if c is _active and c is not NOOP:
            _active = getattr(c, "_prev", NOOP) or NOOP


def span(name: str, /, **attrs: Any):
    """Open a span on the active collector — the one-liner used by
    instrumentation sites::

        with telemetry.span("elle.infer", txns=n) as sp:
            ...
            sp.set_attr(edges=m)
    """
    return _active.span(name, **attrs)


def current() -> Optional[Span]:
    """The innermost open span on this thread (None when disabled or
    at top level) — for attaching attributes after the fact."""
    return _active.current()


#: the phase self-time taxonomy (ISSUE 16): where a span's wall time
#: actually went.  compile_s/execute_s predate this list (stamped by
#: `resilience.guard._stamp_device_time`); the rest are accumulated by
#: their owning subsystems via :func:`add_phase`.  Bucket attrs are
#: plain ``*_s`` float seconds on span attrs, so they ride the existing
#: telemetry.json → ledger → warehouse path with no schema change to
#: the span structure itself.
PHASE_BUCKETS = ("compile_s", "execute_s", "queue_wait_s",
                 "host_pack_s", "device_dispatch_s", "sweep_s",
                 "journal_fsync_s")


def add_phase(bucket: str, seconds: float) -> None:
    """Accumulate `seconds` of phase self-time into `bucket` on the
    innermost open span of this thread.  The disabled path is one
    attribute lookup returning None — cheap enough for hot loops; the
    enabled path is two dict ops.  Never raises."""
    sp = _active.current()
    if sp is None:
        return
    try:
        sp.attrs[bucket] = float(sp.attrs.get(bucket) or 0.0) + float(
            seconds)
    except Exception:  # noqa: BLE001 — accounting must never fail a run
        pass


def traced(name: Optional[str] = None, **attrs: Any):
    """Decorator form: time every call of the function as a span."""

    def deco(fn):
        sp_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            with _active.span(sp_name, **attrs):
                return fn(*args, **kw)

        return wrapper

    return deco


class PhaseTimer:
    """Sequential sibling spans for long linear functions: each
    ``start()`` closes the previous phase and opens the next, without
    the re-indentation a ``with`` block per phase would force::

        ph = telemetry.phases()
        ph.start("elle.infer", txns=n)
        ...
        ph.start("elle.cycle-sweep")
        ...
        ph.end()

    An exception mid-phase leaves the span open; the collector stamps a
    provisional end at export (`close_open_spans`)."""

    __slots__ = ("_collector", "_ctx")

    def __init__(self, collector: Any):
        self._collector = collector
        self._ctx: Any = None

    def start(self, name: str, /, **attrs: Any):
        self.end()
        self._ctx = self._collector.span(name, **attrs)
        return self._ctx.__enter__()

    def end(self) -> None:
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None


def phases() -> PhaseTimer:
    """A :class:`PhaseTimer` over the active collector (no-op when
    telemetry is off)."""
    return PhaseTimer(_active)
