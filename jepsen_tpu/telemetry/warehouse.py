"""The observatory: a zero-dep sqlite telemetry warehouse (ISSUE 6).

PRs 1 and 5 made every run *emit* rich telemetry (``telemetry.json``
span forests, ``events.jsonl`` streams, campaign jsonl ledgers), but
*querying* it still meant re-parsing every file per request — the jsonl
scan cost that bites ``Index.flips()`` / ``span_trend()`` at fleet
scale.  This module ingests all of it into one indexed sqlite file,
``<store>/warehouse.sqlite``, and pushes the hot campaign queries down
to SQL.

Contracts:

- **The jsonl ledgers stay the source of truth.**  The warehouse is a
  derived index: ``rebuild()`` (``cli obs rebuild``) reconstructs it
  from scratch at any time, and every query surface keeps a jsonl
  fallback for stores that never built one.
- **Ingest is incremental.**  Campaign ledgers are keyed by a byte
  cursor (only appended records are parsed on re-ingest), run dirs by a
  stat digest of their artifacts (an unchanged run is a no-op), event
  streams by the live file's byte cursor plus a rotated-segment
  signature.  Re-ingesting an unchanged store touches nothing.
- **Crash-consistent.**  Each ingest unit (one ledger, one run dir,
  one dir's event stream) commits atomically; a crash mid-ingest rolls
  the in-flight unit back and the next ingest simply redoes it.
- **Exact.**  The SQL-backed queries return byte-identical results to
  the jsonl scans (asserted in tests): same ordering, same percentile
  formula, same rounding.

Tables (see ``docs/TELEMETRY.md`` for the query cookbook):

- ``campaign_records`` + ``record_spans`` — one row per ledger record,
  span durations exploded for indexed trend queries.
- ``runs`` / ``run_spans`` / ``run_metrics`` — per run dir: verdict +
  attribution flags, per-span total/count, counter & gauge snapshot;
  runs retired to ``_archive/`` by ``obs gc`` keep their rows with
  ``archived = 1`` (schema v6) so the history stays queryable.
- ``witnesses`` — minimal-witness summaries (``witness.json``).
- ``events`` — streamed flight-recorder events (``cli tail --since``).
- ``bench`` — BENCH payloads (``bench.py`` self-ingests; ``cli obs
  ingest --bench BENCH_r0*.json`` loads the committed trajectory).
- ``ledgers`` / ``event_cursors`` — the incremental-ingest bookkeeping.
"""

from __future__ import annotations

import json
import logging
import os
import re
import sqlite3
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("jepsen.warehouse")

__all__ = ["Warehouse", "warehouse_path", "open_if_exists", "for_ledger",
           "WAREHOUSE_FILE", "SCHEMA_VERSION"]

WAREHOUSE_FILE = "warehouse.sqlite"
SCHEMA_VERSION = 7

_SCHEMA = """
CREATE TABLE IF NOT EXISTS meta(
    key TEXT PRIMARY KEY, value TEXT);
CREATE TABLE IF NOT EXISTS ledgers(
    path TEXT PRIMARY KEY,          -- store-relative ledger path
    cursor INTEGER NOT NULL DEFAULT 0);
CREATE TABLE IF NOT EXISTS campaign_records(
    id INTEGER PRIMARY KEY,
    ledger TEXT NOT NULL,
    campaign TEXT, run TEXT, key TEXT,
    workload TEXT, fault TEXT, seed TEXT,  -- seed JSON-encoded
    valid TEXT,                     -- JSON-encoded verdict; NULL=absent
    error TEXT, degraded TEXT, deadline INTEGER,
    dir TEXT, ops INTEGER, wall_s REAL,
    gen TEXT, spec TEXT, ts TEXT,
    witness TEXT,                   -- JSON witness summary, or NULL
    trace TEXT,                     -- distributed trace id (ISSUE 14)
    phases TEXT,                    -- {span: {bucket: s}} JSON (ISSUE 16)
    counters TEXT);                 -- forensic counter totals, JSON
CREATE INDEX IF NOT EXISTS cr_ledger_key ON campaign_records(ledger, key, id);
CREATE INDEX IF NOT EXISTS cr_ledger_run ON campaign_records(ledger, run, id);
CREATE TABLE IF NOT EXISTS record_spans(
    record_id INTEGER NOT NULL,
    ledger TEXT NOT NULL,
    name TEXT NOT NULL,
    dur_s REAL NOT NULL);
CREATE INDEX IF NOT EXISTS rs_ledger_name ON record_spans(ledger, name, record_id);
CREATE INDEX IF NOT EXISTS rs_record ON record_spans(record_id);
-- materialized at ingest time (the hot queries are O(result), not
-- O(records)): verdict flips per regression key, and per-span-name
-- duration rollups (whole-ledger stats + per-generation p95)
CREATE TABLE IF NOT EXISTS flip_rollup(
    record_id INTEGER NOT NULL,     -- the id of the LATER record
    ledger TEXT NOT NULL,
    key TEXT NOT NULL, run TEXT,
    from_valid TEXT NOT NULL, to_valid TEXT NOT NULL,
    regression INTEGER NOT NULL, ts TEXT, gen TEXT);
CREATE INDEX IF NOT EXISTS flr_ledger ON flip_rollup(ledger, key, record_id);
CREATE TABLE IF NOT EXISTS span_rollup(
    ledger TEXT NOT NULL, name TEXT NOT NULL,
    count INTEGER NOT NULL, min REAL, p50 REAL, p95 REAL, max REAL,
    PRIMARY KEY(ledger, name));
CREATE TABLE IF NOT EXISTS span_gen_rollup(
    ledger TEXT NOT NULL, name TEXT NOT NULL,
    gen TEXT NOT NULL,              -- str(gen or "?"), the trend label
    first_id INTEGER NOT NULL,      -- first sample's record id: order
    p95 REAL,
    PRIMARY KEY(ledger, name, gen));
CREATE TABLE IF NOT EXISTS runs(
    dir TEXT PRIMARY KEY,           -- store-relative run dir
    name TEXT, ts TEXT,
    digest TEXT NOT NULL,
    valid TEXT, error TEXT, degraded TEXT, deadline INTEGER,
    status TEXT NOT NULL DEFAULT 'done',  -- 'running' until results.json
    archived INTEGER NOT NULL DEFAULT 0,  -- 1: retired to _archive/
    ingested_at REAL);
CREATE TABLE IF NOT EXISTS verifier_sessions(
    name TEXT PRIMARY KEY,          -- session dir name
    state TEXT, valid TEXT, anomalies TEXT,
    txns INTEGER, ops INTEGER, segments INTEGER,
    digest TEXT, seal_equal INTEGER, updated REAL);
CREATE TABLE IF NOT EXISTS run_spans(
    dir TEXT NOT NULL, name TEXT NOT NULL,
    total_s REAL NOT NULL, count INTEGER NOT NULL);
CREATE INDEX IF NOT EXISTS runsp_dir ON run_spans(dir);
CREATE INDEX IF NOT EXISTS runsp_name ON run_spans(name);
CREATE TABLE IF NOT EXISTS run_metrics(
    dir TEXT NOT NULL, kind TEXT NOT NULL,
    name TEXT NOT NULL, labels TEXT NOT NULL, value REAL);
CREATE INDEX IF NOT EXISTS runm_dir ON run_metrics(dir);
CREATE TABLE IF NOT EXISTS witnesses(
    dir TEXT PRIMARY KEY,
    ops INTEGER, source_ops INTEGER, digest TEXT,
    anomalies TEXT, probes INTEGER);
CREATE TABLE IF NOT EXISTS events(
    id INTEGER PRIMARY KEY,
    dir TEXT NOT NULL, t REAL, ev TEXT, doc TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS ev_dir_t ON events(dir, t, id);
CREATE TABLE IF NOT EXISTS event_cursors(
    dir TEXT PRIMARY KEY,
    cursor INTEGER NOT NULL,        -- byte cursor into the LIVE file
    sig TEXT NOT NULL,              -- rotated-segment signature (JSON)
    head TEXT NOT NULL DEFAULT ''); -- live file's first line (session id)
CREATE TABLE IF NOT EXISTS fleet_events(
    id INTEGER PRIMARY KEY,
    ledger TEXT NOT NULL,           -- store-relative fleet ledger path
    ev TEXT, run TEXT, worker TEXT, reason TEXT, ts REAL,
    deadline REAL,
    spans TEXT);                    -- complete events: the record's
                                    -- fleet:* segment durations (JSON)
CREATE INDEX IF NOT EXISTS fe_ledger_ev ON fleet_events(ledger, ev, id);
CREATE INDEX IF NOT EXISTS fe_worker ON fleet_events(ledger, worker, id);
-- materialized per-worker rollup (the "which host's cells requeue
-- most" query): recomputed per ingest batch from fleet_events
CREATE TABLE IF NOT EXISTS fleet_worker_rollup(
    ledger TEXT NOT NULL, worker TEXT NOT NULL,
    claims INTEGER NOT NULL DEFAULT 0,
    renews INTEGER NOT NULL DEFAULT 0,
    completes INTEGER NOT NULL DEFAULT 0,
    requeues INTEGER NOT NULL DEFAULT 0,
    duplicates INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY(ledger, worker));
CREATE TABLE IF NOT EXISTS bench(
    source TEXT PRIMARY KEY,
    ingested_at REAL,
    metric TEXT, value REAL, unit TEXT, vs_baseline REAL,
    n_txns INTEGER, backend TEXT, wall_s REAL,
    compile_or_warmup_s REAL, doc TEXT NOT NULL);
-- cross-host timeline stitching (ISSUE 14, schema v4): one row per
-- host-attributed trace segment, stitched from fleet ledgers (enqueue
-- wait / attempts / execute), landed run dirs (telemetry.json phase
-- spans on absolute time), and verifier session snapshots (live
-- sessions).  trace_id is a pure function of the run id, so segments
-- from artifacts that never traveled together join on it.
CREATE TABLE IF NOT EXISTS trace_spans(
    id INTEGER PRIMARY KEY,
    trace_id TEXT NOT NULL,
    origin TEXT NOT NULL,          -- ingest unit, for per-unit wipes
    source TEXT NOT NULL,          -- fleet | run | verifier
    run TEXT, host TEXT,
    name TEXT NOT NULL,
    t0 REAL, t1 REAL, dur_s REAL);
CREATE INDEX IF NOT EXISTS tsp_trace ON trace_spans(trace_id, t0, id);
CREATE INDEX IF NOT EXISTS tsp_run ON trace_spans(run);
CREATE INDEX IF NOT EXISTS tsp_origin ON trace_spans(origin);
-- per-(site, shape-class) device-call profile (ISSUE 16, schema v5):
-- one row per run dir per shape class, exploded from the span attrs
-- `resilience.guard._stamp_device_time` accumulates — the `cli obs
-- profile` treemap's raw material, host-attributed for fleet stitching
CREATE TABLE IF NOT EXISTS span_profile(
    dir TEXT NOT NULL,             -- origin run dir, for per-unit wipes
    host TEXT,
    site TEXT NOT NULL,
    shape TEXT NOT NULL,
    calls INTEGER NOT NULL DEFAULT 0,
    compile_s REAL NOT NULL DEFAULT 0,
    execute_s REAL NOT NULL DEFAULT 0,
    device_dispatch_s REAL NOT NULL DEFAULT 0);
CREATE INDEX IF NOT EXISTS spf_dir ON span_profile(dir);
CREATE INDEX IF NOT EXISTS spf_site ON span_profile(site, shape);
-- generation-horizon compaction (ISSUE 20, schema v7): past a kept
-- horizon, a campaign ledger's raw per-record rows fold into bounded
-- per-generation summaries and are DROPPED (witness-bearing rows
-- survive so witness queries stay exact).  flip_rollup and
-- span_gen_rollup rows are never dropped — the compact-safe queries
-- (flips / span_trend / witness_diffs / the alert tick) union
-- compacted + live transparently, everything else falls back to the
-- jsonl scan once a ledger is compacted.
CREATE TABLE IF NOT EXISTS gen_compact(
    ledger TEXT NOT NULL, gen TEXT NOT NULL,
    first_id INTEGER NOT NULL,      -- first record id: trend order
    records INTEGER NOT NULL,
    verdicts TEXT NOT NULL,         -- {"true": n, "false": n, ...}
    PRIMARY KEY(ledger, gen));
CREATE TABLE IF NOT EXISTS key_compact(
    ledger TEXT NOT NULL, key TEXT NOT NULL,
    last_valid TEXT,                -- last folded verdict (JSON)
    last_id INTEGER NOT NULL,       -- its record id
    PRIMARY KEY(ledger, key));
"""

#: every row-holding table, in wipe order (rebuild / per-unit deletes)
_DATA_TABLES = ("record_spans", "flip_rollup", "span_rollup",
                "span_gen_rollup", "gen_compact", "key_compact",
                "campaign_records", "ledgers",
                "run_spans", "run_metrics", "span_profile",
                "witnesses", "runs",
                "events", "event_cursors", "verifier_sessions",
                "fleet_events", "fleet_worker_rollup", "trace_spans",
                "bench")


def warehouse_path(base: str) -> str:
    """The store's warehouse file: ``<store>/warehouse.sqlite``."""
    return os.path.join(base, WAREHOUSE_FILE)


def _percentile(xs: List[float], q: float) -> float:
    """THE ledger span percentile (round nearest-rank) — imported from
    the jsonl path so the two backends can't disagree."""
    from jepsen_tpu.campaign.index import _percentile as p

    return p(xs, q)


_JSON_SIMPLE = {"true": True, "false": False, "null": None}
_MISS = object()


def _loads(s: str) -> Any:
    """json.loads with a fast path for the three verdict literals —
    the flips/latest decode loop is on the web request path."""
    v = _JSON_SIMPLE.get(s, _MISS)
    return json.loads(s) if v is _MISS else v


class Warehouse:
    """One sqlite warehouse.  Thread-safe: a single connection guarded
    by a lock (handlers on the threaded web server share a cached
    instance via :func:`for_ledger`)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.RLock()
        self._batch_depth = 0
        self.db = sqlite3.connect(path, check_same_thread=False)
        self.db.execute("PRAGMA journal_mode=WAL")
        self.db.execute("PRAGMA synchronous=NORMAL")
        with self._lock, self.db:
            self.db.executescript(_SCHEMA)
            # v1 -> v2 migration: the runs.status column (in-progress
            # runs land as status='running' instead of being
            # indistinguishable from done-but-resultless ones)
            cols = {r[1] for r in self.db.execute(
                "PRAGMA table_info(runs)").fetchall()}
            if "status" not in cols:
                self.db.execute("ALTER TABLE runs ADD COLUMN status "
                                "TEXT NOT NULL DEFAULT 'done'")
            # v3 -> v4 migration: fleet_events.spans (the worker's
            # fleet:* segment durations ride the complete event into
            # the trace_spans view) and campaign_records.trace
            fcols = {r[1] for r in self.db.execute(
                "PRAGMA table_info(fleet_events)").fetchall()}
            if "spans" not in fcols:
                self.db.execute(
                    "ALTER TABLE fleet_events ADD COLUMN spans TEXT")
            ccols = {r[1] for r in self.db.execute(
                "PRAGMA table_info(campaign_records)").fetchall()}
            if "trace" not in ccols:
                self.db.execute("ALTER TABLE campaign_records "
                                "ADD COLUMN trace TEXT")
            # v4 -> v5 migration (ISSUE 16): campaign_records grows the
            # phase-bucket and forensic-counter JSON columns; the new
            # span_profile table itself is covered by the CREATE IF NOT
            # EXISTS above.  ALTER-only: existing rows keep NULL until
            # their ledger is re-ingested (obs rebuild).
            for col in ("phases", "counters"):
                if col not in ccols:
                    self.db.execute("ALTER TABLE campaign_records "
                                    f"ADD COLUMN {col} TEXT")
            # v6 -> v7 migration (ISSUE 20): the gen_compact /
            # key_compact tables are covered by the CREATE IF NOT
            # EXISTS above — no ALTERs; an existing warehouse upgrades
            # in place and stays uncompacted until compact_ledger runs.
            # v5 -> v6 migration (ISSUE 18 satellite): runs.archived —
            # runs retired to _archive/ by `obs gc` stay queryable
            # (``obs sql``) with the dimension to tell them apart from
            # the live store.  ALTER-only, default 0.
            if "archived" not in cols:
                self.db.execute("ALTER TABLE runs ADD COLUMN archived "
                                "INTEGER NOT NULL DEFAULT 0")
            self.db.execute(
                "INSERT OR REPLACE INTO meta(key, value) VALUES "
                "('schema_version', ?)", (str(SCHEMA_VERSION),))
        # on-disk identity at open: lets the handle cache detect a
        # deleted/replaced file (rm + rebuild in another process) and
        # re-open instead of serving an unlinked inode forever
        st = os.stat(path)
        self._file_id = (st.st_ino, st.st_dev)

    def file_unchanged(self) -> bool:
        """True while ``self.path`` still names the inode this handle
        opened — False once the file was deleted or replaced."""
        try:
            st = os.stat(self.path)
        except OSError:
            return False
        return (st.st_ino, st.st_dev) == self._file_id

    def close(self) -> None:
        with self._lock:
            try:
                self.db.close()
            except sqlite3.Error:
                pass

    def __enter__(self) -> "Warehouse":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- ingest batching (ISSUE 20 / ROADMAP 5a) -----------------------------

    @contextmanager
    def _txn(self) -> Any:
        """One ingest unit's transaction boundary.  Standalone, this
        is exactly the old ``with self.db:`` (commit on success, roll
        the unit back on error).  Inside :meth:`batch` it is a no-op
        participant — a nested ``with self.db:`` would COMMIT the
        enclosing batch's work-in-progress on its own exit, which is
        the sqlite footgun the depth counter exists to dodge."""
        with self._lock:
            if self._batch_depth > 0:
                yield
            else:
                with self.db:
                    yield

    @contextmanager
    def batch(self) -> Any:
        """Group many ingest units into ONE transaction (one fsync,
        one cursor flush) — the 100k-run ingest path.  Crash semantics
        coarsen from per-unit to per-batch: a crash mid-batch rolls
        the whole batch back and the next ingest redoes it, which the
        byte cursors make idempotent.  Reentrant: a batch inside a
        batch joins the outer transaction."""
        with self._lock:
            self._batch_depth += 1
            try:
                if self._batch_depth == 1:
                    with self.db:
                        yield
                else:
                    yield
            finally:
                self._batch_depth -= 1

    # -- ingest: byte-cursor jsonl core (campaign + fleet ledgers) -----------

    def _ingest_jsonl(self, path: str, base: str, *,
                      wipe: Any, insert: Any, flush: Any = None) -> int:
        """THE byte-cursor jsonl ingest discipline, shared by every
        ledger family so the subtle invariants can't drift between
        copies: only lines appended since the last ingest are parsed;
        a torn/unparsable tail line is left unconsumed (the writer's
        heal truncates it, after which cursor == size again); a file
        shrunk below the cursor was healed/rewritten — ``wipe(rel)``
        drops its derived rows and ingest restarts from byte 0.  One
        transaction per batch: ``insert(rel, rec)`` rows, the
        ``flush(rel)`` rollup refresh, and the cursor land atomically,
        so a crash mid-ingest rolls the whole unit back and the next
        ingest simply redoes it.  Returns the number of new records."""
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(base))
        try:
            size = os.path.getsize(path)
        except OSError:
            return 0
        with self._lock:
            row = self.db.execute(
                "SELECT cursor FROM ledgers WHERE path = ?",
                (rel,)).fetchone()
            cursor = row[0] if row else 0
            if size < cursor:
                with self._txn():
                    wipe(rel)
                cursor = 0
            if size == cursor:
                return 0
            new = 0
            with self._txn(), open(path, "rb") as f:
                f.seek(cursor)
                for line in f:
                    if not line.endswith(b"\n"):
                        break  # torn tail: an append is in flight
                    if not line.strip():
                        cursor += len(line)
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break  # crash debris: healed by the next writer
                    if isinstance(rec, dict):
                        insert(rel, rec)
                        new += 1
                    cursor += len(line)
                if new and flush is not None:
                    flush(rel)
                self.db.execute(
                    "INSERT INTO ledgers(path, cursor) VALUES (?, ?) "
                    "ON CONFLICT(path) DO UPDATE SET cursor = ?",
                    (rel, cursor, cursor))
            return new

    # -- ingest: campaign ledgers -------------------------------------------

    def ingest_ledger(self, path: str, base: str) -> int:
        """Incrementally ingest one campaign jsonl ledger; returns the
        number of new records (cursor/torn/shrink semantics:
        :meth:`_ingest_jsonl`)."""
        last_valid: Dict[str, Any] = {}  # key -> last verdict seen
        touched_spans: set = set()

        def insert(rel: str, rec: Dict[str, Any]) -> None:
            rid = self._insert_record(rel, rec)
            self._update_flips(rel, rid, rec, last_valid)
            spans = rec.get("spans")
            if isinstance(spans, dict):
                touched_spans.update(spans)

        def flush(rel: str) -> None:
            if touched_spans:
                self._refresh_span_rollups(rel, touched_spans)

        return self._ingest_jsonl(path, base, wipe=self._wipe_ledger,
                                  insert=insert, flush=flush)

    def _update_flips(self, ledger: str, rid: int, rec: Dict[str, Any],
                      last_valid: Dict[str, Any]) -> None:
        """Incrementally maintain the flip rollup: pair this record's
        verdict with the previous verdict-bearing record for the same
        key (seeded from SQL on the key's first sighting in a batch,
        then carried in ``last_valid``).  Comparison is on the DECODED
        Python values — exactly the jsonl scan's ``!=``."""
        key = rec.get("key")
        if "valid?" not in rec or not key:
            return
        cur = rec["valid?"]
        prev = last_valid.get(key, _MISS)
        if prev is _MISS:
            row = self.db.execute(
                "SELECT id, valid FROM campaign_records WHERE ledger = ? "
                "AND key = ? AND valid IS NOT NULL AND id < ? "
                "ORDER BY id DESC LIMIT 1", (ledger, key, rid)).fetchone()
            # compaction may have folded the key's raw history away
            # (leaving at most witness-bearing rows): the folded last
            # verdict lives in key_compact — prefer whichever is later
            krow = self.db.execute(
                "SELECT last_id, last_valid FROM key_compact "
                "WHERE ledger = ? AND key = ?", (ledger, key)).fetchone()
            if krow is not None and krow[1] is not None and \
                    (row is None or krow[0] > row[0]):
                prev = _loads(krow[1])
            elif row is not None:
                prev = _loads(row[1])
        if prev is not _MISS and prev != cur:
            self.db.execute(
                "INSERT INTO flip_rollup(record_id, ledger, key, run, "
                "from_valid, to_valid, regression, ts, gen) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (rid, ledger, key, rec.get("run"), json.dumps(prev),
                 json.dumps(cur), 1 if prev is True else 0,
                 rec.get("ts"), rec.get("gen")))
        last_valid[key] = cur

    def _refresh_span_rollups(self, ledger: str, names: Any) -> None:
        """Recompute the span rollups for the names a batch touched —
        the percentiles can't be maintained incrementally, so ingest
        re-derives them from ``record_spans`` (already in SQL) and the
        queries become single indexed lookups."""
        # compacted generations' per-gen rollups are FROZEN — their
        # raw record_spans are gone, so a recompute would lose them;
        # the refresh only replaces the live gens' rows
        compacted = {g for (g,) in self.db.execute(
            "SELECT gen FROM gen_compact WHERE ledger = ?",
            (ledger,)).fetchall()}
        for name in sorted(names):
            rows = self.db.execute(
                "SELECT s.record_id, s.dur_s, r.gen FROM record_spans s "
                "JOIN campaign_records r ON r.id = s.record_id "
                "WHERE s.ledger = ? AND s.name = ? ORDER BY s.record_id",
                (ledger, name)).fetchall()
            self.db.execute(
                "DELETE FROM span_rollup WHERE ledger = ? AND name = ?",
                (ledger, name))
            if compacted:
                self.db.execute(
                    "DELETE FROM span_gen_rollup WHERE ledger = ? "
                    "AND name = ? AND gen NOT IN (SELECT gen FROM "
                    "gen_compact WHERE ledger = ?)",
                    (ledger, name, ledger))
            else:
                self.db.execute(
                    "DELETE FROM span_gen_rollup WHERE ledger = ? "
                    "AND name = ?", (ledger, name))
            if not rows:
                continue
            vals = [dur for _, dur, _ in rows]
            self.db.execute(
                "INSERT INTO span_rollup(ledger, name, count, min, p50, "
                "p95, max) VALUES (?, ?, ?, ?, ?, ?, ?)",
                (ledger, name, len(vals), round(min(vals), 6),
                 round(_percentile(vals, 50), 6),
                 round(_percentile(vals, 95), 6), round(max(vals), 6)))
            by_gen: Dict[str, List[float]] = {}
            first: Dict[str, int] = {}
            for rid, dur, gen in rows:
                g = str(gen or "?")
                if g not in by_gen:
                    first[g] = rid
                by_gen.setdefault(g, []).append(dur)
            self.db.executemany(
                "INSERT INTO span_gen_rollup(ledger, name, gen, "
                "first_id, p95) VALUES (?, ?, ?, ?, ?)",
                [(ledger, name, g, first[g],
                  round(_percentile(vs, 95), 6))
                 for g, vs in by_gen.items() if g not in compacted])

    def _wipe_ledger(self, rel: str) -> None:
        for tbl in ("record_spans", "flip_rollup", "span_rollup",
                    "span_gen_rollup", "gen_compact", "key_compact"):
            self.db.execute(f"DELETE FROM {tbl} WHERE ledger = ?", (rel,))
        self.db.execute("DELETE FROM campaign_records WHERE ledger = ?",
                        (rel,))
        self.db.execute("DELETE FROM ledgers WHERE path = ?", (rel,))

    def _insert_record(self, ledger: str, rec: Dict[str, Any]) -> int:
        # the id is allocated IN the insert, never below the persisted
        # record_id_floor: sqlite's implicit rowid restarts at
        # MAX(rowid)+1 of the rows *currently present*, so after
        # compact_ledger drops a ledger's raw rows a fresh ingest
        # would otherwise be handed ids BELOW the record_ids that
        # flip_rollup / key_compact still reference — inverting
        # ``ORDER BY key, record_id`` relative to jsonl append order
        w = rec.get("witness")
        phases = rec.get("phases")
        counters = rec.get("counters")
        cur = self.db.execute(
            "INSERT INTO campaign_records(id, ledger, campaign, run, "
            "key, workload, fault, seed, valid, error, degraded, "
            "deadline, dir, ops, wall_s, gen, spec, ts, witness, trace, "
            "phases, counters) "
            "VALUES (MAX((SELECT COALESCE(MAX(id), 0) "
            "             FROM campaign_records), "
            "            (SELECT COALESCE(CAST(value AS INTEGER), 0) "
            "             FROM meta WHERE key = 'record_id_floor')) "
            "        + 1, "
            "?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?, "
            "?, ?, ?)",
            (ledger, rec.get("campaign"), rec.get("run"),
             rec.get("key"),
             rec.get("workload"), rec.get("fault"),
             json.dumps(rec.get("seed")),
             json.dumps(rec["valid?"]) if "valid?" in rec else None,
             rec.get("error"), rec.get("degraded"),
             1 if rec.get("deadline") else 0,
             rec.get("dir"), rec.get("ops"), rec.get("wall_s"),
             rec.get("gen"), rec.get("spec"), rec.get("ts"),
             json.dumps(w) if isinstance(w, dict) else None,
             rec.get("trace"),
             json.dumps(phases) if isinstance(phases, dict) else None,
             json.dumps(counters) if isinstance(counters, dict)
             else None))
        rid = cur.lastrowid
        spans = rec.get("spans") or {}
        if isinstance(spans, dict):
            rows = [(rid, ledger, name, float(dur))
                    for name, dur in spans.items()
                    if isinstance(dur, (int, float))]
            if rows:
                self.db.executemany(
                    "INSERT INTO record_spans(record_id, ledger, name, "
                    "dur_s) VALUES (?, ?, ?, ?)", rows)
        return rid

    def ledger_fresh(self, path: str, base: str) -> bool:
        """True iff this ledger is fully ingested (cursor == file size)
        — the gate for the SQL fast path.  A missing file with no
        cursor row counts as fresh (both sides empty)."""
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(base))
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        with self._lock:
            row = self.db.execute(
                "SELECT cursor FROM ledgers WHERE path = ?",
                (rel,)).fetchone()
        return (row[0] if row else 0) == size

    # -- ingest: run dirs ----------------------------------------------------

    @staticmethod
    def _run_digest(d: str) -> str:
        parts = []
        for fn in ("results.json", "telemetry.json", "witness.json"):
            try:
                st = os.stat(os.path.join(d, fn))
                parts.append(f"{fn}:{st.st_size}:{st.st_mtime_ns}")
            except OSError:
                parts.append(f"{fn}:-")
        return "|".join(parts)

    def ingest_run_dir(self, d: str, base: str,
                       archived: bool = False) -> bool:
        """Ingest one run dir (verdict + spans + metric snapshot +
        witness); returns True if anything changed.  Keyed by a stat
        digest of the artifacts — an unchanged run is a no-op.  Missing
        or unreadable artifacts are tolerated: a run with no
        telemetry.json still gets its verdict row, and a run with no
        ``results.json`` *yet* (still executing, or crashed before
        analysis) is recorded as ``status = 'running'`` instead of
        being skipped — so fleet views and the verifier's session list
        include live work (ISSUE 7 satellite).  When results appear the
        stat digest changes and the row flips to ``'done'``.

        `archived` (ISSUE 18 satellite): the run lives under
        ``_archive/`` (``obs gc`` retention) — its row carries
        ``archived = 1``, and the stale live-path rows the run left
        behind when it was retired are wiped so rollups don't count it
        twice."""
        rel = os.path.relpath(os.path.abspath(d), os.path.abspath(base))
        digest = self._run_digest(d)
        with self._lock:
            row = self.db.execute(
                "SELECT digest FROM runs WHERE dir = ?", (rel,)).fetchone()
            if row and row[0] == digest:
                return False
            valid, flags = self._run_results(d)
            status = "running" if valid is _ABSENT else "done"
            spans, metrics, profile, host = self._run_telemetry(d)
            traces = self._run_trace_rows(d, rel)
            wit = self._run_witness(d)
            # the dir this run occupied before gc moved it (rel is
            # "_archive/<name>/<ts>"; the basename may carry a
            # collision suffix the live dir never had — strip nothing,
            # the live rel is exactly the path minus the prefix)
            stale = (os.path.relpath(rel, "_archive")
                     if archived else None)
            with self._txn():
                for tbl in ("runs", "run_spans", "run_metrics",
                            "witnesses", "span_profile"):
                    self.db.execute(
                        f"DELETE FROM {tbl} WHERE dir = ?", (rel,))
                    if stale:
                        self.db.execute(
                            f"DELETE FROM {tbl} WHERE dir = ?", (stale,))
                self.db.execute(
                    "DELETE FROM trace_spans WHERE origin = ?", (rel,))
                if stale:
                    self.db.execute(
                        "DELETE FROM trace_spans WHERE origin = ?",
                        (stale,))
                if traces:
                    self.db.executemany(
                        "INSERT INTO trace_spans(trace_id, origin, "
                        "source, run, host, name, t0, t1, dur_s) "
                        "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)", traces)
                self.db.execute(
                    "INSERT INTO runs(dir, name, ts, digest, valid, "
                    "error, degraded, deadline, status, archived, "
                    "ingested_at) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                    (rel, os.path.basename(os.path.dirname(rel)) or None,
                     os.path.basename(rel), digest,
                     json.dumps(valid) if valid is not _ABSENT else None,
                     flags.get("error"), flags.get("degraded"),
                     1 if flags.get("deadline") else 0, status,
                     1 if archived else 0, time.time()))
                if spans:
                    self.db.executemany(
                        "INSERT INTO run_spans(dir, name, total_s, count) "
                        "VALUES (?, ?, ?, ?)",
                        [(rel, n, t, c) for n, (t, c) in
                         sorted(spans.items())])
                if metrics:
                    self.db.executemany(
                        "INSERT INTO run_metrics(dir, kind, name, labels, "
                        "value) VALUES (?, ?, ?, ?, ?)",
                        [(rel,) + m for m in metrics])
                if profile:
                    self.db.executemany(
                        "INSERT INTO span_profile(dir, host, site, "
                        "shape, calls, compile_s, execute_s, "
                        "device_dispatch_s) "
                        "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                        [(rel, host, site, shape, c["calls"],
                          round(c["compile_s"], 6),
                          round(c["execute_s"], 6),
                          round(c["device_dispatch_s"], 6))
                         for (site, shape), c in sorted(profile.items())])
                if wit is not None:
                    self.db.execute(
                        "INSERT INTO witnesses(dir, ops, source_ops, "
                        "digest, anomalies, probes) "
                        "VALUES (?, ?, ?, ?, ?, ?)",
                        (rel, wit.get("ops"), wit.get("source-ops"),
                         wit.get("digest"),
                         json.dumps(wit.get("anomaly-types") or []),
                         wit.get("probes")))
            return True

    def run_spans(self, d: str, base: Optional[str] = None
                  ) -> List[Tuple[str, float, int]]:
        """One ingested run's (span name, total seconds, count) rows,
        largest total first — the web run page's span profile.  ``d``
        may be store-relative already (pass ``base=None``)."""
        rel = (d if base is None else
               os.path.relpath(os.path.abspath(d), os.path.abspath(base)))
        with self._lock:
            return self.db.execute(
                "SELECT name, total_s, count FROM run_spans "
                "WHERE dir = ? ORDER BY total_s DESC, name",
                (rel,)).fetchall()

    @staticmethod
    def _run_results(d: str) -> Tuple[Any, Dict[str, Any]]:
        from jepsen_tpu.campaign.core import result_flags

        try:
            with open(os.path.join(d, "results.json")) as f:
                res = json.load(f)
        except (OSError, ValueError):
            return _ABSENT, {}
        if not isinstance(res, dict):
            return _ABSENT, {}
        return res.get("valid?", _ABSENT), result_flags(res)

    @staticmethod
    def _run_telemetry(d: str) -> Tuple[Dict[str, Tuple[float, int]],
                                        List[Tuple],
                                        Dict[Tuple[str, str],
                                             Dict[str, Any]],
                                        Optional[str]]:
        """(spans, metric rows, profile, host) from telemetry.json:
        per-span-name (total seconds, count), counter/gauge/histogram
        snapshot rows for run_metrics, and the run's per-(site,
        shape-class) device-call profile (ISSUE 16) summed over span
        ``profile`` attrs — ONE shared extraction
        (`forensics.profile_from_doc`), so the jsonl fallback and this
        ingest can't drift."""
        try:
            with open(os.path.join(d, "telemetry.json")) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}, [], {}, None
        if not isinstance(doc, dict):
            return {}, [], {}, None
        spans: Dict[str, Tuple[float, int]] = {}

        def walk(sp: Dict[str, Any]) -> None:
            dur = sp.get("dur_ns")
            if isinstance(dur, (int, float)):
                t, c = spans.get(sp["name"], (0.0, 0))
                spans[sp["name"]] = (t + dur / 1e9, c + 1)
            for ch in sp.get("children") or []:
                walk(ch)

        for r in doc.get("spans", []) if isinstance(doc, dict) else []:
            walk(r)
        spans = {n: (round(t, 6), c) for n, (t, c) in spans.items()}
        from .forensics import profile_from_doc

        profile = profile_from_doc(doc)
        meta = doc.get("meta") or {}
        host = meta.get("host") if isinstance(meta, dict) else None
        m = doc.get("metrics") or {} if isinstance(doc, dict) else {}

        def lbl(entry: Dict[str, Any]) -> str:
            return json.dumps(entry.get("labels") or {}, sort_keys=True)

        rows: List[Tuple] = []
        for c in m.get("counters", []):
            if isinstance(c.get("value"), (int, float)):
                rows.append(("counter", c["name"], lbl(c),
                             float(c["value"])))
        for g in m.get("gauges", []):
            if isinstance(g.get("value"), (int, float)):
                rows.append(("gauge", g["name"], lbl(g),
                             float(g["value"])))
        for h in m.get("histograms", []):
            if isinstance(h.get("count"), (int, float)):
                rows.append(("histogram-count", h["name"], lbl(h),
                             float(h["count"])))
            if isinstance(h.get("sum"), (int, float)):
                rows.append(("histogram-sum", h["name"], lbl(h),
                             float(h["sum"])))
        return spans, rows, profile, host

    @staticmethod
    def _run_trace_rows(d: str, rel: str) -> List[Tuple]:
        """Host-attributed trace segments from a run dir's
        telemetry.json (ISSUE 14): the run root plus its direct phase
        children (workload, check:*, live-check.finish, store.save_1
        ...), placed on ABSOLUTE time via the collector's wall-clock
        anchor — so they interleave correctly with the fleet ledger's
        control-plane segments on one timeline.  Runs without a trace
        block (pre-v14 artifacts, non-traced runs) contribute
        nothing."""
        try:
            with open(os.path.join(d, "telemetry.json")) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return []
        if not isinstance(doc, dict):
            return []
        trace = doc.get("trace") or {}
        tid = trace.get("trace-id")
        epoch = doc.get("epoch_ns")
        perf0 = doc.get("perf0_ns")
        if not tid or not isinstance(epoch, (int, float)) \
                or not isinstance(perf0, (int, float)):
            return []
        meta = doc.get("meta") or {}
        host = meta.get("host")
        run = meta.get("run-id")
        rows: List[Tuple] = []

        def abs_s(t_ns: Any) -> Optional[float]:
            if not isinstance(t_ns, (int, float)):
                return None
            return round((epoch + (t_ns - perf0)) / 1e9, 6)

        def add(sp: Dict[str, Any], depth: int) -> None:
            t0 = abs_s(sp.get("t0_ns"))
            dur = sp.get("dur_ns")
            name = str(sp.get("name"))
            if t0 is not None and isinstance(dur, (int, float)):
                rows.append((tid, rel, "run", run, host,
                             name if depth == 0 else f"run:{name}",
                             t0, round(t0 + dur / 1e9, 6),
                             round(dur / 1e9, 6)))
            if depth < 1:
                for c in sp.get("children") or []:
                    add(c, depth + 1)

        for r in doc.get("spans") or []:
            add(r, 0)
        return rows[:64]  # phase-level rows only; leaves stay in
        # telemetry.json (the timeline answers "where did the 40 s
        # go", not "render the whole span forest")

    @staticmethod
    def _run_witness(d: str) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(d, "witness.json")) as f:
                w = json.load(f)
        except (OSError, ValueError):
            return None
        return w if isinstance(w, dict) else None

    # -- ingest: event streams ----------------------------------------------

    def ingest_events(self, d: str, base: str) -> int:
        """Ingest a run dir's streamed ``events.jsonl`` (rotated
        segments included); returns new-event count.  Fast path: when
        the rotated-segment signature AND the live file's first line
        (the session id — a truncate-and-regrow new session can pass a
        pure size check) are unchanged, only bytes appended to the
        live file since the cursor are parsed.  Rotation or a new
        session wipes the dir's events and re-ingests the whole
        segment chain — the stream stays the source of truth — with
        the wipe, re-insert, and cursor in ONE transaction, so a crash
        mid-re-ingest rolls the unit back whole."""
        from .stream import EVENTS_FILE, read_events, segment_files

        rel = os.path.relpath(os.path.abspath(d), os.path.abspath(base))
        live = os.path.join(d, EVENTS_FILE)
        segs = [p for p in segment_files(live) if p != live]
        sig = json.dumps([[os.path.basename(p), self._size(p)]
                          for p in segs])
        head = self._head(live)
        live_size = self._size(live)
        if live_size is None and not segs:
            return 0
        with self._lock:
            row = self.db.execute(
                "SELECT cursor, sig, head FROM event_cursors "
                "WHERE dir = ?", (rel,)).fetchone()
            cursor, old_sig, old_head = row if row else (0, "[]", "")
            incremental = bool(row) and old_sig == sig \
                and old_head == head and (live_size or 0) >= cursor
            if incremental:
                evs, new_cursor = self._read_incremental(live, cursor)
                if not evs and new_cursor == cursor:
                    return 0
            else:
                # rotation / new session / first sight: full re-ingest
                evs = []
                for p in segs:
                    evs.extend(read_events(p, spanning=False))
                live_evs, new_cursor = self._read_incremental(live, 0)
                evs.extend(live_evs)
            with self._txn():
                if not incremental:
                    self.db.execute("DELETE FROM events WHERE dir = ?",
                                    (rel,))
                self.db.executemany(
                    "INSERT INTO events(dir, t, ev, doc) "
                    "VALUES (?, ?, ?, ?)",
                    [(rel, e.get("t"), e.get("ev"),
                      json.dumps(e, separators=(",", ":")))
                     for e in evs])
                self.db.execute(
                    "INSERT INTO event_cursors(dir, cursor, sig, head) "
                    "VALUES (?, ?, ?, ?) ON CONFLICT(dir) DO UPDATE "
                    "SET cursor = ?, sig = ?, head = ?",
                    (rel, new_cursor, sig, head,
                     new_cursor, sig, head))
            return len(evs)

    @staticmethod
    def _head(path: str) -> str:
        """The live file's first complete line, as the session
        identity — ONE implementation shared with the follow_events
        cursor (stream._first_line), so the ingest and the follower
        can't disagree about what counts as the same session."""
        from .stream import _first_line

        return _first_line(path)

    @staticmethod
    def _size(path: str) -> Optional[int]:
        try:
            return os.path.getsize(path)
        except OSError:
            return None

    @staticmethod
    def _read_incremental(path: str, cursor: int
                          ) -> Tuple[List[Dict[str, Any]], int]:
        from .stream import read_events_incremental

        # stop_at_corrupt: index exactly the prefix the read_events
        # scan delivers, so `tail --since` renders identically from
        # either backend (a corrupt line also pins cursor < size,
        # gating events_fresh off — the scan then answers)
        return read_events_incremental(path, cursor, follow_rotation=False,
                                       stop_at_corrupt=True)

    def events_fresh(self, d: str, base: str) -> bool:
        """True iff the dir's event stream is fully ingested — the gate
        for the ``cli tail --since`` warehouse path."""
        from .stream import EVENTS_FILE, segment_files

        rel = os.path.relpath(os.path.abspath(d), os.path.abspath(base))
        live = os.path.join(d, EVENTS_FILE)
        segs = [p for p in segment_files(live) if p != live]
        sig = json.dumps([[os.path.basename(p), self._size(p)]
                          for p in segs])
        size = self._size(live)
        if size is None and not segs:
            return False
        with self._lock:
            row = self.db.execute(
                "SELECT cursor, sig, head FROM event_cursors "
                "WHERE dir = ?", (rel,)).fetchone()
        return bool(row) and row[1] == sig and row[0] == (size or 0) \
            and row[2] == self._head(live)

    def events_since(self, d: str, base: str,
                     since: Optional[float] = None
                     ) -> List[Dict[str, Any]]:
        rel = os.path.relpath(os.path.abspath(d), os.path.abspath(base))
        q = "SELECT doc FROM events WHERE dir = ?"
        args: List[Any] = [rel]
        if since is not None:
            q += " AND t >= ?"
            args.append(float(since))
        q += " ORDER BY id"
        with self._lock:
            rows = self.db.execute(q, args).fetchall()
        return [json.loads(r[0]) for r in rows]

    # -- ingest: verifier sessions -------------------------------------------

    def ingest_verifier_sessions(self, base: str) -> int:
        """Ingest the verifier's ``session.json`` snapshots
        (``<store>/verifier/<name>/``, ISSUE 7): one upserted row per
        session so fleet queries cover the always-on checker's live
        and sealed work.  Returns sessions seen."""
        from jepsen_tpu.verifier import scan_sessions

        rows = []
        traces: List[Tuple[str, List[Tuple]]] = []
        for name, meta in scan_sessions(base):
            v = meta.get("verdict") or {}
            seal = meta.get("seal") or {}
            rows.append((
                name, meta.get("state"),
                json.dumps(v["valid?"]) if "valid?" in v else None,
                json.dumps(v.get("anomaly-types") or []),
                meta.get("txns"), meta.get("ops"), meta.get("segments"),
                meta.get("digest"),
                (1 if seal.get("equal") else 0) if seal else None,
                meta.get("updated")))
            # timeline stitching (ISSUE 14): a session whose config
            # carries its run's trace id contributes one live-session
            # segment (opened → last update, i.e. the window the live
            # sweeps overlapped the workload)
            cfg = meta.get("config") if isinstance(meta.get("config"),
                                                   dict) else {}
            tid = cfg.get("trace-id")
            opened, upd = meta.get("opened"), meta.get("updated")
            origin = "verifier/" + name
            seg: List[Tuple] = []
            if tid and isinstance(opened, (int, float)) \
                    and isinstance(upd, (int, float)) and upd >= opened:
                seg.append((str(tid), origin, "verifier", None,
                            cfg.get("host"),
                            "verifier:live-session", round(opened, 6),
                            round(upd, 6), round(upd - opened, 6)))
            traces.append((origin, seg))
        if not rows:
            return 0
        with self._lock, self._txn():
            self.db.executemany(
                "INSERT OR REPLACE INTO verifier_sessions(name, state, "
                "valid, anomalies, txns, ops, segments, digest, "
                "seal_equal, updated) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?)", rows)
            for origin, seg in traces:
                self.db.execute(
                    "DELETE FROM trace_spans WHERE origin = ?",
                    (origin,))
                if seg:
                    self.db.executemany(
                        "INSERT INTO trace_spans(trace_id, origin, "
                        "source, run, host, name, t0, t1, dur_s) "
                        "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)", seg)
        return len(rows)

    def verifier_sessions(self) -> List[Dict[str, Any]]:
        with self._lock:
            rows = self.db.execute(
                "SELECT name, state, valid, anomalies, txns, ops, "
                "segments, digest, seal_equal, updated "
                "FROM verifier_sessions ORDER BY name").fetchall()
        cols = ("name", "state", "valid", "anomalies", "txns", "ops",
                "segments", "digest", "seal_equal", "updated")
        out = []
        for r in rows:
            d = dict(zip(cols, r))
            d["valid"] = _loads(d["valid"]) if d["valid"] else None
            d["anomalies"] = json.loads(d["anomalies"] or "[]")
            out.append(d)
        return out

    # -- ingest: fleet ledgers (ISSUE 9) -------------------------------------

    def ingest_fleet_ledger(self, path: str, base: str) -> int:
        """Incrementally ingest one fleet work-queue ledger
        (``<store>/fleet/<name>.jsonl``, docs/FLEET.md) into
        ``fleet_events`` + the per-worker rollup; returns new events.
        Shares :meth:`_ingest_jsonl`'s cursor/torn/shrink discipline
        (the ``ledgers`` table keys on the store-relative path, which
        is disjoint from campaign ledgers' ``campaigns/...``)."""
        def insert(rel: str, ev: Dict[str, Any]) -> None:
            extra = None
            if ev.get("ev") == "complete" and \
                    isinstance(ev.get("record"), dict):
                sp = ev["record"].get("spans")
                keep = {k: v for k, v in sp.items()
                        if str(k).startswith("fleet:")
                        and isinstance(v, (int, float))} \
                    if isinstance(sp, dict) else {}
                if keep:
                    extra = json.dumps(keep)
            self.db.execute(
                "INSERT INTO fleet_events(ledger, ev, run, worker, "
                "reason, ts, deadline, spans) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                (rel, ev.get("ev"), ev.get("run"), ev.get("worker"),
                 ev.get("reason"), ev.get("ts"), ev.get("deadline"),
                 extra))

        def flush(rel: str) -> None:
            self._refresh_fleet_rollup(rel)
            self._refresh_fleet_traces(rel)

        return self._ingest_jsonl(path, base,
                                  wipe=self._wipe_fleet_ledger,
                                  insert=insert,
                                  flush=flush)

    def _refresh_fleet_traces(self, rel: str) -> None:
        """Rebuild the ledger's control-plane trace segments (ISSUE
        14): per run, ``fleet:enqueue-wait`` (enqueue → first claim,
        the coordinator's segment), one ``fleet:attempt`` per claim
        that lapsed/released (claim → requeue, attributed to the
        claiming worker), and ``fleet:execute`` (final claim →
        complete, attributed to the completing worker).  Recomputed
        wholesale per ingest batch — the pairing needs the whole event
        sequence, and the rows are few (a handful per cell)."""
        from .spans import trace_id_for

        self.db.execute(
            "DELETE FROM trace_spans WHERE origin = ?", (rel,))
        rows = self.db.execute(
            "SELECT ev, run, worker, ts, spans FROM fleet_events "
            "WHERE ledger = ? AND run IS NOT NULL ORDER BY id",
            (rel,)).fetchall()
        out: List[Tuple] = []
        state: Dict[str, Dict[str, Any]] = {}
        for ev, run, worker, ts, extra in rows:
            if not isinstance(ts, (int, float)):
                continue
            st = state.setdefault(run, {"enqueued": None, "claim": None,
                                        "first_claim": None})
            tid = trace_id_for(run)
            if ev == "enqueue" and st["enqueued"] is None:
                st["enqueued"] = ts
            elif ev == "claim":
                st["claim"] = (ts, worker)
                if st["first_claim"] is None:
                    st["first_claim"] = ts
                    if isinstance(st["enqueued"], (int, float)) \
                            and ts >= st["enqueued"]:
                        out.append((tid, rel, "fleet", run, None,
                                    "fleet:enqueue-wait",
                                    st["enqueued"], ts,
                                    round(ts - st["enqueued"], 6)))
            elif ev == "requeue" and st["claim"] is not None:
                c_ts, c_w = st["claim"]
                st["claim"] = None
                if ts >= c_ts:
                    out.append((tid, rel, "fleet", run, c_w,
                                "fleet:attempt", c_ts, ts,
                                round(ts - c_ts, 6)))
            elif ev == "complete" and st["claim"] is not None:
                c_ts, _c_w = st["claim"]
                st["claim"] = None
                if ts >= c_ts:
                    out.append((tid, rel, "fleet", run, worker,
                                "fleet:execute", c_ts, ts,
                                round(ts - c_ts, 6)))
                    # the worker-measured segments ride the complete
                    # event's record: claim-to-start anchors forward
                    # from the claim, upload backward from the
                    # completion — absolute placement from the
                    # coordinator's ledger clock, durations from the
                    # worker's monotonic clock
                    try:
                        durs = json.loads(extra) if extra else {}
                    except ValueError:
                        durs = {}
                    d = durs.get("fleet:claim-to-start")
                    if isinstance(d, (int, float)) and 0 <= d \
                            and c_ts + d <= ts:
                        out.append((tid, rel, "fleet", run, worker,
                                    "fleet:claim-to-start", c_ts,
                                    round(c_ts + d, 6), round(d, 6)))
                    d = durs.get("fleet:upload")
                    if isinstance(d, (int, float)) and 0 <= d \
                            and ts - d >= c_ts:
                        out.append((tid, rel, "fleet", run, worker,
                                    "fleet:upload", round(ts - d, 6),
                                    ts, round(d, 6)))
        if out:
            self.db.executemany(
                "INSERT INTO trace_spans(trace_id, origin, source, "
                "run, host, name, t0, t1, dur_s) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)", out)

    def _refresh_fleet_rollup(self, rel: str) -> None:
        self.db.execute(
            "DELETE FROM fleet_worker_rollup WHERE ledger = ?", (rel,))
        self.db.execute(
            "INSERT INTO fleet_worker_rollup(ledger, worker, claims, "
            "renews, completes, requeues, duplicates) "
            "SELECT ledger, worker, "
            "SUM(ev = 'claim'), SUM(ev = 'renew'), "
            "SUM(ev = 'complete'), SUM(ev = 'requeue'), "
            "SUM(ev = 'duplicate') "
            "FROM fleet_events WHERE ledger = ? AND worker IS NOT NULL "
            "GROUP BY worker", (rel,))

    def _wipe_fleet_ledger(self, rel: str) -> None:
        self.db.execute("DELETE FROM fleet_events WHERE ledger = ?",
                        (rel,))
        self.db.execute(
            "DELETE FROM fleet_worker_rollup WHERE ledger = ?", (rel,))
        self.db.execute("DELETE FROM trace_spans WHERE origin = ?",
                        (rel,))
        self.db.execute("DELETE FROM ledgers WHERE path = ?", (rel,))

    def fleet_worker_rollup(self, ledger_rel: str
                            ) -> List[Dict[str, Any]]:
        """Per-worker control-plane tallies for one fleet ledger,
        requeue-heaviest first — "which host's cells requeue most"."""
        with self._lock:
            rows = self.db.execute(
                "SELECT worker, claims, renews, completes, requeues, "
                "duplicates FROM fleet_worker_rollup WHERE ledger = ? "
                "ORDER BY requeues DESC, worker", (ledger_rel,)).fetchall()
        cols = ("worker", "claims", "renews", "completes", "requeues",
                "duplicates")
        return [dict(zip(cols, r)) for r in rows]

    # -- ingest: bench -------------------------------------------------------

    def ingest_bench(self, payload: Dict[str, Any], source: str) -> None:
        """Upsert one BENCH payload keyed by ``source`` (a file name
        for committed BENCH_r0*.json, a timestamped tag for bench.py
        self-ingest) — the r03→r05 throughput trajectory becomes a
        queryable series instead of loose files."""
        with self._lock, self.db:
            self.db.execute(
                "INSERT OR REPLACE INTO bench(source, ingested_at, "
                "metric, value, unit, vs_baseline, n_txns, backend, "
                "wall_s, compile_or_warmup_s, doc) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?, ?, ?)",
                (source, time.time(), payload.get("metric"),
                 payload.get("value"), payload.get("unit"),
                 payload.get("vs_baseline"), payload.get("n_txns"),
                 payload.get("backend"), payload.get("wall_s"),
                 payload.get("compile_or_warmup_s"),
                 json.dumps(payload)))

    def ingest_bench_file(self, path: str) -> bool:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("bench ingest skipped %s: %s", path, e)
            return False
        if not isinstance(payload, dict):
            return False
        # the committed BENCH_r0*.json are driver wrappers ({n, cmd,
        # rc, tail, parsed}) around the bench's JSON line — unwrap
        if "metric" not in payload and \
                isinstance(payload.get("parsed"), dict):
            payload = payload["parsed"]
        if "metric" not in payload:
            logger.warning("bench ingest skipped %s: no metric", path)
            return False
        self.ingest_bench(payload, os.path.basename(path))
        return True

    def bench_series(self) -> List[Dict[str, Any]]:
        """The bench trajectory, ordered by source name (BENCH_r03 <
        BENCH_r04 < ...)."""
        with self._lock:
            rows = self.db.execute(
                "SELECT source, metric, value, unit, vs_baseline, "
                "n_txns, backend, wall_s, compile_or_warmup_s "
                "FROM bench ORDER BY source").fetchall()
        cols = ("source", "metric", "value", "unit", "vs_baseline",
                "n_txns", "backend", "wall_s", "compile_or_warmup_s")
        return [dict(zip(cols, r)) for r in rows]

    # -- ingest: whole store -------------------------------------------------

    def ingest_store(self, base: str, events: bool = True,
                     batch_units: int = 64) -> Dict[str, int]:
        """Incrementally ingest everything under a store dir: campaign
        ledgers, run dirs, and (optionally) event streams.  Re-running
        on an unchanged store is a no-op.

        Units commit in batches of ``batch_units`` (ROADMAP 5a: one
        transaction — one fsync, one cursor flush — per N ledgers/run
        dirs instead of per unit), which is where the 100k-run ingest
        speedup comes from; ``batch_units=1`` restores the old
        per-unit commit behavior exactly."""
        from jepsen_tpu import store as store_mod

        stats = {"ledgers": 0, "records": 0, "runs": 0, "events": 0,
                 "sessions": 0, "fleet-events": 0, "archived": 0}
        units: List[Any] = []

        def ledger_unit(p: str) -> None:
            stats["ledgers"] += 1
            stats["records"] += self.ingest_ledger(p, base)

        def fleet_unit(p: str) -> None:
            stats["fleet-events"] += self.ingest_fleet_ledger(p, base)

        def run_unit(d: str) -> None:
            if self.ingest_run_dir(d, base):
                stats["runs"] += 1
            if events:
                stats["events"] += self.ingest_events(d, base)

        def archived_unit(d: str) -> None:
            if self.ingest_run_dir(d, base, archived=True):
                stats["archived"] += 1

        cdir = os.path.join(base, "campaigns")
        if os.path.isdir(cdir):
            for fn in sorted(os.listdir(cdir)):
                if fn.endswith(".jsonl"):
                    units.append((ledger_unit, os.path.join(cdir, fn)))
        fdir = os.path.join(base, "fleet")
        if os.path.isdir(fdir):
            for fn in sorted(os.listdir(fdir)):
                if fn.endswith(".jsonl"):
                    units.append((fleet_unit, os.path.join(fdir, fn)))
        for d in store_mod.tests(base=base):
            units.append((run_unit, d))
        # runs retired by `obs gc` (ISSUE 18 satellite): _archive/ has
        # the same <name>/<ts> layout, so the run-dir scan applies
        # as-is; rows land with archived = 1 (no event streams — those
        # were ingested while the run was live)
        adir = store_mod.archive_dir(base)
        if os.path.isdir(adir):
            for d in store_mod.tests(base=adir):
                units.append((archived_unit, d))
        step = max(1, int(batch_units))
        for i in range(0, len(units), step):
            group = units[i:i + step]
            if len(group) == 1:
                group[0][0](group[0][1])
            else:
                with self.batch():
                    for fn, arg in group:
                        fn(arg)
        stats["sessions"] = self.ingest_verifier_sessions(base)
        return stats

    def rebuild(self, base: str) -> Dict[str, int]:
        """Reconstruct from scratch: wipe every derived row, then
        re-ingest the whole store.  The jsonl ledgers are the source of
        truth; this is always safe.  The ``bench`` table survives — its
        payloads come from OUTSIDE the store (BENCH_*.json files,
        bench.py self-ingest) and can't be rederived from it."""
        with self._lock, self.db:
            for tbl in _DATA_TABLES:
                if tbl != "bench":
                    self.db.execute(f"DELETE FROM {tbl}")
        return self.ingest_store(base)

    def counts(self) -> Dict[str, int]:
        out = {}
        with self._lock:
            for tbl in _DATA_TABLES:
                out[tbl] = self.db.execute(
                    f"SELECT COUNT(*) FROM {tbl}").fetchone()[0]
        return out

    # -- rollup compaction (ISSUE 20 / ROADMAP 5a) ---------------------------

    def compact_ledger(self, path: str, base: str,
                       keep_gens: int = 2) -> Dict[str, int]:
        """Fold a campaign ledger's raw rows past the generation
        horizon into bounded summary rows and DROP them.

        Everything the compact-safe queries need survives exactly:
        ``flip_rollup`` and ``span_gen_rollup`` rows are never touched
        (flips / span_trend answer identically), witness-bearing
        records are kept (witness_diffs answers identically), and
        ``key_compact`` carries each key's last folded verdict so
        future flip detection pairs across the horizon.  Everything
        else (span_stats, latest_by_run, forensics, profile) loses its
        raw rows — the Index falls back to the jsonl scan for those
        once :meth:`ledger_compacted` is true.  The byte cursor is
        untouched: re-ingesting a compacted, unchanged ledger stays a
        no-op."""
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(base))
        stats = {"gens-compacted": 0, "dropped-records": 0,
                 "dropped-spans": 0, "kept-witnesses": 0}
        with self._lock, self._txn():
            gens = self.db.execute(
                "SELECT COALESCE(gen, '?'), MIN(id) "
                "FROM campaign_records WHERE ledger = ? "
                "GROUP BY COALESCE(gen, '?') ORDER BY MIN(id)",
                (rel,)).fetchall()
            if len(gens) <= max(0, int(keep_gens)):
                return stats
            kept = gens[len(gens) - int(keep_gens):] \
                if keep_gens > 0 else []
            cutoff = min(fid for _, fid in kept) if kept else \
                self.db.execute(
                    "SELECT COALESCE(MAX(id), 0) + 1 "
                    "FROM campaign_records WHERE ledger = ?",
                    (rel,)).fetchone()[0]
            fold = [(g, fid) for g, fid in gens if fid < cutoff]
            for g, fid in fold:
                rows = self.db.execute(
                    "SELECT valid, COUNT(*) FROM campaign_records "
                    "WHERE ledger = ? AND COALESCE(gen, '?') = ? "
                    "AND id < ? GROUP BY valid",
                    (rel, g, cutoff)).fetchall()
                verd: Dict[str, int] = {}
                n_rec = 0
                for valid, n in rows:
                    n_rec += n
                    if valid is None:
                        k = "none"
                    else:
                        v = _loads(valid)
                        k = ("true" if v is True else
                             "false" if v is False else "unknown")
                    verd[k] = verd.get(k, 0) + n
                if not n_rec:
                    continue
                prior = self.db.execute(
                    "SELECT first_id, records, verdicts FROM gen_compact "
                    "WHERE ledger = ? AND gen = ?", (rel, g)).fetchone()
                if prior is not None:
                    old = json.loads(prior[2])
                    for k, n in old.items():
                        verd[k] = verd.get(k, 0) + n
                    fid = min(fid, prior[0])
                    n_rec += prior[1]
                self.db.execute(
                    "INSERT OR REPLACE INTO gen_compact(ledger, gen, "
                    "first_id, records, verdicts) VALUES (?, ?, ?, ?, ?)",
                    (rel, g, fid, n_rec, json.dumps(verd,
                                                    sort_keys=True)))
                stats["gens-compacted"] += 1
            # each key's last folded verdict: the flip seed across the
            # horizon (merged with any earlier compaction's entry —
            # the new fold is always later)
            krows = self.db.execute(
                "SELECT r.key, r.valid, r.id FROM campaign_records r "
                "JOIN (SELECT key, MAX(id) AS mid FROM campaign_records "
                "      WHERE ledger = ? AND id < ? AND valid IS NOT "
                "      NULL AND key IS NOT NULL AND key != '' "
                "      GROUP BY key) t ON r.id = t.mid",
                (rel, cutoff)).fetchall()
            self.db.executemany(
                "INSERT INTO key_compact(ledger, key, last_valid, "
                "last_id) VALUES (?, ?, ?, ?) ON CONFLICT(ledger, key) "
                "DO UPDATE SET last_valid = excluded.last_valid, "
                "last_id = excluded.last_id",
                [(rel, k, v, i) for k, v, i in krows])
            stats["dropped-spans"] = self.db.execute(
                "SELECT COUNT(*) FROM record_spans WHERE ledger = ? "
                "AND record_id < ?", (rel, cutoff)).fetchone()[0]
            self.db.execute(
                "DELETE FROM record_spans WHERE ledger = ? "
                "AND record_id < ?", (rel, cutoff))
            stats["kept-witnesses"] = self.db.execute(
                "SELECT COUNT(*) FROM campaign_records WHERE ledger = ? "
                "AND id < ? AND witness IS NOT NULL",
                (rel, cutoff)).fetchone()[0]
            # pin the id floor BEFORE dropping rows: sqlite would
            # otherwise hand the next ingest rowids below the
            # record_ids flip_rollup / key_compact still reference
            # (see _alloc_record_id)
            top = self.db.execute(
                "SELECT COALESCE(MAX(id), 0) FROM campaign_records"
            ).fetchone()[0]
            row = self.db.execute(
                "SELECT value FROM meta WHERE key = 'record_id_floor'"
            ).fetchone()
            floor = max(top, int(row[0]) if row else 0)
            self.db.execute(
                "INSERT OR REPLACE INTO meta(key, value) VALUES "
                "('record_id_floor', ?)", (str(floor),))
            cur = self.db.execute(
                "DELETE FROM campaign_records WHERE ledger = ? "
                "AND id < ? AND witness IS NULL", (rel, cutoff))
            stats["dropped-records"] = cur.rowcount
        return stats

    def ledger_compacted(self, rel: str) -> bool:
        """True once :meth:`compact_ledger` folded anything for this
        ledger — the per-query Index gate (compact-safe queries keep
        the SQL fast path, the rest fall back to the jsonl scan)."""
        with self._lock:
            return self.db.execute(
                "SELECT 1 FROM gen_compact WHERE ledger = ? LIMIT 1",
                (rel,)).fetchone() is not None

    def alert_signals(self) -> Dict[str, float]:
        """The alert tick's warehouse leg: aggregates over ROLLUP
        tables only (flip_rollup / span_gen_rollup / gen_compact) —
        NEVER campaign_records or record_spans, so the tick costs the
        same on a 100k-run store as on a 100-run one (the O(rollup
        rows) acceptance pin, trace-asserted in tests)."""
        out: Dict[str, float] = {}
        with self._lock:
            n, reg = self.db.execute(
                "SELECT COUNT(*), COALESCE(SUM(regression), 0) "
                "FROM flip_rollup").fetchone()
            out["flips"] = float(n)
            out["flip-regressions"] = float(reg)
            out["compacted-gens"] = float(self.db.execute(
                "SELECT COUNT(*) FROM gen_compact").fetchone()[0])
            rows = self.db.execute(
                "SELECT s.name, s.p95 FROM span_gen_rollup s JOIN ("
                "  SELECT ledger, name, MAX(first_id) AS mf "
                "  FROM span_gen_rollup GROUP BY ledger, name) t "
                "ON s.ledger = t.ledger AND s.name = t.name "
                "AND s.first_id = t.mf").fetchall()
        for name, p95 in rows:
            if isinstance(p95, (int, float)):
                key = f"span-p95-s:{name}"
                out[key] = max(out.get(key, 0.0), float(p95))
        return out

    # -- SQL-backed campaign queries (Index fast paths) ----------------------
    #
    # Each returns EXACTLY what the jsonl scan returns (same ordering,
    # same percentile formula, same rounding) — tests assert equality.

    def flips(self, ledger_rel: str) -> List[Dict[str, Any]]:
        with self._lock:
            rows = self.db.execute(
                "SELECT key, run, from_valid, to_valid, regression, "
                "ts, gen FROM flip_rollup WHERE ledger = ? "
                "ORDER BY key, record_id", (ledger_rel,)).fetchall()
        return [{"key": key, "run": run, "from": _loads(pv),
                 "to": _loads(cv), "regression": bool(reg), "when": ts,
                 "gen": gen}
                for key, run, pv, cv, reg, ts, gen in rows]

    def span_values(self, ledger_rel: str) -> Dict[str, List[float]]:
        with self._lock:
            rows = self.db.execute(
                "SELECT name, dur_s FROM record_spans WHERE ledger = ? "
                "ORDER BY record_id", (ledger_rel,)).fetchall()
        out: Dict[str, List[float]] = {}
        for name, dur in rows:
            out.setdefault(name, []).append(dur)
        return out

    def span_stats(self, ledger_rel: str) -> Dict[str, Dict[str, float]]:
        with self._lock:
            rows = self.db.execute(
                "SELECT name, count, min, p50, p95, max FROM span_rollup "
                "WHERE ledger = ?", (ledger_rel,)).fetchall()
        return {name: {"count": count, "min": mn, "p50": p50,
                       "p95": p95, "max": mx}
                for name, count, mn, p50, p95, mx in
                sorted(rows, key=lambda r: r[0])}

    def span_samples(self, ledger_rel: str, name: str
                     ) -> List[Tuple[Optional[str], float]]:
        """(gen, duration) pairs for one span name, in append order —
        the material for span_trend and the regression gate."""
        with self._lock:
            rows = self.db.execute(
                "SELECT r.gen, s.dur_s FROM record_spans s "
                "JOIN campaign_records r ON r.id = s.record_id "
                "WHERE s.ledger = ? AND s.name = ? ORDER BY s.record_id",
                (ledger_rel, name)).fetchall()
        return [(gen, dur) for gen, dur in rows]

    def span_trend(self, ledger_rel: str, name: str
                   ) -> List[Tuple[str, float]]:
        with self._lock:
            rows = self.db.execute(
                "SELECT gen, p95 FROM span_gen_rollup WHERE ledger = ? "
                "AND name = ? ORDER BY first_id",
                (ledger_rel, name)).fetchall()
        return [(gen, p95) for gen, p95 in rows]

    def forensic_records(self, ledger_rel: str
                         ) -> List[Tuple[Optional[str],
                                         Dict[str, float],
                                         Dict[str, Any],
                                         Dict[str, float]]]:
        """(gen, spans, phases, counters) per ledger record in append
        order — the ONE input shape `telemetry.forensics` attributes
        regressions from; `Index.forensic_records` returns the
        identical shape off the raw jsonl (parity asserted in tests)."""
        with self._lock:
            recs = self.db.execute(
                "SELECT id, gen, phases, counters FROM campaign_records "
                "WHERE ledger = ? ORDER BY id", (ledger_rel,)).fetchall()
            span_rows = self.db.execute(
                "SELECT record_id, name, dur_s FROM record_spans "
                "WHERE ledger = ? ORDER BY record_id",
                (ledger_rel,)).fetchall()
        spans_by_rid: Dict[int, Dict[str, float]] = {}
        for rid, name, dur in span_rows:
            spans_by_rid.setdefault(rid, {})[name] = dur
        out = []
        for rid, gen, phases, counters in recs:
            out.append((gen, spans_by_rid.get(rid, {}),
                        json.loads(phases) if phases else {},
                        json.loads(counters) if counters else {}))
        return out

    def campaign_profile(self, ledger_rel: str) -> List[Dict[str, Any]]:
        """The campaign's fleet-wide device-call profile: per (site,
        shape-class, host) call counts and phase self-times summed over
        every run dir its records landed in — the ``cli obs profile``
        treemap rows, biggest total first."""
        with self._lock:
            rows = self.db.execute(
                "SELECT p.site, p.shape, p.host, SUM(p.calls), "
                "SUM(p.compile_s), SUM(p.execute_s), "
                "SUM(p.device_dispatch_s) FROM span_profile p "
                "JOIN (SELECT DISTINCT dir FROM campaign_records "
                "      WHERE ledger = ? AND dir IS NOT NULL) r "
                "ON p.dir = r.dir "
                "GROUP BY p.site, p.shape, p.host",
                (ledger_rel,)).fetchall()
        out = [{"site": site, "shape": shape, "host": host,
                "calls": int(calls or 0),
                "compile_s": round(comp or 0.0, 6),
                "execute_s": round(exe or 0.0, 6),
                "device_dispatch_s": round(disp or 0.0, 6)}
               for site, shape, host, calls, comp, exe, disp in rows]
        out.sort(key=lambda r: -(r["compile_s"] + r["execute_s"]))
        return out

    def latest_by_run(self, ledger_rel: str) -> Dict[str, Dict[str, Any]]:
        """The LATEST verdict-bearing record per run id, reconstructed
        to the shape the web grid and verdict_counts consume."""
        with self._lock:
            rows = self.db.execute(
                "SELECT r.run, r.key, r.workload, r.fault, r.seed, "
                "r.valid, r.error, r.degraded, r.deadline, r.dir, "
                "r.ops, r.wall_s, r.gen, r.ts, r.witness, r.trace "
                "FROM campaign_records r JOIN ("
                "  SELECT run, MAX(id) AS mid FROM campaign_records"
                "  WHERE ledger = ? AND valid IS NOT NULL"
                "    AND run IS NOT NULL AND run != '' GROUP BY run) t "
                "ON r.id = t.mid", (ledger_rel,)).fetchall()
        out: Dict[str, Dict[str, Any]] = {}
        for (run, key, wl, fl, seed, valid, error, degraded, deadline,
             d, ops, wall_s, gen, ts, wit, trace) in rows:
            out[run] = {
                "run": run, "key": key, "workload": wl, "fault": fl,
                "seed": _loads(seed) if seed is not None else None,
                "valid?": _loads(valid),
                "error": error, "degraded": degraded,
                "deadline": bool(deadline), "dir": d, "ops": ops,
                "wall_s": wall_s, "gen": gen, "ts": ts,
                "witness": json.loads(wit) if wit else None,
                "trace": trace,
            }
        return out

    def verdict_counts(self, ledger_rel: str,
                       runs: Optional[Any] = None) -> Dict[str, int]:
        from jepsen_tpu.campaign.index import verdict_counts_over

        latest = self.latest_by_run(ledger_rel)
        if runs is not None:
            wanted = set(runs)
            latest = {k: v for k, v in latest.items() if k in wanted}
        return verdict_counts_over(latest.values())

    def witness_records(self, ledger_rel: str
                        ) -> Dict[str, List[Dict[str, Any]]]:
        """Witness-bearing records grouped by key, in append order —
        the input shape `index.witness_pair_diffs` consumes."""
        with self._lock:
            rows = self.db.execute(
                "SELECT key, gen, witness FROM campaign_records "
                "WHERE ledger = ? AND witness IS NOT NULL "
                "AND key IS NOT NULL AND key != '' ORDER BY id",
                (ledger_rel,)).fetchall()
        out: Dict[str, List[Dict[str, Any]]] = {}
        for key, gen, wit in rows:
            w = json.loads(wit)
            if isinstance(w, dict) and w.get("ops"):
                out.setdefault(key, []).append({"gen": gen, "witness": w})
        return out

    # -- cross-host timelines (ISSUE 14 tentpole c) --------------------------

    def trace_timeline(self, run_or_trace: str) -> Dict[str, Any]:
        """One run's stitched cross-host timeline.  Accepts a run id
        (the trace id derives from it) or a 32-hex trace id.  Returns
        ``{"trace-id", "run", "spans": [...], "orphans": [...]}`` —
        spans ordered by absolute start time, each host-attributed;
        ``orphans`` are rows recorded against this RUN under a
        DIFFERENT trace id (the acceptance's zero-orphans check: a
        relanded/replayed run must stitch to ONE trace)."""
        from .spans import trace_id_for

        key = str(run_or_trace)
        is_tid = len(key) == 32 and all(
            c in "0123456789abcdef" for c in key)
        tid = key if is_tid else trace_id_for(key)
        with self._lock:
            rows = self.db.execute(
                "SELECT trace_id, source, run, host, name, t0, t1, "
                "dur_s FROM trace_spans WHERE trace_id = ? OR run = ? "
                "ORDER BY t0, id", (tid, key)).fetchall()
        cols = ("trace_id", "source", "run", "host", "name", "t0",
                "t1", "dur_s")
        spans, orphans = [], []
        run = None if is_tid else key
        for r in rows:
            d = dict(zip(cols, r))
            if run is None and d.get("run"):
                run = d["run"]
            (spans if d["trace_id"] == tid else orphans).append(d)
        return {"trace-id": tid, "run": run, "spans": spans,
                "orphans": orphans}

    @staticmethod
    def timeline_layout(tl: Dict[str, Any]) -> Dict[str, Any]:
        """Waterfall geometry for one :meth:`trace_timeline` result —
        THE shared layout both renderers (cli ``obs timeline`` and the
        web ``/timeline`` page) consume, so bar math can't drift
        between them.  Empty-safe: a timeline with only orphan rows
        (every artifact disagreed with the derived trace id) lays out
        zero spans but still reports hosts/wall defaults, so the
        renderers can show the orphan diagnostic instead of crashing."""
        spans = tl.get("spans") or []
        t0s = [s["t0"] for s in spans
               if isinstance(s.get("t0"), (int, float))]
        t1s = [s["t1"] for s in spans
               if isinstance(s.get("t1"), (int, float))]
        t_min = min(t0s) if t0s else 0.0
        wall = max((max(t1s) - t_min) if t1s else 0.0, 1e-9)
        rows = []
        for s in spans:
            t0 = s.get("t0")
            off = (t0 - t_min) if isinstance(t0, (int, float)) else 0.0
            dur = s.get("dur_s") or 0.0
            rows.append(dict(s, off=round(off, 6),
                             frac_left=min(max(off / wall, 0.0), 1.0),
                             frac_width=min(max(dur / wall, 0.0), 1.0)))
        return {
            "t_min": t_min, "wall": wall, "spans": rows,
            "hosts": sorted({str(s.get("host")) for s in spans
                             if s.get("host")}),
        }

    def traces(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Recent stitched traces, newest first: one row per trace id
        with its run, span count, distinct hosts, and wall span."""
        with self._lock:
            rows = self.db.execute(
                "SELECT trace_id, MAX(run), COUNT(*), "
                "COUNT(DISTINCT host), MIN(t0), MAX(t1) "
                "FROM trace_spans GROUP BY trace_id "
                "ORDER BY MIN(t0) DESC LIMIT ?", (int(limit),)).fetchall()
        return [{"trace-id": tid, "run": run, "spans": n,
                 "hosts": hosts, "t0": t0, "t1": t1}
                for tid, run, n, hosts, t0, t1 in rows]

    # -- rollups (the /metrics exposition) -----------------------------------

    def rollups(self) -> Dict[str, Any]:
        """Warehouse-wide gauges for the Prometheus exposition: runs by
        verdict (in-progress runs roll up as ``running`` — the ISSUE 7
        status fix), per-campaign latest verdict counts, verifier
        session states, latest bench throughput.  Archived runs are
        excluded — the gauges describe the LIVE store, so `obs gc`
        retiring old runs doesn't move them (the history stays
        queryable via ``obs sql ... WHERE archived = 1``)."""
        with self._lock:
            run_rows = self.db.execute(
                "SELECT valid, status, COUNT(*) FROM runs "
                "WHERE archived = 0 GROUP BY valid, status").fetchall()
            ledgers = [r[0] for r in self.db.execute(
                "SELECT DISTINCT ledger FROM campaign_records").fetchall()]
            vf_rows = self.db.execute(
                "SELECT state, COUNT(*) FROM verifier_sessions "
                "GROUP BY state").fetchall()
        runs_by_verdict: Dict[str, int] = {}
        for valid, status, n in run_rows:
            if valid is None:
                k = "running" if status == "running" else "none"
            else:
                v = json.loads(valid)
                k = ("true" if v is True else
                     "false" if v is False else "unknown")
            runs_by_verdict[k] = runs_by_verdict.get(k, 0) + n
        campaigns = {}
        for led in ledgers:
            name = os.path.basename(led)
            if name.endswith(".jsonl"):
                name = name[:-len(".jsonl")]
            campaigns[name] = self.verdict_counts(led)
        return {"runs_by_verdict": runs_by_verdict,
                "campaigns": campaigns,
                "verifier_by_state": {str(s or "?"): n
                                      for s, n in vf_rows},
                "bench": self.bench_series()}

    # -- raw SQL (cli obs sql; read-only) ------------------------------------

    def query(self, sql: str) -> Tuple[List[str], List[Tuple]]:
        """Run one read-only statement (the ``cli obs sql`` cookbook
        hook).  Writes are refused — enforced at the ENGINE level via a
        throwaway ``mode=ro`` connection, not just the keyword check:
        sqlite accepts CTE-prefixed writes (``WITH x AS (SELECT 1)
        DELETE FROM ...``) that a prefix regex would wave through."""
        if not re.match(r"\s*(SELECT|WITH|EXPLAIN|PRAGMA)\b", sql,
                        re.IGNORECASE):
            raise ValueError("obs sql is read-only (SELECT/WITH only)")
        con = sqlite3.connect(f"file:{self.path}?mode=ro", uri=True)
        try:
            cur = con.execute(sql)
            cols = [c[0] for c in cur.description or []]
            return cols, cur.fetchall()
        finally:
            con.close()


class _Absent:
    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "<absent>"


_ABSENT = _Absent()


# ---------------------------------------------------------------------------
# Shared handles: the web server and Index fast paths reuse one
# connection per warehouse file instead of re-opening sqlite per request.
# ---------------------------------------------------------------------------

_CACHE: Dict[str, Warehouse] = {}
_CACHE_LOCK = threading.Lock()


def _cached(path: str) -> Warehouse:
    key = os.path.abspath(path)
    with _CACHE_LOCK:
        wh = _CACHE.get(key)
        if wh is not None and not wh.file_unchanged():
            # the file was deleted or replaced under the cache (rm +
            # `obs rebuild` in another process): drop the handle bound
            # to the old inode and re-open the path
            wh.close()
            del _CACHE[key]
            wh = None
        if wh is None:
            wh = _CACHE[key] = Warehouse(path)
        return wh


def open_or_create(base: str) -> Warehouse:
    """The store's warehouse, creating the file on first use (``cli
    obs ingest`` / bench self-ingest)."""
    return _cached(warehouse_path(base))


def open_if_exists(base: str) -> Optional[Warehouse]:
    """The store's warehouse ONLY if someone already built one — the
    read surfaces (web, Index fast paths) never create it implicitly.
    A cached handle is only trusted while the file still names the
    inode it opened (a deleted warehouse returns None again; a
    replaced one — rm + rebuild in another process — is re-opened)."""
    p = warehouse_path(base)
    with _CACHE_LOCK:
        wh = _CACHE.get(os.path.abspath(p))
        if wh is not None:
            if wh.file_unchanged():
                return wh
            wh.close()
            del _CACHE[os.path.abspath(p)]
    if not os.path.exists(p):
        return None
    return _cached(p)


def for_ledger(ledger_path: str) -> Optional[Tuple[Warehouse, str]]:
    """(warehouse, ledger-rel-path) when a warehouse exists next to
    this campaign ledger AND fully covers it (cursor == size) — the
    Index fast-path gate.  None means: use the jsonl scan."""
    base = os.path.dirname(os.path.dirname(os.path.abspath(ledger_path)))
    try:
        wh = open_if_exists(base)
        if wh is None:
            return None
        if not wh.ledger_fresh(ledger_path, base):
            return None
        rel = os.path.relpath(os.path.abspath(ledger_path), base)
        return wh, rel
    except sqlite3.Error as e:  # corrupt warehouse: fall back to jsonl
        logger.warning("warehouse unavailable for %s: %s", ledger_path, e)
        return None
