"""Process-wide metrics registry — counters, gauges, histograms.

The second half of the telemetry layer (ISSUE 1): where spans answer
"where did the time go", metrics answer "how much work happened" —
ops invoked/ok/fail/info per worker, generator stall time, checker
throughput, bytes staged to device.

Shapes:
- :class:`Counter` — monotonically increasing float/int (`inc`).
- :class:`Gauge` — last-write-wins value (`set`).
- :class:`Histogram` — fixed bucket upper bounds chosen at creation;
  `observe` bins the value, tracking count/sum (Prometheus-style
  cumulative counts are computed at snapshot time).

Instruments are keyed by (name, sorted labels); asking twice returns
the same instrument, so instrumentation sites never need module-level
handles.  Creation is lock-protected; per-instrument mutation uses one
small lock per instrument — single-writer hot paths (the interpreter
accumulates per-worker counts locally and flushes once) keep that off
the op path entirely.

The process-wide default registry lives here (:func:`registry`);
`export.snapshot` serializes it next to the span tree.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "registry",
           "reset"]

LabelKey = Tuple[Tuple[str, Any], ...]


def _key(name: str, labels: Dict[str, Any]) -> Tuple[str, LabelKey]:
    return name, tuple(sorted(labels.items()))


class Counter:
    __slots__ = ("name", "labels", "_lock", "value")

    def __init__(self, name: str, labels: Dict[str, Any]):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self.value: float = 0

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, Any]):
        self.name = name
        self.labels = labels
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = v  # single 8-byte store; races just last-write-win


DEFAULT_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0, 100.0)


class Histogram:
    """Fixed-bucket histogram: `bounds` are inclusive upper bounds, with
    an implicit +inf bucket at the end."""

    __slots__ = ("name", "labels", "bounds", "_lock", "counts", "sum",
                 "count")

    def __init__(self, name: str, labels: Dict[str, Any],
                 bounds: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.labels = labels
        self.bounds: Tuple[float, ...] = tuple(sorted(bounds))
        self._lock = threading.Lock()
        self.counts: List[int] = [0] * (len(self.bounds) + 1)
        self.sum: float = 0.0
        self.count: int = 0

    def observe(self, v: float) -> None:
        i = 0
        for b in self.bounds:
            if v <= b:
                break
            i += 1
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1


class Registry:
    """Threadsafe instrument registry.  `counter/gauge/histogram` create
    on first use and return the cached instrument afterwards."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, LabelKey], Any] = {}

    def _get(self, cls, name: str, labels: Dict[str, Any], *args):
        k = _key(name, labels)
        m = self._metrics.get(k)
        if m is None:
            with self._lock:
                m = self._metrics.get(k)
                if m is None:
                    m = self._metrics[k] = cls(name, labels, *args)
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  **labels: Any) -> Histogram:
        return self._get(Histogram, name, labels, buckets)

    def snapshot(self) -> Dict[str, List[Dict[str, Any]]]:
        """Serializable view: {"counters": [...], "gauges": [...],
        "histograms": [...]}, each entry carrying name/labels/value(s)."""
        out: Dict[str, List[Dict[str, Any]]] = {
            "counters": [], "gauges": [], "histograms": []}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            base = {"name": m.name, "labels": dict(m.labels)}
            if isinstance(m, Counter):
                out["counters"].append({**base, "value": m.value})
            elif isinstance(m, Gauge):
                out["gauges"].append({**base, "value": m.value})
            else:
                out["histograms"].append({
                    **base,
                    "buckets": list(m.bounds) + ["+inf"],
                    "counts": list(m.counts),
                    "sum": m.sum, "count": m.count,
                })
        return out

    def remove(self, name: str, **labels: Any) -> bool:
        """Drop one instrument — e.g. a per-session labeled gauge when
        the session ends, so a long-lived service doesn't accumulate
        stale label series on /metrics forever."""
        with self._lock:
            return self._metrics.pop(_key(name, labels), None) is not None

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


_default = Registry()


def registry() -> Registry:
    """The process-wide registry (ISSUE 1's "process-wide registry of
    counters, gauges, and fixed-bucket histograms")."""
    return _default


def reset() -> None:
    """Drop all instruments (tests; runs normally accumulate)."""
    _default.clear()
