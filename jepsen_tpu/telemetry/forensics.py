"""Regression forensics + device-call profiles (ISSUE 16 tentpole c).

PR 14's ``obs gate`` is a tripwire: rc 1 when a span's p95 regressed.
This module turns the trip into a diagnosis — WHERE inside the span the
extra time went (the phase-bucket taxonomy ``spans.PHASE_BUCKETS``) and
WHAT co-moved with it (compile-cache misses, retries, requeues, sweep
dispatches) — so a perf PR cites machine-generated before/after
attribution instead of a hand-run bench.

Parity contract: every function here is PURE over the
``(gen, spans, phases, counters)`` record shape that BOTH backends
produce (``Index.forensic_records`` off the raw jsonl,
``Warehouse.forensic_records`` off SQL), so the warehouse fast path and
the jsonl scan fallback reach the identical verdict — the same
discipline as ``index.witness_pair_diffs``.

Attribution rule: per generation, a span's MEAN duration over the
records that carry it; the delta between generations is split across
the mean per-bucket deltas of the same records.  Means (not p95s)
because a bucket share of a p95 is not well defined — the p95 verdict
itself still comes from :mod:`gate`'s Mann-Whitney test, so forensics
never changes a gate decision, only explains it.
"""

from __future__ import annotations

import fnmatch
import os
from typing import Any, Dict, List, Optional, Tuple

from .spans import PHASE_BUCKETS

__all__ = ["profile_from_doc", "profile_rows_from_dirs",
           "render_profile", "attribute_span", "run_diff",
           "render_diff", "resolve_spans"]

#: the span_profile/profile-cell value keys, in display order
PROFILE_KEYS = ("calls", "compile_s", "execute_s", "device_dispatch_s")


# ---------------------------------------------------------------------------
# Device-call profiles: (site, shape-class) self-time cells
# ---------------------------------------------------------------------------

def _empty_cell() -> Dict[str, Any]:
    return {"calls": 0, "compile_s": 0.0, "execute_s": 0.0,
            "device_dispatch_s": 0.0}


def profile_from_doc(doc: Any) -> Dict[Tuple[str, str], Dict[str, Any]]:
    """The run's per-(site, shape-class) device-call profile, summed
    over every span's ``profile`` attr in a telemetry.json document —
    THE extraction both the warehouse run-dir ingest and the jsonl
    fallback use."""
    out: Dict[Tuple[str, str], Dict[str, Any]] = {}
    if not isinstance(doc, dict):
        return out

    def fold(prof: Any) -> None:
        if not isinstance(prof, dict):
            return
        for key, cell in prof.items():
            if not isinstance(cell, dict):
                continue
            site, _, shape = str(key).partition("|")
            agg = out.setdefault((site, shape or "scalar"), _empty_cell())
            agg["calls"] += int(cell.get("calls") or 0)
            for k in ("compile_s", "execute_s", "device_dispatch_s"):
                v = cell.get(k)
                if isinstance(v, (int, float)):
                    agg[k] += float(v)

    def walk(sp: Dict[str, Any]) -> None:
        fold((sp.get("attrs") or {}).get("profile"))
        for c in sp.get("children") or []:
            walk(c)

    for r in doc.get("spans") or []:
        walk(r)
    return out


def profile_rows_from_dirs(base: str, dirs: List[str]
                           ) -> List[Dict[str, Any]]:
    """The jsonl-scan twin of ``Warehouse.campaign_profile``: read each
    run dir's telemetry.json and aggregate per (site, shape, host).
    ``dirs`` are store-relative (ledger record ``dir`` fields)."""
    import json

    agg: Dict[Tuple[str, str, Optional[str]], Dict[str, Any]] = {}
    for rel in dirs:
        if not rel:
            continue
        path = os.path.join(base, rel, "telemetry.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        meta = doc.get("meta") or {} if isinstance(doc, dict) else {}
        host = meta.get("host") if isinstance(meta, dict) else None
        for (site, shape), cell in profile_from_doc(doc).items():
            a = agg.setdefault((site, shape, host), _empty_cell())
            a["calls"] += cell["calls"]
            for k in ("compile_s", "execute_s", "device_dispatch_s"):
                a[k] += cell[k]
    out = [{"site": site, "shape": shape, "host": host,
            "calls": int(c["calls"]),
            "compile_s": round(c["compile_s"], 6),
            "execute_s": round(c["execute_s"], 6),
            "device_dispatch_s": round(c["device_dispatch_s"], 6)}
           for (site, shape, host), c in agg.items()]
    out.sort(key=lambda r: -(r["compile_s"] + r["execute_s"]))
    return out


def render_profile(rows: List[Dict[str, Any]], width: int = 44) -> str:
    """Text treemap of a campaign profile: per site (largest first) a
    bar of its self-time share, then its shape classes indented —
    ``obs profile``'s renderer (the web page shares the row shape)."""
    if not rows:
        return "no device-call profile (no telemetric runs ingested?)"
    by_site: Dict[str, List[Dict[str, Any]]] = {}
    for r in rows:
        by_site.setdefault(r["site"], []).append(r)
    site_total = {s: sum(r["compile_s"] + r["execute_s"] for r in rs)
                  for s, rs in by_site.items()}
    grand = sum(site_total.values()) or 1e-12
    lines = [f"{'site / shape-class':<52} {'calls':>7} "
             f"{'compile':>9} {'execute':>9} {'dispatch':>9}"]
    for site in sorted(by_site, key=lambda s: -site_total[s]):
        rs = by_site[site]
        share = site_total[site] / grand
        bar = "#" * max(1, int(round(share * width)))
        lines.append(f"{site:<38} {bar} {share * 100:5.1f}%")
        for r in sorted(rs, key=lambda r: -(r["compile_s"]
                                            + r["execute_s"])):
            host = f" @{r['host']}" if r.get("host") else ""
            lines.append(
                f"  {r['shape'][:48] + host:<50} {r['calls']:>7} "
                f"{r['compile_s']:>8.3f}s {r['execute_s']:>8.3f}s "
                f"{r['device_dispatch_s']:>8.3f}s")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Regression forensics over ledger records
# ---------------------------------------------------------------------------

def resolve_spans(names: Any, patterns: List[str]) -> List[str]:
    """Expand ``--span`` values (exact names and ``*`` globs) against
    the known span names, preserving pattern order then name order;
    exact names pass through even when absent (the gate reports
    insufficient-data for them, matching single-span behavior)."""
    known = sorted(names)
    out: List[str] = []
    for pat in patterns:
        if any(ch in pat for ch in "*?["):
            out.extend(n for n in known
                       if fnmatch.fnmatchcase(n, pat) and n not in out)
        elif pat not in out:
            out.append(pat)
    return out


def _gen_order(records: List[Tuple]) -> List[str]:
    order: List[str] = []
    for gen, _spans, _ph, _cn in records:
        g = str(gen or "?")
        if g not in order:
            order.append(g)
    return order


def _mean(xs: List[float]) -> float:
    return sum(xs) / len(xs) if xs else 0.0


def attribute_span(span: str, records: List[Tuple],
                   from_gen: str, to_gen: str) -> Dict[str, Any]:
    """Attribute one span's generation-to-generation delta across the
    phase buckets and forensic counter deltas.  ``records`` is the
    backend-shared ``(gen, spans, phases, counters)`` list."""
    def for_gen(g: str) -> Tuple[List[float], List[Dict[str, Any]],
                                 List[Dict[str, float]]]:
        durs, phs, cns = [], [], []
        for gen, spans, phases, counters in records:
            if str(gen or "?") != g:
                continue
            dur = spans.get(span)
            if isinstance(dur, (int, float)):
                durs.append(float(dur))
                phs.append(phases.get(span) or {})
            cns.append(counters or {})
        return durs, phs, cns

    d_from, ph_from, cn_from = for_gen(from_gen)
    d_to, ph_to, cn_to = for_gen(to_gen)
    mean_from, mean_to = _mean(d_from), _mean(d_to)
    delta = mean_to - mean_from
    buckets = []
    for b in PHASE_BUCKETS:
        bf = _mean([float(p.get(b) or 0.0) for p in ph_from])
        bt = _mean([float(p.get(b) or 0.0) for p in ph_to])
        bd = bt - bf
        if not bf and not bt:
            continue
        buckets.append({
            "bucket": b, "from_s": round(bf, 6), "to_s": round(bt, 6),
            "delta_s": round(bd, 6),
            "share": round(bd / delta, 4) if delta > 0 else None,
        })
    buckets.sort(key=lambda e: -e["delta_s"])
    attributed = sum(e["delta_s"] for e in buckets if e["delta_s"] > 0)
    names = sorted({k for c in cn_from + cn_to for k in c})
    counters = []
    for name in names:
        cf = _mean([float(c.get(name) or 0.0) for c in cn_from])
        ct = _mean([float(c.get(name) or 0.0) for c in cn_to])
        if cf == ct:
            continue
        counters.append({"name": name, "from": round(cf, 3),
                         "to": round(ct, 3),
                         "delta": round(ct - cf, 3)})
    counters.sort(key=lambda e: -abs(e["delta"]))
    dominant = next((e["bucket"] for e in buckets
                     if e["delta_s"] > 0), None)
    return {
        "span": span, "from-gen": from_gen, "to-gen": to_gen,
        "n_from": len(d_from), "n_to": len(d_to),
        "mean_from": round(mean_from, 6), "mean_to": round(mean_to, 6),
        "delta_s": round(delta, 6),
        "rel_delta": (round(delta / mean_from, 4) if mean_from > 0
                      else None),
        "phases": buckets,
        "attributed_s": round(attributed, 6),
        "unattributed_s": round(max(0.0, delta - attributed), 6)
        if delta > 0 else 0.0,
        "counters": counters,
        "dominant": dominant,
    }


def run_diff(base: str, campaign: str, *,
             from_gen: Optional[str] = None,
             to_gen: Optional[str] = None,
             spans: Optional[List[str]] = None,
             alpha: float = 0.05, threshold: float = 0.25,
             min_runs: int = 3) -> Dict[str, Any]:
    """The ``cli obs diff`` engine: gate every span between two
    generations and attribute each regression's delta.  Returns a
    report dict with ``status`` in {"regression", "pass",
    "insufficient-data"}; unknown campaigns / missing generations are
    insufficient-data (rc 2), a named regression is rc 1."""
    from jepsen_tpu.campaign.core import index_path
    from jepsen_tpu.campaign.index import Index

    from . import gate as gate_mod

    idx = Index(index_path(campaign, base))
    records = idx.forensic_records()
    order = _gen_order(records)
    report: Dict[str, Any] = {
        "campaign": campaign, "generations": order,
        "alpha": alpha, "threshold": threshold, "min_runs": min_runs,
    }
    if len(order) < 2 and not (from_gen and to_gen):
        report.update(status="insufficient-data",
                      reason=f"need >= 2 generations, have {len(order)}",
                      spans=[])
        return report
    g_from = from_gen or order[-2]
    g_to = to_gen or order[-1]
    report.update({"from-gen": g_from, "to-gen": g_to})
    if g_from == g_to:
        report.update(status="insufficient-data",
                      reason="from-gen == to-gen", spans=[])
        return report
    names = sorted({n for _g, sp, _p, _c in records for n in sp})
    wanted = resolve_spans(names, spans) if spans else names
    entries = []
    for span in wanted:
        by_gen: Dict[str, List[float]] = {}
        for gen, sp, _p, _c in records:
            dur = sp.get(span)
            if isinstance(dur, (int, float)):
                by_gen.setdefault(str(gen or "?"), []).append(float(dur))
        res = gate_mod.gate_samples(by_gen.get(g_from, []),
                                    by_gen.get(g_to, []),
                                    alpha=alpha, threshold=threshold,
                                    min_runs=min_runs)
        entry = attribute_span(span, records, g_from, g_to)
        entry["gate"] = res
        entry["status"] = res.get("status")
        entries.append(entry)
    rank = {"regression": 0, "pass": 1, "insufficient-data": 2}
    entries.sort(key=lambda e: (rank.get(e["status"], 3),
                                -(e.get("rel_delta") or 0.0)))
    report["spans"] = entries
    if any(e["status"] == "regression" for e in entries):
        report["status"] = "regression"
    elif any(e["status"] == "pass" for e in entries):
        report["status"] = "pass"
    else:
        report["status"] = "insufficient-data"
        report.setdefault("reason", "no span had enough samples in "
                                    "both generations")
    return report


def _fmt_pct(x: Optional[float]) -> str:
    return f"{x * 100:+.0f}%" if isinstance(x, (int, float)) else "?"


def render_attribution(entry: Dict[str, Any]) -> List[str]:
    """The per-span forensics lines shared by ``obs diff`` and
    ``obs gate --explain``."""
    lines = []
    head = (f"{entry['span']}: {_fmt_pct(entry.get('rel_delta'))} "
            f"(mean {entry['mean_from']:.4f}s -> "
            f"{entry['mean_to']:.4f}s, "
            f"n={entry['n_from']}/{entry['n_to']})")
    if entry.get("dominant"):
        share = next((e.get("share") for e in entry["phases"]
                      if e["bucket"] == entry["dominant"]), None)
        pct = (f"{share * 100:.0f}% " if isinstance(share, (int, float))
               else "")
        head += f" — {pct}of delta in {entry['dominant']}"
    lines.append(head)
    for e in entry.get("phases") or []:
        share = e.get("share")
        pct = (f" ({share * 100:5.1f}% of delta)"
               if isinstance(share, (int, float)) else "")
        lines.append(f"    {e['bucket']:<18} {e['from_s']:>9.4f}s -> "
                     f"{e['to_s']:>9.4f}s  {e['delta_s']:+9.4f}s{pct}")
    if entry.get("unattributed_s"):
        lines.append(f"    {'(unattributed)':<18} "
                     f"{entry['unattributed_s']:+9.4f}s outside the "
                     "phase buckets")
    for c in (entry.get("counters") or [])[:8]:
        lines.append(f"    {c['name']}  {c['from']:g} -> {c['to']:g} "
                     f"({c['delta']:+g})")
    return lines


def render_diff(report: Dict[str, Any]) -> str:
    lines = [f"obs diff: campaign {report['campaign']} "
             f"{report.get('from-gen', '?')} -> "
             f"{report.get('to-gen', '?')} "
             f"[{report.get('status')}]"]
    if report.get("reason"):
        lines.append(f"  {report['reason']}")
    for entry in report.get("spans") or []:
        marker = {"regression": "REGRESSION", "pass": "ok",
                  "insufficient-data": "n/a"}.get(entry["status"], "?")
        lines.append("")
        lines.append(f"[{marker}] " + render_attribution(entry)[0])
        if entry["status"] == "regression":
            lines.extend(render_attribution(entry)[1:])
        g = entry.get("gate") or {}
        if g.get("status") == "regression":
            lines.append(f"    gate: p95 {g.get('p95_old')}s -> "
                         f"{g.get('p95_new')}s, p={g.get('p_value')}")
    return "\n".join(lines)
