"""Telemetry: run/checker span tracing + metrics registry (ISSUE 1).

Zero-dependency, thread-safe observability for the whole pipeline::

    run → os-setup / db-setup / workload / nemesis / store.save_0
        / check:<name> / store.save_1

Usage — instrumentation sites call the module-level API and pay nothing
when telemetry is off (the active collector is a no-op singleton)::

    from jepsen_tpu import telemetry

    with telemetry.span("elle.infer", txns=n) as sp:
        ...
        sp.set_attr(edges=m)

    telemetry.registry().counter("ops", worker=3, type="ok").inc()

Enabling — any of:

- per run: ``test["telemetry"] = True`` (``core.run`` activates a fresh
  collector for the run and ``store.save_1`` writes ``telemetry.json``
  + Chrome ``trace.json`` into the store dir);
- per process: :func:`enable` (or env ``JEPSEN_TELEMETRY=1``), which
  makes every run telemetric;
- manually: ``collector = telemetry.activate()`` ...
  ``telemetry.deactivate(collector)`` around any code, then
  ``export.snapshot(collector)``.

See ``docs/TELEMETRY.md`` for reading ``trace.json`` in Perfetto.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from . import export, metrics, spans, stream
from .export import chrome_trace, snapshot, summarize, write_run
from .metrics import Registry
from .stream import Heartbeat, HttpHeartbeat, read_events
from .stream import attach as attach_stream
from .stream import event as stream_event

# NOTE: gate, prometheus, and warehouse are sibling modules imported
# lazily by their consumers (cli obs, web /metrics, Index fast paths)
# — importing sqlite3 here would tax every `import jepsen_tpu`.
from .spans import (
    NOOP,
    PHASE_BUCKETS,
    TRACE_HEADER,
    Collector,
    NoopCollector,
    PhaseTimer,
    Span,
    TraceContext,
    activate,
    active,
    add_phase,
    current,
    current_trace,
    deactivate,
    enabled,
    mint_trace,
    parse_trace_header,
    phases,
    set_trace,
    span,
    trace_context,
    trace_id_for,
    trace_scope,
    traced,
)

__all__ = [
    "Collector", "NoopCollector", "PhaseTimer", "Span", "NOOP",
    "Registry", "activate", "active", "current", "deactivate",
    "enabled", "phases", "span", "traced", "registry", "snapshot",
    "chrome_trace", "write_run", "summarize", "enable", "disable",
    "wanted_for", "export", "metrics", "spans", "stream",
    "attach_stream", "stream_event", "read_events", "Heartbeat",
    "HttpHeartbeat",
    "TraceContext", "TRACE_HEADER", "mint_trace", "trace_id_for",
    "trace_context", "parse_trace_header", "current_trace",
    "set_trace", "trace_scope", "add_phase", "PHASE_BUCKETS",
]

def registry() -> Registry:
    """The metrics registry instrumentation should write to: the active
    collector's own registry when a run is being traced (per-run
    isolation — two telemetric runs in one process don't mix tallies),
    else the process-wide default (accumulates across the process, the
    "process-wide registry" backstop for collector-less use)."""
    r = getattr(active(), "registry", None)
    return r if r is not None else metrics.registry()


_process_enabled = False


def enable() -> None:
    """Make every subsequent run telemetric (process-wide opt-in)."""
    global _process_enabled
    _process_enabled = True


def disable() -> None:
    global _process_enabled
    _process_enabled = False


def _env_enabled() -> bool:
    return os.environ.get("JEPSEN_TELEMETRY", "").strip().lower() in (
        "1", "true", "yes", "on")


def wanted_for(test: Optional[dict]) -> bool:
    """Should this run collect telemetry?  True when the test map opts
    in (``"telemetry"`` truthy), the process opted in via
    :func:`enable`, or ``JEPSEN_TELEMETRY`` is truthy."""
    if test and test.get("telemetry"):
        return True
    return _process_enabled or _env_enabled()
