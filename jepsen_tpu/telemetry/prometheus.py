"""Prometheus text exposition (the web ``/metrics`` endpoint, ISSUE 6).

Renders the live metrics registry — the active run's counters, gauges,
and histogram buckets — plus campaign heartbeat freshness and warehouse
rollup gauges as Prometheus **text exposition format 0.0.4**: one
``# HELP``/``# TYPE`` block per metric family, cumulative
``_bucket{le=...}`` lines for histograms, backslash/quote/newline label
escaping.  Scrape-compatible output is pinned by a golden test
(``tests/data/prometheus-golden.txt``) so it can't drift under a
refactor.

Conventions:

- instrument names are sanitized to the Prometheus charset and prefixed
  ``jepsen_`` (``checker-ops-per-s`` → ``jepsen_checker_ops_per_s``);
- counters get the ``_total`` suffix;
- every family's samples are sorted (name, then serialized labels) so
  the exposition is deterministic.
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from . import metrics as _metrics

__all__ = ["exposition", "render_registry", "render_heartbeats",
           "render_warehouse", "render_fleet", "render_alerts",
           "metric_name", "escape_label_value", "CONTENT_TYPE"]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")


def metric_name(name: str, prefix: str = "jepsen_") -> str:
    """Sanitize an instrument name to the Prometheus charset (every
    illegal character becomes ``_``) and prefix it."""
    s = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if not s or s[0].isdigit():
        s = "_" + s
    out = prefix + s
    assert _NAME_OK.match(out), out
    return out


def _label_name(name: str) -> str:
    s = re.sub(r"[^a-zA-Z0-9_]", "_", str(name))
    if not s or s[0].isdigit():
        s = "_" + s
    return s


def escape_label_value(v: Any) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote, and newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v: Any) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labels_str(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_label_name(k)}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items(), key=lambda kv: str(kv[0])))
    return "{" + inner + "}"


def _merge_labels(labels: Dict[str, Any], extra: Dict[str, Any]) -> str:
    merged = dict(labels)
    merged.update(extra)
    return _labels_str(merged)


class _Doc:
    """Accumulates families: one # HELP/# TYPE header per family, then
    its sample lines.  Counter/gauge samples are sorted for
    determinism; histogram samples keep append order — their buckets
    MUST stay in increasing ``le`` order (lexical sort would put
    ``+Inf`` first and ``le="1"`` after ``le="0.1"``), and the callers
    already append label groups in sorted order."""

    def __init__(self) -> None:
        self.families: Dict[str, Tuple[str, str, List[str]]] = {}
        self.order: List[str] = []

    def family(self, name: str, typ: str, help_: str) -> List[str]:
        fam = self.families.get(name)
        if fam is None:
            fam = self.families[name] = (typ, help_, [])
            self.order.append(name)
        return fam[2]

    def render(self) -> List[str]:
        out: List[str] = []
        for name in self.order:
            typ, help_, samples = self.families[name]
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {typ}")
            out.extend(samples if typ == "histogram"
                       else sorted(samples))
        return out


def render_registry(reg: _metrics.Registry,
                    prefix: str = "jepsen_") -> List[str]:
    """The live registry as exposition lines: counters (``_total``),
    gauges, and histograms (cumulative ``_bucket`` + ``_sum`` +
    ``_count``)."""
    snap = reg.snapshot()
    doc = _Doc()
    for c in sorted(snap["counters"],
                    key=lambda c: (c["name"], str(sorted(
                        c["labels"].items(), key=str)))):
        name = metric_name(c["name"], prefix)
        if not name.endswith("_total"):
            name += "_total"
        doc.family(name, "counter", f"jepsen-tpu counter {c['name']}") \
            .append(f"{name}{_labels_str(c['labels'])} "
                    f"{_fmt_value(c['value'])}")
    for g in sorted(snap["gauges"],
                    key=lambda g: (g["name"], str(sorted(
                        g["labels"].items(), key=str)))):
        if g["value"] is None:
            continue
        name = metric_name(g["name"], prefix)
        doc.family(name, "gauge", f"jepsen-tpu gauge {g['name']}") \
            .append(f"{name}{_labels_str(g['labels'])} "
                    f"{_fmt_value(g['value'])}")
    for h in sorted(snap["histograms"],
                    key=lambda h: (h["name"], str(sorted(
                        h["labels"].items(), key=str)))):
        name = metric_name(h["name"], prefix)
        samples = doc.family(name, "histogram",
                             f"jepsen-tpu histogram {h['name']}")
        cum = 0
        bounds = h.get("buckets") or []
        counts = h.get("counts") or []
        for b, n in zip(bounds, counts):
            cum += n
            le = "+Inf" if b == "+inf" else _fmt_value(b)
            samples.append(
                f"{name}_bucket{_merge_labels(h['labels'], {'le': le})}"
                f" {cum}")
        # the snapshot's trailing implicit +inf bucket (buckets list
        # carries finite bounds + "+inf"; counts is one longer than
        # the finite bounds)
        if len(counts) == len(bounds):
            pass  # +inf already emitted above
        elif len(counts) == len(bounds) + 1:
            cum += counts[-1]
            samples.append(
                f"{name}_bucket"
                f"{_merge_labels(h['labels'], {'le': '+Inf'})} {cum}")
        samples.append(f"{name}_sum{_labels_str(h['labels'])} "
                       f"{_fmt_value(h['sum'])}")
        samples.append(f"{name}_count{_labels_str(h['labels'])} "
                       f"{h['count']}")
    return doc.render()


def render_heartbeats(base: str,
                      now: Optional[float] = None) -> List[str]:
    """Campaign heartbeat freshness gauges from every
    ``<store>/campaigns/*.live.json``: age since last update, done/
    total progress, in-flight worker count, finished flag."""
    cdir = os.path.join(base, "campaigns")
    if not os.path.isdir(cdir):
        return []
    now = time.time() if now is None else now
    doc = _Doc()
    for fn in sorted(os.listdir(cdir)):
        if not fn.endswith(".live.json"):
            continue
        try:
            with open(os.path.join(cdir, fn)) as f:
                hb = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(hb, dict):
            continue
        name = fn[:-len(".live.json")]
        lbl = _labels_str({"campaign": hb.get("campaign") or name})
        upd = hb.get("updated")
        if isinstance(upd, (int, float)):
            doc.family("jepsen_campaign_heartbeat_age_seconds", "gauge",
                       "seconds since the campaign heartbeat was "
                       "last written").append(
                "jepsen_campaign_heartbeat_age_seconds"
                f"{lbl} {_fmt_value(max(0.0, round(now - upd, 3)))}")
        doc.family("jepsen_campaign_runs_total_planned", "gauge",
                   "total runs in the campaign plan").append(
            f"jepsen_campaign_runs_total_planned{lbl} "
            f"{_fmt_value(hb.get('total') or 0)}")
        doc.family("jepsen_campaign_runs_done", "gauge",
                   "campaign runs completed").append(
            f"jepsen_campaign_runs_done{lbl} "
            f"{_fmt_value(hb.get('done') or 0)}")
        doc.family("jepsen_campaign_workers_in_flight", "gauge",
                   "campaign worker slots currently holding a run"
                   ).append(
            f"jepsen_campaign_workers_in_flight{lbl} "
            f"{len(hb.get('workers') or {})}")
        doc.family("jepsen_campaign_finished", "gauge",
                   "1 once the campaign scheduler closed its heartbeat"
                   ).append(
            f"jepsen_campaign_finished{lbl} "
            f"{1 if hb.get('finished') else 0}")
    return doc.render()


def render_warehouse(wh: Any) -> List[str]:
    """Warehouse rollup gauges: store runs by verdict, per-campaign
    latest verdict counts, and the bench throughput series."""
    doc = _Doc()
    try:
        roll = wh.rollups()
    except Exception:  # noqa: BLE001 — rollups are best-effort
        return []
    for verdict, n in sorted((roll.get("runs_by_verdict") or {}).items()):
        doc.family("jepsen_warehouse_runs", "gauge",
                   "ingested store runs by verdict").append(
            f"jepsen_warehouse_runs{_labels_str({'valid': verdict})} {n}")
    for camp, counts in sorted((roll.get("campaigns") or {}).items()):
        for verdict in ("true", "false", "unknown"):
            doc.family("jepsen_warehouse_campaign_runs", "gauge",
                       "latest campaign verdict counts").append(
                "jepsen_warehouse_campaign_runs"
                f"{_labels_str({'campaign': camp, 'valid': verdict})} "
                f"{counts.get(verdict, 0)}")
    for state, n in sorted((roll.get("verifier_by_state") or {}).items()):
        doc.family("jepsen_warehouse_verifier_sessions", "gauge",
                   "ingested verifier sessions by state").append(
            "jepsen_warehouse_verifier_sessions"
            f"{_labels_str({'state': state})} {n}")
    for row in roll.get("bench") or []:
        if not isinstance(row.get("value"), (int, float)):
            continue
        doc.family("jepsen_warehouse_bench_ops_per_sec", "gauge",
                   "bench check throughput by source").append(
            "jepsen_warehouse_bench_ops_per_sec"
            f"{_labels_str({'source': row.get('source'), 'n_txns': row.get('n_txns'), 'backend': row.get('backend')})} "
            f"{_fmt_value(row['value'])}")
    return doc.render()


def render_alerts(base: str,
                  now: Optional[float] = None) -> List[str]:
    """The watchtower's alert state (ISSUE 20) as the Prometheus
    convention's literal ``ALERTS`` gauge family — NO ``jepsen_``
    prefix, exactly the series an Alertmanager-era scraper expects:
    ``ALERTS{alertname=...,severity=...,state="pending"|"firing"} 1``.

    Replayed from the store's ``alerts.jsonl`` journal, read-only (a
    reader never heals the journal).  Cardinality is bounded by
    construction: a series exists ONLY while its rule is pending or
    firing and retires the moment it resolves — the same discipline as
    the fleet host series retiring with worker liveness."""
    from . import alerts as alerts_mod

    path = alerts_mod.alerts_path(base)
    if not os.path.exists(path):
        return []
    try:
        journal = alerts_mod.AlertJournal(path)
        active = journal.active()
    except Exception:  # noqa: BLE001 — alerts are best-effort
        return []
    if not active:
        return []
    doc = _Doc()
    fam = doc.family("ALERTS", "gauge",
                     "active watchtower alert rules by state")
    for a in active:
        fam.append(
            "ALERTS" + _labels_str({
                "alertname": a["rule"],
                "severity": a.get("severity") or "warn",
                "state": a.get("state")}) + " 1")
    return doc.render()


def render_fleet(fleet: Any) -> List[str]:
    """Metrics federation (ISSUE 14 tentpole b): the fleet
    coordinator's view of every ALIVE worker's last pushed metrics
    snapshot, as ``jepsen_fleet_host_*`` series with a ``host=`` label
    (one scrape of the coordinator sees the whole fleet) plus
    ``jepsen_fleet_rollup_*`` sums across hosts.  Cardinality is
    bounded by construction: the coordinator caps rows per worker, and
    a worker's series RETIRE with its liveness — expired workers
    simply stop being rendered (the same discipline as PR 13's
    per-session gauge retirement)."""
    doc = _Doc()
    try:
        fed = fleet.federated_metrics()
    except Exception:  # noqa: BLE001 — federation is best-effort
        return []
    doc.family("jepsen_fleet_fed_workers_reporting", "gauge",
               "alive workers whose metrics snapshot is being "
               "federated").append(
        f"jepsen_fleet_fed_workers_reporting {len(fed)}")
    # rolling-upgrade visibility (ISSUE 17 satellite): one info series
    # per ALIVE versioned worker.  Cardinality is pinned the same way
    # as every host_* series — the set retires with worker liveness,
    # so an upgrade churning through worker names keeps the scrape
    # flat instead of accreting dead versions.
    for w in sorted(fed):
        ver = fed[w].get("version")
        if ver:
            doc.family("jepsen_fleet_host_info", "gauge",
                       "alive fleet workers by stamped version"
                       ).append(
                "jepsen_fleet_host_info"
                f"{_labels_str({'host': w, 'version': ver})} 1")
    rollup: Dict[Tuple[str, str, str], float] = {}
    for w in sorted(fed):
        for r in fed[w].get("rows") or []:
            raw = str(r.get("name") or "")
            kind = "counter" if r.get("kind") == "counter" else "gauge"
            try:
                v = float(r.get("value"))
            except (TypeError, ValueError):
                continue
            name = metric_name(raw, "jepsen_fleet_host_")
            if kind == "counter" and not name.endswith("_total"):
                name += "_total"
            labels = dict(r.get("labels") or {})
            labels["host"] = w
            doc.family(name, kind,
                       f"fleet-federated worker {kind} {raw}").append(
                f"{name}{_labels_str(labels)} {_fmt_value(v)}")
            key = (raw, kind, json.dumps(r.get("labels") or {},
                                         sort_keys=True))
            rollup[key] = rollup.get(key, 0.0) + v
    for (raw, kind, lbl) in sorted(rollup):
        name = metric_name(raw, "jepsen_fleet_rollup_")
        if kind == "counter" and not name.endswith("_total"):
            name += "_total"
        doc.family(name, kind,
                   f"fleet rollup (sum over alive hosts) of {raw}"
                   ).append(
            f"{name}{_labels_str(json.loads(lbl))} "
            f"{_fmt_value(rollup[(raw, kind, lbl)])}")
    return doc.render()


def exposition(base: Optional[str] = None,
               registry: Optional[_metrics.Registry] = None,
               now: Optional[float] = None,
               fleet: Any = None) -> str:
    """The full ``/metrics`` document: live registry + federated fleet
    worker series (when a coordinator is attached) + campaign
    heartbeats + warehouse rollups (each section present only when its
    source exists).  Always ends with a newline."""
    from . import registry as active_registry

    reg = registry if registry is not None else active_registry()
    lines = render_registry(reg)
    if fleet is not None:
        lines += render_fleet(fleet)
    if base:
        lines += render_heartbeats(base, now=now)
        lines += render_alerts(base, now=now)
        try:
            from . import warehouse as wmod

            wh = wmod.open_if_exists(base)
        except Exception:  # noqa: BLE001
            wh = None
        if wh is not None:
            lines += render_warehouse(wh)
    return "\n".join(lines) + "\n"
