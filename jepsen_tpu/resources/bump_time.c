/* Clock-fault helper, compiled on each db node by the clock nemesis.
 *
 * Same capability as the reference's resources/bump-time.c
 * (jepsen/nemesis/time.clj compiles it with cc on the node, SURVEY.md
 * §2.5 item 5): jump the system clock by a signed millisecond offset, or
 * strobe it back and forth between +delta and 0 for a duration.
 *
 *   bump_time bump <ms>                      jump clock by <ms>
 *   bump_time strobe <delta_ms> <period_ms> <duration_ms>
 *                                            oscillate for duration
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/time.h>
#include <time.h>
#include <unistd.h>

static int bump(long long ms) {
  struct timeval tv;
  if (gettimeofday(&tv, NULL)) { perror("gettimeofday"); return 1; }
  long long usec = (long long)tv.tv_sec * 1000000LL + tv.tv_usec
                   + ms * 1000LL;
  tv.tv_sec  = usec / 1000000LL;
  tv.tv_usec = usec % 1000000LL;
  if (settimeofday(&tv, NULL)) { perror("settimeofday"); return 1; }
  return 0;
}

static long long now_ms(void) {
  struct timeval tv;
  gettimeofday(&tv, NULL);
  return (long long)tv.tv_sec * 1000LL + tv.tv_usec / 1000LL;
}

static int strobe(long long delta_ms, long long period_ms,
                  long long duration_ms) {
  long long end = now_ms() + duration_ms;
  int up = 0;
  while (now_ms() < end) {
    if (bump(up ? -delta_ms : delta_ms)) return 1;
    up = !up;
    usleep((useconds_t)(period_ms * 1000LL));
  }
  if (up && bump(-delta_ms)) return 1; /* leave the clock where it began */
  return 0;
}

int main(int argc, char **argv) {
  if (argc >= 3 && !strcmp(argv[1], "bump"))
    return bump(atoll(argv[2]));
  if (argc >= 5 && !strcmp(argv[1], "strobe"))
    return strobe(atoll(argv[2]), atoll(argv[3]), atoll(argv[4]));
  fprintf(stderr,
          "usage: %s bump <ms> | strobe <delta_ms> <period_ms> <dur_ms>\n",
          argv[0]);
  return 2;
}
