"""Set workload: add elements, then read them back.

Equivalent of the reference's set workloads (SURVEY.md §2.6, built-in
`checker/set` and `set-full`): clients add unique integers; a final read
(or interleaved reads, for set-full's stale-window analysis) returns the
set.  Lost adds ⇒ invalid.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Optional

from ..checkers import api as checker_api
from ..generator import core as g


class _AddGen:
    def __init__(self):
        self.counter = itertools.count()

    def __call__(self, test, ctx):
        return {"f": "add", "value": next(self.counter)}


def gen(*, reads: bool = False, read_frac: float = 0.1,
        rng: Optional[random.Random] = None) -> Any:
    """Adds of unique ints; with `reads`, interleaved set reads (the
    set-full shape)."""
    adds = _AddGen()
    if not reads:
        return adds
    rng = rng or random.Random()

    def mixed(test, ctx):
        if rng.random() < read_frac:
            return {"f": "read", "value": None}
        return adds(test, ctx)

    return mixed


def final_read() -> Any:
    """The final-generator: one read per thread once clients go quiet
    (reference :final-generator with until-ok semantics)."""
    return g.clients(g.each_thread(g.until_ok({"f": "read", "value": None})))


def workload(*, full: bool = False,
             rng: Optional[random.Random] = None) -> dict:
    """`full=False`: add-then-final-read with `checker/set`.
    `full=True`: interleaved reads with `set-full` stale-window analysis."""
    return {
        "generator": gen(reads=full, rng=rng),
        "final-generator": final_read(),
        "checker": (checker_api.SetFullChecker() if full
                    else checker_api.SetChecker()),
        "workload-kind": "set",
    }
