"""Long-fork workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/long_fork.clj`
(SURVEY.md §2.6): writers insert distinct values into distinct keys (one
write per txn); readers read a whole key group in one txn.  Under snapshot
isolation all reads must observe the writes in a single order; a **long
fork** is two reads that order two writes oppositely:

    read A sees w1 but not w2;  read B sees w2 but not w1.
"""

from __future__ import annotations

import random
from typing import Any, List, Optional

from ..checkers import api as checker_api


class _LongForkGen:
    """Writes cycle through key groups; each key is written at most once
    (value = a global counter), reads cover one whole group."""

    def __init__(self, *, group_size: int = 3, read_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.n = group_size
        self.read_frac = read_frac
        self.rng = rng or random.Random()
        self.next_write = 0

    def _group_of(self, k: int) -> List[int]:
        g = k // self.n
        return list(range(g * self.n, (g + 1) * self.n))

    def __call__(self, test, ctx):
        if self.rng.random() < self.read_frac and self.next_write > 0:
            k = self.rng.randrange(self.next_write)
            return {"f": "txn",
                    "value": [("r", k2, None) for k2 in self._group_of(k)]}
        k = self.next_write
        self.next_write += 1
        return {"f": "txn", "value": [("w", k, k)]}


def gen(**opts) -> Any:
    return _LongForkGen(**opts)


class LongForkChecker(checker_api.Checker):
    """Finds long-fork read pairs (reference `long-fork/checker`),
    delegated to the vectorized predicate checker
    (`checkers/invariants/predicate.py`): group reads become boolean
    observed/absent matrices and fork pairs fall out of a handful of
    matrix reductions (device path guarded by `resilience.device_call`,
    exact host twin), then the elle graph machinery confirms each fork
    as a G-nonadjacent / G2-item cycle with per-edge evidence."""

    def name(self) -> str:
        return "long-fork"

    def check(self, test, history, opts=None):
        from ..checkers.invariants import predicate

        res = predicate.check(history,
                              deadline=(opts or {}).get("deadline"))
        if res.get("valid?") != "unknown" and not res.get("read-count"):
            return {"valid?": "unknown", "read-count": 0}
        # legacy keys the workload tests / perf plots consume
        res["long-forks"] = [
            {"reads": e["reads"], "keys": e["keys"]}
            for e in res.get("anomalies", {}).get("long-fork", ())]
        return res


def workload(*, group_size: int = 3,
             rng: Optional[random.Random] = None) -> dict:
    return {
        "generator": gen(group_size=group_size, rng=rng),
        "checker": LongForkChecker(),
        "workload-kind": "long-fork",
    }
