"""Long-fork workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/long_fork.clj`
(SURVEY.md §2.6): writers insert distinct values into distinct keys (one
write per txn); readers read a whole key group in one txn.  Under snapshot
isolation all reads must observe the writes in a single order; a **long
fork** is two reads that order two writes oppositely:

    read A sees w1 but not w2;  read B sees w2 but not w1.
"""

from __future__ import annotations

import random
from itertools import combinations
from typing import Any, Dict, List, Optional, Tuple

from ..checkers import api as checker_api
from ..history.ops import OK


class _LongForkGen:
    """Writes cycle through key groups; each key is written at most once
    (value = a global counter), reads cover one whole group."""

    def __init__(self, *, group_size: int = 3, read_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.n = group_size
        self.read_frac = read_frac
        self.rng = rng or random.Random()
        self.next_write = 0

    def _group_of(self, k: int) -> List[int]:
        g = k // self.n
        return list(range(g * self.n, (g + 1) * self.n))

    def __call__(self, test, ctx):
        if self.rng.random() < self.read_frac and self.next_write > 0:
            k = self.rng.randrange(self.next_write)
            return {"f": "txn",
                    "value": [("r", k2, None) for k2 in self._group_of(k)]}
        k = self.next_write
        self.next_write += 1
        return {"f": "txn", "value": [("w", k, k)]}


def gen(**opts) -> Any:
    return _LongForkGen(**opts)


class LongForkChecker(checker_api.Checker):
    """Finds long-fork read pairs (reference `long-fork/checker`).

    For each pair of committed group reads over the same keys, and each
    pair of written keys (k1, k2) both covered: if read A has k1 written
    and k2 missing while read B has k2 written and k1 missing, the two
    reads disagree on the write order — G2 long fork."""

    def check(self, test, history, opts=None):
        reads: List[Any] = []
        for op in history:
            if op.type != OK or op.f != "txn":
                continue
            mops = op.value or []
            if mops and all(m[0] == "r" for m in mops):
                reads.append(op)
        if not reads:
            return {"valid?": "unknown", "read-count": 0}
        forks = []
        # Bucket reads by their key set first: reads over different key
        # groups can never witness a fork together, so pairing is
        # O(sum per-group n^2), not O(total-reads^2).
        buckets: Dict[frozenset, List[int]] = {}
        obs = [{m[1]: m[2] for m in op.value} for op in reads]
        for i, o in enumerate(obs):
            buckets.setdefault(frozenset(o), []).append(i)
        pairs = (p for idxs in buckets.values()
                 for p in combinations(idxs, 2))
        for ia, ib in pairs:
            a, b = reads[ia], reads[ib]
            shared = [k for k in obs[ia] if k in obs[ib]]
            for k1, k2 in combinations(shared, 2):
                a1, a2 = obs[ia][k1], obs[ia][k2]
                b1, b2 = obs[ib][k1], obs[ib][k2]
                if a1 is not None and a2 is None \
                        and b1 is None and b2 is not None:
                    forks.append({"reads": [a.index, b.index],
                                  "keys": [k1, k2]})
                elif a1 is None and a2 is not None \
                        and b1 is not None and b2 is None:
                    forks.append({"reads": [a.index, b.index],
                                  "keys": [k2, k1]})
        return {
            "valid?": not forks,
            "read-count": len(reads),
            "long-forks": forks[:8],
            "fork-count": len(forks),
        }


def workload(*, group_size: int = 3,
             rng: Optional[random.Random] = None) -> dict:
    return {
        "generator": gen(group_size=group_size, rng=rng),
        "checker": LongForkChecker(),
    }
