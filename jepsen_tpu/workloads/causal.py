"""Causal-consistency workload.

Equivalent of the reference's `jepsen/tests/causal.clj` (SURVEY.md §2.6,
(L)): register operations whose checker verifies *causal* consistency —
session guarantees (monotonic reads, read-your-writes) plus causal
write ordering — rather than serializability.

TPU-first shape: operations are read-modify-write transactions
(``[("r", k, None), ("w", k, v)]``) and plain reads, so causality is
visible to dependency inference (an rmw's read pins its write's
predecessor version — `elle/rw_register.clj`'s read-then-write source).
A session violation (e.g. a process reading version 2 then version 1)
then shows up as a cycle over {ww, wr, process} edges, optionally with
anti-dependency edges for monotonic-read breaks, and is checked on the
same device pipeline as the wr workload:

- causal write cycles  -> G0-process / G1c-process (causal-cerone's
  prohibited anomalies in the consistency lattice)
- monotonic-read / read-your-writes breaks -> G-single-process
  (explicitly requested — session anomalies are causal violations even
  though the lattice maps them to snapshot-family models)
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ..checkers import api as checker_api

#: anomalies that break causal consistency: causal write cycles per the
#: lattice, plus single-anti-dependency session cycles (monotonic reads)
CAUSAL_ANOMALIES = ("G-single-process",)


class _CausalGen:
    def __init__(self, *, key_count: int = 4, rmw_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.key_count = key_count
        self.rmw_frac = rmw_frac
        self.next_val: Dict[int, int] = {}

    def __call__(self, test, ctx):
        k = self.rng.randrange(self.key_count)
        if self.rng.random() < self.rmw_frac:
            v = self.next_val.get(k, 0)
            self.next_val[k] = v + 1
            value = [("r", k, None), ("w", k, v)]
        else:
            value = [("r", k, None)]
        return {"f": "txn", "value": value}


def gen(**opts) -> Any:
    return _CausalGen(**opts)


class CausalChecker(checker_api.Checker):
    """Causal-consistency verdict over an rw-register-shaped history."""

    def check(self, test, history, opts=None):
        from ..checkers.elle import rw_register, viz  # defers jax init

        res = rw_register.check(
            history, consistency_models=("causal-cerone",),
            anomalies=CAUSAL_ANOMALIES)
        # session anomalies invalidate causal even when the lattice
        # boundary alone wouldn't reject causal-cerone
        session_bad = [a for a in res["anomaly-types"]
                       if a in CAUSAL_ANOMALIES]
        if session_bad and res["valid?"] is True:
            res["valid?"] = False
            res.setdefault("not", []).append("causal-cerone")
        if res["valid?"] is False:
            viz.viz_for_test(res, test, history=history)
        return res


def checker() -> checker_api.Checker:
    return CausalChecker()


def workload(**opts) -> Dict[str, Any]:
    """{generator, checker} bundle, reference workload-map shape."""
    return {"generator": gen(**opts), "checker": checker()}
