"""Bank workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/bank.clj`
(SURVEY.md §2.6): concurrent transfers between accounts plus whole-state
reads; under snapshot isolation the total balance must be invariant, and
read skew shows up as reads whose balances don't sum to the expected total.
Negative balances are flagged unless the test allows them.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

import numpy as np

from ..checkers import api as checker_api
from ..history.ops import OK


class _BankGen:
    def __init__(self, *, accounts=(0, 1, 2, 3, 4, 5, 6, 7),
                 max_transfer: int = 5, read_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.accounts = list(accounts)
        self.max_transfer = max_transfer
        self.read_frac = read_frac
        self.rng = rng or random.Random()

    def __call__(self, test, ctx):
        if self.rng.random() < self.read_frac:
            return {"f": "read", "value": None}
        frm, to = self.rng.sample(self.accounts, 2)
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": 1 + self.rng.randrange(self.max_transfer)}}


def gen(**opts) -> Any:
    return _BankGen(**opts)


class BankChecker(checker_api.Checker):
    """Total-balance invariant over all reads (vectorised: reads become a
    dense [n_reads, n_accounts] matrix; row sums and sign checks are one
    numpy pass — the same shape the device fold would use).

    Reference `bank/checker`: :bad-reads = reads with wrong total or
    (unless negative-balances?) any negative balance."""

    def __init__(self, *, negative_balances_ok: bool = False):
        self.negative_ok = negative_balances_ok

    def check(self, test, history, opts=None):
        total = test.get("total-amount")
        if total is None:
            accounts = test.get("accounts")
            if isinstance(accounts, dict) and accounts:
                total = sum(accounts.values())
        reads = [op for op in history
                 if op.type == OK and op.f == "read"
                 and isinstance(op.value, dict)]
        if not reads:
            return {"valid?": "unknown", "read-count": 0}
        accts = sorted({a for op in reads for a in op.value})
        mat = np.array([[op.value.get(a, 0) for a in accts] for op in reads],
                       dtype=np.int64)
        sums = mat.sum(axis=1)
        if total is None:
            # no configured total: use the modal sum, so a single
            # anomalous read can't become the baseline
            vals, counts = np.unique(sums, return_counts=True)
            total = int(vals[np.argmax(counts)])
        wrong_total = sums != total
        negative = (mat < 0).any(axis=1) if not self.negative_ok \
            else np.zeros(len(reads), dtype=bool)
        bad = wrong_total | negative
        bad_reads = [
            {"op-index": reads[i].index, "total": int(sums[i]),
             "expected-total": total,
             "negative": [accts[j] for j in np.nonzero(mat[i] < 0)[0]]}
            for i in np.nonzero(bad)[0][:8]
        ]
        return {
            "valid?": not bad.any(),
            "read-count": len(reads),
            "bad-read-count": int(bad.sum()),
            "bad-reads": bad_reads,
        }


def workload(*, n_accounts: int = 8, total: int = 80, max_transfer: int = 5,
             negative_balances_ok: bool = False,
             rng: Optional[random.Random] = None) -> dict:
    """Also returns the test-map keys the checker needs (accounts/total),
    like the reference workload's extra test keys."""
    accounts = {i: total // n_accounts for i in range(n_accounts)}
    return {
        "generator": gen(accounts=range(n_accounts),
                         max_transfer=max_transfer, rng=rng),
        "checker": BankChecker(negative_balances_ok=negative_balances_ok),
        "accounts": accounts,
        # derived from the actual initial balances, so a non-divisible
        # `total` can't make every read look invalid
        "total-amount": sum(accounts.values()),
        "workload-kind": "bank",
    }
