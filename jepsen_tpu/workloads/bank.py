"""Bank workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/bank.clj`
(SURVEY.md §2.6): concurrent transfers between accounts plus whole-state
reads; under snapshot isolation the total balance must be invariant, and
read skew shows up as reads whose balances don't sum to the expected total.
Negative balances are flagged unless the test allows them.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ..checkers import api as checker_api


class _BankGen:
    def __init__(self, *, accounts=(0, 1, 2, 3, 4, 5, 6, 7),
                 max_transfer: int = 5, read_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.accounts = list(accounts)
        self.max_transfer = max_transfer
        self.read_frac = read_frac
        self.rng = rng or random.Random()

    def __call__(self, test, ctx):
        if self.rng.random() < self.read_frac:
            return {"f": "read", "value": None}
        frm, to = self.rng.sample(self.accounts, 2)
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": 1 + self.rng.randrange(self.max_transfer)}}


def gen(**opts) -> Any:
    return _BankGen(**opts)


class BankChecker(checker_api.Checker):
    """Total-balance + snapshot-read invariants, delegated to the
    vectorized invariants family (`checkers/invariants/bank.py`): the
    reads become a dense [n_reads, n_accounts] matrix whose row sums /
    sign checks run as one array reduction — on the device path
    (guarded by `resilience.device_call`, host-numpy fallback) or the
    exact host twin.

    Reference `bank/checker`: :bad-reads = reads with wrong total or
    (unless negative-balances?) any negative balance."""

    def __init__(self, *, negative_balances_ok: bool = False):
        self.negative_ok = negative_balances_ok

    def name(self) -> str:
        return "bank"

    def check(self, test, history, opts=None):
        from ..checkers.invariants import bank as inv_bank

        return inv_bank.check(
            history, test,
            negative_balances_ok=self.negative_ok,
            deadline=(opts or {}).get("deadline"))


def workload(*, n_accounts: int = 8, total: int = 80, max_transfer: int = 5,
             negative_balances_ok: bool = False,
             rng: Optional[random.Random] = None) -> dict:
    """Also returns the test-map keys the checker needs (accounts/total),
    like the reference workload's extra test keys."""
    accounts = {i: total // n_accounts for i in range(n_accounts)}
    return {
        "generator": gen(accounts=range(n_accounts),
                         max_transfer=max_transfer, rng=rng),
        "checker": BankChecker(negative_balances_ok=negative_balances_ok),
        "accounts": accounts,
        # derived from the actual initial balances, so a non-divisible
        # `total` can't make every read look invalid
        "total-amount": sum(accounts.values()),
        "workload-kind": "bank",
    }
