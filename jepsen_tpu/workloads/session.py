"""Session-guarantee workload.

Register traffic shaped so the per-key version orders chain exactly
(every write is a read-modify-write), checked by the vectorized
session-guarantee checker (`checkers/invariants/session.py`):
monotonic reads / monotonic writes / read-your-writes /
writes-follow-reads as segmented array passes over the packed history,
device path guarded, DAG-walker fallback on branched histories.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ..checkers import api as checker_api


class _SessionGen:
    """Per-key rmw chains + plain reads (the causal workload's shape,
    biased toward rmw so chains grow)."""

    def __init__(self, *, key_count: int = 4, rmw_frac: float = 0.6,
                 rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.key_count = key_count
        self.rmw_frac = rmw_frac
        self.next_val = 0

    def __call__(self, test, ctx):
        k = self.rng.randrange(self.key_count)
        if self.rng.random() < self.rmw_frac:
            v = self.next_val
            self.next_val += 1
            return {"f": "txn", "value": [("r", k, None), ("w", k, v)]}
        return {"f": "txn", "value": [("r", k, None)]}


def gen(**opts) -> Any:
    return _SessionGen(**opts)


class SessionChecker(checker_api.Checker):
    def __init__(self, guarantees=None):
        self.guarantees = guarantees

    def name(self) -> str:
        return "session"

    def check(self, test, history, opts=None):
        from ..checkers.elle.sessions import GUARANTEES
        from ..checkers.invariants import session as inv_session

        return inv_session.check(
            history, guarantees=self.guarantees or GUARANTEES,
            deadline=(opts or {}).get("deadline"))


def workload(*, key_count: int = 4, rmw_frac: float = 0.6,
             rng: Optional[random.Random] = None) -> Dict[str, Any]:
    return {
        "generator": gen(key_count=key_count, rmw_frac=rmw_frac, rng=rng),
        "checker": SessionChecker(),
        "workload-kind": "session",
    }
