"""List-append workload — the Elle flagship.

Equivalent of the reference's `jepsen/src/jepsen/tests/cycle/append.clj` +
`elle.list-append/gen` (SURVEY.md §2.6): random transactions of
``("append", k, v)`` / ``("r", k, None)`` micro-ops over a rotating pool of
integer keys, with appends globally unique per key, checked by the
TPU-resident Elle list-append pipeline.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ..checkers import api as checker_api


class _TxnGen:
    """Stateful op factory (closed over by the fn-generator): rotates a
    window of active keys so per-key version chains stay bounded, and
    hands out unique append values per key — elle.list-append `gen`."""

    def __init__(self, *, key_count: int = 10, min_txn_length: int = 1,
                 max_txn_length: int = 4, max_writes_per_key: int = 32,
                 read_frac: float = 0.5, rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.key_count = key_count
        self.min_len = min_txn_length
        self.max_len = max_txn_length
        self.max_writes = max_writes_per_key
        self.read_frac = read_frac
        self.next_key = key_count
        self.active = list(range(key_count))
        self.writes: Dict[int, int] = {}

    def _mop(self):
        k = self.rng.choice(self.active)
        if self.rng.random() < self.read_frac:
            return ("r", k, None)
        v = self.writes.get(k, 0)
        self.writes[k] = v + 1
        if self.writes[k] >= self.max_writes:
            # retire the key, introduce a fresh one (elle's key rotation)
            self.active[self.active.index(k)] = self.next_key
            self.next_key += 1
        return ("append", k, v)

    def __call__(self, test, ctx):
        n = self.rng.randint(self.min_len, self.max_len)
        return {"f": "txn", "value": [self._mop() for _ in range(n)]}


def gen(**opts) -> Any:
    """An infinite list-append txn generator (lift-able op factory)."""
    return _TxnGen(**opts)


class AppendChecker(checker_api.Checker):
    """Adapts `elle.list_append.check` to the Checker protocol."""

    def __init__(self, consistency_models=("serializable",), anomalies=()):
        self.models = tuple(consistency_models)
        self.anomalies = tuple(anomalies)

    def check(self, test, history, opts=None):
        from ..checkers.elle import list_append, viz  # defers jax init
        from ..resilience import plan_for

        opts = opts or {}
        res = list_append.check(
            history,
            consistency_models=opts.get("consistency-models", self.models),
            anomalies=opts.get("anomalies", self.anomalies),
            # resilience plumbing: the shared checker deadline placed in
            # opts by check_safe, the run's fault plan, and an optional
            # retry-policy override from the test map
            deadline=opts.get("deadline"),
            policy=(test or {}).get("retry-policy"),
            plan=plan_for(test))
        if test and test.get("store-dir") is not None:
            viz.viz_for_test(res, test, history)
        return res

    def name(self):
        return "list-append"


def workload(*, key_count: int = 10, min_txn_length: int = 1,
             max_txn_length: int = 4, max_writes_per_key: int = 32,
             consistency_models=("serializable",), anomalies=(),
             rng: Optional[random.Random] = None) -> dict:
    """The workload map: {generator, checker} (+ client supplied by the
    db-specific suite, as in the reference)."""
    return {
        "generator": gen(key_count=key_count, min_txn_length=min_txn_length,
                         max_txn_length=max_txn_length,
                         max_writes_per_key=max_writes_per_key, rng=rng),
        "checker": AppendChecker(consistency_models, anomalies),
    }
