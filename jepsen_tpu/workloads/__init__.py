"""Workloads (L5, SURVEY.md §2.6): test suites the framework expresses."""
