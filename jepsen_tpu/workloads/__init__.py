"""Workloads (L5, SURVEY.md §2.6): test suites the framework expresses.

Each workload module exposes ``workload(**opts) -> dict`` with
``generator`` / ``checker`` (and optionally ``final-generator`` plus extra
test-map keys), mirroring the reference's `{:generator :client :checker
:final-generator}` workload maps.  Clients come from the db-specific suite
(or `jepsen_tpu.workloads.mem` for in-process runs).
"""

from . import append, bank, linearizable_register, long_fork, queue, sets, wr

__all__ = ["append", "bank", "linearizable_register", "long_fork",
           "queue", "sets", "wr"]
