"""Synthetic history generation with anomaly injection.

The analogue of the reference ecosystem's `jepsen-io/history.sim`
(SURVEY.md §4): generates complete histories from a simulated
strict-serializable database (overlapping invocations, serial commit
points), plus surgical anomaly injectors used to pin checker behavior and
to drive differential tests at scale.

Also provides `packed_la_history`, a fast vectorized generator that emits
`PackedTxns` arrays directly — the bench path for 10M-op histories, where
building Python Op objects would dominate runtime.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu.history.ops import FAIL, INFO, INVOKE, OK, History, Op
from jepsen_tpu.history.soa import (
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
)

# Bump when packed_la_history / packed_rw_history internals change in a
# way that alters output for the same kwargs — invalidates prestaged
# bench inputs (utils/prestage.py keys filenames on this).
PACKED_GEN_VERSION = 1


def la_history(n_txns: int = 100, n_keys: int = 5, concurrency: int = 5,
               max_mops: int = 4, read_prob: float = 0.5,
               fail_prob: float = 0.0, info_prob: float = 0.0,
               multi_append_prob: float = 0.1,
               seed: int = 0) -> History:
    """Simulate a strict-serializable list-append history.

    Each process runs txns one at a time; a txn's effects apply atomically at
    a commit point between its invoke and completion, so the result is
    always valid (strict-serializable) before any injector runs.
    """
    rng = np.random.default_rng(seed)
    db: Dict[int, List[int]] = {k: [] for k in range(n_keys)}
    append_log: Dict[int, List[int]] = {k: [] for k in range(n_keys)}
    next_val = 1
    ops: List[Op] = []
    open_txn: Dict[int, Tuple[List, int]] = {}  # process -> (mops, invoke idx)
    committed = 0
    t = 0

    def gen_mops():
        nonlocal next_val
        mops = []
        n = int(rng.integers(1, max_mops + 1))
        for _ in range(n):
            k = int(rng.integers(0, n_keys))
            if rng.random() < read_prob:
                mops.append(["r", k, None])
            else:
                mops.append(["append", k, next_val])
                next_val += 1
                if rng.random() < multi_append_prob:
                    mops.append(["append", k, next_val])
                    next_val += 1
        return mops

    while committed < n_txns or open_txn:
        p = int(rng.integers(0, concurrency))
        t += 1
        if p not in open_txn:
            if committed + len(open_txn) >= n_txns:
                # drain: complete somebody instead
                if not open_txn:
                    break
                p = list(open_txn.keys())[int(rng.integers(0, len(open_txn)))]
            else:
                mops = gen_mops()
                ops.append(Op(type=INVOKE, process=p, f="txn",
                              value=[list(m) for m in mops], time=t))
                open_txn[p] = (mops, len(ops) - 1)
                continue
        # complete p's open txn
        mops, _ = open_txn.pop(p)
        r = rng.random()
        if r < fail_prob:
            ops.append(Op(type=FAIL, process=p, f="txn",
                          value=[list(m) for m in mops], time=t))
        else:
            is_info = r < fail_prob + info_prob
            apply_writes = (not is_info) or rng.random() < 0.5
            filled = []
            state_snapshot = {k: list(v) for k, v in db.items()} \
                if not apply_writes else db
            target = db if apply_writes else state_snapshot
            for m in mops:
                if m[0] == "append":
                    target[m[1]].append(m[2])
                    if apply_writes:
                        append_log[m[1]].append(m[2])
                    filled.append(["append", m[1], m[2]])
                else:
                    filled.append(["r", m[1], list(target[m[1]])])
            if is_info:
                ops.append(Op(type=INFO, process=p, f="txn",
                              value=[list(m) for m in mops], time=t))
            else:
                ops.append(Op(type=OK, process=p, f="txn", value=filled, time=t))
        committed += 1
    return History(ops)


# ---------------------------------------------------------------------------
# Anomaly injectors: surgical edits on a valid history.
# ---------------------------------------------------------------------------


def _ok_txns(h: History):
    return [op for op in h.ops if op.type == OK and op.f == "txn"]


def _appends(op: Op):
    return [(i, m) for i, m in enumerate(op.value or []) if m[0] == "append"]


def _reads(op: Op):
    return [(i, m) for i, m in enumerate(op.value or [])
            if m[0] == "r" and m[2] is not None]


def inject_g1a(h: History, rng=None) -> bool:
    """Flip an observed writer ok->fail: its reads become aborted reads."""
    observed = set()
    for op in _ok_txns(h):
        for _, m in _reads(op):
            observed.update(m[2])
    for op in _ok_txns(h):
        vals = [m[2] for _, m in _appends(op)]
        if any(v in observed for v in vals):
            op.type = FAIL
            return True
    return False


def inject_g1b(h: History) -> bool:
    """Truncate a read so it ends at an intermediate (non-final) append."""
    # find a txn appending twice to one key
    for wop in _ok_txns(h):
        per_key: Dict[int, List[int]] = {}
        for _, m in _appends(wop):
            per_key.setdefault(m[1], []).append(m[2])
        for k, vs in per_key.items():
            if len(vs) < 2:
                continue
            inter = vs[0]
            for rop in _ok_txns(h):
                if rop is wop:
                    continue
                for _, m in _reads(rop):
                    if m[1] == k and inter in m[2] and m[2][-1] != inter:
                        # truncating keeps this read a prefix of longer reads,
                        # so the only injected anomaly is the G1b itself
                        m[2][:] = m[2][: m[2].index(inter) + 1]
                        return True
    return False


def _touched_keys(op: Op):
    return {m[1] for m in (op.value or [])}


def inject_wr_cycle(h: History) -> bool:
    """Create a pure wr cycle (G1c): T1 reads T2's append, T2 reads T1's."""
    oks = _ok_txns(h)
    # find two txns each having an append, in different keys
    cand = [(op, _appends(op)[0][1]) for op in oks if _appends(op)]
    for i in range(len(cand)):
        for j in range(i + 1, len(cand)):
            (t1, m1), (t2, m2) = cand[i], cand[j]
            k1, v1 = m1[1], m1[2]
            k2, v2 = m2[1], m2[2]
            # keys must be disjoint from the other txn's touched keys, or the
            # appended read would break the txn's own internal consistency
            if k1 == k2 or k2 in _touched_keys(t1) or k1 in _touched_keys(t2):
                continue
            p1 = _prefix_through(h, k1, v1)
            p2 = _prefix_through(h, k2, v2)
            if p1 is None or p2 is None:
                continue
            t1.value.append(["r", k2, p2])
            t2.value.append(["r", k1, p1])
            return True
    return False


def inject_rw_cycle(h: History) -> bool:
    """Create a write-skew-style cycle of two rw edges (G2-item).

    T1 reads key k1 missing T2's later append; T2 reads key k2 missing T1's
    append: rw edges T1->T2 and T2->T1.
    """
    oks = _ok_txns(h)
    cand = [(op, _appends(op)[0][1]) for op in oks if _appends(op)]
    for i in range(len(cand)):
        for j in range(i + 1, len(cand)):
            (t1, m1), (t2, m2) = cand[i], cand[j]
            k1, v1 = m1[1], m1[2]
            k2, v2 = m2[1], m2[2]
            if k1 == k2 or k2 in _touched_keys(t1) or k1 in _touched_keys(t2):
                continue
            p1 = _prefix_before(h, k1, v1)
            p2 = _prefix_before(h, k2, v2)
            if p1 is None or p2 is None:
                continue
            t1.value.append(["r", k2, p2])  # T1 misses v2 -> rw T1->T2
            t2.value.append(["r", k1, p1])  # T2 misses v1 -> rw T2->T1
            return True
    return False


def _key_order(h: History, k: int) -> List[int]:
    longest: List[int] = []
    for op in _ok_txns(h):
        for _, m in _reads(op):
            if m[1] == k and len(m[2]) > len(longest):
                longest = list(m[2])
    return longest


def _prefix_through(h: History, k: int, v: int) -> Optional[List[int]]:
    order = _key_order(h, k)
    if v in order:
        return order[: order.index(v) + 1]
    # v unobserved: extend the longest observed order with v (stays compatible
    # only if v was appended after everything observed — best effort)
    return None


def _prefix_before(h: History, k: int, v: int) -> Optional[List[int]]:
    order = _key_order(h, k)
    if v in order:
        return order[: order.index(v)]
    return None


# ---------------------------------------------------------------------------
# Fast vectorized packed-history generator (bench path).
# ---------------------------------------------------------------------------


def packed_la_history(n_txns: int, n_keys: int, concurrency: int = 10,
                      mops_per_txn: int = 4, read_frac: float = 0.5,
                      seed: int = 0) -> PackedTxns:
    """Vectorized strict-serializable list-append history as PackedTxns.

    Commit order == txn index.  Each txn has `mops_per_txn` mops; reads
    observe the full committed prefix of their key at commit time.  All txns
    ok.  Runs in O(n) numpy; used for 10M-op benchmarking where Python-object
    histories are too slow to build.
    """
    rng = np.random.default_rng(seed)
    T = n_txns
    M = T * mops_per_txn
    mop_txn = np.repeat(np.arange(T, dtype=np.int32), mops_per_txn)
    is_read = rng.random(M) < read_frac
    mop_kind = np.where(is_read, MOP_READ, MOP_APPEND).astype(np.int8)
    mop_key = rng.integers(0, n_keys, M).astype(np.int32)

    # Appends: assign global value ids in commit order per key -> the version
    # order of key k is exactly the sequence of append val-ids with key k.
    n_app = int((~is_read).sum())
    app_idx = np.nonzero(~is_read)[0]
    mop_val = np.full(M, -1, dtype=np.int32)
    mop_val[app_idx] = np.arange(n_app, dtype=np.int32)

    # Position of each append within its key's order (0-based).
    app_keys = mop_key[app_idx]
    order = np.argsort(app_keys, kind="stable")
    ranks = np.empty(n_app, dtype=np.int64)
    sorted_keys = app_keys[order]
    # rank within key = position - first position of that key
    first = np.searchsorted(sorted_keys, sorted_keys)
    ranks[order] = np.arange(n_app) - first
    app_rank = ranks  # per append, its version position in its key

    # For reads: number of appends to key k committed strictly before txn t,
    # by any txn with index < t, plus own txn's earlier appends in mop order.
    # Build per-key cumulative append counts by mop position.
    app_flag = (~is_read).astype(np.int64)
    # cumulative appends per key up to (and excluding) each mop, computed via
    # sorting mops by (key, position)
    mop_order = np.lexsort((np.arange(M), mop_key))
    k_sorted = mop_key[mop_order]
    a_sorted = app_flag[mop_order]
    key_start = np.searchsorted(k_sorted, k_sorted)
    base = np.cumsum(a_sorted) - a_sorted  # appends before this mop in key run
    run_base = base[key_start]
    before_in_key = base - run_base
    read_len_sorted = before_in_key  # appends to this key before this mop
    read_len = np.empty(M, dtype=np.int64)
    read_len[mop_order] = read_len_sorted
    # NOTE: this counts appends by *mop order across all txns*, which equals
    # commit-time visibility because commit order == txn order and mop order
    # is txn-major.  Reads therefore see every append with a smaller global
    # mop index and same key — including own-txn earlier appends.  This is a
    # serial execution, hence valid.

    rd_len = np.where(is_read, read_len, -1).astype(np.int32)
    rd_start = np.full(M, -1, dtype=np.int32)
    read_ids = np.nonzero(is_read)[0]
    lens = rd_len[read_ids].astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]]) if len(lens) else \
        np.zeros(0, dtype=np.int64)
    rd_start[read_ids] = starts
    R = int(lens.sum()) if len(lens) else 0

    # read elements: for read mop r of key k with length L, the first L
    # appends (val ids) of key k in global order.
    # Per-key sorted append val ids:
    app_vals_sorted = mop_val[app_idx][order]  # grouped by key, in order
    key_first_app = np.searchsorted(sorted_keys, np.arange(n_keys))
    rd_elems = np.empty(R, dtype=np.int32)
    if R:
        # for each read, fill slice from app_vals_sorted[key_first: key_first+L]
        rk = mop_key[read_ids].astype(np.int64)
        # expand: element j of read i is app_vals_sorted[key_first_app[rk[i]]+j]
        reps = np.repeat(np.arange(len(read_ids)), lens)
        offs = np.arange(R) - np.repeat(starts, lens)
        rd_elems[:] = app_vals_sorted[key_first_app[rk[reps]] + offs]

    txn_process = (np.arange(T, dtype=np.int32) % concurrency)
    # invoke/complete positions: serial commit at position 2t+1 with overlap:
    # invoke at 2t, complete at 2t+1 (fully serial; realtime edges dense but
    # the barrier construction keeps them O(n)).
    txn_invoke_pos = (2 * np.arange(T, dtype=np.int32))
    txn_complete_pos = txn_invoke_pos + 1

    key_names = list(range(n_keys))
    # val id -> (key, value) ; value == global append id
    val_keys = np.empty(n_app, dtype=np.int64)
    val_keys[mop_val[app_idx]] = app_keys
    val_names = [(int(val_keys[v]), int(v)) for v in range(n_app)]

    return PackedTxns(
        txn_type=np.full(T, TXN_OK, dtype=np.int8),
        txn_process=txn_process,
        txn_invoke_pos=txn_invoke_pos,
        txn_complete_pos=txn_complete_pos,
        txn_orig_index=np.arange(T, dtype=np.int32) * 2 + 1,
        mop_txn=mop_txn,
        mop_kind=mop_kind,
        mop_key=mop_key,
        mop_val=mop_val,
        mop_rd_start=rd_start,
        mop_rd_len=rd_len,
        rd_elems=rd_elems,
        key_names=key_names,
        val_names=val_names,
        n_events=2 * T,
    )


def packed_rw_history(n_txns: int, n_keys: int, concurrency: int = 10,
                      mops_per_txn: int = 3, read_frac: float = 0.5,
                      seed: int = 0) -> PackedTxns:
    """Vectorized strict-serializable rw-register history as PackedTxns.

    Serial execution in txn order (commit order == txn index): writes get
    globally unique value ids; each read observes the latest write of its
    key by mop order (txn-major, so txn-local writes are visible).  All
    txns ok.  O(n) numpy — the BASELINE config-3 scale (1M ops) can't be
    built through Python Op objects in reasonable time.
    """
    from jepsen_tpu.checkers.elle.rw_register import _seg_exclusive_max

    rng = np.random.default_rng(seed)
    T = n_txns
    M = T * mops_per_txn
    mop_txn = np.repeat(np.arange(T, dtype=np.int32), mops_per_txn)
    is_read = rng.random(M) < read_frac
    mop_kind = np.where(is_read, MOP_READ, MOP_APPEND).astype(np.int8)
    mop_key = rng.integers(0, n_keys, M).astype(np.int32)

    n_app = int((~is_read).sum())
    app_idx = np.nonzero(~is_read)[0]
    mop_val = np.full(M, -1, dtype=np.int32)
    mop_val[app_idx] = np.arange(n_app, dtype=np.int32)

    # latest write of the key strictly before each mop, via per-key runs
    mop_order = np.lexsort((np.arange(M), mop_key))
    k_sorted = mop_key[mop_order]
    run_start = np.concatenate([[True], k_sorted[1:] != k_sorted[:-1]])
    seg_id = np.cumsum(run_start) - 1
    app_sorted = (~is_read)[mop_order]
    wq = np.where(app_sorted, np.arange(M), -1)
    prev_w = _seg_exclusive_max(wq, seg_id)
    val_sorted = mop_val[mop_order]
    read_val_sorted = np.where(prev_w >= 0,
                               val_sorted[np.maximum(prev_w, 0)], -1)
    read_val = np.empty(M, dtype=np.int32)
    read_val[mop_order] = read_val_sorted
    mop_val = np.where(is_read, read_val, mop_val).astype(np.int32)

    rd_len = np.where(is_read, 0, -1).astype(np.int32)  # known scalar reads
    rd_start = np.full(M, -1, dtype=np.int32)

    txn_process = (np.arange(T, dtype=np.int32) % concurrency)
    txn_invoke_pos = (2 * np.arange(T, dtype=np.int32))
    txn_complete_pos = txn_invoke_pos + 1

    key_names = list(range(n_keys))
    app_keys = mop_key[app_idx]
    val_keys = np.empty(n_app, dtype=np.int64)
    val_keys[mop_val[app_idx]] = app_keys
    val_names = [(int(val_keys[v]), int(v)) for v in range(n_app)]

    return PackedTxns(
        txn_type=np.full(T, TXN_OK, dtype=np.int8),
        txn_process=txn_process,
        txn_invoke_pos=txn_invoke_pos,
        txn_complete_pos=txn_complete_pos,
        txn_orig_index=np.arange(T, dtype=np.int32) * 2 + 1,
        mop_txn=mop_txn,
        mop_kind=mop_kind,
        mop_key=mop_key,
        mop_val=mop_val,
        mop_rd_start=rd_start,
        mop_rd_len=rd_len,
        rd_elems=np.zeros(0, dtype=np.int32),
        key_names=key_names,
        val_names=val_names,
        n_events=2 * T,
    )


# ---------------------------------------------------------------------------
# Cross-host nemesis-window histories (ISSUE 11 ddmin corpus).
# ---------------------------------------------------------------------------


def cross_host_window_history(necessary_host: str = "hostA",
                              other_host: str = "hostB",
                              bad_sum_delta: int = 3) -> History:
    """Two hosts' instances of the same nemesis-schedule position, with
    one torn whole-state read inside `necessary_host`'s window only
    (`other_host`'s window is disjoint, before the read).  Nemesis ops
    carry the schedule stamp (`Op.ext["window"]`: pos/digest/fault/
    host) exactly as `nemesis.combined.schedule_package` emits it —
    the fixture for cross-host fault-window ddmin (shared by
    tests/test_invariants.py and scripts/fuzz_faults.py)."""

    def nem_pair(f: str, host: str) -> List[Op]:
        w = {"pos": 0, "digest": f"win-{host}", "fault": "skew",
             "host": host}
        return [Op(type=INVOKE, process="nemesis", f=f, value=None,
                   ext={"window": dict(w)}),
                Op(type=INFO, process="nemesis", f=f, value=None,
                   ext={"window": dict(w)})]

    ops: List[Op] = []
    ops += nem_pair("start-skew", other_host)
    ops += nem_pair("stop-skew", other_host)
    ops += nem_pair("start-skew", necessary_host)
    ops.append(Op(type=INVOKE, process=0, f="read", value=None))
    ops.append(Op(type=OK, process=0, f="read",
                  value={0: 10, 1: 10 - int(bad_sum_delta)}))
    ops += nem_pair("stop-skew", necessary_host)
    return History(ops)


def cross_host_sensitive_check(necessary_host: str = "hostA",
                               total: int = 20):
    """A fault-sensitive check fn (wrap in `checkers.api.FnChecker`):
    the anomaly reproduces only while `necessary_host`'s window is in
    the schedule AND a torn read (wrong total) is present — the shape
    that makes a window reproduction-NECESSARY rather than merely
    overlap-kept."""

    def check(test, history, opts):
        has_host = any(
            ((op.ext or {}).get("window") or {}).get("host")
            == necessary_host for op in history)
        torn = any(op.type == OK and isinstance(op.value, dict)
                   and sum(op.value.values()) != total
                   for op in history)
        if torn and has_host:
            return {"valid?": False,
                    "anomaly-types": ["cross-host-torn-read"],
                    "anomalies": {"cross-host-torn-read": 1}}
        return {"valid?": True}

    return check


# ---------------------------------------------------------------------------
# Linearizable-register histories (knossos test corpus).
# ---------------------------------------------------------------------------


def lin_register_history(n_ops: int = 50, concurrency: int = 3,
                         stale_read_prob: float = 0.0,
                         info_prob: float = 0.05,
                         cas_prob: float = 0.2,
                         seed: int = 0) -> History:
    """Simulate a linearizable r/w/cas register; optionally inject stale
    reads (which make the history non-linearizable w.h.p.)."""
    rng = np.random.default_rng(seed)
    ops: List[Op] = []
    value = None        # current register value
    history_vals = [None]  # all past values (for stale reads)
    open_p: Dict[int, Tuple[str, object]] = {}
    done = 0
    while done < n_ops or open_p:
        p = int(rng.integers(0, concurrency))
        if p not in open_p:
            if done + len(open_p) >= n_ops:
                if not open_p:
                    break
                p = list(open_p.keys())[int(rng.integers(0, len(open_p)))]
            else:
                r = rng.random()
                if r < cas_prob:
                    f, v = "cas", [value if value is not None and
                                   rng.random() < 0.7
                                   else int(rng.integers(0, 5)),
                                   int(rng.integers(0, 5))]
                elif r < 0.6:
                    f, v = "write", int(rng.integers(0, 5))
                else:
                    f, v = "read", None
                ops.append(Op(type=INVOKE, process=p, f=f, value=v))
                open_p[p] = (f, v)
                continue
        f, v = open_p.pop(p)
        done += 1
        if rng.random() < info_prob:
            # crashed: effect applied with probability 1/2
            if f == "write" and rng.random() < 0.5:
                value = v
                history_vals.append(value)
            elif f == "cas" and value == v[0] and rng.random() < 0.5:
                value = v[1]
                history_vals.append(value)
            ops.append(Op(type=INFO, process=p, f=f, value=v))
            continue
        if f == "write":
            value = v
            history_vals.append(value)
            ops.append(Op(type=OK, process=p, f=f, value=v))
        elif f == "cas":
            if value == v[0]:
                value = v[1]
                history_vals.append(value)
                ops.append(Op(type=OK, process=p, f=f, value=v))
            else:
                ops.append(Op(type=FAIL, process=p, f=f, value=v))
        else:  # read
            rv = value
            if stale_read_prob and rng.random() < stale_read_prob \
                    and len(history_vals) > 1:
                rv = history_vals[int(rng.integers(0, len(history_vals) - 1))]
            ops.append(Op(type=OK, process=p, f=f, value=rv))
    return History(ops)


# ---------------------------------------------------------------------------
# rw-register histories.
# ---------------------------------------------------------------------------


def rw_history(n_txns: int = 100, n_keys: int = 5, concurrency: int = 5,
               max_mops: int = 3, read_prob: float = 0.5,
               fail_prob: float = 0.0, info_prob: float = 0.0,
               seed: int = 0) -> History:
    """Simulate a strict-serializable rw-register history (unique writes)."""
    rng = np.random.default_rng(seed)
    db: Dict[int, Optional[int]] = {k: None for k in range(n_keys)}
    next_val = 1
    ops: List[Op] = []
    open_txn: Dict[int, List] = {}
    committed = 0
    while committed < n_txns or open_txn:
        p = int(rng.integers(0, concurrency))
        if p not in open_txn:
            if committed + len(open_txn) >= n_txns:
                if not open_txn:
                    break
                p = list(open_txn.keys())[int(rng.integers(0, len(open_txn)))]
            else:
                mops = []
                for _ in range(int(rng.integers(1, max_mops + 1))):
                    k = int(rng.integers(0, n_keys))
                    if rng.random() < read_prob:
                        mops.append(["r", k, None])
                    else:
                        mops.append(["w", k, next_val])
                        next_val += 1
                ops.append(Op(type=INVOKE, process=p, f="txn",
                              value=[list(m) for m in mops]))
                open_txn[p] = mops
                continue
        mops = open_txn.pop(p)
        committed += 1
        r = rng.random()
        if r < fail_prob:
            ops.append(Op(type=FAIL, process=p, f="txn",
                          value=[list(m) for m in mops]))
            continue
        is_info = r < fail_prob + info_prob
        apply_w = (not is_info) or rng.random() < 0.5
        local = dict(db)
        filled = []
        for m in mops:
            if m[0] == "w":
                local[m[1]] = m[2]
                filled.append(["w", m[1], m[2]])
            else:
                filled.append(["r", m[1], local[m[1]]])
        if apply_w:
            db.update(local)
        if is_info:
            ops.append(Op(type=INFO, process=p, f="txn", value=None))
        else:
            ops.append(Op(type=OK, process=p, f="txn", value=filled))
    return History(ops)


def la_generator(n_keys: int = 5, min_mops: int = 1, max_mops: int = 4,
                 read_frac: float = 0.5, rng=None):
    """Live list-append workload generator (the `elle.list-append/gen`
    equivalent, SURVEY.md §2.3): a generator-DSL function emitting random
    txn op templates with per-key unique, monotonically increasing append
    values.  Feed to the interpreter via `generator.core.lift`."""
    import random as _random

    rng = rng or _random
    counters: Dict[int, int] = {}

    def gen(test, ctx):
        mops = []
        for _ in range(rng.randint(min_mops, max_mops)):
            k = rng.randrange(n_keys)
            if rng.random() < read_frac:
                mops.append(["r", k, None])
            else:
                counters[k] = counters.get(k, 0) + 1
                mops.append(["append", k, counters[k]])
        return {"f": "txn", "value": mops}

    return gen


def rw_generator(n_keys: int = 5, min_mops: int = 1, max_mops: int = 4,
                 read_frac: float = 0.5, rng=None):
    """Live rw-register workload generator (`elle.rw-register/gen`
    equivalent): random [w k v]/[r k nil] txns with globally unique writes
    per key."""
    import random as _random

    rng = rng or _random
    counters: Dict[int, int] = {}

    def gen(test, ctx):
        mops = []
        for _ in range(rng.randint(min_mops, max_mops)):
            k = rng.randrange(n_keys)
            if rng.random() < read_frac:
                mops.append(["r", k, None])
            else:
                counters[k] = counters.get(k, 0) + 1
                mops.append(["w", k, counters[k]])
        return {"f": "txn", "value": mops}

    return gen
