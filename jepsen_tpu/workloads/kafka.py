"""Kafka-style partitioned-log workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/kafka.clj`
(SURVEY.md §2.6): clients send uniquely-valued messages to partitioned
topics ("keys") and poll them back; a consumer's assignment changes over
time via ``assign``/``subscribe`` ops, with consumer-group rebalancing.
Op shapes mirror the reference:

- ``{"f": "send", "value": [("send", k, v)]}`` — completed sends get
  ``("send", k, (offset, v))``;
- ``{"f": "poll", "value": [("poll", None)]}`` — completed polls get
  ``("poll", {k: [(offset, v), ...]})`` for the assigned keys;
- ``{"f": "txn", "value": [mops...]}`` — transactional mix of send and
  poll mops, completed the same way;
- ``{"f": "assign", "value": [k, ...]}`` — self-managed assignment
  (real consumers seek to the last committed position per key);
- ``{"f": "subscribe", "value": [k, ...]}`` — group-managed
  subscription; the broker rebalances partitions round-robin across the
  group's members, and polls resume from the group's committed offsets;
- ``{"f": "crash", ...}`` — client crashes (:info), leaves the group,
  forcing a rebalance.

The checker covers the reference's anomaly taxonomy:

- **lost-write**: a committed send whose offset is below some polled
  offset for that key, yet never polled by anyone;
- **duplicate**: one value at two different offsets of a key;
- **inconsistent-offsets**: two different values observed at one offset;
- **nonmonotonic-poll**: a process's successive polls of a key going
  backwards in offset *without an intervening (re)assignment* — real
  consumers seek back to the committed offset on assign/subscribe, so
  re-delivery across a reassignment is legal (reference behavior);
- **poll-skip**: successive polls of a key by one process jumping over
  offsets that exist, without an intervening reassignment;
- **int-nonmonotonic-poll** / **int-poll-skip**: the same inside a
  single poll batch (never legal);
- **nonmonotonic-send**: one process's acked sends to a key going
  backwards in offset;
- **int-send-skip**: two sends to a key inside one txn landing at
  non-consecutive offsets (another producer interleaved mid-txn);
- **precommitted-read**: a poll observed a value before the send that
  wrote it completed (read-uncommitted behavior);
- **unseen**: committed values never polled by anyone (informational —
  reported but not by itself invalid, matching the reference's
  treatment when final polls may simply not have caught up).
"""

from __future__ import annotations

import itertools
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..checkers import api as checker_api
from ..client import Client
from ..history.ops import INFO, INVOKE, OK

#: minimum same-start subscribe-mode batches before a frozen committed
#: offset counts as stale (pinned equal to
#: `checkers.queue.kafka.STALE_MIN_POLLS` by tests so the scan twin and
#: the packed passes can't drift)
STALE_MIN_POLLS = 3

#: seeded adversarial-client FaultPlan sites (strictly opt-in: a plan
#: must name the site for the client to even consult it).  Caller index
#: is ``member * _FAULT_STRIDE + op-ordinal``, the interpreter idiom.
SITE_DUP = "client.dup-send"
SITE_REORDER = "client.reorder-send"
SITE_ZOMBIE = "client.zombie-resend"
SITE_TORN = "client.torn-send"
ADVERSARY_SITES = {SITE_DUP: "dup-send", SITE_REORDER: "reorder-send",
                   SITE_ZOMBIE: "zombie-resend", SITE_TORN: "torn-send"}
_FAULT_STRIDE = 1_000_003


# ---------------------------------------------------------------------------
# Generator


class _KafkaGen:
    """send/poll mix with assign/subscribe churn and optional txns
    (reference kafka gen shape)."""

    def __init__(self, *, key_count: int = 4, poll_frac: float = 0.4,
                 assign_frac: float = 0.1, subscribe_frac: float = 0.0,
                 crash_frac: float = 0.0, txn_frac: float = 0.0,
                 max_txn_mops: int = 4,
                 rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.key_count = key_count
        self.poll_frac = poll_frac
        self.assign_frac = assign_frac
        self.subscribe_frac = subscribe_frac
        self.crash_frac = crash_frac
        self.txn_frac = txn_frac
        self.max_txn_mops = max_txn_mops
        self.counter = itertools.count()

    def _keys_sample(self):
        n = self.rng.randint(1, self.key_count)
        return sorted(self.rng.sample(range(self.key_count), n))

    def _send_mop(self):
        k = self.rng.randrange(self.key_count)
        return ("send", k, next(self.counter))

    def __call__(self, test, ctx):
        r = self.rng.random()
        if r < self.crash_frac:
            return {"f": "crash", "value": None}
        r = self.rng.random()
        if r < self.assign_frac:
            return {"f": "assign", "value": self._keys_sample()}
        r2 = self.rng.random()
        if r2 < self.subscribe_frac:
            return {"f": "subscribe", "value": self._keys_sample()}
        r3 = self.rng.random()
        if r3 < self.txn_frac:
            mops = [self._send_mop() if self.rng.random() < 0.6
                    else ("poll", None)
                    for _ in range(self.rng.randint(2, self.max_txn_mops))]
            return {"f": "txn", "value": mops}
        if r < self.assign_frac + self.poll_frac:
            return {"f": "poll", "value": [("poll", None)]}
        return {"f": "send", "value": [self._send_mop()]}


def gen(**opts) -> Any:
    return _KafkaGen(**opts)


def final_gen():
    """Final phase: assign everything and poll until quiet (so the
    checker can distinguish lost from merely-unread)."""
    from ..generator import core as g

    def assign_all(test, ctx):
        keys = list(range(test.get("kafka-key-count", 4)))
        return {"f": "assign", "value": keys}

    # a bare fn generator is infinite — wrap in once()
    return g.clients(g.each_thread(g.lift(
        [g.once(assign_all)]
        + [{"f": "poll", "value": [("poll", None)]}] * 16)))


# ---------------------------------------------------------------------------
# In-memory kafka-ish broker + client (the sim-cluster db)


#: broker-side tombstone for torn writes: the offset exists, the
#: payload is gone (never returned by read_from)
_TOMB = object()


class KafkaStore:
    """Partitioned append-only logs + one consumer group with round-robin
    rebalancing and per-group committed offsets."""

    def __init__(self):
        self.lock = threading.Lock()
        self.logs: Dict[Any, List[Any]] = {}
        self.subs: Dict[int, List[Any]] = {}      # member -> subscribed keys
        self.assign: Dict[int, List[Any]] = {}    # member -> assigned keys
        self.committed: Dict[Any, int] = {}       # key -> committed offset
        self.generation = 0                        # bumped per rebalance
        self._member_ids = itertools.count()
        # fault knob: auto-commits stop advancing — subscribe-mode
        # consumers re-read the same window while the log moves on (the
        # stale-consumer-group shape)
        self.freeze_commits = False

    def new_member(self) -> int:
        return next(self._member_ids)

    def append(self, k, v) -> int:
        log = self.logs.setdefault(k, [])
        log.append(v)
        return len(log) - 1

    def append_lost(self, k) -> int:
        """A torn write: the broker allocates (and acks) the offset but
        the payload never lands — consumers skip the hole, so the acked
        offset sits below later polled offsets without ever being
        polled: the checker's **lost-write** shape."""
        log = self.logs.setdefault(k, [])
        log.append(_TOMB)
        return len(log) - 1

    def read_from(self, k, pos: int, limit: int) -> List[Tuple[int, Any]]:
        log = self.logs.get(k, [])
        return [(i, log[i]) for i in range(pos, min(len(log), pos + limit))
                if log[i] is not _TOMB]

    # -- consumer group (caller holds the lock) --

    def rebalance(self) -> None:
        """Round-robin partition assignment over subscribing members."""
        self.generation += 1
        members = sorted(self.subs)
        self.assign = {m: [] for m in members}
        all_keys = sorted({k for keys in self.subs.values() for k in keys})
        for i, k in enumerate(all_keys):
            owners = [m for m in members if k in self.subs[m]]
            if owners:
                self.assign[owners[i % len(owners)]].append(k)

    def subscribe(self, member: int, keys: Sequence[Any]) -> None:
        self.subs[member] = list(keys)
        self.rebalance()

    def leave(self, member: int) -> None:
        # no-op for non-members: a crash of an assign-mode client moves no
        # partitions, and bumping the generation would reset subscribe-mode
        # checkers' epochs, masking real anomalies
        if member in self.subs:
            self.subs.pop(member)
            self.rebalance()


class KafkaClient(Client):
    """One consumer/producer per process (reference kafka client shape).

    Two consumption modes, as in real Kafka: ``assign`` (self-managed
    positions, seeking to the group's committed offset on assignment) and
    ``subscribe`` (group-managed: the broker rebalances partitions and
    polls resume from committed offsets; positions auto-commit).

    Fault knobs for checker tests: `lose_tail_p` — on send, the broker
    "acks" but drops the message (a lost write); `dup_p` — the append is
    applied twice (a duplicate).

    Adversarial-client shapes (ISSUE 19) — the behaviors real message
    systems break under, each producing an anomaly the matching packed
    checker pass attributes.  Triggered EITHER by the probability knobs
    (seeded corpora) or by a seeded `FaultPlan` naming the matching
    ``client.*`` site (strictly opt-in, the interpreter idiom):

    - `dup_send_p` / ``client.dup-send`` — the duplicate-request retry:
      every send mop of the op is applied twice (**duplicate**);
    - `reorder_p` / ``client.reorder-send`` — the broker applies one
      op's sends in reverse arrival order; completions still report
      each mop's true landing offset (**int-send-skip** /
      **nonmonotonic-send**);
    - `zombie_p` / ``client.zombie-resend`` — a zombie retry re-appends
      the client's last ACKED message after the fact, invisibly to its
      own history (**duplicate** at a later offset);
    - `torn_p` / ``client.torn-send`` — a multi-key send is torn: only
      the first key's sends reach the log, the rest are acked with
      fabricated offsets (**lost-write** / **inconsistent-offsets**).
    """

    def __init__(self, store: Optional[KafkaStore] = None, *,
                 poll_limit: int = 8, lose_tail_p: float = 0.0,
                 dup_p: float = 0.0, dup_send_p: float = 0.0,
                 reorder_p: float = 0.0, zombie_p: float = 0.0,
                 torn_p: float = 0.0,
                 rng: Optional[random.Random] = None):
        self.store = store or KafkaStore()
        self.poll_limit = poll_limit
        self.lose_tail_p = lose_tail_p
        self.dup_p = dup_p
        self.dup_send_p = dup_send_p
        self.reorder_p = reorder_p
        self.zombie_p = zombie_p
        self.torn_p = torn_p
        self.rng = rng or random.Random(0)
        self.member = -1
        self.mode = "assign"
        self.assigned: List[Any] = []
        self.pos: Dict[Any, int] = {}
        self._acked: Optional[Tuple[Any, Any]] = None
        self._op_n = 0

    def open(self, test, node):
        c = KafkaClient(self.store, poll_limit=self.poll_limit,
                        lose_tail_p=self.lose_tail_p, dup_p=self.dup_p,
                        dup_send_p=self.dup_send_p,
                        reorder_p=self.reorder_p, zombie_p=self.zombie_p,
                        torn_p=self.torn_p, rng=self.rng)
        c.member = self.store.new_member()
        return c

    # -- adversarial shapes --

    def _inj(self, shape: str) -> None:
        from .. import telemetry

        telemetry.registry().counter(
            "queue-adversarial-injections", shape=shape).inc()

    def _adversary(self, test) -> set:
        """Which adversarial shapes apply to THIS op: seeded FaultPlan
        sites (only consulted when the plan names them) plus the
        probability knobs.  Caller-indexed so fuzz accounting stays
        deterministic per (member, op-ordinal)."""
        self._op_n += 1
        shapes = set()
        plan = None
        if isinstance(test, dict):
            from ..resilience import faults as faults_mod

            plan = faults_mod.plan_for(test)
        if plan is not None:
            idx = self.member * _FAULT_STRIDE + self._op_n
            for site, shape in ADVERSARY_SITES.items():
                if not plan.targets_site(site):
                    continue
                try:
                    plan.fire_at(site, idx)
                except faults_mod.FaultInjected:
                    shapes.add(shape)
        for p, shape in ((self.dup_send_p, "dup-send"),
                         (self.reorder_p, "reorder-send"),
                         (self.zombie_p, "zombie-resend"),
                         (self.torn_p, "torn-send")):
            if p and self.rng.random() < p:
                shapes.add(shape)
        return shapes

    # -- mop handlers (store lock held) --

    def _do_send(self, mop, dup: bool = False):
        s = self.store
        _kind, k, v = mop
        if self.lose_tail_p and self.rng.random() < self.lose_tail_p:
            # broker acks but drops: offset it claims is bogus
            return ("send", k, (len(s.logs.get(k, [])), v))
        off = s.append(k, v)
        self._acked = (k, v)
        if dup or (self.dup_p and self.rng.random() < self.dup_p):
            s.append(k, v)  # duplicated append
        return ("send", k, (off, v))

    def _do_mops(self, mops, shapes: set):
        """Apply an op's send/poll mops with the adversarial shapes."""
        s = self.store
        mops = list(mops)
        send_idx = [n for n, m in enumerate(mops) if m[0] == "send"]
        apply_order = list(range(len(mops)))
        if "reorder-send" in shapes and len(send_idx) >= 2:
            # reverse arrival order for this op's sends; each mop slot
            # still reports the offset its value actually landed at
            rev = dict(zip(send_idx, reversed(send_idx)))
            apply_order = [rev.get(n, n) for n in apply_order]
            self._inj("reorder-send")
        torn_keys: set = set()
        if "torn-send" in shapes:
            keys: List[Any] = []
            for n in send_idx:
                if mops[n][1] not in keys:
                    keys.append(mops[n][1])
            if len(keys) >= 2:
                torn_keys = set(keys[1:])
                self._inj("torn-send")
        dup = "dup-send" in shapes
        if dup and send_idx:
            self._inj("dup-send")
        out: List[Any] = [None] * len(mops)
        for n in apply_order:
            m = mops[n]
            if m[0] != "send":
                out[n] = self._do_poll()
            elif m[1] in torn_keys:
                # torn: the broker allocates and acks the offset but
                # the payload is lost
                out[n] = ("send", m[1], (s.append_lost(m[1]), m[2]))
            else:
                out[n] = self._do_send(m, dup=dup)
        if "zombie-resend" in shapes and self._acked is not None:
            # a zombie retry of the last acked send, invisible to this
            # client's own completions
            s.append(*self._acked)
            self._inj("zombie-resend")
        return out

    def _do_poll(self):
        s = self.store
        if self.mode == "subscribe":
            self.assigned = list(s.assign.get(self.member, []))
        batch: Dict[Any, List[Tuple[int, Any]]] = {}
        for k in self.assigned:
            if self.mode == "subscribe":
                pos = s.committed.get(k, 0)
            else:
                pos = self.pos.get(k, 0)
            msgs = s.read_from(k, pos, self.poll_limit)
            if msgs:
                nxt = msgs[-1][0] + 1
                self.pos[k] = nxt
                if self.mode == "subscribe" and not s.freeze_commits:
                    s.committed[k] = nxt      # auto-commit
            batch[k] = msgs
        return ("poll", batch)

    def invoke(self, test, op):
        f = op["f"]
        s = self.store
        with s.lock:
            if f == "send":
                out = self._do_mops(op["value"], self._adversary(test))
                return dict(op, type="ok", value=out)
            if f == "poll":
                done = dict(op, type="ok", value=[self._do_poll()])
                if self.mode == "subscribe":
                    # consumers learn of rebalances via their listener; the
                    # checker uses this to bound cross-poll comparisons to
                    # one assignment epoch (reference: :rebalance log ops)
                    done["rebalance"] = s.generation
                return done
            if f == "txn":
                out = self._do_mops(op["value"], self._adversary(test))
                done = dict(op, type="ok", value=out)
                if self.mode == "subscribe":
                    done["rebalance"] = s.generation
                return done
            if f == "assign":
                if self.mode == "subscribe":
                    s.leave(self.member)
                self.mode = "assign"
                self.assigned = list(op["value"])
                for k in self.assigned:
                    # real consumers seek to the committed offset
                    self.pos[k] = max(self.pos.get(k, 0),
                                      s.committed.get(k, 0))
                return dict(op, type="ok")
            if f == "subscribe":
                self.mode = "subscribe"
                s.subscribe(self.member, op["value"])
                return dict(op, type="ok")
            if f == "crash":
                s.leave(self.member)
                self.mode = "assign"
                self.assigned = []
                return dict(op, type="info", error="client crashed")
        raise ValueError(f"unknown kafka op {f!r}")


# ---------------------------------------------------------------------------
# Checker


def _observations(history):
    """Facts from the history, one ordered pass.

    Returns (sends, polls, reassigns) where
    sends:        (k, offset, v, ok-op-index, process)
    polls:        (k, [(off, v), ...], process, op-index, mop-slot,
                   rebalance-generation-or-None)
    reassigns:    (process, op-index) for assign/subscribe/crash completions
    send_invoked: {(k, v): earliest send-invocation op index}.
    """
    sends: List[Tuple[Any, int, Any, int, Any]] = []
    polls: List[Tuple[Any, List[Tuple[int, Any]], Any, int, int, Any]] = []
    reassigns: List[Tuple[Any, int]] = []
    send_invoked: Dict[Tuple[Any, Any], int] = {}
    for op in history:
        if not op.is_client_op():
            continue
        if op.f in ("assign", "subscribe"):
            if op.type == OK:
                reassigns.append((op.process, op.index))
            continue
        if op.f == "crash":
            if op.type in (OK, INFO):
                reassigns.append((op.process, op.index))
            continue
        if op.type == INVOKE and op.f in ("send", "txn"):
            for mop in op.value or ():
                if isinstance(mop, (tuple, list)) and len(mop) == 3 \
                        and mop[0] == "send":
                    send_invoked.setdefault((mop[1], mop[2]), op.index)
            continue
        if op.type != OK or op.f not in ("send", "poll", "txn"):
            continue
        gen = (op.ext or {}).get("rebalance")
        for slot, mop in enumerate(op.value or ()):
            if not isinstance(mop, (tuple, list)) or len(mop) < 2:
                continue
            kind = mop[0]
            if kind == "send" and isinstance(mop[2], tuple):
                off, v = mop[2]
                sends.append((mop[1], int(off), v, op.index, op.process))
            elif kind == "poll" and isinstance(mop[1], dict):
                for k, msgs in mop[1].items():
                    polls.append((k, [(int(o), v) for (o, v) in msgs],
                                  op.process, op.index, slot, gen))
    return sends, polls, reassigns, send_invoked


class KafkaChecker(checker_api.Checker):
    """The reference kafka checker's anomaly taxonomy (module docstring)."""

    def check(self, test, history, opts=None):
        sends, polls, reassigns, send_invoked = _observations(history)
        if not sends and not polls:
            return {"valid?": "unknown"}

        # version map: (k, offset) -> set of values observed there
        at: Dict[Tuple[Any, int], set] = {}
        polled_offsets: Dict[Any, set] = {}
        polled_values: Dict[Any, Dict[Any, set]] = {}
        for (k, off, v, _i, _p) in sends:
            at.setdefault((k, off), set()).add(v)
        for (k, msgs, _p, _i, _s, _g) in polls:
            for (off, v) in msgs:
                at.setdefault((k, off), set()).add(v)
                polled_offsets.setdefault(k, set()).add(off)
                polled_values.setdefault(k, {}).setdefault(v, set()).add(off)

        inconsistent_offsets = sorted(
            (k, off, sorted(vs, key=repr))
            for (k, off), vs in at.items() if len(vs) > 1)

        duplicates = sorted(
            (k, v, sorted(offs))
            for k, vals in polled_values.items()
            for v, offs in vals.items() if len(offs) > 1)

        # lost: committed send below the max polled offset, never polled
        lost = []
        for (k, off, v, i, _p) in sends:
            seen = polled_offsets.get(k, set())
            if not seen:
                continue
            if off < max(seen) and off not in seen:
                lost.append((k, off, v))
        lost = sorted(set(lost))

        # unseen (informational): committed values never polled anywhere
        unseen: Dict[Any, int] = {}
        for (k, off, v, i, _p) in sends:
            if off not in polled_offsets.get(k, set()):
                unseen[k] = unseen.get(k, 0) + 1

        # ---- poll-side order anomalies -----------------------------------
        # reassignment windows: real consumers seek back to the committed
        # offset on (re)assign, so cross-poll tracking resets there — polls
        # are compared only within the same assignment epoch (the reference
        # excludes poll pairs that cross an (re)assignment)
        reassign_by_proc: Dict[Any, List[int]] = {}
        for (p, i) in reassigns:
            reassign_by_proc.setdefault(p, []).append(i)

        def epoch(p, op_index):
            """Count of p's reassignments before this op."""
            import bisect

            lst = reassign_by_proc.get(p, ())
            return bisect.bisect_left(lst, op_index)

        nonmonotonic = []
        skipped = []
        int_nonmono = []
        int_skipped = []
        last_polled: Dict[Tuple[Any, Any], Tuple[int, Any]] = {}
        for (k, msgs, p, i, _s, gen) in sorted(polls, key=lambda t: (t[3], t[4])):
            if not msgs:
                continue
            offs = [o for (o, _v) in msgs]
            # epoch combines the process's own (re)assign count with the
            # broker's rebalance generation (attached by subscribe-mode
            # clients): a rebalance triggered by ANOTHER member also moves
            # partitions, and committed-offset seeks across it are legal
            ep = (epoch(p, i), gen)
            prev = last_polled.get((p, k))
            if prev is not None and prev[1] == ep and offs[0] <= prev[0]:
                nonmonotonic.append({"process": p, "key": k,
                                     "prev": prev[0], "next": offs[0],
                                     "op-index": i})
            if prev is not None and prev[1] == ep and offs[0] > prev[0] + 1 \
                    and any(prev[0] < o < offs[0]
                            for o in polled_offsets.get(k, ())):
                skipped.append({"key": k, "from": prev[0], "to": offs[0],
                                "process": p, "op-index": i})
            for a, b in zip(offs, offs[1:]):
                if b <= a:
                    int_nonmono.append({"key": k, "prev": a, "next": b,
                                        "op-index": i})
                elif b != a + 1 and any(a < o < b
                                        for o in polled_offsets.get(k, ())):
                    int_skipped.append({"key": k, "from": a, "to": b,
                                        "op-index": i})
            last_polled[(p, k)] = (offs[-1], ep)

        # ---- send-side order anomalies -----------------------------------
        nonmono_send = []
        int_send_skip = []
        last_sent: Dict[Tuple[Any, Any], int] = {}
        by_op: Dict[int, List[Tuple[Any, int]]] = {}
        for (k, off, v, i, p) in sorted(sends, key=lambda t: t[3]):
            prev = last_sent.get((p, k))
            if prev is not None and off <= prev:
                nonmono_send.append({"process": p, "key": k, "prev": prev,
                                     "next": off, "op-index": i})
            last_sent[(p, k)] = off
            by_op.setdefault(i, []).append((k, off))
        for i, kos in by_op.items():
            if len(kos) < 2:
                continue
            seen_k: Dict[Any, int] = {}
            for (k, off) in kos:
                if k in seen_k and off != seen_k[k] + 1:
                    int_send_skip.append({"key": k, "from": seen_k[k],
                                          "to": off, "op-index": i})
                seen_k[k] = off

        # ---- precommitted-read -------------------------------------------
        # a poll observed (k, v) at an index before the send of v was even
        # INVOKED.  Comparing completion indices would false-positive:
        # completion recording order can invert relative to broker order
        # under concurrency, so only the invocation gives a sound "this
        # value could not exist yet" bound.
        precommitted = []
        if send_invoked:
            for (k, msgs, p, i, _s, _g) in polls:
                for (off, v) in msgs:
                    j = send_invoked.get((k, v))
                    if j is not None and i < j:
                        precommitted.append({"key": k, "value": v,
                                             "poll-op": i, "send-op": j})

        # ---- stale consumer group ----------------------------------------
        # a frozen committed offset: >= STALE_MIN_POLLS subscribe-mode
        # batches of one (key, rebalance-generation) re-reading the SAME
        # start offset while the key's log has moved past them.  1-2
        # same-start re-reads happen benignly around rebalances; three
        # with the log ahead mean the group's commit stopped advancing.
        key_max: Dict[Any, int] = {}
        for (k, off, _v, _i, _p) in sends:
            key_max[k] = max(key_max.get(k, -1), off)
        for k, offs in polled_offsets.items():
            key_max[k] = max(key_max.get(k, -1), max(offs))
        stale_groups: Dict[Tuple[Any, int, int], List[int]] = {}
        for (k, msgs, _p, _i, _s, gen) in polls:
            if not msgs or gen is None:
                continue
            stale_groups.setdefault(
                (k, gen, msgs[0][0]), []).append(msgs[-1][0])
        stale = []
        for (k, gen, start), lasts in stale_groups.items():
            if len(lasts) < STALE_MIN_POLLS:
                continue
            behind = sum(1 for la in lasts if key_max.get(k, -1) > la)
            if behind:
                stale.append({"key": k, "generation": gen,
                              "start": start, "polls": len(lasts),
                              "behind": behind})
        stale.sort(key=lambda e: (repr(e["key"]), e["generation"],
                                  e["start"]))

        anomalies = {
            "lost-write": lost[:16],
            "duplicate": duplicates[:16],
            "inconsistent-offsets": inconsistent_offsets[:16],
            "nonmonotonic-poll": nonmonotonic[:16],
            "poll-skip": skipped[:16],
            "int-nonmonotonic-poll": int_nonmono[:16],
            "int-poll-skip": int_skipped[:16],
            "nonmonotonic-send": nonmono_send[:16],
            "int-send-skip": int_send_skip[:16],
            "precommitted-read": precommitted[:16],
            "stale-consumer-group": stale[:16],
        }
        found = {k: v for k, v in anomalies.items() if v}
        out = {
            "valid?": not found,
            "anomaly-types": sorted(found),
            "anomalies": found,
            "send-count": len(sends),
            "poll-count": len(polls),
        }
        if unseen:
            out["unseen"] = dict(sorted(unseen.items(), key=repr)[:16])
        return out


def workload(*, key_count: int = 4, crash_frac: float = 0.0,
             subscribe_frac: float = 0.0, txn_frac: float = 0.0,
             rng: Optional[random.Random] = None) -> dict:
    from ..checkers.queue.kafka import PackedKafkaChecker

    return {
        "generator": gen(key_count=key_count, crash_frac=crash_frac,
                         subscribe_frac=subscribe_frac, txn_frac=txn_frac,
                         rng=rng),
        "final-generator": final_gen(),
        "checker": PackedKafkaChecker(),
        "kafka-key-count": key_count,
        "workload-kind": "kafka",
    }
