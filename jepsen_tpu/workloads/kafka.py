"""Kafka-style partitioned-log workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/kafka.clj`
(SURVEY.md §2.6): clients send uniquely-valued messages to partitioned
topics ("keys") and poll them back; a consumer's assignment changes over
time via ``assign``/``subscribe`` ops.  Op shapes mirror the reference:

- ``{"f": "send", "value": [("send", k, v)]}`` — completed sends get
  ``("send", k, (offset, v))``;
- ``{"f": "poll", "value": [("poll", None)]}`` — completed polls get
  ``("poll", {k: [(offset, v), ...]})`` for the assigned keys;
- ``{"f": "assign", "value": [k, ...]}`` — replace the assignment (seeks
  to the last committed position per key);
- ``{"f": "crash", ...}`` — client crashes (:info), forcing reassignment.

The checker hunts the reference's anomaly families:

- **lost-write**: a committed send whose offset is below some polled
  offset for that key, yet never polled by anyone;
- **duplicate**: one value at two different offsets of a key;
- **inconsistent-offsets**: two different values observed at one offset;
- **nonmonotonic-poll**: a process's successive polls of a key going
  backwards in offset;
- **skipped-poll** (int-poll-skip): a single poll batch jumping over an
  offset that some poll observed.
"""

from __future__ import annotations

import itertools
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..checkers import api as checker_api
from ..client import Client
from ..history.ops import OK


# ---------------------------------------------------------------------------
# Generator


class _KafkaGen:
    """send/poll mix with occasional assign churn (reference kafka gen)."""

    def __init__(self, *, key_count: int = 4, poll_frac: float = 0.4,
                 assign_frac: float = 0.1, crash_frac: float = 0.0,
                 rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.key_count = key_count
        self.poll_frac = poll_frac
        self.assign_frac = assign_frac
        self.crash_frac = crash_frac
        self.counter = itertools.count()

    def _keys_sample(self):
        n = self.rng.randint(1, self.key_count)
        return sorted(self.rng.sample(range(self.key_count), n))

    def __call__(self, test, ctx):
        r = self.rng.random()
        if r < self.crash_frac:
            return {"f": "crash", "value": None}
        r = self.rng.random()
        if r < self.assign_frac:
            return {"f": "assign", "value": self._keys_sample()}
        if r < self.assign_frac + self.poll_frac:
            return {"f": "poll", "value": [("poll", None)]}
        k = self.rng.randrange(self.key_count)
        return {"f": "send", "value": [("send", k, next(self.counter))]}


def gen(**opts) -> Any:
    return _KafkaGen(**opts)


def final_gen():
    """Final phase: assign everything and poll until quiet (so the
    checker can distinguish lost from merely-unread)."""
    from ..generator import core as g

    def assign_all(test, ctx):
        keys = list(range(test.get("kafka-key-count", 4)))
        return {"f": "assign", "value": keys}

    # a bare fn generator is infinite — wrap in once()
    return g.clients(g.each_thread(g.lift(
        [g.once(assign_all)]
        + [{"f": "poll", "value": [("poll", None)]}] * 16)))


# ---------------------------------------------------------------------------
# In-memory kafka-ish broker + client (the sim-cluster db)


class KafkaStore:
    """Partitioned append-only logs with per-consumer positions."""

    def __init__(self):
        self.lock = threading.Lock()
        self.logs: Dict[Any, List[Any]] = {}

    def append(self, k, v) -> int:
        log = self.logs.setdefault(k, [])
        log.append(v)
        return len(log) - 1

    def read_from(self, k, pos: int, limit: int) -> List[Tuple[int, Any]]:
        log = self.logs.get(k, [])
        return [(i, log[i]) for i in range(pos, min(len(log), pos + limit))]


class KafkaClient(Client):
    """One consumer/producer per process (reference kafka client shape).

    `lose_tail_p`: on send, with this probability the broker "acks" but
    drops the message (a lost write, for checker tests)."""

    def __init__(self, store: Optional[KafkaStore] = None, *,
                 poll_limit: int = 8, lose_tail_p: float = 0.0,
                 dup_p: float = 0.0, rng: Optional[random.Random] = None):
        self.store = store or KafkaStore()
        self.poll_limit = poll_limit
        self.lose_tail_p = lose_tail_p
        self.dup_p = dup_p
        self.rng = rng or random.Random(0)
        self.assigned: List[Any] = []
        self.pos: Dict[Any, int] = {}

    def open(self, test, node):
        c = KafkaClient(self.store, poll_limit=self.poll_limit,
                        lose_tail_p=self.lose_tail_p, dup_p=self.dup_p,
                        rng=self.rng)
        return c

    def invoke(self, test, op):
        f = op["f"]
        s = self.store
        with s.lock:
            if f == "send":
                out = []
                for (_kind, k, v) in op["value"]:
                    if self.lose_tail_p and self.rng.random() < self.lose_tail_p:
                        # broker acks but drops: offset it claims is bogus
                        out.append(("send", k, (len(s.logs.get(k, [])), v)))
                        continue
                    off = s.append(k, v)
                    if self.dup_p and self.rng.random() < self.dup_p:
                        s.append(k, v)  # duplicated append
                    out.append(("send", k, (off, v)))
                return dict(op, type="ok", value=out)
            if f == "poll":
                batch: Dict[Any, List[Tuple[int, Any]]] = {}
                for k in self.assigned:
                    msgs = s.read_from(k, self.pos.get(k, 0),
                                       self.poll_limit)
                    if msgs:
                        self.pos[k] = msgs[-1][0] + 1
                    batch[k] = msgs
                return dict(op, type="ok", value=[("poll", batch)])
            if f == "assign":
                self.assigned = list(op["value"])
                for k in self.assigned:
                    self.pos.setdefault(k, 0)
                return dict(op, type="ok")
            if f == "subscribe":
                # sim broker: subscribe == assign (no group rebalance)
                self.assigned = list(op["value"])
                for k in self.assigned:
                    self.pos.setdefault(k, 0)
                return dict(op, type="ok")
            if f == "crash":
                return dict(op, type="info", error="client crashed")
        raise ValueError(f"unknown kafka op {f!r}")


# ---------------------------------------------------------------------------
# Checker


def _observations(history):
    """Collected facts from the history, one pass."""
    sends: List[Tuple[Any, int, Any, int]] = []   # (k, offset, v, op-index)
    polls: List[Tuple[Any, List[Tuple[int, Any]], Any, int]] = []
    for op in history:
        if op.type != OK or not op.is_client_op() \
                or op.f not in ("send", "poll", "txn"):
            continue  # assign/subscribe values are key lists, not mops
        for mop in op.value or ():
            if not isinstance(mop, (tuple, list)) or len(mop) < 2:
                continue
            kind = mop[0]
            if kind == "send" and isinstance(mop[2], tuple):
                off, v = mop[2]
                sends.append((mop[1], int(off), v, op.index))
            elif kind == "poll" and isinstance(mop[1], dict):
                for k, msgs in mop[1].items():
                    polls.append((k, [(int(o), v) for (o, v) in msgs],
                                  op.process, op.index))
    return sends, polls


class KafkaChecker(checker_api.Checker):
    """The reference kafka checker's core anomaly families."""

    def check(self, test, history, opts=None):
        sends, polls = _observations(history)
        if not sends and not polls:
            return {"valid?": "unknown"}

        # version map: (k, offset) -> set of values observed there
        at: Dict[Tuple[Any, int], set] = {}
        polled_offsets: Dict[Any, set] = {}
        polled_values: Dict[Any, Dict[Any, set]] = {}
        for (k, off, v, _i) in sends:
            at.setdefault((k, off), set()).add(v)
        for (k, msgs, _p, _i) in polls:
            for (off, v) in msgs:
                at.setdefault((k, off), set()).add(v)
                polled_offsets.setdefault(k, set()).add(off)
                polled_values.setdefault(k, {}).setdefault(v, set()).add(off)

        inconsistent_offsets = sorted(
            (k, off, sorted(vs, key=repr))
            for (k, off), vs in at.items() if len(vs) > 1)

        duplicates = sorted(
            (k, v, sorted(offs))
            for k, vals in polled_values.items()
            for v, offs in vals.items() if len(offs) > 1)

        # lost: committed send below the max polled offset, never polled
        lost = []
        for (k, off, v, i) in sends:
            seen = polled_offsets.get(k, set())
            if not seen:
                continue
            if off < max(seen) and off not in seen:
                lost.append((k, off, v))
        lost = sorted(set(lost))

        # per-process nonmonotonic polls; per-batch skips
        nonmonotonic = []
        skipped = []
        last_polled: Dict[Tuple[Any, Any], int] = {}
        for (k, msgs, p, i) in polls:
            if not msgs:
                continue
            offs = [o for (o, _v) in msgs]
            prev = last_polled.get((p, k))
            if prev is not None and offs[0] <= prev:
                nonmonotonic.append({"process": p, "key": k,
                                     "prev": prev, "next": offs[0],
                                     "op-index": i})
            for a, b in zip(offs, offs[1:]):
                if b != a + 1 and any(a < o < b
                                      for o in polled_offsets.get(k, ())):
                    skipped.append({"key": k, "from": a, "to": b,
                                    "op-index": i})
            last_polled[(p, k)] = offs[-1]

        anomalies = {
            "lost-write": lost[:16],
            "duplicate": duplicates[:16],
            "inconsistent-offsets": inconsistent_offsets[:16],
            "nonmonotonic-poll": nonmonotonic[:16],
            "skipped-poll": skipped[:16],
        }
        found = {k: v for k, v in anomalies.items() if v}
        return {
            "valid?": not found,
            "anomaly-types": sorted(found),
            "anomalies": found,
            "send-count": len(sends),
            "poll-count": len(polls),
        }


def workload(*, key_count: int = 4, crash_frac: float = 0.0,
             rng: Optional[random.Random] = None) -> dict:
    return {
        "generator": gen(key_count=key_count, crash_frac=crash_frac,
                         rng=rng),
        "final-generator": final_gen(),
        "checker": KafkaChecker(),
        "kafka-key-count": key_count,
    }
