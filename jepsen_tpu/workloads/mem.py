"""In-process simulated cluster.

The reference tests `core/run!` without SSH via noop dbs and docker
(SURVEY.md §4); this module is the equivalent pure-Python strategy: a
shared in-memory store with a `Client` implementation covering the standard
workload op shapes, plus optional fault knobs (latency, crash probability)
so interpreter/core tests can exercise :info paths deterministically.

Supported op :f shapes (matching the workloads in jepsen_tpu.workloads):
  read / write / cas        — single register ops (linearizable-register)
  txn                       — list of mops [["append",k,v] | ["r",k,None] |
                              ["w",k,v]] executed atomically (elle
                              workloads)
  add / read                — set workload (add element, read all)
  enqueue / dequeue         — queue workload
  transfer / read           — bank workload (value {from,to,amount} /
                              account->balance map)
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from jepsen_tpu.client import Client


class MemStore:
    """The 'cluster': a lock-protected shared state."""

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: Dict[Any, Any] = {}
        self.lists: Dict[Any, List[Any]] = {}
        self.set_elems: set = set()
        self.queue: List[Any] = []
        self.accounts: Dict[Any, int] = {}


class MemClient(Client):
    """Client over a MemStore.

    `latency` sleeps that long per op (seconds); `crash_p` completes ops as
    :info with that probability *after* applying them (indeterminate but
    actually-applied — the hard case checkers must handle); `fail_p`
    completes as :fail *without* applying (clean abort)."""

    def __init__(self, store: Optional[MemStore] = None, *,
                 latency: float = 0.0, crash_p: float = 0.0,
                 fail_p: float = 0.0, rng: Optional[random.Random] = None,
                 txn_kind: str = "list-append"):
        self.store = store or MemStore()
        self.latency = latency
        self.crash_p = crash_p
        self.fail_p = fail_p
        self.rng = rng or random.Random(0)
        self.txn_kind = txn_kind  # "list-append" | "rw-register"

    def open(self, test, node):
        return self  # connectionless; all "nodes" share the store

    def invoke(self, test, op):
        if self.latency:
            time.sleep(self.latency)
        if self.fail_p and self.rng.random() < self.fail_p:
            return dict(op, type="fail", error="simulated-abort")
        s = self.store
        f = op["f"]
        v = op.get("value")
        with s.lock:
            if f == "read" and not isinstance(v, dict):
                out = dict(op, type="ok", value=self._read_value(test))
            elif f == "write":
                s.kv["x"] = v
                out = dict(op, type="ok")
            elif f == "cas":
                old, new = v
                if s.kv.get("x") == old:
                    s.kv["x"] = new
                    out = dict(op, type="ok")
                else:
                    out = dict(op, type="fail")
            elif f == "txn":
                out = dict(op, type="ok", value=self._apply_txn(v))
            elif f == "add":
                s.set_elems.add(v)
                out = dict(op, type="ok")
            elif f == "enqueue":
                s.queue.append(v)
                out = dict(op, type="ok")
            elif f == "dequeue":
                if s.queue:
                    out = dict(op, type="ok", value=s.queue.pop(0))
                else:
                    out = dict(op, type="fail", error="empty")
            elif f == "transfer":
                frm, to, amt = v["from"], v["to"], v["amount"]
                if s.accounts.get(frm, 0) < amt:
                    out = dict(op, type="fail", error="insufficient")
                else:
                    s.accounts[frm] -= amt
                    s.accounts[to] = s.accounts.get(to, 0) + amt
                    out = dict(op, type="ok")
            else:
                raise ValueError(f"unknown op f {f!r}")
        if out["type"] == "ok" and self.crash_p \
                and self.rng.random() < self.crash_p:
            return dict(op, type="info", error="simulated-crash")
        return out

    def _read_value(self, test):
        s = self.store
        workload = (test or {}).get("workload-kind", "register")
        if workload == "set":
            return sorted(s.set_elems)
        if workload == "bank":
            return dict(s.accounts)
        return s.kv.get("x")

    def _apply_txn(self, mops):
        s = self.store
        out = []
        for mop in mops:
            kind, k, v = mop[0], mop[1], mop[2] if len(mop) > 2 else None
            if kind == "append":
                s.lists.setdefault(k, []).append(v)
                out.append(["append", k, v])
            elif kind == "r":
                if self.txn_kind == "rw-register":
                    out.append(["r", k, s.kv.get(k)])
                else:
                    out.append(["r", k, list(s.lists.get(k, []))])
            elif kind == "w":
                s.kv[k] = v
                out.append(["w", k, v])
            else:
                raise ValueError(f"unknown mop kind {kind!r}")
        return out


def bank_store(n_accounts: int = 8, balance: int = 10) -> MemStore:
    s = MemStore()
    s.accounts = {i: balance for i in range(n_accounts)}
    return s
