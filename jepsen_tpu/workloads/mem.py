"""In-process simulated cluster.

The reference tests `core/run!` without SSH via noop dbs and docker
(SURVEY.md §4); this module is the equivalent pure-Python strategy: a
shared in-memory store with a `Client` implementation covering the standard
workload op shapes, plus optional fault knobs (latency, crash probability)
so interpreter/core tests can exercise :info paths deterministically.

Supported op :f shapes (matching the workloads in jepsen_tpu.workloads):
  read / write / cas        — single register ops (linearizable-register)
  txn                       — list of mops [["append",k,v] | ["r",k,None] |
                              ["w",k,v]] executed atomically (elle
                              workloads)
  add / read                — set workload (add element, read all)
  enqueue / dequeue         — queue workload
  transfer / read           — bank workload (value {from,to,amount} /
                              account->balance map)
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from jepsen_tpu.client import Client


class MemStore:
    """The 'cluster': a lock-protected shared state.

    Fault surfaces (driven by the sim nemeses in `nemesis/sim.py`):

    - **clock skew** (`start_skew` / `stop_skew`): while skewed, reads
      observe a *torn* state — a seeded per-key/account mix of a
      snapshot taken at skew start and the live state, which is what a
      snapshot read assembled from nodes with disagreeing clocks looks
      like.  Writes always apply to the live state, so bank totals stop
      summing and register reads go stale — real, checker-visible
      anomalies.
    - **membership** (`members` set): when tracked (non-None), clients
      bound to a node outside the set fail ops cleanly."""

    def __init__(self):
        self.lock = threading.Lock()
        self.kv: Dict[Any, Any] = {}
        self.lists: Dict[Any, List[Any]] = {}
        self.set_elems: set = set()
        self.queue: List[Any] = []
        self.accounts: Dict[Any, int] = {}
        self.members: Optional[set] = None  # None = not tracked
        self._skew: Optional[dict] = None   # snapshot state while skewed

    # ---- clock-skew surface ---------------------------------------------
    def start_skew(self, salt: float = 0.0) -> None:
        """Snapshot the state and enter skewed-read mode.  `salt` seeds
        which half of each read comes from the past."""
        with self.lock:
            self._skew = {
                "kv": dict(self.kv),
                "lists": {k: list(v) for k, v in self.lists.items()},
                "accounts": dict(self.accounts),
                "rng": random.Random(salt),
            }

    def stop_skew(self) -> None:
        with self.lock:
            self._skew = None

    def _torn(self, live: Dict[Any, Any], snap: Dict[Any, Any]
              ) -> Dict[Any, Any]:
        """A read mixing snapshot and live values per key (call with
        the lock held).  Seeded per call: roughly half of the keys come
        from the past."""
        rng = self._skew["rng"]
        keys = sorted(set(live) | set(snap), key=repr)
        out = {}
        for k in keys:
            src = snap if rng.random() < 0.5 else live
            if k in src:
                out[k] = src[k]
            elif k in live:
                out[k] = live[k]
        return out


class MemClient(Client):
    """Client over a MemStore.

    `latency` sleeps that long per op (seconds); `crash_p` completes ops as
    :info with that probability *after* applying them (indeterminate but
    actually-applied — the hard case checkers must handle); `fail_p`
    completes as :fail *without* applying (clean abort)."""

    def __init__(self, store: Optional[MemStore] = None, *,
                 latency: float = 0.0, crash_p: float = 0.0,
                 fail_p: float = 0.0, rng: Optional[random.Random] = None,
                 txn_kind: str = "list-append",
                 dup_enqueue_p: float = 0.0, lose_enqueue_p: float = 0.0,
                 reorder_dequeue_p: float = 0.0):
        self.store = store or MemStore()
        self.latency = latency
        self.crash_p = crash_p
        self.fail_p = fail_p
        self.rng = rng or random.Random(0)
        self.txn_kind = txn_kind  # "list-append" | "rw-register"
        # queue adversarial shapes (ISSUE 19): duplicate-request retry
        # (applied twice, acked once -> queue-phantom), ack-without-apply
        # (-> queue-lost), tail-pop reorder (-> queue-fifo-violation)
        self.dup_enqueue_p = dup_enqueue_p
        self.lose_enqueue_p = lose_enqueue_p
        self.reorder_dequeue_p = reorder_dequeue_p

    def _inj(self, shape: str) -> None:
        from .. import telemetry

        telemetry.registry().counter(
            "queue-adversarial-injections", shape=shape).inc()

    def open(self, test, node):
        # connectionless — all "nodes" share the store — but each
        # worker's handle remembers its node so membership changes can
        # reject ops routed to a removed node
        import copy

        c = copy.copy(self)
        c.node = node
        return c

    def invoke(self, test, op):
        if self.latency:
            time.sleep(self.latency)
        if self.fail_p and self.rng.random() < self.fail_p:
            return dict(op, type="fail", error="simulated-abort")
        s = self.store
        members = s.members
        if members is not None and getattr(self, "node", None) is not None \
                and self.node not in members:
            return dict(op, type="fail", error="node-removed")
        f = op["f"]
        v = op.get("value")
        with s.lock:
            if f == "read" and not isinstance(v, dict):
                out = dict(op, type="ok", value=self._read_value(test))
            elif f == "write":
                s.kv["x"] = v
                out = dict(op, type="ok")
            elif f == "cas":
                old, new = v
                if s.kv.get("x") == old:
                    s.kv["x"] = new
                    out = dict(op, type="ok")
                else:
                    out = dict(op, type="fail")
            elif f == "txn":
                out = dict(op, type="ok", value=self._apply_txn(v))
            elif f == "add":
                s.set_elems.add(v)
                out = dict(op, type="ok")
            elif f == "enqueue":
                if self.lose_enqueue_p and \
                        self.rng.random() < self.lose_enqueue_p:
                    self._inj("lose-enqueue")   # acked, never applied
                else:
                    s.queue.append(v)
                    if self.dup_enqueue_p and \
                            self.rng.random() < self.dup_enqueue_p:
                        s.queue.append(v)       # retry applied twice
                        self._inj("dup-enqueue")
                out = dict(op, type="ok")
            elif f == "dequeue":
                if s.queue:
                    i = 0
                    if len(s.queue) >= 2 and self.reorder_dequeue_p and \
                            self.rng.random() < self.reorder_dequeue_p:
                        i = -1                  # tail pop: FIFO broken
                        self._inj("reorder-dequeue")
                    out = dict(op, type="ok", value=s.queue.pop(i))
                else:
                    out = dict(op, type="fail", error="empty")
            elif f == "transfer":
                frm, to, amt = v["from"], v["to"], v["amount"]
                if s.accounts.get(frm, 0) < amt:
                    out = dict(op, type="fail", error="insufficient")
                else:
                    s.accounts[frm] -= amt
                    s.accounts[to] = s.accounts.get(to, 0) + amt
                    out = dict(op, type="ok")
            else:
                raise ValueError(f"unknown op f {f!r}")
        if out["type"] == "ok" and self.crash_p \
                and self.rng.random() < self.crash_p:
            return dict(op, type="info", error="simulated-crash")
        return out

    def _read_value(self, test):
        s = self.store
        workload = (test or {}).get("workload-kind", "register")
        if workload == "set":
            return sorted(s.set_elems)
        if workload == "bank":
            if s._skew is not None:
                # a "snapshot" read assembled under skewed clocks:
                # part past, part present — totals stop conserving
                return s._torn(s.accounts, s._skew["accounts"])
            return dict(s.accounts)
        if s._skew is not None and s._skew["rng"].random() < 0.5:
            return s._skew["kv"].get("x")
        return s.kv.get("x")

    def _apply_txn(self, mops):
        s = self.store
        skew = s._skew
        out = []
        for mop in mops:
            kind, k, v = mop[0], mop[1], mop[2] if len(mop) > 2 else None
            if kind == "append":
                s.lists.setdefault(k, []).append(v)
                out.append(["append", k, v])
            elif kind == "r":
                stale = skew is not None and skew["rng"].random() < 0.5
                if self.txn_kind == "rw-register":
                    src = skew["kv"] if stale else s.kv
                    out.append(["r", k, src.get(k)])
                else:
                    src = skew["lists"] if stale else s.lists
                    out.append(["r", k, list(src.get(k, []))])
            elif kind == "w":
                s.kv[k] = v
                out.append(["w", k, v])
            else:
                raise ValueError(f"unknown mop kind {kind!r}")
        return out


def bank_store(n_accounts: int = 8, balance: int = 10) -> MemStore:
    s = MemStore()
    s.accounts = {i: balance for i in range(n_accounts)}
    return s
