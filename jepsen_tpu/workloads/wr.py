"""RW-register workload.

Equivalent of the reference's `jepsen/src/jepsen/tests/cycle/wr.clj` +
`elle.rw-register` (SURVEY.md §2.6): transactions of ``("w", k, v)`` /
``("r", k, None)`` with globally unique writes per key, checked by the
TPU rw-register pipeline.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ..checkers import api as checker_api


class _TxnGen:
    def __init__(self, *, key_count: int = 8, min_txn_length: int = 1,
                 max_txn_length: int = 4, read_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.key_count = key_count
        self.min_len = min_txn_length
        self.max_len = max_txn_length
        self.read_frac = read_frac
        self.next_val: Dict[int, int] = {}

    def _mop(self):
        k = self.rng.randrange(self.key_count)
        if self.rng.random() < self.read_frac:
            return ("r", k, None)
        v = self.next_val.get(k, 0)
        self.next_val[k] = v + 1  # unique writes — rw-register's invariant
        return ("w", k, v)

    def __call__(self, test, ctx):
        n = self.rng.randint(self.min_len, self.max_len)
        return {"f": "txn", "value": [self._mop() for _ in range(n)]}


def gen(**opts) -> Any:
    return _TxnGen(**opts)


class WrChecker(checker_api.Checker):
    """Adapts `elle.rw_register.check` to the Checker protocol."""

    def __init__(self, consistency_models=("snapshot-isolation",),
                 anomalies=()):
        self.models = tuple(consistency_models)
        self.anomalies = tuple(anomalies)

    def check(self, test, history, opts=None):
        from ..checkers.elle import rw_register, viz  # defers jax init

        opts = opts or {}
        res = rw_register.check(
            history,
            consistency_models=opts.get("consistency-models", self.models),
            anomalies=opts.get("anomalies", self.anomalies))
        if test and test.get("store-dir") is not None:
            viz.viz_for_test(res, test, history)
        return res

    def name(self):
        # the canonical checker name (like AppendChecker's
        # "list-append"): span labels, error attribution, and the
        # shrink probe pool's device classification all key on it
        return "rw-register"


def workload(*, key_count: int = 8, min_txn_length: int = 1,
             max_txn_length: int = 4,
             consistency_models=("snapshot-isolation",), anomalies=(),
             rng: Optional[random.Random] = None) -> dict:
    return {
        "generator": gen(key_count=key_count, min_txn_length=min_txn_length,
                         max_txn_length=max_txn_length, rng=rng),
        "checker": WrChecker(consistency_models, anomalies),
    }
