"""Write-skew workload.

The classic snapshot-isolation counterexample as a generator/checker
bundle: keys come in pairs (a "constraint group"); every update txn
reads BOTH keys of its pair and then writes one of them (a
read-then-write, so version inference chains exactly).  Two concurrent
txns that each read the pre-state and write different keys of the same
pair form mutual anti-dependencies — write skew — which the predicate
checker (`checkers/invariants/predicate.py`) finds as a vectorized
mutual-rw pass plus a G2-item cycle with per-edge evidence.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ..checkers import api as checker_api


class _WriteSkewGen:
    """Txns over key pairs (2g, 2g+1): read both, write one (unique
    values); plus plain pair reads."""

    def __init__(self, *, pairs: int = 2, read_frac: float = 0.3,
                 rng: Optional[random.Random] = None):
        self.pairs = pairs
        self.read_frac = read_frac
        self.rng = rng or random.Random()
        self.next_val = 0

    def __call__(self, test, ctx):
        g = self.rng.randrange(self.pairs)
        k1, k2 = 2 * g, 2 * g + 1
        if self.rng.random() < self.read_frac:
            return {"f": "txn", "value": [("r", k1, None), ("r", k2, None)]}
        w = self.rng.choice((k1, k2))
        v = self.next_val
        self.next_val += 1
        return {"f": "txn",
                "value": [("r", k1, None), ("r", k2, None), ("w", w, v)]}


def gen(**opts) -> Any:
    return _WriteSkewGen(**opts)


class WriteSkewChecker(checker_api.Checker):
    """Predicate checker pinned on the write-skew anomaly family."""

    def name(self) -> str:
        return "write-skew"

    def check(self, test, history, opts=None):
        from ..checkers.invariants import predicate

        return predicate.check(history,
                               deadline=(opts or {}).get("deadline"))


def workload(*, pairs: int = 2, read_frac: float = 0.3,
             rng: Optional[random.Random] = None) -> Dict[str, Any]:
    return {
        "generator": gen(pairs=pairs, read_frac=read_frac, rng=rng),
        "checker": WriteSkewChecker(),
        "workload-kind": "write-skew",
    }
