"""Linearizable register workload.

Equivalent of the reference's
`jepsen/src/jepsen/tests/linearizable_register.clj` (SURVEY.md §2.6):
read / write / cas ops against one register, checked for linearizability by
the Knossos-equivalent search (`BASELINE.json:7`'s etcd-register shape).
"""

from __future__ import annotations

import random
from typing import Any, Optional

from ..checkers import api as checker_api
from ..models import cas_register


class _RegisterGen:
    def __init__(self, *, values: int = 5, rng: Optional[random.Random] = None):
        self.rng = rng or random.Random()
        self.values = values

    def __call__(self, test, ctx):
        r = self.rng.random()
        if r < 1 / 3:
            return {"f": "read", "value": None}
        if r < 2 / 3:
            return {"f": "write", "value": self.rng.randrange(self.values)}
        return {"f": "cas", "value": [self.rng.randrange(self.values),
                                      self.rng.randrange(self.values)]}


def gen(**opts) -> Any:
    return _RegisterGen(**opts)


def workload(*, values: int = 5, algorithm: str = "auto",
             rng: Optional[random.Random] = None) -> dict:
    return {
        "generator": gen(values=values, rng=rng),
        "checker": checker_api.Linearizable(model=cas_register(),
                                            algorithm=algorithm),
    }
