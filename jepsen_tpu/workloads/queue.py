"""Queue workload: enqueue/dequeue with a final drain.

Equivalent of the reference's queue workloads (SURVEY.md §2.6, built-in
`checker/queue` and `total-queue`): clients enqueue unique values and
dequeue concurrently; the final generator drains.  `total-queue` semantics:
every enqueued value should be dequeued exactly once (lost = enqueued-ok
never dequeued, duplicated = dequeued twice, phantom = dequeued but never
enqueued).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Optional

from ..checkers import api as checker_api
from ..generator import core as g


class _QueueGen:
    def __init__(self, *, dequeue_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.counter = itertools.count()
        self.dequeue_frac = dequeue_frac
        self.rng = rng or random.Random()

    def __call__(self, test, ctx):
        if self.rng.random() < self.dequeue_frac:
            return {"f": "dequeue", "value": None}
        return {"f": "enqueue", "value": next(self.counter)}


def gen(**opts) -> Any:
    return _QueueGen(**opts)


def drain(n: int = 32) -> Any:
    """Final drain: keep dequeuing until empty (bounded; a bare map
    generator emits once, so repeat it)."""
    return g.clients(g.limit(n, g.repeat({"f": "dequeue", "value": None})))


def workload(*, total: bool = True, drain_ops: int = 64,
             rng: Optional[random.Random] = None) -> dict:
    return {
        "generator": gen(rng=rng),
        "final-generator": drain(drain_ops),
        "checker": (checker_api.TotalQueueChecker() if total
                    else checker_api.QueueChecker()),
    }
