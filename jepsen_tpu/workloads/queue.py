"""Queue workload: enqueue/dequeue with a final drain.

Equivalent of the reference's queue workloads (SURVEY.md §2.6, built-in
`checker/queue` and `total-queue`): clients enqueue unique values and
dequeue concurrently; the final generator drains.  `total-queue` semantics:
every enqueued value should be dequeued exactly once (lost = enqueued-ok
never dequeued, duplicated = dequeued twice, phantom = dequeued but never
enqueued).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Optional

from ..checkers import api as checker_api
from ..generator import core as g


class _QueueGen:
    def __init__(self, *, dequeue_frac: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.counter = itertools.count()
        self.dequeue_frac = dequeue_frac
        self.rng = rng or random.Random()

    def __call__(self, test, ctx):
        if self.rng.random() < self.dequeue_frac:
            return {"f": "dequeue", "value": None}
        return {"f": "enqueue", "value": next(self.counter)}


def gen(**opts) -> Any:
    return _QueueGen(**opts)


def _is_empty_fail(event: dict) -> bool:
    """Did this completion signal queue-empty?  Only an explicit empty
    error counts — an aborted/transient failed dequeue must NOT end the
    drain (items would be falsely reported lost)."""
    return (event.get("type") == "fail" and event.get("f") == "dequeue"
            and str(event.get("error", "")).lower() in ("empty", "exhausted"))


class _Drain(g.Generator):
    """Dequeue until this thread observes empty.  With no producers left,
    first-empty implies drained."""

    def __init__(self, inner=None, done: bool = False):
        self.inner = inner if inner is not None \
            else g.lift(g.repeat({"f": "dequeue", "value": None}))
        self.done = done

    def op(self, test, ctx):
        if self.done:
            return None
        res = g.next_op(self.inner, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        return (op_, _Drain(gen2, False))

    def update(self, test, ctx, event):
        if _is_empty_fail(event):
            return _Drain(self.inner, True)
        return _Drain(g.gen_update(self.inner, test, ctx, event), self.done)


def drain(n: int = 10_000) -> Any:
    """Final drain: every thread dequeues until it sees empty (n is a
    runaway bound, not the expected drain size)."""
    return g.clients(g.each_thread(g.limit(n, _Drain())))


def workload(*, total: bool = True, fifo: bool = False,
             drain_ops: int = 10_000,
             rng: Optional[random.Random] = None) -> dict:
    if total:
        from ..checkers.queue.fifo import PackedQueueChecker

        checker: Any = PackedQueueChecker(fifo=fifo)
    else:
        checker = checker_api.QueueChecker()
    return {
        "generator": gen(rng=rng),
        "final-generator": drain(drain_ops),
        "checker": checker,
        "workload-kind": "queue",
    }
