"""`shrink(run_dir)` — automated anomaly triage for invalid runs.

The orchestrator: load a stored run whose checker said ``valid?
false``, establish the target anomaly signature with one baseline
re-check, delta-debug the history down through the three structural
phases (:mod:`~.reduce`), re-checking candidates in parallel through
the campaign scheduler (:mod:`~.probe`), and persist the minimal
failing witness plus its explained cycle (:mod:`~.witness`).

Telemetry: one ``shrink`` root span, one ``shrink.round`` child per
probe round carrying phase, candidates tried, ops remaining after the
round, and probe p50/p95 — a telemetric shrink's full reduction history
reads straight out of ``telemetry-shrink.json`` / Perfetto.

Determinism: candidate generation, canonical-order selection among
failing candidates, and the checkers themselves are all deterministic,
so the same stored run shrinks to the identical witness on every
machine — and the witness's *source digest* makes the second shrink of
an unchanged run a pure cache hit (0 probes).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, Optional, Union

from jepsen_tpu import store, telemetry
from jepsen_tpu.history.ops import History

from jepsen_tpu.minimize import probe as probe_mod
from jepsen_tpu.minimize import reduce as reduce_mod
from jepsen_tpu.minimize import witness as witness_mod

logger = logging.getLogger("jepsen.minimize")

__all__ = ["shrink"]


def _load(run_or_dir: Union[str, dict]) -> tuple:
    """(test map, materialized History, run dir)."""
    if isinstance(run_or_dir, str):
        test = store.load(run_or_dir)
        run_dir = os.path.realpath(run_or_dir)
    else:
        test = run_or_dir
        run_dir = store.test_dir(test)
    hist = test.get("history")
    if hist is None:
        raise ValueError(f"run {run_dir} has no stored history")
    if not isinstance(hist, History):
        hist = hist.materialize()
        test["history"] = hist
    return test, hist, run_dir


def shrink(run_or_dir: Union[str, dict], *,
           checker=None,
           rounds: Optional[int] = None,
           probe_deadline_s: Optional[float] = None,
           workers: int = 2,
           device_slots: int = 1,
           host_oracle: bool = False,
           anomalies: Optional[Any] = None,
           force: bool = False) -> Dict[str, Any]:
    """Shrink a stored invalid run to a minimal failing witness.

    Accepts a store run directory or a loaded test map (with a live
    ``"checker"``).  Knobs: `rounds` caps the total probe rounds
    (None = run to 1-minimality), `probe_deadline_s` bounds each
    candidate re-check, `workers`/`device_slots` size the probe pool
    (device-pipeline probes serialize through the slots),
    `host_oracle` probes through the exact host reference checker
    where one exists (shrink candidates are many and small — the
    anti-amortization shape for per-shape jit compiles), `anomalies`
    pins the target to a subset of the baseline's anomaly types (by
    default ANY of them keeps a candidate, so ddmin gravitates to the
    cheapest-to-witness class).  `force` ignores a cached witness.

    Returns the summary dict (also the shape of ``witness.json``):
    ``{"valid?", "ops", "source-ops", "digest", "source-digest",
    "anomaly-types", "anomalies", "rounds", "probes", "cached",
    "paths", ...}``.  A run that is not invalid returns
    ``{"error": "not-invalid", ...}`` without probing further.
    """
    test, hist, run_dir = _load(run_or_dir)
    source_digest = witness_mod.history_digest(hist)
    wanted = {str(a) for a in ([anomalies] if isinstance(anomalies, str)
                               else anomalies or ())}

    if not force:
        cached = witness_mod.load_witness(run_dir)
        if cached is not None and cached.get("source-digest") == \
                source_digest and cached.get("valid?") is False and (
                    not wanted or wanted & set(
                        cached.get("anomaly-types") or ())):
            # a cache hit requires a witness that (a) matches the
            # current history, (b) actually REPRODUCES (a confirm pass
            # that expired/flaked must not be pinned forever), and
            # (c) exhibits one of the requested --anomaly types;
            # anything else falls through and re-shrinks
            logger.info("shrink %s: witness cached (digest %s), no-op",
                        run_dir, cached.get("digest"))
            cached.update({"cached": True, "probes": 0, "rounds": 0,
                           "paths": witness_mod.witness_paths(run_dir)})
            return cached

    chk = checker if checker is not None \
        else probe_mod.resolve_checker(test, hist)
    # the probe checker may be the cheap host twin, but the final
    # confirmation re-check always runs the ORIGINAL checker: only the
    # device pipeline attaches the Explainer's per-edge justifications
    # (explain.py), and the persisted witness must carry them
    confirm_chk = chk
    device = None
    if host_oracle:
        host = probe_mod.host_equivalent(chk)
        if host is not None:
            chk, device = host, False

    own_tel = None
    recorder = None
    tel = telemetry.active()
    if not tel.enabled and telemetry.wanted_for(test):
        own_tel = tel = telemetry.activate()
        # flight-record the shrink session itself (events-shrink.jsonl
        # so the original run's stream is never appended to): round /
        # probe progress is followable live via `cli tail`
        try:
            recorder = telemetry.attach_stream(
                own_tel, run_dir, meta={"name": test.get("name"),
                                        "shrink": True},
                filename=telemetry.stream.SHRINK_EVENTS_FILE)
        except Exception as e:  # noqa: BLE001 — never fail a shrink
            logger.warning("shrink flight recorder unavailable: %s", e)
    try:
        summary = _shrink_run(test, hist, run_dir, chk, confirm_chk,
                              tel, source_digest, rounds,
                              probe_deadline_s, workers, device_slots,
                              device, anomalies)
    finally:
        if recorder is not None:
            recorder.close()
        if own_tel is not None:
            telemetry.deactivate(own_tel)
            try:
                telemetry.write_run(run_dir, own_tel,
                                    meta={"name": test.get("name"),
                                          "shrink": True},
                                    suffix="-shrink")
            except Exception as e:  # noqa: BLE001 — never fail a shrink
                logger.warning("shrink telemetry export failed: %s", e)
    return summary


def _shrink_run(test, hist, run_dir, chk, confirm_chk, tel,
                source_digest, rounds, probe_deadline_s, workers,
                device_slots, device=None, anomalies=None
                ) -> Dict[str, Any]:
    t0 = time.monotonic()
    with tel.span("shrink", ops=len(hist), dir=run_dir) as root:
        pool = probe_mod.ProbePool(
            test, chk, probe_deadline_s=probe_deadline_s,
            workers=workers, device_slots=device_slots, device=device)

        # baseline: confirm the full history reproduces and pin the
        # target anomaly signature (also warms the jit cache at the
        # largest shape, so candidate probes hit compiled programs).
        # UNBOUNDED: the per-probe deadline is sized for small ddmin
        # candidates; the full-history re-check needs the original
        # run's budget or every big invalid run would be refused
        with tel.span("shrink.baseline") as bsp:
            base = pool.check_history(hist, bounded=False)
            bsp.set_attr(valid=base.get("valid?"))
        if base.get("valid?") is not False:
            root.set_attr(outcome="not-invalid")
            logger.warning("shrink %s: baseline re-check is %r, nothing "
                           "to shrink", run_dir, base.get("valid?"))
            return {"valid?": base.get("valid?"), "error": "not-invalid",
                    "checker": probe_mod._name(chk),
                    "source-digest": source_digest, "probes": 1}
        target = sorted(base.get("anomaly-types") or ())
        if anomalies:
            wanted = {str(a) for a in ([anomalies] if isinstance(
                anomalies, str) else anomalies)}
            hit = sorted(set(target) & wanted)
            if not hit:
                root.set_attr(outcome="target-absent")
                return {"valid?": False, "error": "target-absent",
                        "anomaly-types": target, "requested": sorted(
                            wanted), "source-digest": source_digest,
                        "probes": 1}
            target = hit
        pool.target = frozenset(target)
        root.set_attr(target=target)

        # the reduction: per-round spans carry phase/candidates, and
        # the _note callback back-fills ops-remaining + improvement
        # (span attrs stay writable until export)
        last_span = {}

        def probe_batch(phase: str, cands) -> list:
            with tel.span("shrink.round", phase=phase,
                          candidates=len(cands)) as sp:
                before = len(pool.durations_s)
                res = pool.probe_batch(phase, cands)
                lat = sorted(pool.durations_s[before:])
                if lat:
                    sp.set_attr(
                        probe_p50_s=probe_mod.quantile(lat, 0.50),
                        probe_p95_s=probe_mod.quantile(lat, 0.95))
                last_span["sp"] = sp
                return res

        def on_round(st: reduce_mod.RoundStats) -> None:
            sp = last_span.get("sp")
            if sp is not None:
                sp.set_attr(ops_remaining=st.ops_remaining,
                            improved=st.improved)
            # the span-close event has already streamed by the time
            # these attrs land, so round progress gets its own event
            telemetry.stream_event(
                "shrink-round", phase=st.phase, candidates=st.candidates,
                ops_remaining=st.ops_remaining, improved=st.improved)

        units = reduce_mod.units_of(hist)
        reducer = reduce_mod.Reducer(probe_batch=probe_batch,
                                     max_rounds=rounds,
                                     on_round=on_round)
        minimal = reducer.run(units)
        wit = reduce_mod.build_history(minimal)

        # final confirmation re-check through the ORIGINAL checker: the
        # full result — explained cycles included — goes into
        # witness.json verbatim (the witness is tiny, so one device
        # check is cheap even when probing ran on the host twin)
        confirm_pool = pool if confirm_chk is chk else \
            probe_mod.ProbePool(test, confirm_chk,
                                probe_deadline_s=probe_deadline_s)
        with tel.span("shrink.confirm", ops=len(wit),
                      checker=probe_mod._name(confirm_chk)):
            final = confirm_pool.check_history(wit, bounded=False)

        meta = {
            "source-digest": source_digest,
            "source-ops": len(hist),
            # the surviving fault-window set (nemesis-schedule ddmin):
            # every window still in the witness, with its op indices —
            # digest-stable at any worker count like the ops themselves
            "fault-windows": getattr(reducer, "windows_meta", []),
            "valid?": final.get("valid?"),
            "anomaly-types": sorted(final.get("anomaly-types") or ()),
            "target": target,
            "anomalies": final.get("anomalies") or {},
            "checker": probe_mod._name(confirm_chk),
            "probe-checker": probe_mod._name(chk),
            "rounds": reducer.rounds,
            "probes": pool.n_probes + 2,  # + baseline + confirm
            "phases": [{"phase": s.phase, "candidates": s.candidates,
                        "ops-remaining": s.ops_remaining,
                        "improved": s.improved}
                       for s in reducer.history],
            "wall_s": round(time.monotonic() - t0, 3),
            **pool.latency_quantiles(),
        }
        paths = witness_mod.save_witness(run_dir, wit, meta)
        root.set_attr(witness_ops=len(wit), rounds=reducer.rounds,
                      probes=meta["probes"])
        logger.info("shrink %s: %d ops -> %d ops in %d rounds "
                    "(%d probes, %.1fs); anomalies %s", run_dir,
                    len(hist), len(wit), reducer.rounds, meta["probes"],
                    meta["wall_s"], meta["anomaly-types"])
        return {**meta, "digest": witness_mod.history_digest(wit),
                "ops": len(wit), "cached": False, "paths": paths,
                "witness-history": wit}
