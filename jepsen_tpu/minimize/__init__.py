"""Automated anomaly triage (ISSUE 4): shrink invalid runs to minimal
failing witnesses.

An invalid verdict over a 100k-op history is not actionable on its own;
what a human debugs is the 6-op core of the cycle (the Elle insight:
minimal witnesses are what make anomaly reports usable).  This package
delta-debugs (ddmin, Zeller & Hildebrandt TSE '02) any stored run whose
checker returned ``valid? false`` down to a minimal sub-history that
STILL fails with the same anomaly class:

- :mod:`~.reduce`  — the ddmin engine over closure-safe invoke/ok
  units, with structure-aware phases (drop processes → project keys →
  ddmin op ranges);
- :mod:`~.probe`   — candidate re-checks through the original checker,
  fanned out in parallel via the campaign scheduler (device probes
  serialized through DeviceSlots), each under a per-probe Deadline;
- :mod:`~.witness` — the persisted ``witness.jsonl`` + ``witness.json``
  (explained cycle, stable digests; re-shrinking an unchanged run is a
  cache hit);
- :mod:`~.core`    — the :func:`shrink` orchestrator with per-round
  telemetry spans.

Surfaces: ``cli shrink <run-dir>``, the campaign spec key
``"shrink": true`` (invalid cells get a witness column), and the web
``/run/<rel>/witness`` page.  See ``docs/MINIMIZE.md``.
"""

from jepsen_tpu.minimize.core import shrink
from jepsen_tpu.minimize.probe import ProbePool, resolve_checker
from jepsen_tpu.minimize.reduce import (
    Reducer,
    Unit,
    build_history,
    units_of,
)
from jepsen_tpu.minimize.witness import (
    history_digest,
    load_witness,
    save_witness,
    witness_paths,
)

__all__ = [
    "shrink", "ProbePool", "resolve_checker", "Reducer", "Unit",
    "build_history", "units_of", "history_digest", "load_witness",
    "save_witness", "witness_paths",
]
