"""Candidate re-checking — the shrinker's execution engine.

Each ddmin candidate is a re-closed sub-history that must be run back
through the SAME checker that judged the original run, under a
per-probe :class:`resilience.Deadline` (a pathological candidate must
cost at most ``probe_deadline_s``, never the whole shrink budget) and
the `device_call` guard the checkers already wrap their device seams in
(transient XLA flakes retry, persistent failures degrade to the host
oracle — a degraded probe still yields a usable verdict).

Fan-out reuses the campaign layer's machinery wholesale: candidates of
one round become throwaway :class:`~jepsen_tpu.campaign.plan.RunSpec`\\s
executed by :class:`~jepsen_tpu.campaign.scheduler.Scheduler` — device
-pipeline probes (elle list-append / rw-register, knossos device WGL)
serialize through its :class:`DeviceSlots` exactly like campaign cells
(one jax runtime), while host-only probes fill all workers.  A probe
that crashes out of its retries comes back as an attributable
``valid? unknown`` record, which the shrinker conservatively treats as
"does not reproduce" — a flaky probe can cost minimality, never
soundness.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu import telemetry
from jepsen_tpu.campaign.plan import RunSpec
from jepsen_tpu.campaign.scheduler import Scheduler
from jepsen_tpu.checkers import api as checker_api
from jepsen_tpu.history.ops import History

from jepsen_tpu.minimize.reduce import Unit, build_history
from jepsen_tpu.telemetry import export as tel_export

__all__ = ["resolve_checker", "is_device_checker", "host_equivalent",
           "ProbePool"]

#: checker name() values whose check() dispatches to the device
#: pipelines — probes of these serialize through DeviceSlots
DEVICE_CHECKER_NAMES = frozenset({
    "list-append", "rw-register", "Linearizable", "QueueChecker",
    "bank", "long-fork", "write-skew", "session",
    "kafka", "total-queue",
})

#: workload-kind (stamped into test maps by the workload bundles) ->
#: (workloads submodule, checker class): the declarative dispatch for
#: stored runs whose checker object didn't survive serialization
_KIND_CHECKERS = {
    "bank": ("bank", "BankChecker"),
    "long-fork": ("long_fork", "LongForkChecker"),
    "write-skew": ("write_skew", "WriteSkewChecker"),
    "session": ("session", "SessionChecker"),
    "kafka": ("..checkers.queue.kafka", "PackedKafkaChecker"),
    "queue": ("..checkers.queue.fifo", "PackedQueueChecker"),
}


def _wl_checker(mod: str, cls: str):
    import importlib

    name = (f"jepsen_tpu.{mod[2:]}" if mod.startswith("..")
            else f"jepsen_tpu.workloads.{mod}")
    m = importlib.import_module(name)
    return getattr(m, cls)()


def resolve_checker(test: Optional[dict], history: History
                    ) -> checker_api.Checker:
    """Rebuild a checker for a stored run.

    Stored tests persist checker objects only as ``"§obj"``
    placeholders, so re-checking needs a fresh instance.  A live
    checker on the test map wins; then the test's ``workload-kind``
    stamp (the invariants workloads carry one); otherwise the
    history's own shape decides (the same dispatch the workloads
    encode): list-append txns → the elle list-append pipeline,
    rw-register txns → rw-register, read/write/cas registers → knossos
    linearizability, transfer/whole-state-read ops → bank."""
    chk = (test or {}).get("checker")
    if chk is not None and hasattr(chk, "check"):
        return chk
    kind = (test or {}).get("workload-kind")
    if kind in _KIND_CHECKERS:
        return _wl_checker(*_KIND_CHECKERS[kind])
    # shape scan: distinctive markers (transfer ops, dict-valued
    # snapshot reads, txn mop kinds) decide immediately; bare register
    # reads only RECORD register shape — a bank history whose first
    # client op happens to be a read must still reach its transfer ops
    register_seen = False
    for op in history:
        if not op.is_client_op():
            continue
        if op.f == "transfer":
            return _wl_checker(*_KIND_CHECKERS["bank"])
        if op.f == "read" and isinstance(op.value, dict):
            return _wl_checker(*_KIND_CHECKERS["bank"])
        if op.f in ("send", "poll", "subscribe", "assign"):
            return _wl_checker(*_KIND_CHECKERS["kafka"])
        if op.f in ("enqueue", "dequeue"):
            return _wl_checker(*_KIND_CHECKERS["queue"])
        if op.f == "txn" and isinstance(op.value, (list, tuple)):
            for m in op.value:
                if not (isinstance(m, (list, tuple)) and m):
                    continue
                if m[0] == "append":
                    from jepsen_tpu.workloads.append import AppendChecker

                    return AppendChecker()
                if m[0] == "w":
                    from jepsen_tpu.workloads.wr import WrChecker

                    return WrChecker()
                if m[0] in ("send", "poll"):
                    return _wl_checker(*_KIND_CHECKERS["kafka"])
        if op.f in ("write", "cas"):
            return checker_api.Linearizable()
        if op.f == "read":
            register_seen = True
    if register_seen:
        return checker_api.Linearizable()
    raise ValueError(
        "cannot infer a checker from this history's op shapes; "
        "pass one explicitly (shrink(..., checker=...))")


def is_device_checker(chk: checker_api.Checker) -> bool:
    try:
        return chk.name() in DEVICE_CHECKER_NAMES
    except Exception:  # noqa: BLE001 — a broken name() is not a device
        return False


def host_equivalent(chk: checker_api.Checker
                    ) -> Optional[checker_api.Checker]:
    """A host-side twin for cheap probing, or None.

    Shrink probes are many and SMALL — the opposite of the shape the
    device pipeline is built for (one big history amortizing its jit
    compiles).  For list-append the exact host oracle is the reference
    the device path is differentially tested against, so probing
    through it cannot change a verdict, only skip per-shape compile
    cost; other checkers have no faster exact twin and probe as-is."""
    if _name(chk) == "list-append":
        from jepsen_tpu.checkers.elle import oracle

        models = tuple(getattr(chk, "models", ("serializable",)))
        anomalies = tuple(getattr(chk, "anomalies", ()))

        def fn(test, history, opts):
            return oracle.check(history, models, anomalies,
                                deadline=(opts or {}).get("deadline"))

        return checker_api.FnChecker(fn, "list-append-host")
    if _name(chk) == "rw-register":
        # the rw host path (use_device=False) IS the oracle the fused
        # device pipeline is differentially tested against — probing
        # through it cannot change a verdict, only skip the per-shape
        # jit compile every small ddmin candidate would otherwise pay
        from jepsen_tpu.checkers.elle import rw_register

        rw_models = tuple(getattr(chk, "models", ("snapshot-isolation",)))
        rw_anoms = tuple(getattr(chk, "anomalies", ()))

        def rw_fn(test, history, opts):
            return rw_register.check(
                history, consistency_models=rw_models,
                anomalies=rw_anoms, use_device=False,
                deadline=(opts or {}).get("deadline"))

        return checker_api.FnChecker(rw_fn, "rw-register-host")
    if _name(chk) == "bank":
        # the invariants checkers' use_device=False path IS their host
        # oracle twin (same arrays, numpy instead of jnp) — probing
        # through it skips the per-candidate device dispatch
        from jepsen_tpu.checkers.invariants import bank as inv_bank

        neg_ok = bool(getattr(chk, "negative_ok", False))

        def bank_fn(test, history, opts):
            return inv_bank.check(history, test, use_device=False,
                                  negative_balances_ok=neg_ok,
                                  deadline=(opts or {}).get("deadline"))

        return checker_api.FnChecker(bank_fn, "bank-host")
    if _name(chk) in ("long-fork", "write-skew"):
        from jepsen_tpu.checkers.invariants import predicate

        def pred_fn(test, history, opts):
            return predicate.check(history, use_device=False,
                                   deadline=(opts or {}).get("deadline"))

        return checker_api.FnChecker(pred_fn, _name(chk) + "-host")
    if _name(chk) == "session":
        from jepsen_tpu.checkers.invariants import session as inv_sess

        guarantees = getattr(chk, "guarantees", None)

        def sess_fn(test, history, opts):
            kw = {"guarantees": guarantees} if guarantees else {}
            return inv_sess.check(history, use_device=False,
                                  deadline=(opts or {}).get("deadline"),
                                  **kw)

        return checker_api.FnChecker(sess_fn, "session-host")
    if _name(chk) == "kafka":
        # the packed kafka checker's use_device=False path is the host
        # oracle twin (same packing, numpy reductions) — exact, minus
        # the per-candidate device dispatch
        from jepsen_tpu.checkers.queue import kafka as q_kafka

        def kafka_fn(test, history, opts):
            return q_kafka.check(history, test, use_device=False,
                                 deadline=(opts or {}).get("deadline"))

        return checker_api.FnChecker(kafka_fn, "kafka-host")
    if _name(chk) == "total-queue":
        from jepsen_tpu.checkers.queue import fifo as q_fifo

        want_fifo = bool(getattr(chk, "fifo", False))

        def tq_fn(test, history, opts):
            return q_fifo.check(history, test, fifo=want_fifo,
                                use_device=False,
                                deadline=(opts or {}).get("deadline"))

        return checker_api.FnChecker(tq_fn, "total-queue-host")
    return None


class ProbePool:
    """Runs batches of candidate sub-histories through the checker.

    One pool per shrink: holds the scheduler configuration (workers,
    device slots, per-probe deadline), the target-anomaly signature,
    and the probe tallies (count, durations) the orchestrator turns
    into per-round telemetry attrs.
    """

    def __init__(self, test: dict, chk: checker_api.Checker, *,
                 target: Sequence[str] = (),
                 probe_deadline_s: Optional[float] = None,
                 workers: int = 2, device_slots: int = 1,
                 device: Optional[bool] = None):
        self.test = test
        self.checker = chk
        self.target = frozenset(target)
        self.probe_deadline_s = probe_deadline_s
        self.workers = max(1, int(workers))
        self.device = is_device_checker(chk) if device is None \
            else bool(device)
        self.slots = max(1, int(device_slots))
        self.n_probes = 0
        self.durations_s: List[float] = []
        self._seq = 0

    # -- verdict interpretation ---------------------------------------------

    def reproduces(self, result: Dict[str, Any]) -> bool:
        """Does a probe result still show the target anomaly?  Invalid
        AND (no target pinned, or anomaly classes overlap).  Unknowns
        (deadline-expired, crashed probes) never count: the shrinker
        may only keep a candidate it POSITIVELY re-confirmed, else the
        witness could stop reproducing."""
        if result.get("valid?") is not False:
            return False
        if not self.target:
            return True
        return bool(self.target & set(result.get("anomaly-types") or ()))

    # -- probing ------------------------------------------------------------

    def check_history(self, h: History, *,
                      bounded: bool = True) -> Dict[str, Any]:
        """One candidate through check_safe: the per-probe Deadline is
        created by check_safe from opts["time-limit"]; the checkers'
        own device_call guards pick it up from there.  `bounded=False`
        skips the per-probe deadline — the baseline re-check of the
        FULL history (which legitimately needs the original run's
        budget) and the final confirm must not be refused by a budget
        sized for small ddmin candidates."""
        opts: Dict[str, Any] = {}
        if bounded and self.probe_deadline_s is not None:
            opts["time-limit"] = float(self.probe_deadline_s)
        # probes must not re-render per-run artifacts into the store
        # dir on every candidate (blank the store-dir the viz hooks key
        # on), and must NOT replay the run's own fault plan: the
        # anomaly lives in the HISTORY, and a chaos plan's shared call
        # counter advanced by parallel probes would make verdicts
        # scheduling-dependent.  A process-installed/env plan (the
        # degradation-drill idiom) still applies.
        t = {k: v for k, v in self.test.items()
             if k not in ("store-dir", "faults", "faults-plan")}
        return checker_api.check_safe(self.checker, t, h, opts)

    def probe_batch(self, phase: str, candidates: List[List[Unit]]
                    ) -> List[bool]:
        """Probe every candidate of one round in parallel; returns the
        reproduces-flags in candidate order (deterministic regardless
        of scheduling).  `phase` is the reduction phase label (the
        orchestrator wraps this in a per-round telemetry span)."""
        if not candidates:
            return []
        base = self._seq + 1
        self._seq += len(candidates)
        specs = [RunSpec(run_id=f"probe-{base + i}", campaign="minimize",
                         workload="probe", seed=0, device=self.device)
                 for i in range(len(candidates))]
        histories = [build_history(c) for c in candidates]
        results: Dict[str, Dict[str, Any]] = {}

        def execute(rs: RunSpec) -> Dict[str, Any]:
            i = int(rs.run_id.rsplit("-", 1)[1]) - base
            t0 = time.perf_counter()
            res = self.check_history(histories[i])
            dt = time.perf_counter() - t0
            telemetry.registry().histogram(
                "shrink-probe-duration-s",
                buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
                checker=_name(self.checker)).observe(dt)
            return {"run": rs.run_id, "valid?": res.get("valid?"),
                    "result": res, "wall_s": dt}

        sched = Scheduler(min(self.workers, len(specs)),
                          device_slots=self.slots)
        for rec in sched.run(specs, execute):
            results[rec["run"]] = rec
        out: List[bool] = []
        for rs in specs:
            rec = results.get(rs.run_id) or {}
            self.n_probes += 1
            if "wall_s" in rec:
                self.durations_s.append(float(rec["wall_s"]))
            out.append(self.reproduces(rec.get("result") or rec))
        return out

    # -- probe latency aggregates (telemetry attrs) -------------------------

    def latency_quantiles(self) -> Dict[str, float]:
        if not self.durations_s:
            return {}
        s = sorted(self.durations_s)
        return {"probe_p50_s": quantile(s, 0.50),
                "probe_p95_s": quantile(s, 0.95)}


def quantile(sorted_vals: List[float], p: float) -> float:
    """THE quantile rule for probe durations — delegates to the shared
    telemetry formula (`export.quantile`, also behind `trace --top`'s
    p95 column) so the per-round span attrs, the persisted witness
    meta, and the trace tables can never disagree."""
    return round(tel_export.quantile(sorted_vals, p), 4)


def _name(chk: checker_api.Checker) -> str:
    try:
        return chk.name()
    except Exception:  # noqa: BLE001
        return type(chk).__name__
