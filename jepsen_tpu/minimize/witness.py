"""Minimal-witness persistence.

Two artifacts land in the run's store dir when a shrink completes:

- ``witness.jsonl`` — the minimal failing sub-history, one op per line
  (the same codec/shape as ``history.json``, so every existing loader
  and differ applies);
- ``witness.json``  — the metadata: content digests (witness + the
  source history it was shrunk from), op/txn counts, the surviving
  anomaly types, the re-check's full anomaly map — including the
  explained cycles whose edges carry the elle Explainer's per-edge
  justification (key, values, the "why" sentence; see
  ``checkers/elle/explain.py``) — and the shrink run's stats (rounds,
  probes, probe latency quantiles).

The **source digest** is what makes re-shrinking a no-op: ``shrink``
compares the stored ``source-digest`` against the current history's
digest and returns the cached witness instantly when they match — a
campaign that auto-shrinks on every generation pays for each distinct
failure once.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Iterable, Optional

from jepsen_tpu.history.ops import History, Op
from jepsen_tpu.store import codec

__all__ = ["history_digest", "save_witness", "load_witness",
           "witness_paths", "WITNESS_META", "WITNESS_OPS"]

WITNESS_META = "witness.json"
WITNESS_OPS = "witness.jsonl"


def history_digest(history: Iterable[Op], n: int = 16) -> str:
    """Content digest of a history: op dicts, canonical JSON, in
    order.  Index-independent fields only would be wrong here — the
    interleaving IS the anomaly — so the full dict (index, time,
    process, type, f, value, error) feeds the hash."""
    h = hashlib.sha256()
    for op in history:
        d = op.to_dict() if hasattr(op, "to_dict") else dict(op)
        h.update(json.dumps(d, sort_keys=True, default=str).encode())
        h.update(b"\n")
    return h.hexdigest()[:n]


def witness_paths(run_dir: str) -> Dict[str, str]:
    return {"meta": os.path.join(run_dir, WITNESS_META),
            "ops": os.path.join(run_dir, WITNESS_OPS)}


def save_witness(run_dir: str, witness: History,
                 meta: Dict[str, Any]) -> Dict[str, str]:
    """Persist both artifacts; returns their paths.  `meta` is written
    verbatim plus the witness digest/op count (the caller supplies
    source-digest, anomalies, stats)."""
    paths = witness_paths(run_dir)
    os.makedirs(run_dir, exist_ok=True)
    with open(paths["ops"], "w") as f:
        for op in witness:
            f.write(codec.dumps(op.to_dict()).decode() + "\n")
    doc = {
        "version": 1,
        "digest": history_digest(witness),
        "ops": len(witness),
        **meta,
    }
    tmp = paths["meta"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(_jsonable(doc), f, indent=1, sort_keys=True)
    os.replace(tmp, paths["meta"])
    return paths


def load_witness(run_dir: str) -> Optional[Dict[str, Any]]:
    """Load a stored witness: the meta doc with ``"history"`` attached
    (the re-closed History from witness.jsonl).  None when absent or
    unreadable — a corrupt witness just means re-shrinking."""
    paths = witness_paths(run_dir)
    if not (os.path.exists(paths["meta"]) and os.path.exists(paths["ops"])):
        return None
    try:
        with open(paths["meta"]) as f:
            doc = json.load(f)
        ops = []
        with open(paths["ops"], "rb") as f:
            for line in f:
                if line.strip():
                    # the codec, not json.loads: save_witness writes
                    # codec-tagged dicts (tuples, int-keyed poll maps)
                    ops.append(Op.from_dict(codec.loads(line)))
    except (OSError, ValueError, KeyError):
        return None
    doc["history"] = History(ops, reindex=False)
    return doc


def _jsonable(v: Any) -> Any:
    """Same best-effort coercion rule as telemetry export: a witness
    save must never crash on a numpy scalar inside an anomaly map."""
    from jepsen_tpu.telemetry.export import _jsonable as tj

    return tj(v)
