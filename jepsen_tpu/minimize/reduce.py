"""Delta debugging over histories — the reduction engine.

Classic ddmin (Zeller & Hildebrandt, "Simplifying and Isolating
Failure-Inducing Input", TSE '02) specialized for Jepsen histories.
The unit of reduction is never a single op: dropping an ``ok`` without
its ``invoke`` would leave an orphaned completion, so the engine works
over :class:`Unit`\\s — invoke/completion *pairs* (plus lone infos /
unpaired tails as single-op units) — and every candidate sub-history is
re-closed by construction (`build_history` keeps original op order and
reindexes densely).

Reduction runs in three structure-aware phases, cheapest first, exactly
because a history has exploitable structure a flat byte-ddmin lacks:

1. **processes** — drop every op of one process at a time (a whole
   worker's timeline is the coarsest irrelevant chunk);
2. **keys** — project keys away from transactional mop lists
   (`elle`-style independence: an anomaly on keys {x, y} survives the
   removal of every other key's mops);
3. **ops** — classic ddmin over the remaining units (subsets, then
   complements, doubling granularity), which ends 1-minimal: no single
   remaining unit can be removed.

Every phase asks the same question — "does this candidate still
reproduce the anomaly?" — through a caller-supplied *batch* probe
callback ``probe_batch(list[list[Unit]]) -> list[bool]``, so all
candidates of one round fan out in parallel (the campaign scheduler is
the execution engine, see :mod:`~.probe`) while the *choice* among
failing candidates stays canonical-order deterministic: same history +
same probe verdicts → same witness, regardless of probe completion
order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.history.ops import History, Op

__all__ = ["Unit", "units_of", "build_history", "unit_keys",
           "drop_key", "Reducer", "is_nemesis_unit", "unit_window",
           "fault_windows", "window_descriptors"]

#: the interpreter's nemesis thread id — fault ops carry it as their
#: process (generator/context.NEMESIS_THREAD)
NEMESIS_PROCESS = "nemesis"

#: mop kinds whose middle element is a key (list-append + rw-register
#: transactional values: ["append" k v] / ["w" k v] / ["r" k v-or-nil])
_TXN_MOP_KINDS = ("append", "w", "r")

ProbeBatch = Callable[[str, List[List["Unit"]]], List[bool]]


@dataclass
class Unit:
    """One irreducible chunk of history: an invoke/completion pair, or
    a single unpaired op.  `ops` holds the original Op objects in
    original order; `order` is the first op's original index (the sort
    key that keeps rebuilt histories in real-time order)."""

    ops: Tuple[Op, ...]
    order: int

    @property
    def process(self) -> Any:
        return self.ops[0].process

    def __len__(self) -> int:
        return len(self.ops)


def units_of(history: History) -> List[Unit]:
    """Group a history into closure-safe units via the pair index."""
    paired: Dict[int, int] = {}
    for op in history:
        j = history.pair_index(op.index) if 0 <= op.index < len(history) \
            else -1
        if j >= 0:
            paired[op.index] = j
    units: List[Unit] = []
    seen = set()
    for op in history:
        if op.index in seen:
            continue
        j = paired.get(op.index, -1)
        if j >= 0 and j > op.index:
            seen.add(op.index)
            seen.add(j)
            units.append(Unit(ops=(op, history.get_index(j)),
                              order=op.index))
        elif j < 0:
            seen.add(op.index)
            units.append(Unit(ops=(op,), order=op.index))
    units.sort(key=lambda u: u.order)
    return units


def build_history(units: Sequence[Unit]) -> History:
    """Re-close a candidate: flatten units, restore original op order,
    reindex densely.  Ops are copied so reduction never mutates the
    source history."""
    ops = [op for u in units for op in u.ops]
    ops.sort(key=lambda op: op.index)
    return History([op.with_() for op in ops], reindex=True)


# -- key projection ---------------------------------------------------------

def unit_keys(u: Unit) -> set:
    """Keys touched by a unit's transactional mops (empty for non-txn
    ops — those are untouched by the key phase)."""
    out: set = set()
    for op in u.ops:
        if op.f == "txn" and isinstance(op.value, (list, tuple)):
            for m in op.value:
                if (isinstance(m, (list, tuple)) and len(m) >= 2
                        and m[0] in _TXN_MOP_KINDS):
                    out.add(m[1])
    return out


def drop_key(units: Sequence[Unit], key: Any) -> List[Unit]:
    """Project one key away: filter its mops out of every txn value
    (invoke and completion alike); units whose txns become empty are
    dropped entirely.  Non-txn units pass through untouched."""
    out: List[Unit] = []
    for u in units:
        if key not in unit_keys(u):
            out.append(u)
            continue
        new_ops = []
        empty = False
        for op in u.ops:
            if op.f == "txn" and isinstance(op.value, (list, tuple)):
                mops = [list(m) for m in op.value
                        if not (isinstance(m, (list, tuple)) and
                                len(m) >= 2 and m[0] in _TXN_MOP_KINDS
                                and m[1] == key)]
                if not mops and op.type != "info":
                    empty = True
                new_ops.append(op.with_(value=mops))
            else:
                new_ops.append(op)
        if not empty:
            out.append(Unit(ops=tuple(new_ops), order=u.order))
    return out


# -- fault windows ----------------------------------------------------------

def is_nemesis_unit(u: Unit) -> bool:
    return u.process == NEMESIS_PROCESS


def unit_window(u: Unit) -> Optional[dict]:
    """The window identity a scheduled nemesis op carries (`Op.ext`
    ``"window"``: pos/digest/fault/host, stamped by
    `nemesis.combined.schedule_package`), or None for unscheduled
    fault ops.  This is the **host dimension** of the cross-host
    fault-window ddmin: ops from different hosts' windows never share
    an identity, so each host's window is its own drop candidate and
    the minimal witness records *whose* window mattered."""
    for op in u.ops:
        w = (op.ext or {}).get("window")
        if isinstance(w, dict) and w.get("digest") is not None:
            return w
    return None


_STOP_PREFIXES = ("stop", "heal", "resume", "fast", "reset")


def _win_suffix(f: str) -> str:
    """The fault family a start/stop f belongs to: 'start-skew' and
    'stop-skew' share suffix 'skew', so interleaved windows from
    composed packages pair up correctly."""
    for pre in ("start-", "stop-", "heal-", "resume-", "reset-"):
        if f.startswith(pre):
            return f[len(pre):]
    return f


def fault_windows(nem_units: Sequence[Unit]) -> List[List[int]]:
    """Group nemesis units into fault *windows* (indices into
    `nem_units`, deterministic order).

    Scheduled ops group EXACTLY: units stamped with a window identity
    (`unit_window`) belong to the window keyed by (host, digest) — the
    host dimension — so a merged multi-host history keeps each host's
    instance of the same schedule position as a separate droppable
    window.  Unstamped ops fall back to the suffix-aware heuristic
    mirroring `perf.nemesis_intervals`: a start-like f opens a window;
    a stop/heal-like f closes the open window of the SAME fault family
    (suffix after the start-/stop- prefix), falling back to the most
    recent open window — so composed packages' interleaved windows
    (start-skew, start-partition, stop-skew, stop-partition) pair
    correctly.  One-shot faults (``leave-node``, ``bump-clock``, ...)
    join the most recent open window, or stand alone outside any.
    Output order is by first unit index — canonical at any worker
    count."""
    stamped: Dict[tuple, List[int]] = {}
    plain: List[int] = []
    for i, u in enumerate(nem_units):
        w = unit_window(u)
        if w is not None:
            key = (str(w.get("host") or ""), str(w["digest"]))
            stamped.setdefault(key, []).append(i)
        else:
            plain.append(i)
    wins = [sorted(v) for v in stamped.values()]
    sub = [nem_units[i] for i in plain]
    wins.extend([plain[j] for j in w] for w in _heuristic_windows(sub))
    wins.sort(key=lambda w: w[0])
    return wins


def _heuristic_windows(nem_units: Sequence[Unit]) -> List[List[int]]:
    """The start/stop pairing heuristic over unstamped nemesis units
    (indices into `nem_units`)."""
    wins: List[List[int]] = []
    open_wins: List[tuple] = []  # (suffix, window) in open order
    for i, u in enumerate(nem_units):
        f = str(u.ops[0].f or "")
        is_stop = f.startswith(_STOP_PREFIXES)
        if is_stop:
            sfx = _win_suffix(f)
            hit = next((j for j in range(len(open_wins) - 1, -1, -1)
                        if open_wins[j][0] == sfx),
                       len(open_wins) - 1 if open_wins else None)
            if hit is None:
                wins.append([i])  # orphan heal: its own window
            else:
                _, w = open_wins.pop(hit)
                w.append(i)
                wins.append(w)
        elif f.startswith("start"):
            open_wins.append((_win_suffix(f), [i]))
        elif open_wins:
            open_wins[-1][1].append(i)
        else:
            wins.append([i])  # one-shot fault
    wins.extend(w for _, w in open_wins)  # still open at history end
    return wins


def window_descriptors(nem_units: Sequence[Unit],
                       wins: Sequence[List[int]],
                       kept: Optional[Sequence[str]] = None
                       ) -> List[dict]:
    """The witness-meta shape for a window set: per window, its
    opening f, the original op indices it spans, and the index span;
    scheduled windows add their identity (``pos``/``digest``/``fault``
    from the schedule — host-free, so distributed and single-process
    runs of one spec agree — plus ``host``, the executing host, as
    attribution).  `kept` labels why each window survived reduction
    (``necessary`` / ``overlap`` / ``interaction``)."""
    out = []
    for j, w in enumerate(wins):
        ops = [op.index for i in w for op in nem_units[i].ops]
        d = {
            "f": str(nem_units[w[0]].ops[0].f),
            "ops": sorted(ops),
            "span": [min(ops), max(ops)],
        }
        ident = unit_window(nem_units[w[0]])
        if ident is not None:
            d.update(pos=ident.get("pos"), digest=ident.get("digest"),
                     fault=ident.get("fault"),
                     host=ident.get("host") or None)
        if kept is not None:
            d["kept"] = kept[j]
        out.append(d)
    return out


def _merge(client: Sequence[Unit], nem: Sequence[Unit]) -> List[Unit]:
    return sorted([*client, *nem], key=lambda u: u.order)


# -- the reducer ------------------------------------------------------------

@dataclass
class RoundStats:
    phase: str
    candidates: int
    ops_remaining: int
    improved: bool


@dataclass
class Reducer:
    """Drives the three phases against a batch probe.

    `probe_batch` maps candidate unit lists to "still fails?" booleans;
    `max_rounds` bounds the TOTAL number of probe rounds across phases
    (None = run to 1-minimality); `on_round(RoundStats)` observes
    progress (the telemetry hook)."""

    probe_batch: ProbeBatch
    max_rounds: Optional[int] = None
    on_round: Optional[Callable[[RoundStats], None]] = None
    rounds: int = 0
    probes: int = 0
    history: List[RoundStats] = field(default_factory=list)

    def _budget_left(self) -> bool:
        return self.max_rounds is None or self.rounds < self.max_rounds

    def _probe(self, phase: str, candidates: List[List[Unit]]
               ) -> List[bool]:
        self.rounds += 1
        self.probes += len(candidates)
        # client-phase candidates carry the CURRENT fault schedule
        # along, so fault-sensitive checkers see the same windows in
        # every probe; the fault-windows phase builds its own merges
        nem = getattr(self, "_nemesis", None)
        if nem and phase != "fault-windows":
            candidates = [_merge(c, nem) for c in candidates]
        return self.probe_batch(phase, candidates)

    def _note(self, phase: str, n_cand: int, units: Sequence[Unit],
              improved: bool) -> None:
        st = RoundStats(phase=phase, candidates=n_cand,
                        ops_remaining=sum(len(u) for u in units),
                        improved=improved)
        self.history.append(st)
        if self.on_round is not None:
            self.on_round(st)

    # -- phase 1: processes -------------------------------------------------

    def drop_processes(self, units: List[Unit]) -> List[Unit]:
        """Greedy complement search over processes: each round probes
        "units minus process p" for every remaining process in
        parallel, then keeps the smallest failing complement (ties →
        canonical process order).  Repeats until no process can go."""
        while self._budget_left():
            procs = sorted({u.process for u in units}, key=repr)
            if len(procs) <= 1:
                return units
            cands = [[u for u in units if u.process != p] for p in procs]
            keep = [(p, c) for (p, c) in zip(procs, cands) if c]
            if not keep:
                return units
            res = self._probe("processes", [c for _, c in keep])
            failing = [(len(c), i, c) for i, ((_, c), ok) in
                       enumerate(zip(keep, res)) if ok]
            if not failing:
                self._note("processes", len(keep), units, False)
                return units
            failing.sort()
            units = failing[0][2]
            self._note("processes", len(keep), units, True)
        return units

    # -- phase 2: keys ------------------------------------------------------

    def project_keys(self, units: List[Unit]) -> List[Unit]:
        """Greedy key projection: probe "units with key k projected
        away" for every key in parallel; keep the smallest failing
        projection; repeat."""
        while self._budget_left():
            keys = sorted({k for u in units for k in unit_keys(u)},
                          key=repr)
            if len(keys) <= 1:
                return units
            cands = [(k, drop_key(units, k)) for k in keys]
            keep = [(k, c) for (k, c) in cands if c and c != list(units)]
            if not keep:
                return units
            res = self._probe("keys", [c for _, c in keep])
            failing = [(sum(len(u) for u in c), i, c)
                       for i, ((_, c), ok) in enumerate(zip(keep, res))
                       if ok]
            if not failing:
                self._note("keys", len(keep), units, False)
                return units
            failing.sort(key=lambda t: (t[0], t[1]))
            units = failing[0][2]
            self._note("keys", len(keep), units, True)
        return units

    # -- phase 3: ddmin over op units ---------------------------------------

    def ddmin(self, units: List[Unit]) -> List[Unit]:
        """Classic ddmin: probe the n chunks, then the n complements,
        all in one parallel batch per round; reduce to the FIRST
        failing subset in canonical order; double granularity when
        nothing fails.  Terminates 1-minimal (granularity == length and
        no single-unit removal reproduces)."""
        n = 2
        while len(units) >= 2 and self._budget_left():
            n = min(n, len(units))
            chunks = _split(units, n)
            cands: List[List[Unit]] = list(chunks)
            kinds = [("subset", i) for i in range(len(chunks))]
            if n > 2:
                for i in range(len(chunks)):
                    cands.append([u for j, c in enumerate(chunks)
                                  if j != i for u in c])
                    kinds.append(("complement", i))
            res = self._probe("ops", cands)
            hit = next((k for k, (kind, ok) in
                        enumerate(zip(kinds, res)) if ok), None)
            if hit is not None:
                kind, _ = kinds[hit]
                units = cands[hit]
                n = 2 if kind == "subset" else max(n - 1, 2)
                self._note("ops", len(cands), units, True)
                continue
            self._note("ops", len(cands), units, False)
            if n >= len(units):
                break
            n = min(len(units), 2 * n)
        return units

    # -- phase 4: fault windows ---------------------------------------------

    def reduce_fault_windows(self, client: List[Unit]) -> List[Unit]:
        """Shrink the nemesis schedule alongside the ops: one parallel
        probe round asks, per fault window, whether dropping it still
        reproduces; a window survives only if it is reproduction-
        necessary (fault-sensitive checkers) or it OVERLAPS the minimal
        client ops — the fault the anomaly actually lives inside stays
        as attribution, every other window goes.  A final combined
        probe guards against window-interaction effects (failure keeps
        the whole schedule — conservative, never unsound).  Selection
        is canonical-order deterministic: same history + verdicts →
        same surviving window set at any worker count."""
        nem = list(getattr(self, "_nemesis", ()) or ())
        self.windows_meta: List[dict] = []
        if not nem:
            return client
        wins = fault_windows(nem)
        droppable = [False] * len(wins)
        if self._budget_left():
            cands = []
            for w in wins:
                drop = set(w)
                cands.append(_merge(client,
                                    [u for i, u in enumerate(nem)
                                     if i not in drop]))
            droppable = self._probe("fault-windows", cands)
        lo = min((u.order for u in client), default=0)
        hi = max((max(op.index for op in u.ops) for u in client),
                 default=0)
        verdicts = []  # (window, drop_ok, overlaps)
        for w, drop_ok in zip(wins, droppable):
            ops = [op.index for i in w for op in nem[i].ops]
            verdicts.append((w, drop_ok,
                             min(ops) <= hi and max(ops) >= lo))
        keep: List[List[int]] = []
        reasons: List[str] = []
        for w, drop_ok, overlaps in verdicts:
            if not drop_ok:
                keep.append(w)
                reasons.append("necessary")
            elif overlaps:
                keep.append(w)
                reasons.append("overlap")
        kept = [nem[i] for w in keep for i in w]
        improved = len(kept) < len(nem)
        if improved:
            # the combined interaction guard is mandatory: two windows
            # individually droppable may not be JOINTLY droppable, so
            # an exhausted budget keeps the whole schedule rather than
            # shipping an unconfirmed multi-window drop
            if self._budget_left() and \
                    self._probe("fault-windows",
                                [_merge(client, kept)])[0]:
                pass
            else:
                kept, keep, improved = nem, wins, False
                reasons = ["necessary" if not ok else
                           ("overlap" if ov else "interaction")
                           for _, ok, ov in verdicts]
        self._note("fault-windows", len(wins), _merge(client, kept),
                   improved)
        self._nemesis = kept
        self.windows_meta = window_descriptors(nem, keep, reasons)
        return _merge(client, kept)

    def run(self, units: List[Unit]) -> List[Unit]:
        client = [u for u in units if not is_nemesis_unit(u)]
        self._nemesis: List[Unit] = [u for u in units
                                     if is_nemesis_unit(u)]
        client = self.drop_processes(client)
        client = self.project_keys(client)
        client = self.ddmin(client)
        return self.reduce_fault_windows(client)


def _split(xs: List[Unit], n: int) -> List[List[Unit]]:
    """n near-equal contiguous chunks (ddmin's partition)."""
    k, m = divmod(len(xs), n)
    out, i = [], 0
    for j in range(n):
        size = k + (1 if j < m else 0)
        if size:
            out.append(xs[i:i + size])
        i += size
    return out
