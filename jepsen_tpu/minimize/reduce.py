"""Delta debugging over histories — the reduction engine.

Classic ddmin (Zeller & Hildebrandt, "Simplifying and Isolating
Failure-Inducing Input", TSE '02) specialized for Jepsen histories.
The unit of reduction is never a single op: dropping an ``ok`` without
its ``invoke`` would leave an orphaned completion, so the engine works
over :class:`Unit`\\s — invoke/completion *pairs* (plus lone infos /
unpaired tails as single-op units) — and every candidate sub-history is
re-closed by construction (`build_history` keeps original op order and
reindexes densely).

Reduction runs in three structure-aware phases, cheapest first, exactly
because a history has exploitable structure a flat byte-ddmin lacks:

1. **processes** — drop every op of one process at a time (a whole
   worker's timeline is the coarsest irrelevant chunk);
2. **keys** — project keys away from transactional mop lists
   (`elle`-style independence: an anomaly on keys {x, y} survives the
   removal of every other key's mops);
3. **ops** — classic ddmin over the remaining units (subsets, then
   complements, doubling granularity), which ends 1-minimal: no single
   remaining unit can be removed.

Every phase asks the same question — "does this candidate still
reproduce the anomaly?" — through a caller-supplied *batch* probe
callback ``probe_batch(list[list[Unit]]) -> list[bool]``, so all
candidates of one round fan out in parallel (the campaign scheduler is
the execution engine, see :mod:`~.probe`) while the *choice* among
failing candidates stays canonical-order deterministic: same history +
same probe verdicts → same witness, regardless of probe completion
order.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from jepsen_tpu.history.ops import History, Op

__all__ = ["Unit", "units_of", "build_history", "unit_keys",
           "drop_key", "Reducer"]

#: mop kinds whose middle element is a key (list-append + rw-register
#: transactional values: ["append" k v] / ["w" k v] / ["r" k v-or-nil])
_TXN_MOP_KINDS = ("append", "w", "r")

ProbeBatch = Callable[[str, List[List["Unit"]]], List[bool]]


@dataclass
class Unit:
    """One irreducible chunk of history: an invoke/completion pair, or
    a single unpaired op.  `ops` holds the original Op objects in
    original order; `order` is the first op's original index (the sort
    key that keeps rebuilt histories in real-time order)."""

    ops: Tuple[Op, ...]
    order: int

    @property
    def process(self) -> Any:
        return self.ops[0].process

    def __len__(self) -> int:
        return len(self.ops)


def units_of(history: History) -> List[Unit]:
    """Group a history into closure-safe units via the pair index."""
    paired: Dict[int, int] = {}
    for op in history:
        j = history.pair_index(op.index) if 0 <= op.index < len(history) \
            else -1
        if j >= 0:
            paired[op.index] = j
    units: List[Unit] = []
    seen = set()
    for op in history:
        if op.index in seen:
            continue
        j = paired.get(op.index, -1)
        if j >= 0 and j > op.index:
            seen.add(op.index)
            seen.add(j)
            units.append(Unit(ops=(op, history.get_index(j)),
                              order=op.index))
        elif j < 0:
            seen.add(op.index)
            units.append(Unit(ops=(op,), order=op.index))
    units.sort(key=lambda u: u.order)
    return units


def build_history(units: Sequence[Unit]) -> History:
    """Re-close a candidate: flatten units, restore original op order,
    reindex densely.  Ops are copied so reduction never mutates the
    source history."""
    ops = [op for u in units for op in u.ops]
    ops.sort(key=lambda op: op.index)
    return History([op.with_() for op in ops], reindex=True)


# -- key projection ---------------------------------------------------------

def unit_keys(u: Unit) -> set:
    """Keys touched by a unit's transactional mops (empty for non-txn
    ops — those are untouched by the key phase)."""
    out: set = set()
    for op in u.ops:
        if op.f == "txn" and isinstance(op.value, (list, tuple)):
            for m in op.value:
                if (isinstance(m, (list, tuple)) and len(m) >= 2
                        and m[0] in _TXN_MOP_KINDS):
                    out.add(m[1])
    return out


def drop_key(units: Sequence[Unit], key: Any) -> List[Unit]:
    """Project one key away: filter its mops out of every txn value
    (invoke and completion alike); units whose txns become empty are
    dropped entirely.  Non-txn units pass through untouched."""
    out: List[Unit] = []
    for u in units:
        if key not in unit_keys(u):
            out.append(u)
            continue
        new_ops = []
        empty = False
        for op in u.ops:
            if op.f == "txn" and isinstance(op.value, (list, tuple)):
                mops = [list(m) for m in op.value
                        if not (isinstance(m, (list, tuple)) and
                                len(m) >= 2 and m[0] in _TXN_MOP_KINDS
                                and m[1] == key)]
                if not mops and op.type != "info":
                    empty = True
                new_ops.append(op.with_(value=mops))
            else:
                new_ops.append(op)
        if not empty:
            out.append(Unit(ops=tuple(new_ops), order=u.order))
    return out


# -- the reducer ------------------------------------------------------------

@dataclass
class RoundStats:
    phase: str
    candidates: int
    ops_remaining: int
    improved: bool


@dataclass
class Reducer:
    """Drives the three phases against a batch probe.

    `probe_batch` maps candidate unit lists to "still fails?" booleans;
    `max_rounds` bounds the TOTAL number of probe rounds across phases
    (None = run to 1-minimality); `on_round(RoundStats)` observes
    progress (the telemetry hook)."""

    probe_batch: ProbeBatch
    max_rounds: Optional[int] = None
    on_round: Optional[Callable[[RoundStats], None]] = None
    rounds: int = 0
    probes: int = 0
    history: List[RoundStats] = field(default_factory=list)

    def _budget_left(self) -> bool:
        return self.max_rounds is None or self.rounds < self.max_rounds

    def _probe(self, phase: str, candidates: List[List[Unit]]
               ) -> List[bool]:
        self.rounds += 1
        self.probes += len(candidates)
        return self.probe_batch(phase, candidates)

    def _note(self, phase: str, n_cand: int, units: Sequence[Unit],
              improved: bool) -> None:
        st = RoundStats(phase=phase, candidates=n_cand,
                        ops_remaining=sum(len(u) for u in units),
                        improved=improved)
        self.history.append(st)
        if self.on_round is not None:
            self.on_round(st)

    # -- phase 1: processes -------------------------------------------------

    def drop_processes(self, units: List[Unit]) -> List[Unit]:
        """Greedy complement search over processes: each round probes
        "units minus process p" for every remaining process in
        parallel, then keeps the smallest failing complement (ties →
        canonical process order).  Repeats until no process can go."""
        while self._budget_left():
            procs = sorted({u.process for u in units}, key=repr)
            if len(procs) <= 1:
                return units
            cands = [[u for u in units if u.process != p] for p in procs]
            keep = [(p, c) for (p, c) in zip(procs, cands) if c]
            if not keep:
                return units
            res = self._probe("processes", [c for _, c in keep])
            failing = [(len(c), i, c) for i, ((_, c), ok) in
                       enumerate(zip(keep, res)) if ok]
            if not failing:
                self._note("processes", len(keep), units, False)
                return units
            failing.sort()
            units = failing[0][2]
            self._note("processes", len(keep), units, True)
        return units

    # -- phase 2: keys ------------------------------------------------------

    def project_keys(self, units: List[Unit]) -> List[Unit]:
        """Greedy key projection: probe "units with key k projected
        away" for every key in parallel; keep the smallest failing
        projection; repeat."""
        while self._budget_left():
            keys = sorted({k for u in units for k in unit_keys(u)},
                          key=repr)
            if len(keys) <= 1:
                return units
            cands = [(k, drop_key(units, k)) for k in keys]
            keep = [(k, c) for (k, c) in cands if c and c != list(units)]
            if not keep:
                return units
            res = self._probe("keys", [c for _, c in keep])
            failing = [(sum(len(u) for u in c), i, c)
                       for i, ((_, c), ok) in enumerate(zip(keep, res))
                       if ok]
            if not failing:
                self._note("keys", len(keep), units, False)
                return units
            failing.sort(key=lambda t: (t[0], t[1]))
            units = failing[0][2]
            self._note("keys", len(keep), units, True)
        return units

    # -- phase 3: ddmin over op units ---------------------------------------

    def ddmin(self, units: List[Unit]) -> List[Unit]:
        """Classic ddmin: probe the n chunks, then the n complements,
        all in one parallel batch per round; reduce to the FIRST
        failing subset in canonical order; double granularity when
        nothing fails.  Terminates 1-minimal (granularity == length and
        no single-unit removal reproduces)."""
        n = 2
        while len(units) >= 2 and self._budget_left():
            n = min(n, len(units))
            chunks = _split(units, n)
            cands: List[List[Unit]] = list(chunks)
            kinds = [("subset", i) for i in range(len(chunks))]
            if n > 2:
                for i in range(len(chunks)):
                    cands.append([u for j, c in enumerate(chunks)
                                  if j != i for u in c])
                    kinds.append(("complement", i))
            res = self._probe("ops", cands)
            hit = next((k for k, (kind, ok) in
                        enumerate(zip(kinds, res)) if ok), None)
            if hit is not None:
                kind, _ = kinds[hit]
                units = cands[hit]
                n = 2 if kind == "subset" else max(n - 1, 2)
                self._note("ops", len(cands), units, True)
                continue
            self._note("ops", len(cands), units, False)
            if n >= len(units):
                break
            n = min(len(units), 2 * n)
        return units

    def run(self, units: List[Unit]) -> List[Unit]:
        units = self.drop_processes(units)
        units = self.project_keys(units)
        return self.ddmin(units)


def _split(xs: List[Unit], n: int) -> List[List[Unit]]:
    """n near-equal contiguous chunks (ddmin's partition)."""
    k, m = divmod(len(xs), n)
    out, i = [], 0
    for j in range(n):
        size = k + (1 if j < m else 0)
        if size:
            out.append(xs[i:i + size])
        i += size
    return out
