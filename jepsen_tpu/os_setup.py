"""OS protocol: prepare a node's operating system.

Equivalent of the reference's `jepsen/os.clj` + `os/debian.clj` /
`os/ubuntu.clj` / `os/centos.clj` (SURVEY.md §2.1): an `OS` with
`setup`/`teardown` run on every node before/after the db, typically
installing packages and disabling time sync so clock nemeses work.
"""

from __future__ import annotations

from typing import Sequence

from jepsen_tpu import control
from jepsen_tpu.control.core import RemoteError


class OS:
    def setup(self, test: dict, node: str) -> None:
        pass

    def teardown(self, test: dict, node: str) -> None:
        pass


class Noop(OS):
    """No OS preparation (reference: `os/noop`)."""


noop = Noop()


class Debian(OS):
    """Debian/Ubuntu setup (reference: `os/debian.clj`): apt package
    install, NTP/timesyncd disable (so clock nemeses own the clock)."""

    def __init__(self, packages: Sequence[str] = (),
                 disable_time_sync: bool = True):
        self.packages = list(packages)
        self.disable_time_sync = disable_time_sync

    def install(self, pkgs: Sequence[str]) -> None:
        if not pkgs:
            return
        control.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                      "apt-get", "install", "-y", "--no-install-recommends",
                      *pkgs)

    def setup(self, test, node):
        try:
            control.exec_("apt-get", "update", "-q")
        except RemoteError:
            pass  # stale mirrors shouldn't kill the run; install will retry
        self.install(self.packages)
        if self.disable_time_sync:
            for svc in ("ntp", "systemd-timesyncd", "chrony"):
                control.exec_result("systemctl", "stop", svc)
                control.exec_result("systemctl", "disable", svc)

    def teardown(self, test, node):
        pass


class Ubuntu(Debian):
    """Ubuntu setup (reference: `os/ubuntu.clj`, which extends debian):
    Debian behavior plus Ubuntu-specific background jobs that fight
    nemeses and db installs.  apt-daily/apt-daily-upgrade TIMERS are what
    relaunch unattended-upgrade runs (stopping only the service leaves
    the dpkg-lock contention in place), and snap refreshes are held via
    snapd's own hold — there is no stoppable refresh unit."""

    def setup(self, test, node):
        super().setup(test, node)
        for unit in ("apt-daily.timer", "apt-daily-upgrade.timer",
                     "unattended-upgrades"):
            control.exec_result("systemctl", "stop", unit)
            control.exec_result("systemctl", "disable", unit)
        control.exec_result("snap", "refresh", "--hold")


class Centos(OS):
    """CentOS/RHEL setup (reference: `os/centos.clj`)."""

    def __init__(self, packages: Sequence[str] = (),
                 disable_time_sync: bool = True):
        self.packages = list(packages)
        self.disable_time_sync = disable_time_sync

    def setup(self, test, node):
        if self.packages:
            control.exec_("yum", "install", "-y", *self.packages)
        if self.disable_time_sync:
            for svc in ("ntpd", "chronyd"):
                control.exec_result("systemctl", "stop", svc)
                control.exec_result("systemctl", "disable", svc)

    def teardown(self, test, node):
        pass
