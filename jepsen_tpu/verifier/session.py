"""Incremental list-append verification sessions (ISSUE 7 tentpole).

The batch checkers answer "was this finished history valid?".  A
:class:`VerifierSession` answers the production question instead: ops
stream in (client-appended segments, in history order) and a *rolling*
verdict streams out, recomputed incrementally —

- **packing** reuses :class:`~jepsen_tpu.history.soa.TxnPacker`'s
  chunk-feed path: a segment becomes SoA columns with global txn ids,
  never a whole-history op list;
- **edges** are maintained against a per-key tail index: a new txn
  touches only the keys its mops name, and each touched key re-derives
  its version order / ww / wr / rw edges from that key's own state
  (bounded by per-key activity — real list-append generators rotate
  keys, so a key's read/append set stays small while the session
  grows).  Process and realtime(barrier) edges are append-only by
  construction because segments arrive in history order;
- **cycle detection** re-sweeps only the *dirty region*: every cycle
  that is new since the last sweep must pass through an edge added
  since the last sweep, so the sweep BFS-bounds the search to
  ``reach(new-edge heads) ∩ coreach(new-edge tails)`` per rel
  projection and runs Tarjan + the rel-constrained cycle search only
  there.  Dirty work is batched into device-sized chunks
  (``sweep_chunk``, default the device sweep's ``MAX_K_CAP``) with
  each chunk dispatched through ``resilience.device_call`` — the same
  guard seam (fault injection, transient retries, deadline polls) as
  the device pipelines, so the TPU path amortizes and chaos tooling
  reaches it;
- **the verdict tail is shared**: :func:`oracle.boundary_verdict` is
  the single implementation the batch oracle, the device pipeline, and
  this session all call, so agreement on the anomaly set implies
  agreement on the verdict.

Equality contract (pinned by tests and asserted at :meth:`seal`): for
any op stream, sealing a session yields the same ``valid?`` and
``anomaly-types`` as the batch checker run once over the concatenated
history.  The incremental state is a pure function of the op sequence
— not of its segmentation — which is what makes journal replay
(:mod:`.journal`) reach the identical verdict digest after a crash.

Retraction corner: a later, longer-but-incompatible read can *replace*
a key's inferred version order, invalidating edges derived from the
old order.  Edges are therefore owned per key; a retraction marks the
graph for one full re-sweep (the rare slow path) instead of poisoning
the dirty-region induction.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

import numpy as np

from jepsen_tpu import resilience, telemetry
from jepsen_tpu.checkers.elle import consistency, oracle
from jepsen_tpu.checkers.elle.graph import (
    REL_NAMES,
    REL_PROCESS,
    REL_REALTIME,
    REL_RW,
    REL_WR,
    REL_WW,
    EdgeList,
    find_cycle,
)
from jepsen_tpu.checkers.elle.specs import CYCLE_ANOMALY_SPECS, SPEC_ORDER
from jepsen_tpu.history.ops import Op
from jepsen_tpu.history.soa import (
    _CHUNK_COLS,
    MOP_APPEND,
    MOP_READ,
    TXN_FAIL,
    TXN_INFO,
    TXN_OK,
    PackedTxns,
    TxnPacker,
    _DenseValNames,
)
from jepsen_tpu.resilience import Deadline, DeadlineExceeded, deadline_result

__all__ = ["VerifierSession", "VerdictMismatch", "verdict_digest",
           "iter_packed_segments", "SWEEP_SITE", "INGEST_SITE"]

#: resilience fault/guard sites on the verifier path (FaultPlan targets)
SWEEP_SITE = "verifier.sweep"
INGEST_SITE = "verifier.ingest"

#: default dirty-edge batch per guarded sweep dispatch — the device
#: sweep kernel's backward-edge cap, so host chunks mirror the unit the
#: TPU path amortizes over (import kept lazy-free: the cap is a constant)
SWEEP_CHUNK = 8192


class VerdictMismatch(AssertionError):
    """Sealing found the incremental verdict != the batch verdict —
    an incremental-maintenance bug, never expected in production."""

    def __init__(self, incremental: Dict[str, Any], batch: Dict[str, Any]):
        super().__init__(
            f"incremental verdict {incremental.get('valid?')!r} "
            f"{incremental.get('anomaly-types')} != batch "
            f"{batch.get('valid?')!r} {batch.get('anomaly-types')}")
        self.incremental = incremental
        self.batch = batch


def verdict_digest(verdict: Dict[str, Any]) -> str:
    """Stable digest of the parts of a verdict that replay must
    reproduce bit-identically: the verdict, the anomaly set, and the
    graph shape.  Timestamps and report items (which carry caps and
    wall-clock fields) are deliberately excluded."""
    doc = {
        "valid?": verdict.get("valid?"),
        "anomaly-types": verdict.get("anomaly-types"),
        "txns": verdict.get("txns"),
        "edge-counts": verdict.get("edge-counts"),
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


class _KeyState:
    """Per-key tail index: this key's reads, inferred version order,
    derived edges, and structural reports — everything a touched-key
    recompute needs, with no global scans."""

    __slots__ = ("reads", "order", "edges", "reports")

    def __init__(self) -> None:
        # (rd tuple, txn node, orig op index) for OK reads with a
        # known result (rd may be empty — empty reads still anchor rw)
        self.reads: List[Tuple[Tuple[int, ...], int, int]] = []
        self.order: List[int] = []
        self.edges: Set[Tuple[int, int, int]] = set()
        self.reports: Dict[str, List[Any]] = {}


class VerifierSession:
    """One always-on checking session over a streamed list-append
    history.  Feed segments with :meth:`append_ops` (op dicts / Ops,
    the service path) or :meth:`append_columns` (pre-packed SoA
    columns, the bench path); read :meth:`verdict` any time; call
    :meth:`seal` to run the batch checker over the concatenated
    history and assert incremental/batch equality."""

    def __init__(self, name: str = "session",
                 consistency_models: Sequence[str] = ("serializable",),
                 anomalies: Sequence[str] = (),
                 max_reported: int = 8,
                 sweep_chunk: int = SWEEP_CHUNK,
                 batch_check=None,
                 plan=None):
        self.name = name
        self.consistency_models = tuple(consistency_models)
        self.extra_anomalies = tuple(anomalies)
        self.max_reported = int(max_reported)
        self.sweep_chunk = max(1, int(sweep_chunk))
        self.plan = plan  # pinned FaultPlan for the guarded sweep seam
        # batch_check(PackedTxns) -> result; default = the host oracle
        self._batch_check = batch_check or (
            lambda p: oracle.check(p, self.consistency_models,
                                   self.extra_anomalies,
                                   max_reported=self.max_reported))
        self.want = set(consistency.anomalies_for_models(
            [consistency.canonical(m) for m in self.consistency_models]))
        self.want |= set(self.extra_anomalies)
        self.want |= {"duplicate-appends", "duplicate-elements",
                      "incompatible-order"}
        self._cycle_specs = [s for s in SPEC_ORDER
                             if s in self.want and s in CYCLE_ANOMALY_SPECS]

        # -- ingest state ---------------------------------------------------
        self.packer = TxnPacker("list-append")
        self._mode: Optional[str] = None  # "ops" | "packed"
        self._chunks: List[dict] = []     # retained columns for seal
        self._next_op_index = 0
        self.n_events = 0                 # op positions consumed
        self.n_txns = 0
        self.n_ok = 0
        self.segments = 0
        self.sealed: Optional[Dict[str, Any]] = None
        # packed-mode bookkeeping for seal-time PackedTxns assembly
        self._pk_keys = 0
        self._pk_vals = 0
        self._packed_rd: Optional[np.ndarray] = None

        # -- graph node space (txns and barriers share one dense space) -----
        self._n_nodes = 0
        self._node_orig: List[int] = []   # node -> orig op index (-1 barrier)
        self._node_type: List[int] = []   # node -> txn type (0 = barrier)
        self._txn_node: List[int] = []    # txn id -> node id

        # -- incremental checker state --------------------------------------
        self._writer: Dict[int, int] = {}        # val -> writer node
        self._fail_vals: Set[int] = set()        # vals written by FAIL txns
        self._final_append: Dict[int, bool] = {}
        self._keys: Dict[int, _KeyState] = {}
        self._global_reports: Dict[str, List[Any]] = {}
        self._last_proc: Dict[int, int] = {}     # process -> last ok/info node
        self._barrier_comps: List[int] = []      # ok completion positions
        self._barrier_nodes: List[int] = []

        # -- edge store + sweep state ---------------------------------------
        self._swept: List[np.ndarray] = []       # (n,3) chunks, already swept
        self._pending: List[Tuple[int, int, int]] = []
        self._rebuild = False                    # retraction -> full resweep
        #: monotonic count of sweep commits — the batched sweep's
        #: staleness stamp (len(_swept) won't do: a rebuild resets it)
        self._sweep_epoch = 0
        self._cycle_found: Dict[str, Any] = {}
        self._first_seen: Dict[str, float] = {}
        self._last_names: List[str] = []
        self._edge_counts_cache: Optional[Dict[str, int]] = None

    # ------------------------------------------------------------------ #
    # ingest
    # ------------------------------------------------------------------ #

    def append_ops(self, ops: Iterable[Any]) -> int:
        """Append one segment of ops (dicts or Ops, history order).
        Returns txns completed by this segment."""
        if self._mode == "packed":
            raise ValueError("session already fed packed columns")
        self._mode = "ops"
        seq: List[Op] = []
        for o in ops:
            op = Op.from_dict(o) if isinstance(o, dict) else o
            if op.index is None or op.index < 0:
                op.index = self._next_op_index
            self._next_op_index = max(self._next_op_index, op.index + 1)
            seq.append(op)
        rd_base = self.packer.n_rd_elems
        cols = self.packer.feed(seq)
        return self.append_columns(cols, rd_elems=cols["rd_elems"],
                                   rd_base=rd_base,
                                   n_events=self.packer.pos)

    def append_columns(self, cols: Dict[str, np.ndarray], *,
                       rd_elems: Optional[np.ndarray] = None,
                       rd_base: int = 0,
                       n_events: Optional[int] = None) -> int:
        """Append one segment of packed SoA columns (the TxnPacker
        chunk shape, with GLOBAL txn ids / rd offsets).  ``rd_elems``
        is the array the segment's ``mop_rd_start`` offsets index
        (minus ``rd_base``)."""
        if self._mode is None:
            self._mode = "packed"
        if self._mode == "packed" and rd_base != 0:
            raise ValueError(
                "packed-mode segments must carry global rd offsets "
                "(rd_base == 0, one stable rd_elems array)")
        if rd_elems is None:
            rd_elems = cols.get("rd_elems", np.zeros(0, np.int32))
        n = len(cols["txn_type"])
        with telemetry.span("verifier.append", session=self.name, txns=n):
            self._ingest_segment(cols, rd_elems, rd_base)
        self.segments += 1
        if n_events is not None:
            self.n_events = max(self.n_events, int(n_events))
        else:
            cp = cols["txn_complete_pos"]
            if len(cp):
                self.n_events = max(self.n_events, int(cp[-1]) + 1)
        if self._mode == "packed":
            # rd offsets are global into ONE stable array — keep a
            # reference, never concatenate per-segment copies
            self._packed_rd = np.asarray(rd_elems)
            self._chunks.append({k: v for k, v in cols.items()
                                 if k != "rd_elems"})
            if len(cols["mop_key"]):
                self._pk_keys = max(self._pk_keys,
                                    int(cols["mop_key"].max()) + 1)
            mv = cols["mop_val"]
            if len(mv):
                self._pk_vals = max(self._pk_vals, int(mv.max()) + 1)
            re_ = np.asarray(rd_elems)
            if len(re_):
                self._pk_vals = max(self._pk_vals, int(re_.max()) + 1)
        else:
            self._chunks.append(cols)
        return n

    def _ingest_segment(self, cols, rd_elems, rd_base) -> None:
        tt = np.asarray(cols["txn_type"]).tolist()
        tp = np.asarray(cols["txn_process"]).tolist()
        ti = np.asarray(cols["txn_invoke_pos"]).tolist()
        tc = np.asarray(cols["txn_complete_pos"]).tolist()
        to = (np.asarray(cols["txn_orig_index"]).tolist()
              if "txn_orig_index" in cols else [-1] * len(tt))
        m_txn = np.asarray(cols["mop_txn"]).tolist()
        m_kind = np.asarray(cols["mop_kind"]).tolist()
        m_key = np.asarray(cols["mop_key"]).tolist()
        m_val = np.asarray(cols["mop_val"]).tolist()
        rs_arr = np.asarray(cols["mop_rd_start"])
        rl_arr = np.asarray(cols["mop_rd_len"])
        m_rs = rs_arr.tolist()
        m_rl = rl_arr.tolist()
        # convert only the rd window this segment references — in
        # packed mode rd_elems is the WHOLE global array and a
        # full-array conversion per segment would be O(history) each
        live = (rl_arr > 0) & (rs_arr >= 0)
        if live.any():
            lo = int(rs_arr[live].min())
            hi = int((rs_arr[live] + rl_arr[live]).max())
        else:
            lo = hi = rd_base
        rd_l = np.asarray(rd_elems)[lo - rd_base:hi - rd_base].tolist()
        touched: Set[int] = set()
        mi = 0
        n_m = len(m_txn)
        t_base = self.n_txns
        for i, ttype in enumerate(tt):
            t_global = t_base + i
            node = self._n_nodes
            self._n_nodes += 1
            self._node_orig.append(to[i])
            self._node_type.append(ttype)
            self._txn_node.append(node)
            self.n_txns += 1
            if ttype == TXN_OK:
                self.n_ok += 1
            # this txn's mops (mop_txn ascending, packer layout)
            mops: List[Tuple[int, int, int, Optional[Tuple[int, ...]]]] = []
            while mi < n_m and m_txn[mi] == t_global:
                kind, key, val = m_kind[mi], m_key[mi], m_val[mi]
                rd: Optional[Tuple[int, ...]] = None
                if kind == MOP_READ and m_rl[mi] >= 0:
                    s = m_rs[mi] - lo
                    rd = tuple(rd_l[s:s + m_rl[mi]])
                mops.append((kind, key, val, rd))
                touched.add(key)
                mi += 1
            self._arrive_txn(node, ttype, tp[i], ti[i], tc[i], to[i], mops)
        for k in touched:
            self._recompute_key(k)
        self._edge_counts_cache = None

    def _arrive_txn(self, node, ttype, proc, inv, comp, orig, mops) -> None:
        """Global (non-per-key) arrival work, ported stage by stage from
        the oracle's whole-history passes — each is per-txn local."""
        writer = self._writer
        # writer map + duplicate-appends + final-append flags
        last_per_key: Dict[int, int] = {}
        own_vals: List[int] = []
        for (kind, key, val, _rd) in mops:
            if kind == MOP_APPEND:
                if val in writer:
                    self._report("duplicate-appends", {
                        "value": val,
                        "txns": [self._node_orig[writer[val]], orig]})
                else:
                    writer[val] = node
                    own_vals.append(val)
                    if ttype == TXN_FAIL:
                        self._fail_vals.add(val)
                last_per_key[key] = val
        for v in own_vals:
            self._final_append[v] = False
        for key, val in last_per_key.items():
            if writer.get(val) == node:
                self._final_append[val] = True
        # internal consistency + duplicate elements (ok txns only)
        if ttype == TXN_OK:
            cur: Dict[int, Optional[List[int]]] = {}
            suffix: Dict[int, List[int]] = {}
            for mj, (kind, key, val, rd) in enumerate(mops):
                if kind == MOP_APPEND:
                    if cur.get(key) is not None:
                        cur[key] = cur[key] + [val]
                    else:
                        suffix.setdefault(key, []).append(val)
                else:
                    if rd is None:
                        continue
                    rdl = list(rd)
                    if len(set(rdl)) != len(rdl):
                        self._report("duplicate-elements",
                                     {"op": orig, "mop": mj, "key": key})
                    c = cur.get(key)
                    if c is not None:
                        if rdl != c:
                            self._report("internal",
                                         {"op": orig, "mop": mj,
                                          "expected": c, "got": rdl})
                    else:
                        sfx = suffix.get(key, [])
                        if sfx and (len(rdl) < len(sfx)
                                    or rdl[-len(sfx):] != sfx):
                            self._report("internal",
                                         {"op": orig, "mop": mj,
                                          "expected-suffix": sfx,
                                          "got": rdl})
                    cur[key] = rdl
            # per-key read store (edges + G1 recomputed per key)
            for (kind, key, val, rd) in mops:
                if kind == MOP_READ and rd is not None:
                    self._key(key).reads.append((rd, node, orig))
        # process chain (ok/info only; fail txns chain nowhere)
        if ttype in (TXN_OK, TXN_INFO):
            prev = self._last_proc.get(proc)
            if prev is not None:
                self._pending.append((prev, node, REL_PROCESS))
            self._last_proc[proc] = node
            # realtime in-edge: latest ok completion before our invoke
            b = bisect.bisect_left(self._barrier_comps, inv) - 1
            if b >= 0:
                self._pending.append(
                    (self._barrier_nodes[b], node, REL_REALTIME))
        # realtime barrier for ok completions (arrival order == comp order)
        if ttype == TXN_OK:
            bnode = self._n_nodes
            self._n_nodes += 1
            self._node_orig.append(-1)
            self._node_type.append(0)
            self._pending.append((node, bnode, REL_REALTIME))
            if self._barrier_nodes:
                self._pending.append(
                    (self._barrier_nodes[-1], bnode, REL_REALTIME))
            self._barrier_comps.append(comp)
            self._barrier_nodes.append(bnode)

    def _key(self, k: int) -> _KeyState:
        ks = self._keys.get(k)
        if ks is None:
            ks = self._keys[k] = _KeyState()
        return ks

    def _graph_txn(self, node: int) -> bool:
        return self._node_type[node] in (TXN_OK, TXN_INFO)

    def _report(self, name: str, item: Any) -> None:
        lst = self._global_reports.setdefault(name, [])
        if len(lst) < self.max_reported:
            lst.append(item)

    # ------------------------------------------------------------------ #
    # per-key recompute (the tail index)
    # ------------------------------------------------------------------ #

    def _recompute_key(self, k: int) -> None:
        """Re-derive one key's version order, structural reports, and
        ww/wr/rw edges from that key's own state — the oracle's per-key
        passes, scoped to a single key.  Added edges go dirty; any
        removed edge (a replaced version order) arms the full resweep."""
        ks = self._keys.get(k)
        if ks is None:
            return
        writer = self._writer
        ntype = self._node_type
        # version order: the FIRST longest ok read (max() semantics)
        order: List[int] = []
        for (rd, _t, _o) in ks.reads:
            if len(rd) > len(order):
                order = list(rd)
        reports: Dict[str, List[Any]] = {}

        def rep(name: str, item: Any) -> None:
            lst = reports.setdefault(name, [])
            if len(lst) < self.max_reported:
                lst.append(item)

        compat: List[Tuple[Tuple[int, ...], int]] = []
        for (rd, t, o) in ks.reads:
            if list(rd) != order[:len(rd)]:
                rep("incompatible-order",
                    {"key": k, "read": list(rd), "longest": order, "op": o})
            else:
                compat.append((rd, t))
        for a, b in zip(order[:-1], order[1:]):
            wa, wb = writer.get(a), writer.get(b)
            if (wa is not None and wb is not None
                    and ntype[wa] == TXN_FAIL and ntype[wb] == TXN_OK):
                rep("dirty-update",
                    {"key": k, "aborted-value": a, "committed-value": b,
                     "aborted-writer": self._node_orig[wa],
                     "committed-writer": self._node_orig[wb]})
        # G1a / G1b over this key's reads (writer types known at their
        # arrival; a late writer touches this key and re-triggers us).
        # The per-element G1a scan is gated on the global fail-written
        # value set — almost always empty, and set.isdisjoint is a
        # C-speed pre-check against the O(len(rd)) inner loop
        fail_vals = self._fail_vals
        for (rd, t, o) in ks.reads:
            if not rd:
                continue
            if fail_vals and not fail_vals.isdisjoint(rd):
                for v in rd:
                    if v in fail_vals:
                        w = writer[v]
                        rep("G1a", {"op": o, "value": v,
                                    "writer": self._node_orig[w]})
            last = rd[-1]
            w = writer.get(last)
            if (w is not None and w != t
                    and not self._final_append.get(last, True)):
                rep("G1b", {"op": o, "value": last,
                            "writer": self._node_orig[w]})
        # edges
        edges: Set[Tuple[int, int, int]] = set()
        for a, b in zip(order[:-1], order[1:]):
            wa, wb = writer.get(a), writer.get(b)
            if (wa is not None and wb is not None and wa != wb
                    and self._graph_txn(wa) and self._graph_txn(wb)):
                edges.add((wa, wb, REL_WW))
        for (rd, t) in compat:
            if rd:
                w = writer.get(rd[-1])
                if w is not None and w != t and self._graph_txn(w):
                    edges.add((w, t, REL_WR))
            if len(rd) < len(order):
                nxt = writer.get(order[len(rd)])
                if nxt is not None and nxt != t and self._graph_txn(nxt):
                    edges.add((t, nxt, REL_RW))
        added = edges - ks.edges
        removed = ks.edges - edges
        if removed:
            # a replaced version order retracted edges: the dirty-region
            # induction no longer covers the graph — full resweep
            self._rebuild = True
        self._pending.extend(sorted(added))
        ks.edges = edges
        ks.order = order
        ks.reports = reports

    # ------------------------------------------------------------------ #
    # dirty-region cycle sweep
    # ------------------------------------------------------------------ #

    def _all_edges(self) -> np.ndarray:
        parts = [c for c in self._swept if len(c)]
        if self._pending:
            parts.append(np.asarray(self._pending, dtype=np.int64)
                         .reshape(-1, 3))
        if not parts:
            return np.zeros((0, 3), dtype=np.int64)
        return np.concatenate(parts, axis=0)

    def _compute_rebuilt(self) -> np.ndarray:
        """The whole edge array reconstructed from the per-key sets +
        the append-only process/realtime edges (pure — the caller
        commits it into the swept store only after a successful
        sweep)."""
        stat = [c for c in self._swept if len(c)]
        static = (np.concatenate(stat, axis=0) if stat
                  else np.zeros((0, 3), np.int64))
        # swept chunks may contain per-key edges from former sweeps:
        # keep only process/realtime rows, the truly append-only part
        if len(static):
            static = static[np.isin(static[:, 2],
                                    (REL_PROCESS, REL_REALTIME))]
        pend = (np.asarray(self._pending, np.int64).reshape(-1, 3)
                if self._pending else np.zeros((0, 3), np.int64))
        if len(pend):
            pend = pend[np.isin(pend[:, 2], (REL_PROCESS, REL_REALTIME))]
        keyed = [np.asarray(sorted(ks.edges), np.int64).reshape(-1, 3)
                 for ks in self._keys.values() if ks.edges]
        allp = [p for p in (static, pend, *keyed) if len(p)]
        return (np.concatenate(allp, axis=0) if allp
                else np.zeros((0, 3), np.int64))

    def sweep(self, deadline: Optional[Deadline] = None) -> None:
        """Run the incremental cycle sweep over dirty edges.  Batches
        dirty work into ``sweep_chunk``-sized dispatches, each through
        the resilience guard (fault site ``verifier.sweep``) — expiry
        raises :class:`DeadlineExceeded` to the caller.  Failure-safe:
        dirty state commits only after every chunk succeeded, so an
        injected fault / expired budget leaves the backlog intact for
        the next sweep instead of silently dropping dirtiness."""
        if not self._pending and not self._rebuild:
            return
        with telemetry.span("verifier.sweep", session=self.name,
                            dirty=len(self._pending),
                            rebuild=self._rebuild):
            rebuilding = self._rebuild
            if rebuilding:
                full = self._compute_rebuilt()
                dirty = full
                prev_found = self._cycle_found
                self._cycle_found = {}
            else:
                dirty = np.asarray(self._pending,
                                   np.int64).reshape(-1, 3)
                full = self._all_edges()  # swept + pending
            try:
                ctx = self._sweep_context(full)
                for c0 in range(0, len(dirty), self.sweep_chunk):
                    chunk = dirty[c0:c0 + self.sweep_chunk]
                    t0 = time.perf_counter()
                    resilience.device_call(
                        SWEEP_SITE, self._sweep_chunk, ctx, chunk,
                        deadline, deadline=deadline, plan=self.plan)
                    telemetry.add_phase("sweep_s",
                                        time.perf_counter() - t0)
            except BaseException:
                if rebuilding:
                    # restore the pre-rebuild cache; _rebuild stays
                    # armed, so the next sweep redoes the whole pass
                    self._cycle_found = prev_found
                raise
            if rebuilding:
                self._swept = [full]
                self._rebuild = False
            else:
                self._swept.append(dirty)
            self._pending = []
            self._sweep_epoch += 1
        self._edge_counts_cache = None

    def _sweep_context(self, full: np.ndarray) -> Dict[str, Any]:
        """Per-sweep shared state: the union projection (one rel set
        covers every cycle spec — a spec cycle is strongly connected
        under the union too, and `find_cycle` restricts itself to the
        spec's rels) with its forward/backward CSR adjacency, built
        ONCE and reused by every dirty chunk of this sweep."""
        union: Set[int] = set()
        for name in self._cycle_specs:
            union |= CYCLE_ANOMALY_SPECS[name].rels
        p_mask = np.isin(full[:, 2], list(union)) if len(full) else \
            np.zeros(0, bool)
        src = full[p_mask, 0]
        dst = full[p_mask, 1]
        rel = full[p_mask, 2]
        return {
            "union": union,
            "src": src, "dst": dst, "rel": rel,
            "fwd": _csr(self._n_nodes, src, dst),
            "bwd": _csr(self._n_nodes, dst, src),
        }

    def _sweep_chunk(self, ctx: Dict[str, Any], dirty: np.ndarray,
                     deadline: Optional[Deadline] = None) -> None:
        """Sweep one dirty-edge chunk: bound the search to
        ``reach(dirty heads) ∩ coreach(dirty tails)`` in the union
        projection, find nontrivial SCCs there (none on the steady
        valid path), then run the per-spec rel-constrained cycle
        search inside each."""
        pending_specs = [s for s in self._cycle_specs
                         if s not in self._cycle_found]
        if not pending_specs or not len(dirty):
            return
        if deadline is not None:
            deadline.check(SWEEP_SITE)
        src, dst = ctx["src"], ctx["dst"]
        if not len(src):
            return
        d_mask = np.isin(dirty[:, 2], list(ctx["union"]))
        if not d_mask.any():
            return
        heads = np.unique(dirty[d_mask, 1])
        tails = np.unique(dirty[d_mask, 0])
        fwd = _reach(self._n_nodes, ctx["fwd"], heads)
        bwd = _reach(self._n_nodes, ctx["bwd"], tails, within=fwd)
        region = np.nonzero(fwd & bwd)[0]
        if not len(region):
            return
        remap = np.full(self._n_nodes, -1, np.int64)
        remap[region] = np.arange(len(region))
        in_r = (remap[src] >= 0) & (remap[dst] >= 0)
        sccs = _nontrivial_groups(len(region), remap[src[in_r]],
                                  remap[dst[in_r]])
        if not sccs:
            return  # region acyclic: the steady valid-history path
        proj = EdgeList()
        proj.src = src.astype(np.int32)
        proj.dst = dst.astype(np.int32)
        proj.rel = ctx["rel"].astype(np.int8)
        for name in pending_specs:
            if deadline is not None:
                deadline.check(SWEEP_SITE)
            spec = CYCLE_ANOMALY_SPECS[name]
            for scc in sccs:
                cyc = find_cycle(region[scc], proj, spec)
                if cyc is not None:
                    self._cycle_found[name] = {
                        "cycle": self._render_cycle(cyc)}
                    break

    def _render_cycle(self, cyc) -> List[Dict[str, Any]]:
        """Contract barrier pseudo-nodes into txn->txn realtime steps
        (the oracle's rendering rule, over the unified node space)."""
        is_txn = [self._node_type[s] != 0 for (s, _r, _d) in cyc]
        k = next((i for i, t in enumerate(is_txn) if t), 0)
        cyc = cyc[k:] + cyc[:k]
        out = []
        pend_src = None
        for (s, rel, d) in cyc:
            s_txn = self._node_type[s] != 0
            d_txn = self._node_type[d] != 0
            if not d_txn:
                if s_txn:
                    pend_src = s
                continue
            src = s if s_txn else pend_src
            out.append({"src": self._node_orig[src] if src is not None
                        else None,
                        "rel": REL_NAMES[rel],
                        "dst": self._node_orig[d]})
        return out

    # ------------------------------------------------------------------ #
    # verdicts
    # ------------------------------------------------------------------ #

    def edge_counts(self) -> Dict[str, int]:
        """Deduplicated per-rel edge counts — the oracle's
        ``edge-counts`` map, for cross-checking the incremental graph
        against the batch one.  Dedup runs on a scalar int64 encoding
        of (src, dst, rel): one 1-D sort instead of np.unique(axis=0)'s
        structured row sort (~50x on 100k-session edge arrays)."""
        if self._edge_counts_cache is None:
            full = self._all_edges()
            if not len(full):
                self._edge_counts_cache = {}
            else:
                m = int(self._n_nodes) + 1
                codes = (full[:, 0] * m + full[:, 1]) * 8 + full[:, 2]
                rels = np.unique(codes) % 8
                cnt = np.bincount(rels.astype(np.int64), minlength=8)
                self._edge_counts_cache = {
                    REL_NAMES[int(r)]: int(cnt[r])
                    for r in np.nonzero(cnt)[0]}
        return self._edge_counts_cache

    def _found(self) -> Dict[str, List[Any]]:
        found: Dict[str, List[Any]] = {}
        for name, items in self._global_reports.items():
            if items:
                found.setdefault(name, []).extend(
                    items[:self.max_reported])
        for ks in self._keys.values():
            for name, items in ks.reports.items():
                lst = found.setdefault(name, [])
                for it in items:
                    if len(lst) < self.max_reported:
                        lst.append(it)
        for name, item in self._cycle_found.items():
            found.setdefault(name, []).append(item)
        return found

    def verdict(self, deadline: Optional[Deadline] = None,
                sweep: bool = True) -> Dict[str, Any]:
        """The rolling verdict: sweep dirty work (unless ``sweep`` is
        False), then assemble the oracle-shaped result plus session
        meta, anomaly first-seen timestamps, and the delta vs the
        previous verdict call."""
        try:
            if sweep:
                self.sweep(deadline=deadline)
        except DeadlineExceeded as e:
            res = deadline_result(
                checker="verifier", session=self.name,
                **{"anomaly-types": sorted(self._found()),
                   "partial": f"sweep interrupted at {e.what or 'sweep'}"})
            return res
        found = self._found()
        res = oracle.boundary_verdict(
            found, self.consistency_models, self.want,
            has_ok=self.n_ok > 0, sess_checked=False,
            edge_counts=self.edge_counts())
        now = time.time()
        names = res["anomaly-types"]
        for n in names:
            self._first_seen.setdefault(n, round(now, 3))
        res.update({
            "session": self.name,
            "txns": self.n_txns,
            "ops": self.n_events,
            "segments": self.segments,
            "sealed": self.sealed is not None,
            "first-seen": {n: self._first_seen[n] for n in names},
            "new": [n for n in names if n not in self._last_names],
            "cleared": [n for n in self._last_names if n not in names],
        })
        self._last_names = list(names)
        return res

    def restore_rolling(self, first_seen: Optional[Dict[str, float]],
                        last_names: Optional[Sequence[str]]) -> None:
        """Re-seed the rolling-delta state from a persisted snapshot
        (the service's recovery path): without this, every anomaly a
        restarted session still sees would re-report as ``new`` with a
        reset first-seen timestamp."""
        if first_seen:
            for k, v in first_seen.items():
                if isinstance(v, (int, float)):
                    self._first_seen.setdefault(str(k), float(v))
        if last_names:
            self._last_names = [str(n) for n in last_names]

    def to_packed(self) -> PackedTxns:
        """The concatenated history as one PackedTxns — what the batch
        checker sees at seal."""
        if self._mode == "ops" or self._mode is None:
            return self.packer.to_packed(self._chunks)
        cols = {}
        names = ("txn_type", "txn_process", "txn_invoke_pos",
                 "txn_complete_pos", "txn_orig_index", "mop_txn",
                 "mop_kind", "mop_key", "mop_val", "mop_rd_start",
                 "mop_rd_len")
        for name in names:
            parts = [c[name] for c in self._chunks if name in c]
            cols[name] = (np.concatenate(parts) if parts
                          else np.zeros(0, np.int32))
        cols["rd_elems"] = (self._packed_rd if self._packed_rd is not None
                            else np.zeros(0, np.int32))
        return PackedTxns(
            key_names=list(range(self._pk_keys)),
            val_names=_DenseValNames(self._pk_vals, cols["mop_key"],
                                     cols["mop_val"]),
            n_events=self.n_events, **cols)

    # ------------------------------------------------------------------ #
    # checkpoint / restore (journal compaction, ISSUE 13)
    # ------------------------------------------------------------------ #

    def checkpoint_state(self) -> Tuple[Dict[str, np.ndarray],
                                        Dict[str, Any]]:
        """Snapshot the ingested prefix for journal compaction: the
        concatenated packed columns (binary, ~10x smaller than the
        jsonl they let the journal drop) plus the packer's interner
        state and counters.  The incremental checker state itself is
        NOT serialized — it is a pure function of the op sequence, so
        :meth:`load_checkpoint` re-derives it from the columns with one
        vectorized re-ingest (no JSON parse, no re-packing), and the
        restored session's verdict digest is identical by construction.
        Only op-fed (service-path) sessions checkpoint; the packed
        bench path keeps its own columns already."""
        if self._mode == "packed":
            raise ValueError("packed-mode sessions don't checkpoint")
        cols: Dict[str, np.ndarray] = {}
        for name, dt in _CHUNK_COLS:
            parts = [c[name] for c in self._chunks if name in c]
            cols[name] = (np.concatenate(parts) if parts
                          else np.zeros(0, dt))
        pk = self.packer
        meta = {
            "packer": {
                "key_names": list(pk.key_names),
                "val_names": [list(v) for v in pk.val_names],
                "pending": {str(p): op.to_dict()
                            for p, op in pk.pending.items()},
                "pos": pk.pos, "n_txns": pk.n_txns,
                "n_mops": pk.n_mops,
                "max_mops_txn": pk.max_mops_txn,
                "n_rd_elems": pk.n_rd_elems,
            },
            "n_events": self.n_events,
            "n_txns": self.n_txns,
            "segments": self.segments,
            "next_op_index": self._next_op_index,
        }
        return cols, meta

    def load_checkpoint(self, cols: Dict[str, np.ndarray],
                        meta: Dict[str, Any]) -> None:
        """Restore a fresh session from a checkpoint: re-seed the
        packer interners, re-ingest the packed prefix (one vectorized
        segment), and resume counters — after this, :meth:`append_ops`
        continues exactly where the checkpointed session stopped."""
        if self.n_txns or self._mode is not None:
            raise ValueError("load_checkpoint needs a fresh session")
        pkm = meta["packer"]
        pk = self.packer
        pk.key_names = list(pkm["key_names"])
        pk.key_ids = {k: i for i, k in enumerate(pk.key_names)}
        pk.val_names = [tuple(v) for v in pkm["val_names"]]
        pk.val_ids = {(int(ki), v): i
                      for i, (ki, v) in enumerate(pk.val_names)}
        pk.pending = {int(p): Op.from_dict(d)
                      for p, d in (pkm.get("pending") or {}).items()}
        pk.pos = int(pkm["pos"])
        pk.n_txns = int(pkm["n_txns"])
        pk.n_mops = int(pkm["n_mops"])
        pk.max_mops_txn = int(pkm["max_mops_txn"])
        pk.n_rd_elems = int(pkm["n_rd_elems"])
        self._mode = "ops"
        cols = {k: np.asarray(v) for k, v in cols.items()}
        self._chunks.append(cols)
        if len(cols["txn_type"]):
            with telemetry.span("verifier.restore", session=self.name,
                                txns=len(cols["txn_type"])):
                self._ingest_segment(cols, cols["rd_elems"], 0)
        self.n_ok = int(np.sum(cols["txn_type"] == TXN_OK))
        self.n_events = int(meta["n_events"])
        self.n_txns = int(meta["n_txns"])
        self.segments = int(meta["segments"])
        self._next_op_index = int(meta["next_op_index"])
        self._edge_counts_cache = None

    def seal(self, deadline: Optional[Deadline] = None) -> Dict[str, Any]:
        """Seal the session: final incremental verdict, then the full
        batch checker over the concatenated history, asserting the two
        agree on ``valid?`` and the anomaly set.  Raises
        :class:`VerdictMismatch` on disagreement."""
        inc = self.verdict(deadline=deadline)
        with telemetry.span("verifier.seal-batch-check",
                            session=self.name, txns=self.n_txns):
            batch = resilience.device_call(
                "verifier.seal", self._batch_check, self.to_packed(),
                deadline=deadline, plan=self.plan)
        equal = (batch.get("valid?") == inc.get("valid?")
                 and list(batch.get("anomaly-types") or [])
                 == list(inc.get("anomaly-types") or []))
        if not equal:
            raise VerdictMismatch(inc, batch)
        self.sealed = {
            "sealed": True,
            "equal": True,
            "verdict": batch,
            "incremental": inc,
            "digest": verdict_digest(inc),
            "txns": self.n_txns,
            "ops": self.n_events,
        }
        return self.sealed

    def digest(self) -> str:
        """Digest of the current rolling verdict (sweeps first)."""
        return verdict_digest(self.verdict())


# ---------------------------------------------------------------------- #
# helpers
# ---------------------------------------------------------------------- #

def _nontrivial_groups(n: int, src: np.ndarray, dst: np.ndarray
                       ) -> List[np.ndarray]:
    """Nontrivial SCC node groups (size > 1, or a self-loop) over a
    compacted subgraph.  Same answer as `graph.nontrivial_sccs`, but
    materializes ONLY the nontrivial groups — the generic version
    np.split's one array per component, which is millions of tiny
    allocations on the all-singleton (acyclic) sweeps this path runs
    all day."""
    from jepsen_tpu.checkers.elle.graph import tarjan_scc

    if n == 0 or not len(src):
        return []
    comp = tarjan_scc(n, src, dst)
    cnt = np.bincount(comp)
    want = cnt > 1
    loops = src[src == dst]
    if len(loops):
        want[comp[loops]] = True
    labels = np.nonzero(want)[0]
    return [np.nonzero(comp == lbl)[0].astype(np.int64)
            for lbl in labels]


def _csr(n: int, src: np.ndarray, dst: np.ndarray):
    """CSR-ish adjacency (sorted-dst array + per-node slice bounds) —
    built once per sweep, shared by every chunk's reach passes."""
    order = np.argsort(src, kind="stable")
    ss, dd = src[order], dst[order]
    starts = np.searchsorted(ss, np.arange(n))
    ends = np.searchsorted(ss, np.arange(n), side="right")
    return dd, starts, ends


def _reach(n: int, csr, roots: np.ndarray,
           within: Optional[np.ndarray] = None) -> np.ndarray:
    """Boolean reachability from ``roots`` over a prebuilt CSR,
    optionally restricted to nodes where ``within`` is True (the
    coreach-inside-reach bound that keeps the dirty region small on
    acyclic graphs)."""
    dd, starts, ends = csr
    seen = np.zeros(n, bool)
    if not len(roots):
        return seen
    if within is not None:
        roots = roots[within[roots]]
        if not len(roots):
            return seen
    seen[roots] = True
    frontier = np.unique(roots)
    while len(frontier):
        counts = ends[frontier] - starts[frontier]
        total = int(counts.sum())
        if total == 0:
            break
        # vectorized multi-slice gather (device_core._expand_all shape)
        idx = np.repeat(starts[frontier], counts) + \
            (np.arange(total) - np.repeat(np.cumsum(counts) - counts,
                                          counts))
        outs = dd[idx]
        if within is not None:
            outs = outs[within[outs]]
        outs = outs[~seen[outs]]
        if not len(outs):
            break
        seen[outs] = True
        frontier = np.unique(outs)
    return seen


def iter_packed_segments(p: PackedTxns, seg_txns: int):
    """Slice a PackedTxns into append_columns-shaped segments of
    ``seg_txns`` transactions each (the bench --streaming feeder).
    Yields ``(cols, rd_elems, rd_base)`` triples; rd offsets stay
    global, so ``rd_elems`` is the whole array with ``rd_base`` 0."""
    mop_txn = np.asarray(p.mop_txn)
    for t0 in range(0, p.n_txns, seg_txns):
        t1 = min(t0 + seg_txns, p.n_txns)
        m0, m1 = np.searchsorted(mop_txn, [t0, t1])
        cols = {
            "txn_type": p.txn_type[t0:t1],
            "txn_process": p.txn_process[t0:t1],
            "txn_invoke_pos": p.txn_invoke_pos[t0:t1],
            "txn_complete_pos": p.txn_complete_pos[t0:t1],
            "txn_orig_index": p.txn_orig_index[t0:t1],
            "mop_txn": mop_txn[m0:m1],
            "mop_kind": p.mop_kind[m0:m1],
            "mop_key": p.mop_key[m0:m1],
            "mop_val": p.mop_val[m0:m1],
            "mop_rd_start": p.mop_rd_start[m0:m1],
            "mop_rd_len": p.mop_rd_len[m0:m1],
        }
        yield cols, p.rd_elems, 0
