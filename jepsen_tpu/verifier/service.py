"""The always-on verifier service (ISSUE 7): sessions over HTTP.

Session lifecycle, storage, and observability for a fleet of
:class:`~.session.VerifierSession`\\ s, served by ``cli serve
--ingest`` (the web server routes ``POST /ingest/<session>``,
``GET /verdict/<session>``, and the ``/verifier/<session>/<verb>``
lifecycle endpoints here).

Protocol (all JSON):

- ``POST /ingest/<session>?cursor=N`` — body is op-dict jsonl (the
  ``history.json`` line format).  The longest prefix of complete,
  parseable lines is journaled (fsync'd) and fed to the incremental
  checker; the response acks ``{"cursor": <journal bytes>}``.
  ``cursor`` is the byte offset of the segment's first byte in the
  client's logical stream: a resend after a lost ack overlaps, and the
  server skips the already-journaled prefix (idempotent re-append).  A
  cursor PAST the journal is a gap → 409, nothing accepted.
- ``GET /verdict/<session>`` — the rolling verdict: oracle-shaped
  result + ``new``/``cleared`` anomaly deltas and per-anomaly
  ``first-seen`` timestamps.
- ``POST /verifier/<session>/open|seal|expire`` — lifecycle.  ``open``
  takes an optional config body (``consistency-models``,
  ``anomalies``, ``sweep-deadline-s``, ``sweep-chunk``); ``seal`` runs
  the full batch checker over the concatenated history and asserts
  equality with the incremental verdict; ``expire`` drops the session
  from memory (journal + state stay on disk, reloadable).

Durability: a session is its journal.  On restart (or first touch of
an on-disk session) the journal replays through a fresh
:class:`VerifierSession` and reaches the identical verdict digest —
pinned by the crash tests.

Observability: per-session ``events.jsonl`` (ingest/verdict/seal
events — the web ``/live/verifier/<name>`` page renders it), verifier
gauges/counters on the live registry (scraped by ``/metrics``), and an
atomically-replaced ``session.json`` snapshot per session so read-only
surfaces (web pages without ``--ingest``, warehouse ingest) never need
the service process.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import resilience, store, telemetry
from jepsen_tpu.resilience import Deadline, DeadlineExceeded
from jepsen_tpu.telemetry import spans as spans_mod
from jepsen_tpu.telemetry.stream import EventStream

from .journal import (
    JOURNAL_FILE,
    META_FILE,
    SessionJournal,
    read_checkpoint,
    read_meta,
    split_segment,
)
from .session import (
    INGEST_SITE,
    SWEEP_CHUNK,
    VerdictMismatch,
    VerifierSession,
    verdict_digest,
)

logger = logging.getLogger("jepsen.verifier")

__all__ = ["VerifierService", "VERIFIER_DIR", "ARCHIVE_DIR",
           "scan_sessions"]

VERIFIER_DIR = "verifier"

#: sealed-session archival target under the verifier root; leading
#: underscore so session scans (and the warehouse ingest) skip it
ARCHIVE_DIR = "_archive"

#: sweep-duration histogram bounds (seconds) — p95 derivable from the
#: cumulative buckets on /metrics
_SWEEP_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0)


def _registry():
    return telemetry.registry()


class _Live:
    """One in-memory session: checker + journal + event stream."""

    def __init__(self, name: str, dirpath: str,
                 config: Dict[str, Any]):
        self.name = name
        self.dir = dirpath
        self.config = config
        self.lock = threading.RLock()
        # set (under self.lock) when expire() retires this object: a
        # handler that fetched it before the pop must not keep using
        # the zombie — it re-resolves and gets a freshly recovered one
        self.dead = False
        # set at recovery when the compacted prefix is unrecoverable
        # (checkpoint unusable, journal already truncated): the session
        # must refuse to serve normal-looking verdicts over a partial
        # history — ingest/verdict/seal answer 410 instead
        self.recovery_error: Optional[str] = None
        self.journal = SessionJournal(dirpath)
        self.session = VerifierSession(
            name,
            consistency_models=tuple(
                config.get("consistency-models") or ("serializable",)),
            anomalies=tuple(config.get("anomalies") or ()),
            sweep_chunk=int(config.get("sweep-chunk") or 0) or SWEEP_CHUNK,
            max_reported=int(config.get("max-reported") or 8))
        self.opened = round(time.time(), 3)
        self.last_ingest = self.opened
        self.last_verdict_ts = self.opened
        self.last_verdict: Optional[Dict[str, Any]] = None
        self.seal_result: Optional[Dict[str, Any]] = None
        self.stream = EventStream(
            os.path.join(dirpath, "events.jsonl"),
            meta={"name": f"verifier:{name}", "session": name})

    @property
    def state(self) -> str:
        return "sealed" if self.seal_result is not None else "open"

    def deadline(self) -> Optional[Deadline]:
        s = self.config.get("sweep-deadline-s")
        return Deadline(float(s)) if s else None

    def snapshot(self, verdict: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        if verdict is None:
            verdict = self.last_verdict  # keep the last one on disk
        doc = {
            "session": self.name,
            "state": self.state,
            "opened": self.opened,
            "updated": round(time.time(), 3),
            "cursor": self.journal.cursor,
            "ops": self.session.n_events,
            "txns": self.session.n_txns,
            "segments": self.session.segments,
            "config": self.config,
        }
        if self.recovery_error:
            doc["recovery-error"] = self.recovery_error
        if verdict is not None:
            doc["verdict"] = {
                k: verdict.get(k) for k in
                ("valid?", "anomaly-types", "error", "edge-counts",
                 "first-seen", "not", "also-not")}
            doc["digest"] = verdict_digest(verdict)
        if self.seal_result is not None:
            doc["seal"] = {
                "equal": self.seal_result.get("equal"),
                "digest": self.seal_result.get("digest"),
                "valid?": (self.seal_result.get("verdict") or {}).get(
                    "valid?"),
                "anomaly-types": (self.seal_result.get("verdict") or {}
                                  ).get("anomaly-types"),
            }
        return doc

    def persist(self, verdict: Optional[Dict[str, Any]] = None) -> None:
        if verdict is not None:
            self.last_verdict = verdict
        self.journal.write_meta(self.snapshot(verdict))

    def idle_s(self, now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        return max(0.0, now - max(self.last_ingest,
                                  self.last_verdict_ts))

    def compact(self) -> Dict[str, Any]:
        """Checkpoint-then-truncate (caller holds self.lock): persist
        the packed prefix as ``checkpoint.npz``, then rewrite the
        journal down to the un-checkpointed suffix.  Ordering is the
        crash discipline — a kill between the two writes leaves the
        full journal AND a checkpoint; recovery replays only the
        suffix past the checkpoint's cursor, so nothing doubles."""
        before = self.journal.disk_bytes()
        cursor = self.journal.cursor
        cols, meta = self.session.checkpoint_state()
        meta["cursor"] = cursor
        self.journal.write_checkpoint(cols, meta)
        self.journal.compact(cursor)
        out = {"session": self.name, "cursor": cursor,
               "journal-bytes-before": before,
               "journal-bytes-after": self.journal.disk_bytes()}
        self.stream.emit("compact", **{k: v for k, v in out.items()
                                       if k != "session"})
        _registry().counter("verifier-compactions").inc()
        return out

    def close(self, reason: str) -> None:
        self.stream.close(reason=reason)
        self.journal.close()


class VerifierService:
    """Session manager behind the ingest endpoints.  Thread-safe: the
    web server's handler threads call straight in."""

    def __init__(self, base: Optional[str] = None,
                 default_config: Optional[Dict[str, Any]] = None):
        self.base = base or store.BASE
        self.root = os.path.join(self.base, VERIFIER_DIR)
        self.default_config = dict(default_config or {})
        # reentrant: _get holds it while _update_gauges re-acquires.
        # Held only for DICT bookkeeping — construction + journal
        # replay of a session happen under its per-name lock, so
        # recovering one big session never stalls the whole service
        self._lock = threading.RLock()
        self._live: Dict[str, _Live] = {}
        self._name_locks: Dict[str, threading.RLock] = {}
        self._maint: Optional[threading.Thread] = None
        self._maint_stop = threading.Event()

    # -- lookup / lifecycle -------------------------------------------------

    def _dir(self, name: str) -> str:
        return os.path.join(self.root, name)

    @staticmethod
    def valid_name(name: str) -> bool:
        # leading "_" / "." are infrastructure namespaces (``_archive/``
        # retention, dot-prefixed staging) that the session and store
        # scans skip — a session there would journal into the retention
        # subtree or be invisible to listings and gc
        return bool(name) and store.sanitize(name) == name \
            and not name.startswith(("_", "."))

    def _nlock(self, name: str) -> threading.RLock:
        with self._lock:
            return self._name_locks.setdefault(name, threading.RLock())

    def _get(self, name: str, create: bool = False,
             config: Optional[Dict[str, Any]] = None) -> Optional[_Live]:
        if not self.valid_name(name):
            raise ValueError(f"bad session name {name!r}")
        with self._lock:
            live = self._live.get(name)
            if live is not None:
                return live
        nlock = self._nlock(name)
        with nlock:
            with self._lock:
                live = self._live.get(name)  # a racer built it first
                if live is not None:
                    return live
            d = self._dir(name)
            on_disk = os.path.exists(os.path.join(d, JOURNAL_FILE)) or \
                os.path.exists(os.path.join(d, META_FILE))
            if not on_disk and not create:
                return None
            cfg = dict(self.default_config)
            meta = read_meta(d) if on_disk else None
            if meta and isinstance(meta.get("config"), dict):
                cfg.update(meta["config"])
            if config:
                cfg.update(config)
            # construction + journal replay OUTSIDE the service lock:
            # only this name's lock is held, other sessions keep moving
            live = _Live(name, d, cfg)
            if on_disk:
                self._recover(live, meta)
            with self._lock:
                self._live[name] = live
            self._update_gauges()
            live.persist()
            return live

    def _recover(self, live: _Live, meta: Optional[Dict[str, Any]]
                 ) -> None:
        """Replay the journal into the fresh session — the restart
        path.  With a checkpoint on disk (a compacted session) the
        packed prefix restores vectorized and only the journal suffix
        past the checkpoint cursor replays line by line; the reached
        verdict digest is identical either way.  A sealed session
        keeps its recorded seal block instead of re-running the batch
        checker."""
        n = 0
        t0 = time.time()
        start = None
        ckpt = read_checkpoint(live.dir)
        if ckpt is None and live.journal.base > 0:
            # compaction truncated the journal but its checkpoint is
            # missing/unreadable: the prefix cannot be rebuilt.
            # Quarantine rather than serve valid?-looking verdicts
            # over a suffix-only replay
            live.recovery_error = ("checkpoint missing or unreadable "
                                   "and the journal prefix was "
                                   "compacted away")
            logger.error("verifier: session %s unrecoverable: %s",
                         live.name, live.recovery_error)
            return
        if ckpt is not None:
            cols, cmeta = ckpt
            try:
                live.session.load_checkpoint(cols, cmeta)
                start = int(cmeta["cursor"])
            except Exception as e:  # noqa: BLE001 — external corruption
                if live.journal.base > 0:
                    # the journal prefix was compacted away: without
                    # the checkpoint the history cannot be rebuilt.
                    # Quarantine rather than serve valid?-looking
                    # verdicts over a truncated replay
                    live.recovery_error = (
                        f"checkpoint unusable ({e}) and the journal "
                        "prefix was compacted away")
                    logger.error("verifier: session %s unrecoverable: "
                                 "%s", live.name, live.recovery_error)
                    return
                logger.warning(
                    "verifier: checkpoint for %s unusable (%s); "
                    "replaying the journal", live.name, e)
                live.session = VerifierSession(
                    live.name,
                    consistency_models=live.session.consistency_models,
                    anomalies=live.session.extra_anomalies,
                    sweep_chunk=live.session.sweep_chunk,
                    max_reported=live.session.max_reported)
                start = None
        for chunk in live.journal.read_ops(from_cursor=start):
            live.session.append_ops(chunk)
            n += len(chunk)
        v = (meta.get("verdict") or {}) if meta else {}
        live.session.restore_rolling(v.get("first-seen"),
                                     v.get("anomaly-types"))
        if meta and meta.get("state") == "sealed" and \
                isinstance(meta.get("seal"), dict):
            live.seal_result = {"equal": meta["seal"].get("equal"),
                                "digest": meta["seal"].get("digest"),
                                "verdict": dict(meta["seal"]),
                                "recovered": True}
        live.stream.emit("recover", ops=n,
                         wall_s=round(time.time() - t0, 3))
        logger.info("verifier: recovered session %s (%d journaled ops)",
                    live.name, n)

    @staticmethod
    def _adopt_trace(live: _Live) -> None:
        """Stitch the session onto its run's distributed trace (ISSUE
        14): the first request arriving with a trace — open config
        carrying ``trace-id``, or a ``Jepsen-Trace`` header the web
        layer installed on this handler thread — pins the session's
        trace id into its config (persisted in session.json, the
        journal session metadata) and the event stream."""
        if live.config.get("trace-id"):
            return
        ctx = spans_mod.current_trace()
        if ctx is not None:
            live.config["trace-id"] = ctx.trace_id
            live.stream.emit("trace", trace=ctx.trace_id)

    def open(self, name: str, config: Optional[Dict[str, Any]] = None
             ) -> Tuple[int, Dict[str, Any]]:
        try:
            live = self._get(name, create=True, config=config)
        except ValueError as e:
            return 400, {"error": str(e)}
        with live.lock:
            self._adopt_trace(live)
            live.persist()
            return 200, live.snapshot()

    def ingest(self, name: str, body: bytes,
               cursor: Optional[int] = None
               ) -> Tuple[int, Dict[str, Any]]:
        """Accept one streamed segment; journal-then-ack.  Runs under
        the resilience guard (fault site ``verifier.ingest``) so chaos
        tooling can hit the ingest path; the guarded unit is idempotent
        — the overlap skip recomputes from the journal cursor, so a
        retried attempt never double-appends."""
        for _ in range(2):  # once more if expire() retired our handle
            try:
                live = self._get(name, create=True)
            except ValueError as e:
                return 400, {"error": str(e)}
            with live.lock:
                if live.dead:
                    continue  # re-resolve: a fresh recovery replaces it
                if live.recovery_error:
                    return 410, {"error": "session unrecoverable: "
                                 + live.recovery_error}
                if live.state == "sealed":
                    return 409, {"error": "session sealed",
                                 "cursor": live.journal.cursor}
                try:
                    return resilience.device_call(
                        INGEST_SITE, self._ingest_locked, live, body,
                        cursor)
                except DeadlineExceeded:
                    raise
                except Exception as e:  # noqa: BLE001 — persistent
                    logger.warning("verifier ingest failed for %s: %s",
                                   name, e)
                    return 503, {"error": f"{type(e).__name__}: {e}",
                                 "cursor": live.journal.cursor}
        return 503, {"error": "session expired mid-request; retry"}

    def _ingest_locked(self, live: _Live, body: bytes,
                       cursor: Optional[int]) -> Tuple[int, Dict[str, Any]]:
        self._adopt_trace(live)
        jr = live.journal
        if cursor is not None:
            cursor = int(cursor)
            if cursor > jr.cursor:
                return 409, {"error": "cursor gap", "cursor": jr.cursor,
                             "client-cursor": cursor}
            skip = jr.cursor - cursor
            if skip >= len(body):
                # pure replay of already-acked bytes: idempotent no-op
                return 200, {"cursor": jr.cursor, "ops": 0,
                             "txns": live.session.n_txns,
                             "replayed": True}
            body = body[skip:]
        accepted, n_lines, ops = split_segment(body)
        if not accepted:
            return 200, {"cursor": jr.cursor, "ops": 0,
                         "txns": live.session.n_txns}
        jr.append(accepted)  # fsync BEFORE the ack or any checking
        txns = live.session.append_ops(ops) if ops else 0
        live.last_ingest = time.time()
        reg = _registry()
        reg.counter("verifier-ops-ingested").inc(n_lines)
        reg.gauge("verifier-verdict-freshness-s",
                  session=live.name).set(
            round(live.last_ingest - live.last_verdict_ts, 3))
        live.stream.emit("ingest", ops=n_lines, txns=txns,
                         cursor=jr.cursor)
        # auto-compaction (ISSUE 13): once the on-disk journal outgrows
        # the configured budget, checkpoint + truncate inline — the
        # cost amortizes over the bytes that grew it, and a month-long
        # session's journal stays bounded instead of monotone
        cb = live.config.get("compact-bytes")
        try:
            cb = int(cb) if cb else 0
        except (TypeError, ValueError):
            cb = 0
        if cb and jr.disk_bytes() >= cb:
            try:
                live.compact()
            except Exception as e:  # noqa: BLE001 — compaction is an
                # optimization; a failed one leaves the journal whole
                logger.warning("verifier: auto-compact of %s failed: "
                               "%s", live.name, e)
        live.persist()
        return 200, {"cursor": jr.cursor, "ops": n_lines, "txns": txns}

    def verdict(self, name: str) -> Tuple[int, Dict[str, Any]]:
        for _ in range(2):
            try:
                live = self._get(name)
            except ValueError as e:
                return 400, {"error": str(e)}
            if live is None:
                return 404, {"error": f"no such session {name!r}"}
            with live.lock:
                if live.dead:
                    continue
                return self._verdict_locked(live)
        return 503, {"error": "session expired mid-request; retry"}

    def _verdict_locked(self, live: _Live) -> Tuple[int, Dict[str, Any]]:
        if live.recovery_error:
            return 410, {"error": "session unrecoverable: "
                         + live.recovery_error}
        t0 = time.perf_counter()
        try:
            res = live.session.verdict(deadline=live.deadline())
        except Exception as e:  # noqa: BLE001 — injected persistent
            return 503, {"error": f"{type(e).__name__}: {e}"}
        dt = time.perf_counter() - t0
        reg = _registry()
        reg.histogram("verifier-sweep-s", _SWEEP_BUCKETS).observe(dt)
        live.last_verdict_ts = time.time()
        reg.gauge("verifier-verdict-freshness-s",
                  session=live.name).set(0.0)
        live.stream.emit("verdict", valid=res.get("valid?"),
                         anomalies=res.get("anomaly-types"),
                         new=res.get("new"), dur_s=round(dt, 6))
        res["digest"] = verdict_digest(res)
        live.persist(res)
        return 200, res

    def seal(self, name: str) -> Tuple[int, Dict[str, Any]]:
        for _ in range(2):
            try:
                live = self._get(name)
            except ValueError as e:
                return 400, {"error": str(e)}
            if live is None:
                return 404, {"error": f"no such session {name!r}"}
            with live.lock:
                if live.dead:
                    continue
                return self._seal_locked(live)
        return 503, {"error": "session expired mid-request; retry"}

    def _seal_locked(self, live: _Live) -> Tuple[int, Dict[str, Any]]:
        if live.recovery_error:
            return 410, {"error": "session unrecoverable: "
                         + live.recovery_error}
        if live.state == "sealed":
            return 200, live.seal_result
        try:
            sealed = live.session.seal(deadline=live.deadline())
        except VerdictMismatch as e:
            live.stream.emit("seal-mismatch", error=str(e))
            return 500, {"error": "verdict mismatch",
                         "incremental": e.incremental,
                         "batch": e.batch}
        except Exception as e:  # noqa: BLE001
            return 503, {"error": f"{type(e).__name__}: {e}"}
        live.seal_result = sealed
        live.stream.emit("seal", equal=sealed["equal"],
                         digest=sealed["digest"],
                         valid=sealed["verdict"].get("valid?"))
        live.persist(sealed.get("incremental"))
        self._drop_session_series(live.name)
        self._update_gauges()
        return 200, sealed

    def compact(self, name: str) -> Tuple[int, Dict[str, Any]]:
        """Explicit journal compaction for one live session (the
        ``POST /verifier/<s>/compact`` verb); auto-compaction via the
        ``compact-bytes`` config key covers the steady state."""
        for _ in range(2):
            try:
                live = self._get(name)
            except ValueError as e:
                return 400, {"error": str(e)}
            if live is None:
                return 404, {"error": f"no such session {name!r}"}
            with live.lock:
                if live.dead:
                    continue
                if live.recovery_error:
                    return 410, {"error": "session unrecoverable: "
                                 + live.recovery_error}
                try:
                    out = live.compact()
                except Exception as e:  # noqa: BLE001
                    return 503, {"error": f"{type(e).__name__}: {e}"}
                live.persist()
                return 200, out
        return 503, {"error": "session expired mid-request; retry"}

    def expire(self, name: str) -> Tuple[int, Dict[str, Any]]:
        """Drop a session from memory; journal + session.json stay on
        disk (a later touch recovers it by replay).  The retired
        object is marked dead under its own lock, so a handler that
        fetched it pre-pop re-resolves instead of writing through a
        zombie journal handle alongside the recovered replacement."""
        with self._lock:
            live = self._live.pop(name, None)
        if live is None:
            return 404, {"error": f"no such live session {name!r}"}
        with live.lock:
            live.dead = True
            live.persist()
            live.close("expired")
        self._drop_session_series(name)
        self._update_gauges()
        return 200, {"expired": name}

    # -- retention / maintenance (ISSUE 13) ---------------------------------

    def _archive(self, name: str) -> bool:
        """Move a sealed session's dir under ``<root>/_archive/`` —
        journal + checkpoint + snapshot intact, but out of the session
        scans, the warehouse ingest, and the /metrics surfaces."""
        src = self._dir(name)
        if not os.path.isdir(src):
            return False
        adir = os.path.join(self.root, ARCHIVE_DIR)
        os.makedirs(adir, exist_ok=True)
        dst = os.path.join(adir, name)
        if os.path.exists(dst):
            dst = f"{dst}.{int(time.time() * 1000)}"
        try:
            os.replace(src, dst)
        except OSError as e:
            logger.warning("verifier: archive of %s failed: %s",
                           name, e)
            return False
        return True

    def gc(self, now: Optional[float] = None) -> Dict[str, int]:
        """Retention pass: expire open sessions idle past
        ``gc-idle-s`` (journal stays, a later touch recovers them) and
        archive sealed sessions idle past ``archive-sealed-s`` —
        including on-disk sealed sessions from before a restart.  Both
        knobs come from the service default config (or per-session
        config); unset means that policy is off.  Keeps the long-lived
        daemon's RSS and /metrics cardinality bounded: expired/archived
        sessions' per-session gauges are retired with them."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "archived": 0}
        idle_s = _as_float(self.default_config.get("gc-idle-s"))
        arch_s = _as_float(self.default_config.get("archive-sealed-s"))
        # no early-out on unset defaults: the per-session loop still
        # runs, so a session that carried its own gc-idle-s /
        # archive-sealed-s in its open config gets retention too
        with self._lock:
            items = list(self._live.items())
        for name, live in items:
            with live.lock:
                if live.dead:
                    continue
                cfg_idle = _as_float(live.config.get("gc-idle-s"),
                                     idle_s)
                cfg_arch = _as_float(
                    live.config.get("archive-sealed-s"), arch_s)
                sealed = live.state == "sealed"
                idle = live.idle_s(now)
            if sealed and cfg_arch is not None and idle > cfg_arch:
                # per-name lock across expire→archive: a concurrent
                # touch can't recover the session from disk between
                # the two steps and be left writing through a dir the
                # rename just moved under _archive/
                with self._nlock(name):
                    self.expire(name)
                    if self._archive(name):
                        stats["archived"] += 1
            elif not sealed and cfg_idle is not None \
                    and idle > cfg_idle:
                self.expire(name)
                stats["expired"] += 1
        # sealed sessions left on disk by an earlier process life.
        # Not gated on the DEFAULT arch knob: a session that carried
        # its own archive-sealed-s in its open config must still
        # archive after a restart, when only its persisted meta knows
        # the knob
        with self._lock:
            live_names = set(self._live)
        for name, meta in scan_sessions(self.base):
            if name in live_names:
                continue
            upd = meta.get("updated")
            mcfg = meta.get("config") if isinstance(
                meta.get("config"), dict) else {}
            m_arch = _as_float(mcfg.get("archive-sealed-s"), arch_s)
            if meta.get("state") == "sealed" \
                    and isinstance(upd, (int, float)) \
                    and m_arch is not None \
                    and now - upd > m_arch:
                with self._nlock(name):
                    with self._lock:
                        if name in self._live:  # recovered since
                            continue            # the scan
                    if self._archive(name):
                        stats["archived"] += 1
        self._journal_gauge()
        return stats

    def sweep_dirty(self) -> Dict[str, int]:
        """One multi-tenant batched sweep over every dirty live
        session (docs/VERIFIER.md): many sessions' dirty regions, ONE
        ``ops.cycle_sweep`` dispatch — the per-session host sweep stops
        being the scaling wall."""
        from . import sweep as sweep_mod

        with self._lock:
            lives = list(self._live.values())
        return sweep_mod.batched_sweep(lives)

    def maintain(self) -> Dict[str, Any]:
        """One maintenance tick: batched sweep + GC + gauge refresh.
        Every part is best-effort — a failing tick never takes the
        service down."""
        out: Dict[str, Any] = {}
        try:
            out["sweep"] = self.sweep_dirty()
        except Exception as e:  # noqa: BLE001
            logger.warning("verifier maintenance sweep failed: %s", e)
            out["sweep-error"] = str(e)
        try:
            out["gc"] = self.gc()
        except Exception as e:  # noqa: BLE001
            logger.warning("verifier maintenance gc failed: %s", e)
            out["gc-error"] = str(e)
        return out

    def start_maintenance(self, interval_s: float = 5.0) -> None:
        """Run :meth:`maintain` on a daemon thread every
        ``interval_s`` — the production-service mode ``cli serve
        --ingest`` enables."""
        if self._maint is not None:
            return
        self._maint_stop.clear()

        def loop() -> None:
            while not self._maint_stop.wait(interval_s):
                self.maintain()

        self._maint = threading.Thread(
            target=loop, daemon=True, name="verifier-maintenance")
        self._maint.start()

    def _journal_gauge(self) -> None:
        """Aggregate on-disk journal bytes across live sessions — the
        quantity compaction bounds (ISSUE 13 acceptance: bounded, not
        monotone)."""
        with self._lock:
            lives = list(self._live.values())
        total = 0
        for live in lives:
            total += live.journal.disk_bytes()
        _registry().gauge("verifier-journal-bytes").set(total)

    # -- listings / metrics -------------------------------------------------

    def sessions(self) -> List[Dict[str, Any]]:
        """Every session, live ones first-hand, on-disk ones from
        their ``session.json`` snapshots."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, meta in scan_sessions(self.base):
            out[name] = dict(meta, live=False)
        with self._lock:
            lives = list(self._live.values())
        for live in lives:
            with live.lock:
                out[live.name] = dict(live.snapshot(), live=True)
        return [out[k] for k in sorted(out)]

    @staticmethod
    def _drop_session_series(name: str) -> None:
        """Retire a finished session's per-session labeled series — a
        long-lived daemon handling many short sessions must not grow
        /metrics (and registry memory) monotonically."""
        try:
            _registry().remove("verifier-verdict-freshness-s",
                               session=name)
        except Exception:  # noqa: BLE001 — observability cleanup only
            pass

    def host_freshness(self) -> Dict[str, Dict[str, Any]]:
        """Per-host verdict freshness over OPEN live sessions whose
        config names the executing host (fleet cells stamp it via
        their ``fleet-host``) — the /fleet dashboard's ingest-lag
        column (ISSUE 14 satellite).  Freshness is measured entirely
        on this service's clock (last ingest vs last verdict), so it
        needs no worker clock correction."""
        with self._lock:
            lives = list(self._live.values())
        out: Dict[str, Dict[str, Any]] = {}
        for live in lives:
            with live.lock:
                if live.dead or live.seal_result is not None:
                    continue
                host = live.config.get("host")
                if not host:
                    continue
                fresh = round(max(0.0, live.last_ingest
                                  - live.last_verdict_ts), 3)
            cur = out.setdefault(str(host),
                                 {"freshness-s": 0.0, "sessions": 0})
            cur["sessions"] += 1
            cur["freshness-s"] = max(cur["freshness-s"], fresh)
        return out

    def _update_gauges(self) -> None:
        with self._lock:
            active = sum(1 for v in self._live.values()
                         if v.seal_result is None)
        _registry().gauge("verifier-sessions-active").set(active)

    def close(self) -> None:
        if self._maint is not None:
            self._maint_stop.set()
            self._maint.join(timeout=5)
            self._maint = None
        with self._lock:
            lives = list(self._live.values())
            self._live.clear()
        for live in lives:
            with live.lock:
                live.dead = True
                live.persist()
                live.close("service-stop")


def _as_float(v: Any, default: Optional[float] = None
              ) -> Optional[float]:
    if v is None:
        return default
    try:
        f = float(v)
    except (TypeError, ValueError):
        return default
    return f if f > 0 else default


def scan_sessions(base: str) -> List[Tuple[str, Dict[str, Any]]]:
    """On-disk session snapshots under ``<store>/verifier/`` — the
    read-only listing the web pages use when no service is attached.
    Skips the ``_archive/`` retention subtree (and anything else
    ``_``/``.``-prefixed — not session dirs)."""
    root = os.path.join(base, VERIFIER_DIR)
    out: List[Tuple[str, Dict[str, Any]]] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for n in names:
        d = os.path.join(root, n)
        if not os.path.isdir(d) or n.startswith(("_", ".")):
            continue
        meta = read_meta(d)
        if meta is None:
            meta = {"session": n, "state": "?"}
        out.append((n, meta))
    return out
