"""The always-on verifier service (ISSUE 7): sessions over HTTP.

Session lifecycle, storage, and observability for a fleet of
:class:`~.session.VerifierSession`\\ s, served by ``cli serve
--ingest`` (the web server routes ``POST /ingest/<session>``,
``GET /verdict/<session>``, and the ``/verifier/<session>/<verb>``
lifecycle endpoints here).

Protocol (all JSON):

- ``POST /ingest/<session>?cursor=N`` — body is op-dict jsonl (the
  ``history.json`` line format).  The longest prefix of complete,
  parseable lines is journaled (fsync'd) and fed to the incremental
  checker; the response acks ``{"cursor": <journal bytes>}``.
  ``cursor`` is the byte offset of the segment's first byte in the
  client's logical stream: a resend after a lost ack overlaps, and the
  server skips the already-journaled prefix (idempotent re-append).  A
  cursor PAST the journal is a gap → 409, nothing accepted.
- ``GET /verdict/<session>`` — the rolling verdict: oracle-shaped
  result + ``new``/``cleared`` anomaly deltas and per-anomaly
  ``first-seen`` timestamps.
- ``POST /verifier/<session>/open|seal|expire`` — lifecycle.  ``open``
  takes an optional config body (``consistency-models``,
  ``anomalies``, ``sweep-deadline-s``, ``sweep-chunk``); ``seal`` runs
  the full batch checker over the concatenated history and asserts
  equality with the incremental verdict; ``expire`` drops the session
  from memory (journal + state stay on disk, reloadable).

Durability: a session is its journal.  On restart (or first touch of
an on-disk session) the journal replays through a fresh
:class:`VerifierSession` and reaches the identical verdict digest —
pinned by the crash tests.

Observability: per-session ``events.jsonl`` (ingest/verdict/seal
events — the web ``/live/verifier/<name>`` page renders it), verifier
gauges/counters on the live registry (scraped by ``/metrics``), and an
atomically-replaced ``session.json`` snapshot per session so read-only
surfaces (web pages without ``--ingest``, warehouse ingest) never need
the service process.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import resilience, store, telemetry
from jepsen_tpu.resilience import Deadline, DeadlineExceeded
from jepsen_tpu.telemetry.stream import EventStream

from .journal import (
    JOURNAL_FILE,
    META_FILE,
    SessionJournal,
    read_meta,
    split_segment,
)
from .session import (
    INGEST_SITE,
    SWEEP_CHUNK,
    VerdictMismatch,
    VerifierSession,
    verdict_digest,
)

logger = logging.getLogger("jepsen.verifier")

__all__ = ["VerifierService", "VERIFIER_DIR", "scan_sessions"]

VERIFIER_DIR = "verifier"

#: sweep-duration histogram bounds (seconds) — p95 derivable from the
#: cumulative buckets on /metrics
_SWEEP_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0)


def _registry():
    return telemetry.registry()


class _Live:
    """One in-memory session: checker + journal + event stream."""

    def __init__(self, name: str, dirpath: str,
                 config: Dict[str, Any]):
        self.name = name
        self.dir = dirpath
        self.config = config
        self.lock = threading.RLock()
        # set (under self.lock) when expire() retires this object: a
        # handler that fetched it before the pop must not keep using
        # the zombie — it re-resolves and gets a freshly recovered one
        self.dead = False
        self.journal = SessionJournal(dirpath)
        self.session = VerifierSession(
            name,
            consistency_models=tuple(
                config.get("consistency-models") or ("serializable",)),
            anomalies=tuple(config.get("anomalies") or ()),
            sweep_chunk=int(config.get("sweep-chunk") or 0) or SWEEP_CHUNK,
            max_reported=int(config.get("max-reported") or 8))
        self.opened = round(time.time(), 3)
        self.last_ingest = self.opened
        self.last_verdict_ts = self.opened
        self.last_verdict: Optional[Dict[str, Any]] = None
        self.seal_result: Optional[Dict[str, Any]] = None
        self.stream = EventStream(
            os.path.join(dirpath, "events.jsonl"),
            meta={"name": f"verifier:{name}", "session": name})

    @property
    def state(self) -> str:
        return "sealed" if self.seal_result is not None else "open"

    def deadline(self) -> Optional[Deadline]:
        s = self.config.get("sweep-deadline-s")
        return Deadline(float(s)) if s else None

    def snapshot(self, verdict: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        if verdict is None:
            verdict = self.last_verdict  # keep the last one on disk
        doc = {
            "session": self.name,
            "state": self.state,
            "opened": self.opened,
            "updated": round(time.time(), 3),
            "cursor": self.journal.cursor,
            "ops": self.session.n_events,
            "txns": self.session.n_txns,
            "segments": self.session.segments,
            "config": self.config,
        }
        if verdict is not None:
            doc["verdict"] = {
                k: verdict.get(k) for k in
                ("valid?", "anomaly-types", "error", "edge-counts",
                 "first-seen", "not", "also-not")}
            doc["digest"] = verdict_digest(verdict)
        if self.seal_result is not None:
            doc["seal"] = {
                "equal": self.seal_result.get("equal"),
                "digest": self.seal_result.get("digest"),
                "valid?": (self.seal_result.get("verdict") or {}).get(
                    "valid?"),
                "anomaly-types": (self.seal_result.get("verdict") or {}
                                  ).get("anomaly-types"),
            }
        return doc

    def persist(self, verdict: Optional[Dict[str, Any]] = None) -> None:
        if verdict is not None:
            self.last_verdict = verdict
        self.journal.write_meta(self.snapshot(verdict))

    def close(self, reason: str) -> None:
        self.stream.close(reason=reason)
        self.journal.close()


class VerifierService:
    """Session manager behind the ingest endpoints.  Thread-safe: the
    web server's handler threads call straight in."""

    def __init__(self, base: Optional[str] = None,
                 default_config: Optional[Dict[str, Any]] = None):
        self.base = base or store.BASE
        self.root = os.path.join(self.base, VERIFIER_DIR)
        self.default_config = dict(default_config or {})
        # reentrant: _get holds it while _update_gauges re-acquires.
        # Held only for DICT bookkeeping — construction + journal
        # replay of a session happen under its per-name lock, so
        # recovering one big session never stalls the whole service
        self._lock = threading.RLock()
        self._live: Dict[str, _Live] = {}
        self._name_locks: Dict[str, threading.RLock] = {}

    # -- lookup / lifecycle -------------------------------------------------

    def _dir(self, name: str) -> str:
        return os.path.join(self.root, name)

    @staticmethod
    def valid_name(name: str) -> bool:
        return bool(name) and store.sanitize(name) == name

    def _get(self, name: str, create: bool = False,
             config: Optional[Dict[str, Any]] = None) -> Optional[_Live]:
        if not self.valid_name(name):
            raise ValueError(f"bad session name {name!r}")
        with self._lock:
            live = self._live.get(name)
            if live is not None:
                return live
            nlock = self._name_locks.setdefault(name, threading.RLock())
        with nlock:
            with self._lock:
                live = self._live.get(name)  # a racer built it first
                if live is not None:
                    return live
            d = self._dir(name)
            on_disk = os.path.exists(os.path.join(d, JOURNAL_FILE)) or \
                os.path.exists(os.path.join(d, META_FILE))
            if not on_disk and not create:
                return None
            cfg = dict(self.default_config)
            meta = read_meta(d) if on_disk else None
            if meta and isinstance(meta.get("config"), dict):
                cfg.update(meta["config"])
            if config:
                cfg.update(config)
            # construction + journal replay OUTSIDE the service lock:
            # only this name's lock is held, other sessions keep moving
            live = _Live(name, d, cfg)
            if on_disk:
                self._recover(live, meta)
            with self._lock:
                self._live[name] = live
            self._update_gauges()
            live.persist()
            return live

    def _recover(self, live: _Live, meta: Optional[Dict[str, Any]]
                 ) -> None:
        """Replay the journal into the fresh session — the restart
        path.  A sealed session keeps its recorded seal block instead
        of re-running the batch checker."""
        n = 0
        t0 = time.time()
        for chunk in live.journal.read_ops():
            live.session.append_ops(chunk)
            n += len(chunk)
        v = (meta.get("verdict") or {}) if meta else {}
        live.session.restore_rolling(v.get("first-seen"),
                                     v.get("anomaly-types"))
        if meta and meta.get("state") == "sealed" and \
                isinstance(meta.get("seal"), dict):
            live.seal_result = {"equal": meta["seal"].get("equal"),
                                "digest": meta["seal"].get("digest"),
                                "verdict": dict(meta["seal"]),
                                "recovered": True}
        live.stream.emit("recover", ops=n,
                         wall_s=round(time.time() - t0, 3))
        logger.info("verifier: recovered session %s (%d journaled ops)",
                    live.name, n)

    def open(self, name: str, config: Optional[Dict[str, Any]] = None
             ) -> Tuple[int, Dict[str, Any]]:
        try:
            live = self._get(name, create=True, config=config)
        except ValueError as e:
            return 400, {"error": str(e)}
        with live.lock:
            live.persist()
            return 200, live.snapshot()

    def ingest(self, name: str, body: bytes,
               cursor: Optional[int] = None
               ) -> Tuple[int, Dict[str, Any]]:
        """Accept one streamed segment; journal-then-ack.  Runs under
        the resilience guard (fault site ``verifier.ingest``) so chaos
        tooling can hit the ingest path; the guarded unit is idempotent
        — the overlap skip recomputes from the journal cursor, so a
        retried attempt never double-appends."""
        for _ in range(2):  # once more if expire() retired our handle
            try:
                live = self._get(name, create=True)
            except ValueError as e:
                return 400, {"error": str(e)}
            with live.lock:
                if live.dead:
                    continue  # re-resolve: a fresh recovery replaces it
                if live.state == "sealed":
                    return 409, {"error": "session sealed",
                                 "cursor": live.journal.cursor}
                try:
                    return resilience.device_call(
                        INGEST_SITE, self._ingest_locked, live, body,
                        cursor)
                except DeadlineExceeded:
                    raise
                except Exception as e:  # noqa: BLE001 — persistent
                    logger.warning("verifier ingest failed for %s: %s",
                                   name, e)
                    return 503, {"error": f"{type(e).__name__}: {e}",
                                 "cursor": live.journal.cursor}
        return 503, {"error": "session expired mid-request; retry"}

    def _ingest_locked(self, live: _Live, body: bytes,
                       cursor: Optional[int]) -> Tuple[int, Dict[str, Any]]:
        jr = live.journal
        if cursor is not None:
            cursor = int(cursor)
            if cursor > jr.cursor:
                return 409, {"error": "cursor gap", "cursor": jr.cursor,
                             "client-cursor": cursor}
            skip = jr.cursor - cursor
            if skip >= len(body):
                # pure replay of already-acked bytes: idempotent no-op
                return 200, {"cursor": jr.cursor, "ops": 0,
                             "txns": live.session.n_txns,
                             "replayed": True}
            body = body[skip:]
        accepted, n_lines, ops = split_segment(body)
        if not accepted:
            return 200, {"cursor": jr.cursor, "ops": 0,
                         "txns": live.session.n_txns}
        jr.append(accepted)  # fsync BEFORE the ack or any checking
        txns = live.session.append_ops(ops) if ops else 0
        live.last_ingest = time.time()
        reg = _registry()
        reg.counter("verifier-ops-ingested").inc(n_lines)
        reg.gauge("verifier-verdict-freshness-s",
                  session=live.name).set(
            round(live.last_ingest - live.last_verdict_ts, 3))
        live.stream.emit("ingest", ops=n_lines, txns=txns,
                         cursor=jr.cursor)
        live.persist()
        return 200, {"cursor": jr.cursor, "ops": n_lines, "txns": txns}

    def verdict(self, name: str) -> Tuple[int, Dict[str, Any]]:
        for _ in range(2):
            try:
                live = self._get(name)
            except ValueError as e:
                return 400, {"error": str(e)}
            if live is None:
                return 404, {"error": f"no such session {name!r}"}
            with live.lock:
                if live.dead:
                    continue
                return self._verdict_locked(live)
        return 503, {"error": "session expired mid-request; retry"}

    def _verdict_locked(self, live: _Live) -> Tuple[int, Dict[str, Any]]:
        t0 = time.perf_counter()
        try:
            res = live.session.verdict(deadline=live.deadline())
        except Exception as e:  # noqa: BLE001 — injected persistent
            return 503, {"error": f"{type(e).__name__}: {e}"}
        dt = time.perf_counter() - t0
        reg = _registry()
        reg.histogram("verifier-sweep-s", _SWEEP_BUCKETS).observe(dt)
        live.last_verdict_ts = time.time()
        reg.gauge("verifier-verdict-freshness-s",
                  session=live.name).set(0.0)
        live.stream.emit("verdict", valid=res.get("valid?"),
                         anomalies=res.get("anomaly-types"),
                         new=res.get("new"), dur_s=round(dt, 6))
        res["digest"] = verdict_digest(res)
        live.persist(res)
        return 200, res

    def seal(self, name: str) -> Tuple[int, Dict[str, Any]]:
        for _ in range(2):
            try:
                live = self._get(name)
            except ValueError as e:
                return 400, {"error": str(e)}
            if live is None:
                return 404, {"error": f"no such session {name!r}"}
            with live.lock:
                if live.dead:
                    continue
                return self._seal_locked(live)
        return 503, {"error": "session expired mid-request; retry"}

    def _seal_locked(self, live: _Live) -> Tuple[int, Dict[str, Any]]:
        if live.state == "sealed":
            return 200, live.seal_result
        try:
            sealed = live.session.seal(deadline=live.deadline())
        except VerdictMismatch as e:
            live.stream.emit("seal-mismatch", error=str(e))
            return 500, {"error": "verdict mismatch",
                         "incremental": e.incremental,
                         "batch": e.batch}
        except Exception as e:  # noqa: BLE001
            return 503, {"error": f"{type(e).__name__}: {e}"}
        live.seal_result = sealed
        live.stream.emit("seal", equal=sealed["equal"],
                         digest=sealed["digest"],
                         valid=sealed["verdict"].get("valid?"))
        live.persist(sealed.get("incremental"))
        self._drop_session_series(live.name)
        self._update_gauges()
        return 200, sealed

    def expire(self, name: str) -> Tuple[int, Dict[str, Any]]:
        """Drop a session from memory; journal + session.json stay on
        disk (a later touch recovers it by replay).  The retired
        object is marked dead under its own lock, so a handler that
        fetched it pre-pop re-resolves instead of writing through a
        zombie journal handle alongside the recovered replacement."""
        with self._lock:
            live = self._live.pop(name, None)
        if live is None:
            return 404, {"error": f"no such live session {name!r}"}
        with live.lock:
            live.dead = True
            live.persist()
            live.close("expired")
        self._drop_session_series(name)
        self._update_gauges()
        return 200, {"expired": name}

    # -- listings / metrics -------------------------------------------------

    def sessions(self) -> List[Dict[str, Any]]:
        """Every session, live ones first-hand, on-disk ones from
        their ``session.json`` snapshots."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, meta in scan_sessions(self.base):
            out[name] = dict(meta, live=False)
        with self._lock:
            lives = list(self._live.values())
        for live in lives:
            with live.lock:
                out[live.name] = dict(live.snapshot(), live=True)
        return [out[k] for k in sorted(out)]

    @staticmethod
    def _drop_session_series(name: str) -> None:
        """Retire a finished session's per-session labeled series — a
        long-lived daemon handling many short sessions must not grow
        /metrics (and registry memory) monotonically."""
        try:
            _registry().remove("verifier-verdict-freshness-s",
                               session=name)
        except Exception:  # noqa: BLE001 — observability cleanup only
            pass

    def _update_gauges(self) -> None:
        with self._lock:
            active = sum(1 for v in self._live.values()
                         if v.seal_result is None)
        _registry().gauge("verifier-sessions-active").set(active)

    def close(self) -> None:
        with self._lock:
            lives = list(self._live.values())
            self._live.clear()
        for live in lives:
            with live.lock:
                live.dead = True
                live.persist()
                live.close("service-stop")


def scan_sessions(base: str) -> List[Tuple[str, Dict[str, Any]]]:
    """On-disk session snapshots under ``<store>/verifier/`` — the
    read-only listing the web pages use when no service is attached."""
    root = os.path.join(base, VERIFIER_DIR)
    out: List[Tuple[str, Dict[str, Any]]] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for n in names:
        d = os.path.join(root, n)
        if not os.path.isdir(d):
            continue
        meta = read_meta(d)
        if meta is None:
            meta = {"session": n, "state": "?"}
        out.append((n, meta))
    return out
