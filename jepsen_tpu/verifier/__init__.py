"""verifier/ — the always-on incremental checking service (ISSUE 7).

Turns the batch checker into infrastructure: clients stream history
segments in (op-dict jsonl, the ``history.json`` line format), rolling
verdicts stream out, and sealing a session runs the full batch checker
and asserts it agrees with the incremental result.

Layers (see ``docs/VERIFIER.md``):

- :mod:`.session` — :class:`VerifierSession`, the incremental checker:
  per-key tail-index edge maintenance, dirty-region cycle sweeps in
  device-sized guarded chunks, the shared
  :func:`~jepsen_tpu.checkers.elle.oracle.boundary_verdict` tail.
- :mod:`.journal` — fsync'd per-session journals; accept → fsync → ack;
  byte-cursor resume; crash replay to the identical verdict digest.
- :mod:`.service` — :class:`VerifierService`, the session manager the
  web server (``cli serve --ingest``) routes to; journal compaction,
  session GC/archival, and the maintenance loop (ISSUE 13).
- :mod:`.sweep` — multi-tenant batched dirty-region sweeps: many
  sessions' regions, one ``ops.cycle_sweep`` dispatch (ISSUE 13).
- :mod:`.client` — :class:`LiveCheck`, the live-checking client
  `core.run`'s interpreter streams through (ISSUE 13).
"""

from .client import LiveCheck, live_check_for
from .journal import (
    SessionJournal,
    read_checkpoint,
    read_meta,
    split_segment,
    write_checkpoint,
)
from .service import VerifierService, scan_sessions
from .session import (
    VerdictMismatch,
    VerifierSession,
    iter_packed_segments,
    verdict_digest,
)

__all__ = [
    "VerifierSession", "VerifierService", "SessionJournal",
    "VerdictMismatch", "verdict_digest", "iter_packed_segments",
    "split_segment", "scan_sessions", "read_meta", "LiveCheck",
    "live_check_for", "read_checkpoint", "write_checkpoint",
]
