"""Per-session ingest journals: accept → fsync → ack (ISSUE 7).

The durability half of the verifier service: every segment a client
streams is validated line by line, appended to the session's
``journal.jsonl``, and **fsync'd before the acknowledgment leaves the
server** — so an acked op can never be lost to a crash.  The journal
byte size *is* the ack cursor: a client resumes by resending from the
last cursor it saw acked, and the server drops the already-journaled
overlap (idempotent re-append).

Crash discipline mirrors the flight recorder / campaign ledger: a
``kill -9`` mid-append leaves at most one torn trailing line.  On
recovery the journal is opened with :meth:`SessionJournal.recover`,
which truncates crash debris back to the last complete line — the
replayed session then reaches the identical verdict digest, because
the incremental state is a pure function of the accepted op sequence.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["SessionJournal", "split_segment", "op_feedable", "read_meta",
           "JOURNAL_FILE", "META_FILE"]

JOURNAL_FILE = "journal.jsonl"
META_FILE = "session.json"


def read_meta(dirpath: str) -> Optional[Dict[str, Any]]:
    """A session dir's ``session.json`` snapshot, or None.  Module-level
    so read-only surfaces (web listings, warehouse ingest) never
    construct a :class:`SessionJournal` — whose recovery would
    *truncate* another process's torn tail out from under it."""
    try:
        with open(os.path.join(dirpath, META_FILE)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


_OP_TYPES = frozenset({"invoke", "ok", "fail", "info"})
_SCALAR = (str, int, float, bool, type(None))


def _mop_ok(m: Any) -> bool:
    if not isinstance(m, (list, tuple)) or len(m) < 2 \
            or not isinstance(m[1], _SCALAR):
        return False
    kind = m[0]
    if kind in ("append", "w"):
        return len(m) >= 3 and isinstance(m[2], _SCALAR)
    if kind == "r":
        if len(m) < 3 or m[2] is None:
            return True
        return isinstance(m[2], list) and \
            all(isinstance(v, _SCALAR) for v in m[2])
    return False


def op_feedable(rec: Dict[str, Any]) -> bool:
    """Can the packer/session actually consume this op dict?  The
    journal's acceptance predicate: a line that parses as JSON but
    would blow up `Op.from_dict`/`TxnPacker.feed` (missing/unknown
    ``type``, a non-list client value, malformed or unhashable mops)
    must be REFUSED before it is fsync'd — a journaled-but-unfeedable
    op would brick the session on every replay."""
    if rec.get("type") not in _OP_TYPES:
        return False
    p = rec.get("process")
    if not (isinstance(p, int) and p >= 0):
        return True  # non-client op: the packer skips it entirely
    v = rec.get("value")
    if v is None:
        return True
    if not isinstance(v, list):
        return False
    return all(_mop_ok(m) for m in v)


def split_segment(body: bytes) -> Tuple[bytes, int, List[Dict[str, Any]]]:
    """Validate one streamed segment: returns ``(accepted_bytes,
    n_lines, ops)`` where ``accepted_bytes`` is the longest prefix of
    complete, parseable, FEEDABLE op-dict lines (:func:`op_feedable`).
    A torn trailing line (no newline) is left for the client's next
    send; a complete-but-corrupt/unfeedable line stops acceptance at
    its start (the client gets the cursor before it and must fix its
    stream)."""
    ops: List[Dict[str, Any]] = []
    accepted = 0
    n = 0
    start = 0
    while True:
        nl = body.find(b"\n", start)
        if nl < 0:
            break
        line = body[start:nl]
        if line.strip():
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if not isinstance(rec, dict) or not op_feedable(rec):
                break
            ops.append(rec)
            n += 1
        start = nl + 1
        accepted = start
    return body[:accepted], n, ops


class SessionJournal:
    """Append-only fsync'd op journal for one verifier session."""

    def __init__(self, dirpath: str):
        self.dir = dirpath
        self.path = os.path.join(dirpath, JOURNAL_FILE)
        os.makedirs(dirpath, exist_ok=True)
        self._f = None
        self.cursor = self.recover()

    def recover(self) -> int:
        """Scan the journal, truncating a torn/corrupt/unfeedable tail
        back to the last complete replayable line; returns the durable
        cursor — exactly the prefix :meth:`read_ops` will replay, so
        the ack cursor and the replayed state can't diverge."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        good = 0
        with open(self.path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break
                if line.strip():
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break
                    if not isinstance(rec, dict) or not op_feedable(rec):
                        break
                good += len(line)
        if good < size:
            with open(self.path, "rb+") as f:
                f.truncate(good)
        return good

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "ab", buffering=0)
        return self._f

    def append(self, data: bytes) -> int:
        """Append pre-validated journal bytes; fsync; return the new
        cursor.  The caller (the service) acks only after this
        returns — accepted segments land durably before the ack."""
        if not data:
            return self.cursor
        f = self._file()
        f.write(data)
        os.fsync(f.fileno())
        self.cursor += len(data)
        return self.cursor

    def read_ops(self, chunk_lines: int = 4096
                 ) -> Iterator[List[Dict[str, Any]]]:
        """Replay the journal as op-dict chunks (history order).  A
        torn tail (only possible before :meth:`recover` ran) is
        dropped, and replay STOPS at an unfeedable line (impossible
        through `split_segment`; external corruption otherwise) — the
        same discipline as every jsonl reader in the repo."""
        out: List[Dict[str, Any]] = []
        try:
            f = open(self.path, "rb")
        except OSError:
            return
        with f:
            for line in f:
                if not line.endswith(b"\n"):
                    break
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break
                if not isinstance(rec, dict) or not op_feedable(rec):
                    break
                out.append(rec)
                if len(out) >= chunk_lines:
                    yield out
                    out = []
        if out:
            yield out

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    # -- session meta (atomic state snapshot for read-only surfaces) -----

    def write_meta(self, state: Dict[str, Any]) -> None:
        """Atomically replace ``session.json`` — the state snapshot the
        web pages and the warehouse ingest read without the service."""
        tmp = os.path.join(self.dir, META_FILE + ".tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(state, f, indent=1, sort_keys=True)
            os.replace(tmp, os.path.join(self.dir, META_FILE))
        except OSError:
            pass

    def read_meta(self) -> Optional[Dict[str, Any]]:
        return read_meta(self.dir)
