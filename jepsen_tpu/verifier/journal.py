"""Per-session ingest journals: accept → fsync → ack (ISSUE 7).

The durability half of the verifier service: every segment a client
streams is validated line by line, appended to the session's
``journal.jsonl``, and **fsync'd before the acknowledgment leaves the
server** — so an acked op can never be lost to a crash.  The journal
byte size *is* the ack cursor: a client resumes by resending from the
last cursor it saw acked, and the server drops the already-journaled
overlap (idempotent re-append).

Crash discipline mirrors the flight recorder / campaign ledger: a
``kill -9`` mid-append leaves at most one torn trailing line.  On
recovery the journal is opened with :meth:`SessionJournal.recover`,
which truncates crash debris back to the last complete line — the
replayed session then reaches the identical verdict digest, because
the incremental state is a pure function of the accepted op sequence.

Compaction (ISSUE 13): a month-long session must not keep an unbounded
jsonl replay prefix.  :meth:`SessionJournal.compact` rewrites the file
as ``header + suffix`` where the header line ``{"_journal": 1,
"base": C}`` records that the first ``C`` logical bytes of the stream
now live in the session's checkpoint (``checkpoint.npz``, written by
the service BEFORE the journal truncates).  The ack cursor is the
LOGICAL stream offset (``base + payload bytes``), so clients never see
compaction: resend-from-cursor semantics are unchanged.  Both rewrites
are single ``os.replace``\\ s, so any crash leaves either the old or
the new file — and a crash between checkpoint write and journal
truncate is healed on recovery by replaying only the journal suffix
past the checkpoint's cursor (:meth:`read_ops` ``from_cursor``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple


def _fsync_booked(fd: int) -> None:
    """fsync, booking the wall as ``journal_fsync_s`` phase self-time
    on the enclosing telemetry span (ISSUE 16) — the durability tax
    becomes attributable instead of vanishing into span totals."""
    t0 = time.perf_counter()
    os.fsync(fd)
    from jepsen_tpu.telemetry import spans as _spans

    _spans.add_phase("journal_fsync_s", time.perf_counter() - t0)

__all__ = ["SessionJournal", "split_segment", "op_feedable", "read_meta",
           "write_checkpoint", "read_checkpoint",
           "JOURNAL_FILE", "META_FILE", "CHECKPOINT_FILE"]

JOURNAL_FILE = "journal.jsonl"
META_FILE = "session.json"
CHECKPOINT_FILE = "checkpoint.npz"


def read_meta(dirpath: str) -> Optional[Dict[str, Any]]:
    """A session dir's ``session.json`` snapshot, or None.  Module-level
    so read-only surfaces (web listings, warehouse ingest) never
    construct a :class:`SessionJournal` — whose recovery would
    *truncate* another process's torn tail out from under it."""
    try:
        with open(os.path.join(dirpath, META_FILE)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


_OP_TYPES = frozenset({"invoke", "ok", "fail", "info"})
_SCALAR = (str, int, float, bool, type(None))


def _mop_ok(m: Any) -> bool:
    if not isinstance(m, (list, tuple)) or len(m) < 2 \
            or not isinstance(m[1], _SCALAR):
        return False
    kind = m[0]
    if kind in ("append", "w"):
        return len(m) >= 3 and isinstance(m[2], _SCALAR)
    if kind == "r":
        if len(m) < 3 or m[2] is None:
            return True
        return isinstance(m[2], list) and \
            all(isinstance(v, _SCALAR) for v in m[2])
    return False


def op_feedable(rec: Dict[str, Any]) -> bool:
    """Can the packer/session actually consume this op dict?  The
    journal's acceptance predicate: a line that parses as JSON but
    would blow up `Op.from_dict`/`TxnPacker.feed` (missing/unknown
    ``type``, a non-list client value, malformed or unhashable mops)
    must be REFUSED before it is fsync'd — a journaled-but-unfeedable
    op would brick the session on every replay."""
    if rec.get("type") not in _OP_TYPES:
        return False
    p = rec.get("process")
    if not (isinstance(p, int) and p >= 0):
        return True  # non-client op: the packer skips it entirely
    v = rec.get("value")
    if v is None:
        return True
    if not isinstance(v, list):
        return False
    return all(_mop_ok(m) for m in v)


def split_segment(body: bytes) -> Tuple[bytes, int, List[Dict[str, Any]]]:
    """Validate one streamed segment: returns ``(accepted_bytes,
    n_lines, ops)`` where ``accepted_bytes`` is the longest prefix of
    complete, parseable, FEEDABLE op-dict lines (:func:`op_feedable`).
    A torn trailing line (no newline) is left for the client's next
    send; a complete-but-corrupt/unfeedable line stops acceptance at
    its start (the client gets the cursor before it and must fix its
    stream)."""
    ops: List[Dict[str, Any]] = []
    accepted = 0
    n = 0
    start = 0
    while True:
        nl = body.find(b"\n", start)
        if nl < 0:
            break
        line = body[start:nl]
        if line.strip():
            try:
                rec = json.loads(line)
            except ValueError:
                break
            if not isinstance(rec, dict) or not op_feedable(rec):
                break
            ops.append(rec)
            n += 1
        start = nl + 1
        accepted = start
    return body[:accepted], n, ops


def _header_line(base: int) -> bytes:
    return json.dumps({"_journal": 1, "base": int(base)}).encode() + b"\n"


def _parse_header(line: bytes) -> Optional[int]:
    """The compaction header's base cursor, or None when `line` is an
    ordinary (pre-compaction) payload line."""
    if not line.startswith(b'{"_journal"'):
        return None
    try:
        doc = json.loads(line)
    except ValueError:
        return None
    if isinstance(doc, dict) and doc.get("_journal") == 1:
        try:
            return max(0, int(doc.get("base", 0)))
        except (TypeError, ValueError):
            return None
    return None


class SessionJournal:
    """Append-only fsync'd op journal for one verifier session.

    ``cursor`` is the LOGICAL stream offset (``base`` + on-disk payload
    bytes); ``base > 0`` after a :meth:`compact` — the truncated prefix
    lives in the session checkpoint."""

    def __init__(self, dirpath: str):
        self.dir = dirpath
        self.path = os.path.join(dirpath, JOURNAL_FILE)
        os.makedirs(dirpath, exist_ok=True)
        self._f = None
        self.base = 0
        self._header_len = 0
        self.cursor = self.recover()

    def recover(self) -> int:
        """Scan the journal, truncating a torn/corrupt/unfeedable tail
        back to the last complete replayable line; returns the durable
        cursor — exactly the prefix :meth:`read_ops` will replay, so
        the ack cursor and the replayed state can't diverge."""
        self.base = 0
        self._header_len = 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0
        good = 0
        first = True
        with open(self.path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break
                if first:
                    first = False
                    base = _parse_header(line)
                    if base is not None:
                        self.base = base
                        self._header_len = len(line)
                        good += len(line)
                        continue
                if line.strip():
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break
                    if not isinstance(rec, dict) or not op_feedable(rec):
                        break
                good += len(line)
        if good < size:
            with open(self.path, "rb+") as f:
                f.truncate(good)
        return self.base + (good - self._header_len)

    def disk_bytes(self) -> int:
        """On-disk journal size — the quantity compaction bounds (the
        ``verifier-journal-bytes`` gauge)."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def compact(self, upto: int) -> None:
        """Truncate the replayed prefix: rewrite the journal as a
        ``base=upto`` header plus the payload past ``upto``.  The
        caller (the service) has already checkpointed the session state
        at cursor ``upto``; the rewrite is one atomic ``os.replace``,
        and the logical cursor is unchanged."""
        upto = int(upto)
        if upto < self.base or upto > self.cursor:
            raise ValueError(
                f"compact cursor {upto} outside journal window "
                f"[{self.base}, {self.cursor}]")
        suffix = b""
        try:
            with open(self.path, "rb") as f:
                f.seek(self._header_len + (upto - self.base))
                suffix = f.read()
        except FileNotFoundError:
            if upto < self.cursor:
                # acked payload past `upto` must exist on disk —
                # rewriting header-only here would silently drop it
                # and break resend-from-cursor.  (A read failure on a
                # present file propagates for the same reason: the
                # caller treats a failed compact as a no-op that
                # leaves the journal whole.)
                raise
        self.close()  # the append handle points at the old inode
        header = _header_line(upto)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(header + suffix)
            f.flush()
            _fsync_booked(f.fileno())
        os.replace(tmp, self.path)
        self.base = upto
        self._header_len = len(header)

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "ab", buffering=0)
        return self._f

    def append(self, data: bytes) -> int:
        """Append pre-validated journal bytes; fsync; return the new
        cursor.  The caller (the service) acks only after this
        returns — accepted segments land durably before the ack."""
        if not data:
            return self.cursor
        f = self._file()
        f.write(data)
        _fsync_booked(f.fileno())
        self.cursor += len(data)
        return self.cursor

    def read_ops(self, chunk_lines: int = 4096,
                 from_cursor: Optional[int] = None
                 ) -> Iterator[List[Dict[str, Any]]]:
        """Replay the journal as op-dict chunks (history order).  A
        torn tail (only possible before :meth:`recover` ran) is
        dropped, and replay STOPS at an unfeedable line (impossible
        through `split_segment`; external corruption otherwise) — the
        same discipline as every jsonl reader in the repo.

        ``from_cursor`` (a logical stream offset, e.g. a checkpoint's
        cursor) skips the already-checkpointed prefix — it is always a
        line boundary because cursors only ever advance by accepted
        complete lines."""
        out: List[Dict[str, Any]] = []
        try:
            f = open(self.path, "rb")
        except OSError:
            return
        with f:
            if self._header_len:
                f.seek(self._header_len)
            if from_cursor is not None and from_cursor > self.base:
                f.seek(self._header_len + (from_cursor - self.base))
            for line in f:
                if not line.endswith(b"\n"):
                    break
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break
                if not isinstance(rec, dict) or not op_feedable(rec):
                    break
                out.append(rec)
                if len(out) >= chunk_lines:
                    yield out
                    out = []
        if out:
            yield out

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    # -- checkpoint (the compacted prefix's state snapshot) --------------

    def write_checkpoint(self, cols: Dict[str, Any],
                         meta: Dict[str, Any]) -> None:
        write_checkpoint(self.dir, cols, meta)

    def read_checkpoint(self):
        return read_checkpoint(self.dir)

    # -- session meta (atomic state snapshot for read-only surfaces) -----

    def write_meta(self, state: Dict[str, Any]) -> None:
        """Atomically replace ``session.json`` — the state snapshot the
        web pages and the warehouse ingest read without the service."""
        tmp = os.path.join(self.dir, META_FILE + ".tmp")
        try:
            with open(tmp, "w") as f:
                json.dump(state, f, indent=1, sort_keys=True)
            os.replace(tmp, os.path.join(self.dir, META_FILE))
        except OSError:
            pass

    def read_meta(self) -> Optional[Dict[str, Any]]:
        return read_meta(self.dir)


def write_checkpoint(dirpath: str, cols: Dict[str, Any],
                     meta: Dict[str, Any]) -> None:
    """Persist a session checkpoint: the packed SoA prefix (binary
    columns — ~10x smaller than the jsonl they replace) plus a JSON
    meta blob (packer interners, counters, the checkpoint cursor)
    embedded as a uint8 array so the whole checkpoint is ONE file and
    one atomic ``os.replace``."""
    import numpy as np

    blob = np.frombuffer(json.dumps(meta, sort_keys=True).encode(),
                         dtype=np.uint8)
    tmp = os.path.join(dirpath, CHECKPOINT_FILE + ".tmp.npz")
    # np.savez appends .npz when missing — name the tmp with the suffix
    # so the path we fsync/replace is the one actually written
    np.savez(tmp[:-len(".npz")], _meta_json=blob,
             **{k: np.asarray(v) for k, v in cols.items()})
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(dirpath, CHECKPOINT_FILE))


def read_checkpoint(dirpath: str):
    """Load a session checkpoint → ``(cols, meta)`` or None (absent or
    unreadable — the caller then replays the whole journal, which is
    only possible when no compaction ever truncated it)."""
    import zipfile

    import numpy as np

    path = os.path.join(dirpath, CHECKPOINT_FILE)
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["_meta_json"]).decode())
            cols = {k: z[k] for k in z.files if k != "_meta_json"}
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None
    if not isinstance(meta, dict) or "cursor" not in meta:
        return None
    return cols, meta
