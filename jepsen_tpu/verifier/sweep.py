"""Multi-tenant batched dirty-region sweeps (ISSUE 13 tentpole).

The per-session sweep (`session.VerifierSession.sweep`) is exact but
host-bound: each session computes its own dirty region and runs Tarjan
plus the per-spec cycle search there.  With hundreds of live sessions
that per-session host pass is the scaling wall — each dispatch is tiny,
so nothing amortizes.

This module packs MANY sessions' dirty regions into ONE
`ops.cycle_sweep.detect_cycles` dispatch:

1. per session (cheap, host, under that session's lock): compute the
   dirty region ``reach(dirty heads) ∩ coreach(dirty tails)`` in the
   union cycle-spec projection and extract its compacted subgraph —
   an empty region means the session is clean this round and commits
   without any dispatch;
2. concatenate every non-empty region block-diagonally (node offsets;
   rank = node id, so each block keeps its arrival order and no edge
   crosses blocks), pad nodes/edges to power-of-two shape classes so
   the kernel executable is shared across rounds, and run ONE guarded
   `detect_cycles` rank-sweep (fault site ``verifier.sweep`` — the
   same seam the per-session chunks use, so chaos tooling and retry
   policies reach it);
3. sessions whose block carries **no backward-edge witness** are
   proven acyclic in their region — every new cycle must lie inside
   it — and commit their dirty backlog; sessions with witnesses (or a
   non-converged sweep) fall back to their own exact per-session sweep
   for spec classification, preserving verdict equality bit for bit.

The batched dispatch runs under a ``verifier.sweep`` telemetry span
(``batched=True``), so `cli obs gate` can regression-gate it like any
checker span.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu import resilience, telemetry
from jepsen_tpu.checkers.elle.specs import CYCLE_ANOMALY_SPECS
from jepsen_tpu.resilience import Deadline

from .session import SWEEP_SITE, VerifierSession, _csr, _reach

logger = logging.getLogger("jepsen.verifier")

__all__ = ["region_snapshot", "batched_sweep"]


def _union_rels(sess: VerifierSession) -> set:
    union: set = set()
    for name in sess._cycle_specs:
        union |= CYCLE_ANOMALY_SPECS[name].rels
    return union


def region_snapshot(sess: VerifierSession) -> Optional[Dict[str, Any]]:
    """One session's dirty-region subgraph, computed under the caller's
    (the session's) lock.  Returns None when there is nothing to sweep,
    ``{"kind": "rebuild"}`` when a retraction armed the full resweep
    (that session sweeps itself), ``{"kind": "clean", "k": n}`` when
    the dirty edges provably close no region (commit immediately), or
    ``{"kind": "region", ...}`` with the compacted region subgraph."""
    if sess._rebuild:
        return {"kind": "rebuild"}
    k = len(sess._pending)
    if not k:
        return None
    # staleness stamp: a concurrent per-session sweep (an HTTP verdict
    # between this snapshot and the batched commit) bumps the epoch —
    # the commit must notice and not mark the POST-snapshot dirty
    # edges as swept.  The epoch is monotonic; len(_swept) would not
    # do, since a rebuild sweep resets it to 1
    stamp = sess._sweep_epoch
    pending_specs = [s for s in sess._cycle_specs
                     if s not in sess._cycle_found]
    if not pending_specs:
        return {"kind": "clean", "k": k, "stamp": stamp}
    union = _union_rels(sess)
    full = sess._all_edges()
    p_mask = np.isin(full[:, 2], list(union)) if len(full) else \
        np.zeros(0, bool)
    src = full[p_mask, 0]
    dst = full[p_mask, 1]
    dirty = np.asarray(sess._pending, np.int64).reshape(-1, 3)
    d_mask = np.isin(dirty[:, 2], list(union))
    if not d_mask.any() or not len(src):
        return {"kind": "clean", "k": k, "stamp": stamp}
    heads = np.unique(dirty[d_mask, 1])
    tails = np.unique(dirty[d_mask, 0])
    fwd = _reach(sess._n_nodes, _csr(sess._n_nodes, src, dst), heads)
    bwd = _reach(sess._n_nodes, _csr(sess._n_nodes, dst, src), tails,
                 within=fwd)
    region = np.nonzero(fwd & bwd)[0]
    if not len(region):
        return {"kind": "clean", "k": k, "stamp": stamp}
    remap = np.full(sess._n_nodes, -1, np.int64)
    remap[region] = np.arange(len(region))
    in_r = (remap[src] >= 0) & (remap[dst] >= 0)
    rs = remap[src[in_r]]
    rd = remap[dst[in_r]]
    if not len(rs):
        return {"kind": "clean", "k": k, "stamp": stamp}
    return {"kind": "region", "k": k, "stamp": stamp,
            "n": int(len(region)),
            "src": rs.astype(np.int32), "dst": rd.astype(np.int32)}


def _commit(sess: VerifierSession, k: int) -> None:
    """Move the first ``k`` dirty edges (the swept snapshot prefix —
    `_pending` is append-only between sweeps, so edges ingested after
    the snapshot stay dirty) into the swept store."""
    if k <= 0:
        return
    chunk = np.asarray(sess._pending[:k], np.int64).reshape(-1, 3)
    if len(chunk):
        sess._swept.append(chunk)
    sess._pending = sess._pending[k:]
    sess._sweep_epoch += 1


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _dispatch(regions: List[Dict[str, Any]],
              deadline: Optional[Deadline],
              n_sessions: int) -> Tuple[bool, set]:
    """One block-diagonal `detect_cycles` over every region.  Returns
    ``(converged, hit_blocks)`` — blocks whose region carries a
    backward-edge witness (a cycle passes through them)."""
    from jepsen_tpu.ops.cycle_sweep import SweepGraph, detect_cycles

    node_off: List[int] = []
    edge_bounds: List[int] = [0]
    srcs, dsts = [], []
    n_nodes = 0
    for r in regions:
        node_off.append(n_nodes)
        srcs.append(r["src"] + n_nodes)
        dsts.append(r["dst"] + n_nodes)
        n_nodes += r["n"]
        edge_bounds.append(edge_bounds[-1] + len(r["src"]))
    src = np.concatenate(srcs).astype(np.int32)
    dst = np.concatenate(dsts).astype(np.int32)
    n_edges = len(src)
    # pow2 shape classes: the jitted kernel executable is shared across
    # maintenance rounds instead of recompiling per (N, E)
    n_pad = _pow2(max(2, n_nodes))
    e_pad = _pow2(max(2, n_edges))
    mask = np.zeros(e_pad, bool)
    mask[:n_edges] = True
    g = SweepGraph(
        n_nodes=n_pad,
        rank=np.arange(n_pad, dtype=np.int32),
        nc_src=np.concatenate(
            [src, np.zeros(e_pad - n_edges, np.int32)]),
        nc_dst=np.concatenate(
            [dst, np.zeros(e_pad - n_edges, np.int32)]),
        nc_mask=mask,
        chain_nodes=np.zeros(0, np.int32),
        chain_starts=np.zeros(0, bool),
        chain_mask=np.zeros(0, bool),
    )
    with telemetry.span("verifier.sweep", batched=True,
                        sessions=n_sessions, regions=len(regions),
                        nodes=n_nodes, edges=n_edges):
        t0 = time.perf_counter()
        res = resilience.device_call(SWEEP_SITE, detect_cycles, g,
                                     deadline=deadline)
        telemetry.add_phase("sweep_s", time.perf_counter() - t0)
    if not res.converged:
        return False, set()
    hits: set = set()
    if res.has_cycle:
        bounds = np.asarray(edge_bounds[1:])
        for eid in np.asarray(res.witness_edge_ids):
            hits.add(int(np.searchsorted(bounds, int(eid),
                                         side="right")))
    return True, hits


def batched_sweep(lives: List[Any],
                  deadline: Optional[Deadline] = None
                  ) -> Dict[str, int]:
    """Sweep every dirty session in ``lives`` (service `_Live` objects)
    through one batched dispatch.  Returns stats: sessions considered /
    committed clean / classified via their own sweep / rebuilt."""
    stats = {"dirty": 0, "clean": 0, "classified": 0, "rebuild": 0,
             "dispatched": 0}
    snaps: List[Tuple[Any, Dict[str, Any]]] = []
    for live in lives:
        with live.lock:
            if live.dead or live.state == "sealed":
                continue
            snap = region_snapshot(live.session)
        if snap is not None:
            snaps.append((live, snap))
    if not snaps:
        return stats
    stats["dirty"] = len(snaps)
    regions = [(i, live, s) for i, (live, s) in enumerate(snaps)
               if s["kind"] == "region"]
    conv = True
    hits: set = set()
    if regions:
        stats["dispatched"] = 1
        conv, hit_blocks = _dispatch([s for _, _, s in regions],
                                     deadline, len(snaps))
        hits = {regions[b][0] for b in hit_blocks if b < len(regions)}
    for i, (live, snap) in enumerate(snaps):
        with live.lock:
            if live.dead:
                continue
            sess = live.session
            if snap["kind"] == "rebuild":
                stats["rebuild"] += 1
                sess.sweep(deadline=deadline)
            elif snap["kind"] == "region" and (not conv or i in hits):
                # a witness passes through this block (or the batched
                # pass could not prove anything): the session's own
                # exact sweep classifies per spec — verdict equality
                # with the unbatched path holds bit for bit
                stats["classified"] += 1
                sess.sweep(deadline=deadline)
            elif sess._sweep_epoch != snap["stamp"] \
                    or len(sess._pending) < snap["k"] \
                    or sess._rebuild:
                # STALE: a per-session sweep (an HTTP verdict) ran
                # between our snapshot and this commit — the first k
                # pending edges are no longer the ones we proved
                # acyclic.  Re-sweep exactly; never mark post-snapshot
                # edges swept.
                stats["classified"] += 1
                sess.sweep(deadline=deadline)
            else:
                stats["clean"] += 1
                _commit(sess, snap["k"])
    return stats
