"""The live-check client: `core.run`'s interpreter → verifier stream.

ISSUE 13 tentpole (a).  A :class:`LiveCheck` turns a running test into
a verifier session *while it executes*: the interpreter's dispatch loop
feeds every history event (invokes and completions, in history order)
into :meth:`feed`, a background sender flushes them as journal-shaped
jsonl segments with the cursor protocol (resend-from-acked-cursor on
reconnect, so a lost ack or a restarted verifier never doubles ops),
and :meth:`finish` closes the loop — rolling verdict, optional seal
(incremental == batch asserted server-side).

Transports:

- ``{"url": "http://host:port"}`` — a remote verifier service
  (``cli serve --ingest``, or a fleet coordinator serving one); every
  call rides `resilience.device_call` (fault site ``verifier.live``)
  with a seeded `RetryPolicy` + `is_transient_http`, so coordinator
  restarts and partitions are ridden out with bounded backoff;
- ``{"inproc": true}`` — an in-process `VerifierService` over the
  run's own store (no daemon needed; campaign cells use this, and the
  service's ``verifier.sweep`` spans then land in the run's telemetry
  where ``cli obs gate`` can regression-gate them).

Graceful degradation is the contract: a verifier partitioned past
``budget-s`` (cumulative outage) flips the client to **degraded** —
feeding becomes a no-op, the run completes normally, the ordinary
stored-history check stands alone, and the results carry
``{"live-check": {"state": "degraded", ...}}``.  The live path is an
accelerant, never a dependency.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
import zlib
from typing import Any, Dict, Optional

from jepsen_tpu import resilience, store
from jepsen_tpu.resilience import RetryPolicy
from jepsen_tpu.resilience.policy import is_transient_http
from jepsen_tpu.telemetry import spans as spans_mod

logger = logging.getLogger("jepsen.verifier")

__all__ = ["LiveCheck", "live_check_for", "LIVE_SITE"]

#: the client-side fault/guard site (FaultPlan target): chaos tooling
#: partitions the live stream here without touching the workload
LIVE_SITE = "verifier.live"


class LiveCheck:
    """One live-checked session for one run.  Thread contract: `feed`
    is called from the interpreter's single dispatch thread (cheap:
    serialize + append under a lock); a daemon sender thread owns all
    network/service I/O, so a slow or partitioned verifier never
    stalls the workload."""

    def __init__(self, target: Any, session: str, *,
                 seal: bool = True,
                 budget_s: float = 5.0,
                 flush_ops: int = 256,
                 flush_interval_s: float = 0.25,
                 timeout_s: float = 3.0,
                 retry: Optional[RetryPolicy] = None,
                 open_config: Optional[Dict[str, Any]] = None):
        self.session = session
        self.seal = bool(seal)
        self.budget_s = float(budget_s)
        self.flush_ops = max(1, int(flush_ops))
        self.flush_interval_s = float(flush_interval_s)
        self.timeout_s = float(timeout_s)
        self.retry = retry or RetryPolicy(
            max_attempts=4, base_delay_s=0.1, multiplier=2.0,
            max_delay_s=1.0,
            # stable per-session seed: hash() is randomized per
            # process (PYTHONHASHSEED), which would make the backoff
            # jitter — alone in this repo — non-replayable
            seed=zlib.crc32(session.encode()) & 0xFFFF,
            classify=is_transient_http)
        self._url: Optional[str] = None
        self._svc = None
        self._own_svc = False  # set by live_check_for for in-proc mode
        if isinstance(target, str):
            self._url = target.rstrip("/")
        else:
            self._svc = target
        # distributed trace (ISSUE 14): the session rides its run's
        # trace — from the open config's trace-id (fleet cells), else
        # whatever trace core.run installed on this thread.  Captured
        # here because the sender runs on its own thread, where the
        # thread-local would be empty.
        tid = (open_config or {}).get("trace-id")
        self._trace: Optional[spans_mod.TraceContext] = (
            spans_mod.trace_context(str(tid), "verifier:live") if tid
            else spans_mod.current_trace())
        self._lock = threading.Lock()
        self._buf = bytearray()      # unacked bytes (suffix of stream)
        self._cursor = 0             # acked logical stream offset
        self.ops_fed = 0
        self.ops_dropped = 0         # unserializable ops (skipped)
        self.degraded = False
        self.last_error: Optional[str] = None
        self._outage_s = 0.0
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._sender: Optional[threading.Thread] = None
        self._opened = False
        self._open(open_config)
        if not self.degraded:
            self._sender = threading.Thread(
                target=self._sender_loop, daemon=True,
                name=f"live-check-{session}")
            self._sender.start()

    # -- transport ----------------------------------------------------------

    def _call(self, what: str, fn) -> Any:
        """One guarded verifier call: fault site ``verifier.live``,
        transient retries per the seeded policy, run under the
        session's trace context (so the in-proc service's trace
        adoption sees it even from the sender thread).  Raises when
        retries are exhausted — the caller accounts the outage, this
        just names the verb (`what`) in the diagnostic."""
        try:
            with spans_mod.trace_scope(self._trace):
                return resilience.device_call(LIVE_SITE, fn,
                                              policy=self.retry)
        except Exception as e:
            logger.debug("live-check %s: %s failed (%s)",
                         self.session, what, e)
            raise

    def _http(self, method: str, path: str, body: bytes = b""
              ) -> Dict[str, Any]:
        req = urllib.request.Request(
            self._url + path, data=body if method == "POST" else None,
            method=method)
        if self._trace is not None:
            req.add_header(spans_mod.TRACE_HEADER, self._trace.header())
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            return json.loads(r.read().decode() or "{}")

    def _svc_checked(self, code: int, doc: Dict[str, Any]
                     ) -> Dict[str, Any]:
        """In-proc responses mirror HTTP semantics: 5xx raises like a
        transport error (retried / counted against the budget), 4xx is
        a protocol error and propagates."""
        if code >= 500:
            raise OSError(f"verifier {code}: {doc.get('error')}")
        if code >= 400:
            raise ValueError(f"verifier {code}: {doc.get('error')}")
        return doc

    def _open(self, config: Optional[Dict[str, Any]]) -> None:
        try:
            if self._svc is not None:
                self._call("open", lambda: self._svc_checked(
                    *self._svc.open(self.session, config)))
            else:
                body = json.dumps(config or {}).encode()
                self._call("open", lambda: self._http(
                    "POST", f"/verifier/{self.session}/open", body))
            self._opened = True
        except Exception as e:  # noqa: BLE001 — a dead verifier at
            # open time degrades immediately; the run proceeds
            self._degrade(f"open failed: {type(e).__name__}: {e}")

    def _ingest(self, body: bytes, cursor: int) -> Dict[str, Any]:
        if self._svc is not None:
            return self._call("ingest", lambda: self._svc_checked(
                *self._svc.ingest(self.session, body, cursor=cursor)))
        return self._call("ingest", lambda: self._http(
            "POST", f"/ingest/{self.session}?cursor={cursor}", body))

    # -- the feed path (interpreter dispatch thread) ------------------------

    def feed(self, op: Dict[str, Any]) -> None:
        """Append one history event.  Never raises, never blocks on
        I/O; once degraded it is a no-op."""
        if self.degraded:
            return
        try:
            line = json.dumps(op).encode() + b"\n"
        except (TypeError, ValueError):
            self.ops_dropped += 1
            return
        with self._lock:
            self._buf.extend(line)
            self.ops_fed += 1
            n = len(self._buf)
        if n >= self.flush_ops * 64:  # rough bytes heuristic; the
            self._kick.set()          # sender also wakes on interval
        if self.ops_fed % self.flush_ops == 0:
            self._kick.set()

    # -- the sender (background) --------------------------------------------

    def _degrade(self, why: str) -> None:
        self.last_error = why
        if not self.degraded:
            self.degraded = True
            with self._lock:
                self._buf.clear()
            logger.warning("live-check %s degraded: %s (run proceeds; "
                           "stored-history check takes over)",
                           self.session, why)
            try:
                from jepsen_tpu import telemetry

                telemetry.registry().counter(
                    "verifier-live-degraded").inc()
            except Exception:  # noqa: BLE001
                pass

    def _flush_once(self) -> bool:
        """Send the unacked buffer from the acked cursor.  Returns True
        when something was acked (or nothing needed sending)."""
        with self._lock:
            body = bytes(self._buf)
            cursor = self._cursor
        if not body:
            return True
        t0 = time.monotonic()
        try:
            r = self._ingest(body, cursor)
        except Exception as e:  # noqa: BLE001 — outage accounting
            self._outage_s += time.monotonic() - t0
            self.last_error = f"{type(e).__name__}: {e}"
            if self._outage_s > self.budget_s:
                self._degrade(
                    f"outage {self._outage_s:.1f}s past the "
                    f"{self.budget_s:.1f}s budget ({self.last_error})")
            return False
        self._outage_s = 0.0  # contact restored resets the budget
        new_cursor = int(r.get("cursor", cursor))
        if new_cursor > cursor:
            with self._lock:
                drop = new_cursor - self._cursor
                if 0 < drop <= len(self._buf):
                    del self._buf[:drop]
                self._cursor = new_cursor
        return True

    def _sender_loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.flush_interval_s)
            self._kick.clear()
            if self.degraded:
                return
            self._flush_once()

    # -- finish -------------------------------------------------------------

    def _verdict(self) -> Dict[str, Any]:
        if self._svc is not None:
            return self._call("verdict", lambda: self._svc_checked(
                *self._svc.verdict(self.session)))
        return self._call("verdict", lambda: self._http(
            "GET", f"/verdict/{self.session}"))

    def _seal(self) -> Dict[str, Any]:
        if self._svc is not None:
            return self._call("seal", lambda: self._svc_checked(
                *self._svc.seal(self.session)))
        return self._call("seal", lambda: self._http(
            "POST", f"/verifier/{self.session}/seal"))

    def finish(self) -> Dict[str, Any]:
        """Drain the stream and close the loop: final flush, rolling
        verdict, optional seal.  Returns the summary `core.run` stamps
        into ``results["live-check"]``.  Never raises."""
        self._stop.set()
        self._kick.set()
        if self._sender is not None:
            self._sender.join(timeout=self.budget_s + 5.0)
        # the final flush gets its own bounded budget window
        deadline = time.monotonic() + self.budget_s
        while not self.degraded:
            if self._flush_once():
                with self._lock:
                    if not self._buf:
                        break
            if time.monotonic() > deadline:
                self._degrade("final flush outlasted the budget")
                break
            time.sleep(0.05)
        base = {"session": self.session, "ops": self.ops_fed,
                "ops-dropped": self.ops_dropped,
                "cursor": self._cursor}
        try:
            if self.degraded:
                return dict(base, state="degraded",
                            **({"reason": self.last_error}
                               if self.last_error else {}))
            try:
                v = self._verdict()
                out = dict(base, state="ok", **{
                    "valid?": v.get("valid?"),
                    "anomaly-types": v.get("anomaly-types"),
                    "digest": v.get("digest"),
                    "txns": v.get("txns"),
                })
                if self.seal:
                    s = self._seal()
                    out["seal"] = {"equal": s.get("equal"),
                                   "digest": s.get("digest")}
                return out
            except Exception as e:  # noqa: BLE001
                self._degrade(f"verdict/seal failed: "
                              f"{type(e).__name__}: {e}")
                return dict(base, state="degraded",
                            reason=self.last_error)
        finally:
            self._close_own_service()

    def _close_own_service(self) -> None:
        if self._own_svc and self._svc is not None:
            try:
                self._svc.close()
            except Exception:  # noqa: BLE001
                pass
            self._svc = None

    def close(self) -> None:
        """Abandon without a verdict (crashed workloads): stop the
        sender, keep whatever was already journaled server-side."""
        self._stop.set()
        self._kick.set()
        if self._sender is not None:
            self._sender.join(timeout=2.0)
        self._close_own_service()


def live_check_for(test: dict) -> Optional[LiveCheck]:
    """Build the run's `LiveCheck` from its ``"live-check"`` test key
    (campaign spec opts pass straight through `plan.build_test`):

    - a URL string, or ``{"url": ...}`` — remote service;
    - ``{"inproc": true}`` (or ``true``) — in-process service over the
      run's store;
    - knobs: ``session`` (default: the run dir identity), ``seal``,
      ``budget-s``, ``flush-ops``, ``timeout-s``, plus any verifier
      session config under ``config`` (forwarded to open).
    """
    cfg = test.get("live-check")
    if not cfg:
        return None
    if isinstance(cfg, str):
        cfg = {"url": cfg}
    elif cfg is True:
        cfg = {"inproc": True}
    if not isinstance(cfg, dict):
        raise ValueError(f"bad live-check config {cfg!r}")
    session = cfg.get("session")
    if not session:
        d = store.test_dir(test)
        session = store.sanitize(
            f"{test.get('name', 'run')}-{os.path.basename(d)}"
        ).replace(" ", "_")
    own_svc = False
    target: Any
    if cfg.get("url"):
        target = str(cfg["url"])
    else:
        from .service import VerifierService

        target = VerifierService(store._base(test))
        own_svc = True
    open_config = dict(cfg.get("config") or {})
    # trace + host attribution (ISSUE 14): the session's journal
    # metadata names the run's trace and the executing fleet host, so
    # the warehouse can stitch live-sweep segments into the run's
    # cross-host timeline and the /fleet page can show per-host
    # verdict freshness
    if test.get("trace-id"):
        open_config.setdefault("trace-id", str(test["trace-id"]))
    if test.get("fleet-host"):
        open_config.setdefault("host", str(test["fleet-host"]))
    lc = LiveCheck(
        target, str(session),
        seal=bool(cfg.get("seal", True)),
        budget_s=float(cfg.get("budget-s", 5.0)),
        flush_ops=int(cfg.get("flush-ops", 256)),
        flush_interval_s=float(cfg.get("flush-interval-s", 0.25)),
        timeout_s=float(cfg.get("timeout-s", 3.0)),
        open_config=open_config or None)
    lc._own_svc = own_svc
    return lc
