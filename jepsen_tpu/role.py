"""Role composition: one cluster, several roles.

Equivalent of the reference's `jepsen/src/jepsen/role.clj` (SURVEY.md
§2.1): split the node list into named roles (e.g. two shards plus a
coordinator), then restrict DBs, clients, nemeses, and generators to the
nodes of one role.  The test map carries ``test["roles"] = {role:
[nodes...]}``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from . import db as db_proto
from .nemesis.core import Nemesis


def roles(assignment: Dict[str, Sequence[str]]) -> Dict[str, List[str]]:
    """Normalize a role assignment map."""
    return {r: list(ns) for r, ns in assignment.items()}


def role_of(test: dict, node: str) -> Optional[str]:
    """Which role a node plays (reference `role/role`)."""
    for r, ns in (test.get("roles") or {}).items():
        if node in ns:
            return r
    return None


def nodes_of(test: dict, role: str) -> List[str]:
    """The nodes holding a role (reference `role/nodes`)."""
    return list((test.get("roles") or {}).get(role, ()))


def restrict_test(test: dict, role: str) -> dict:
    """A view of the test scoped to one role's nodes (reference
    `role/restrict-test`)."""
    sub = dict(test)
    sub["nodes"] = nodes_of(test, role)
    return sub


class RoleDB(db_proto.DB, db_proto.LogFiles, db_proto.Primary):
    """Dispatches db lifecycle calls to the role-specific DB for each node
    (reference `role/db`).  Nodes with no role (or no db for their role)
    are no-ops."""

    def __init__(self, dbs: Dict[str, Any]):
        self.dbs = dict(dbs)

    def _db_for(self, test: dict, node: str):
        return self.dbs.get(role_of(test, node))

    def setup(self, test, node):
        db = self._db_for(test, node)
        if db is not None:
            db.setup(restrict_test(test, role_of(test, node)), node)

    def teardown(self, test, node):
        db = self._db_for(test, node)
        if db is not None:
            db.teardown(restrict_test(test, role_of(test, node)), node)

    def log_files(self, test, node):
        db = self._db_for(test, node)
        if db is not None and db_proto.supports(db, db_proto.LogFiles):
            return db.log_files(restrict_test(test, role_of(test, node)),
                                node)
        return []

    def primaries(self, test):
        out = []
        for role, db in self.dbs.items():
            if db_proto.supports(db, db_proto.Primary):
                out.extend(db.primaries(restrict_test(test, role)))
        return out

    def setup_primary(self, test, node):
        db = self._db_for(test, node)
        if db is not None and db_proto.supports(db, db_proto.Primary):
            db.setup_primary(restrict_test(test, role_of(test, node)), node)


class RoleNemesis(Nemesis):
    """Scopes an inner nemesis to one role: it sees a test whose nodes are
    only that role's (reference `role/nemesis`)."""

    def __init__(self, role: str, nemesis: Nemesis):
        self.role = role
        self.nemesis = nemesis

    def setup(self, test):
        inner = self.nemesis.setup(restrict_test(test, self.role))
        return RoleNemesis(self.role, inner or self.nemesis)

    def invoke(self, test, op):
        return self.nemesis.invoke(restrict_test(test, self.role), op)

    def teardown(self, test):
        self.nemesis.teardown(restrict_test(test, self.role))


def restrict_client(role: str, client):
    """A client whose opens are pinned to the role's nodes (reference
    `role/restrict-client`): process->node mapping cycles within role."""
    from .client import Client

    class _RoleClient(Client):
        def __init__(self, inner):
            self.inner = inner

        def open(self, test, node):
            ns = nodes_of(test, role)
            if ns:
                # re-map whatever node the worker picked into the role
                idx = (test.get("nodes") or [node]).index(node) \
                    if node in (test.get("nodes") or []) else 0
                node = ns[idx % len(ns)]
            opened = self.inner.open(restrict_test(test, role), node)
            return _RoleClient(opened) if opened is not self.inner else self

        def setup(self, test):
            self.inner.setup(restrict_test(test, role))

        def invoke(self, test, op):
            return self.inner.invoke(restrict_test(test, role), op)

        def teardown(self, test):
            self.inner.teardown(restrict_test(test, role))

        def close(self, test):
            self.inner.close(restrict_test(test, role))

    return _RoleClient(client)
