"""Pure-data generator DSL.

Equivalent of the reference's `jepsen/generator.clj` (SURVEY.md §2.1): a
`Generator` protocol with two pure operations —

    op(test, ctx)            -> None | (op-or-PENDING, next-generator)
    update(test, ctx, event) -> next-generator

— plus lifting rules (dicts are one-shot op templates, functions are
infinite op factories, sequences run their elements in order) and the
combinator library (stagger, delay, sleep, mix, phases, then, any, limit,
time-limit, repeat, cycle, reserve, clients, nemesis, on-threads,
synchronize, log, until-ok, flip-flop, filter, each-thread, trace).

Generators never mutate: every transition returns a fresh generator value,
so the interpreter (and the pure test simulator in `generator/sim.py`) can
replay and backtrack freely, exactly like the reference's design.

Times in op maps are nanoseconds on the test clock; DSL entry points take
seconds (floats), mirroring the reference's second-based sugar over
nanosecond internals.
"""

from __future__ import annotations

import inspect
import logging
import random as _random
from typing import Any, Callable, List, Optional, Sequence, Tuple

from jepsen_tpu.generator.context import NEMESIS_THREAD, Context

logger = logging.getLogger("jepsen.generator")

OpResult = Optional[Tuple[Any, Optional["Generator"]]]


class _Pending:
    """Sentinel: nothing to emit right now.  May carry a wake time so the
    interpreter can sleep precisely instead of spinning."""

    __slots__ = ("time",)

    def __init__(self, time: Optional[int] = None):
        self.time = time

    def __repr__(self):
        return f"Pending(until={self.time})"


PENDING = _Pending()


def is_pending(x: Any) -> bool:
    return isinstance(x, _Pending)


def pending_until(t: int) -> _Pending:
    return _Pending(t)


def _s_to_ns(seconds: float) -> int:
    return int(seconds * 1e9)


# ---------------------------------------------------------------------------
# Protocol


class Generator:
    def op(self, test: dict, ctx: Context) -> OpResult:
        """Produce the next op.

        Returns None when exhausted, or a pair (op, gen') where op is an op
        dict (with at least :f; :process/:time filled in from ctx when
        missing) or PENDING when nothing can be emitted yet."""
        raise NotImplementedError

    def update(self, test: dict, ctx: Context, event: dict) -> "Generator":
        """Feed back an event (invoke/ok/fail/info).  Default: ignore."""
        return self


def fill_op(op: dict, ctx: Context) -> Optional[dict]:
    """Complete an op template from the context (reference `fill-in-op`):
    assign a free process and the current time where missing.  Returns None
    if the op needs a process and none is free."""
    out = dict(op)
    out.setdefault("type", "invoke")
    if out.get("process") is None:
        p = ctx.some_free_process()
        if p is None:
            return None
        out["process"] = p
    elif out["process"] not in ctx.free_processes():
        return None
    if out.get("time") is None:
        out["time"] = ctx.time
    return out


def lift(x: Any) -> Optional["Generator"]:
    """Lift a spec into a Generator.

    - None           -> None (exhausted)
    - Generator      -> itself
    - dict           -> one-shot op template
    - callable       -> infinite op factory, called as f(test, ctx) or f()
    - list/tuple     -> run elements in order
    """
    if x is None or isinstance(x, Generator):
        return x
    if isinstance(x, dict):
        return _MapGen(x)
    if callable(x):
        return _FnGen(x)
    if isinstance(x, (list, tuple)):
        return _SeqGen([e for e in x])
    raise TypeError(f"can't lift {type(x).__name__} to a generator")


def next_op(gen: Optional[Generator], test: dict, ctx: Context) -> OpResult:
    """op() on a possibly-exhausted generator."""
    if gen is None:
        return None
    return gen.op(test, ctx)


def gen_update(gen: Optional[Generator], test: dict, ctx: Context,
               event: dict) -> Optional[Generator]:
    if gen is None:
        return None
    return gen.update(test, ctx, event)


# ---------------------------------------------------------------------------
# Lifted primitives


class _MapGen(Generator):
    """A dict yields exactly one op (itself), then is exhausted — matching
    the reference, where infinite streams come from fns or `repeat`."""

    def __init__(self, template: dict):
        self.template = template

    def op(self, test, ctx):
        filled = fill_op(self.template, ctx)
        if filled is None:
            return (PENDING, self)
        return (filled, None)

    def __repr__(self):
        return f"MapGen({self.template!r})"


class _FnGen(Generator):
    """A function is an infinite generator: each op() calls f(test, ctx)
    (or f()) for an op template.  If f returns a non-dict spec, that spec
    runs to exhaustion before f is called again."""

    def __init__(self, f: Callable):
        self.f = f
        # Call f(test, ctx) whenever f *can take* two positionals (required
        # or defaulted), like the reference's 2-arity preference; f() only
        # when it can't.
        try:
            sig = inspect.signature(f)
            params = list(sig.parameters.values())
            can_take_2 = (
                any(p.kind is p.VAR_POSITIONAL for p in params)
                or len([p for p in params if p.kind in
                        (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]) >= 2)
            self._nullary = not can_take_2
        except (TypeError, ValueError):  # builtins without signatures
            self._nullary = False

    def _call(self, test, ctx):
        if self._nullary:
            return self.f()
        return self.f(test, ctx)

    def op(self, test, ctx):
        if ctx.some_free_process() is None:
            return (PENDING, self)
        x = self._call(test, ctx)
        if x is None:
            return None
        if isinstance(x, dict):
            filled = fill_op(x, ctx)
            if filled is None:
                return (PENDING, self)
            return (filled, self)
        sub = lift(x)
        return next_op(_SeqGen([sub, self]), test, ctx)


class _SeqGen(Generator):
    """Runs element generators in order; updates go to the active element."""

    def __init__(self, elements: Sequence[Any]):
        self.elements: List[Any] = list(elements)

    def op(self, test, ctx):
        elems = self.elements
        while elems:
            head = lift(elems[0])
            if head is None:
                elems = elems[1:]
                continue
            res = head.op(test, ctx)
            if res is None:
                elems = elems[1:]
                continue
            op_, head2 = res
            rest = [head2] + list(elems[1:]) if head2 is not None else list(elems[1:])
            return (op_, _SeqGen(rest) if rest else None)
        return None

    def update(self, test, ctx, event):
        if not self.elements:
            return self
        head = lift(self.elements[0])
        if head is None:
            return _SeqGen(self.elements[1:]).update(test, ctx, event)
        head2 = head.update(test, ctx, event)
        return _SeqGen([head2] + list(self.elements[1:]))


# ---------------------------------------------------------------------------
# Scheduling combinators


class _Stagger(Generator):
    """Ops spaced by uniform random delays averaging dt (reference
    `stagger`).  The schedule is tracked against the context clock, so slow
    clients don't cause a burst of catch-up ops."""

    def __init__(self, dt_ns: int, gen: Any, next_time: Optional[int] = None,
                 rng: Optional[_random.Random] = None):
        self.dt_ns = dt_ns
        self.gen = lift(gen)
        self.next_time = next_time
        self.rng = rng

    def _rand(self) -> float:
        return (self.rng or _random).random()

    def op(self, test, ctx):
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        nt = self.next_time if self.next_time is not None else ctx.time
        if is_pending(op_):
            return (op_, _Stagger(self.dt_ns, gen2, nt, self.rng))
        op_ = dict(op_)
        op_["time"] = max(op_.get("time", 0) or 0, nt)
        nt2 = nt + int(self._rand() * 2 * self.dt_ns)
        return (op_, _Stagger(self.dt_ns, gen2, nt2, self.rng))

    def update(self, test, ctx, event):
        return _Stagger(self.dt_ns, gen_update(self.gen, test, ctx, event),
                        self.next_time, self.rng)


def stagger(dt_seconds: float, gen: Any,
            rng: Optional[_random.Random] = None) -> Generator:
    return _Stagger(_s_to_ns(dt_seconds), gen, rng=rng)


class _Delay(Generator):
    """Ops spaced by exactly dt (reference `delay`)."""

    def __init__(self, dt_ns: int, gen: Any, next_time: Optional[int] = None):
        self.dt_ns = dt_ns
        self.gen = lift(gen)
        self.next_time = next_time

    def op(self, test, ctx):
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        nt = self.next_time if self.next_time is not None else ctx.time
        if is_pending(op_):
            return (op_, _Delay(self.dt_ns, gen2, nt))
        op_ = dict(op_)
        op_["time"] = max(op_.get("time", 0) or 0, nt)
        return (op_, _Delay(self.dt_ns, gen2, nt + self.dt_ns))

    def update(self, test, ctx, event):
        return _Delay(self.dt_ns, gen_update(self.gen, test, ctx, event),
                      self.next_time)


def delay(dt_seconds: float, gen: Any) -> Generator:
    return _Delay(_s_to_ns(dt_seconds), gen)


class _Sleep(Generator):
    """Emits nothing for dt, then is exhausted (reference `sleep`)."""

    def __init__(self, dt_ns: int, end: Optional[int] = None):
        self.dt_ns = dt_ns
        self.end = end

    def op(self, test, ctx):
        end = self.end if self.end is not None else ctx.time + self.dt_ns
        if ctx.time >= end:
            return None
        return (pending_until(end), _Sleep(self.dt_ns, end))


def sleep(dt_seconds: float) -> Generator:
    return _Sleep(_s_to_ns(dt_seconds))


class _TimeLimit(Generator):
    """Passes ops through until dt has elapsed from first op() call
    (reference `time-limit`)."""

    def __init__(self, dt_ns: int, gen: Any, deadline: Optional[int] = None):
        self.dt_ns = dt_ns
        self.gen = lift(gen)
        self.deadline = deadline

    def op(self, test, ctx):
        deadline = self.deadline if self.deadline is not None \
            else ctx.time + self.dt_ns
        if ctx.time >= deadline:
            return None
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        if not is_pending(op_) and (op_.get("time") or 0) >= deadline:
            return None
        return (op_, _TimeLimit(self.dt_ns, gen2, deadline))

    def update(self, test, ctx, event):
        return _TimeLimit(self.dt_ns, gen_update(self.gen, test, ctx, event),
                          self.deadline)


def time_limit(dt_seconds: float, gen: Any) -> Generator:
    return _TimeLimit(_s_to_ns(dt_seconds), gen)


# ---------------------------------------------------------------------------
# Cardinality combinators


class _Limit(Generator):
    def __init__(self, remaining: int, gen: Any):
        self.remaining = remaining
        self.gen = lift(gen)

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        n = self.remaining if is_pending(op_) else self.remaining - 1
        return (op_, _Limit(n, gen2))

    def update(self, test, ctx, event):
        return _Limit(self.remaining, gen_update(self.gen, test, ctx, event))


def limit(n: int, gen: Any) -> Generator:
    return _Limit(n, gen)


def once(gen: Any) -> Generator:
    return _Limit(1, gen)


class _Repeat(Generator):
    """Re-lifts the original spec each time it exhausts; n cycles or
    forever (reference `repeat` / `cycle`)."""

    def __init__(self, spec: Any, n: Optional[int] = None,
                 active: Optional[Generator] = None):
        self.spec = spec
        self.n = n
        self.active = active

    def op(self, test, ctx):
        active, n = self.active, self.n
        for _ in range(2):  # current cycle, then at most one fresh cycle
            if active is None:
                if n is not None:
                    if n <= 0:
                        return None
                    n = n - 1
                active = lift(self.spec)
            res = next_op(active, test, ctx)
            if res is not None:
                op_, gen2 = res
                return (op_, _Repeat(self.spec, n, gen2))
            active = None
        return None

    def update(self, test, ctx, event):
        return _Repeat(self.spec, self.n,
                       gen_update(self.active, test, ctx, event))


def repeat(spec: Any, n: Optional[int] = None) -> Generator:
    return _Repeat(spec, n)


def cycle(spec: Any) -> Generator:
    return _Repeat(spec, None)


# ---------------------------------------------------------------------------
# Composition combinators


def then(first: Any, then_gen: Any) -> Generator:
    """first, then then_gen (reference `then`, argument order normalized)."""
    return _SeqGen([first, then_gen])


class _Mix(Generator):
    """Random uniform mixture; updates broadcast to all (reference `mix`)."""

    def __init__(self, gens: Sequence[Any], rng: Optional[_random.Random] = None):
        self.gens = [lift(g) for g in gens]
        self.rng = rng

    def op(self, test, ctx):
        gens = [g for g in self.gens if g is not None]
        rng = self.rng or _random
        while gens:
            i = rng.randrange(len(gens))
            res = gens[i].op(test, ctx)
            if res is None:
                gens = gens[:i] + gens[i + 1:]
                continue
            op_, gen2 = res
            out = list(gens)
            if gen2 is None:
                out = gens[:i] + gens[i + 1:]
            else:
                out[i] = gen2
            return (op_, _Mix(out, self.rng) if out else None)
        return None

    def update(self, test, ctx, event):
        return _Mix([gen_update(g, test, ctx, event) for g in self.gens
                     if g is not None], self.rng)


def mix(gens: Sequence[Any], rng: Optional[_random.Random] = None) -> Generator:
    return _Mix(gens, rng)


class _Any(Generator):
    """Emits the soonest op offered by any sub-generator (reference `any`)."""

    def __init__(self, gens: Sequence[Any]):
        self.gens = [lift(g) for g in gens]

    def op(self, test, ctx):
        best = None  # (time, i, op, gen2)
        pend = None
        alive = False
        out = list(self.gens)
        for i, g in enumerate(self.gens):
            res = next_op(g, test, ctx)
            if res is None:
                out[i] = None
                continue
            alive = True
            op_, gen2 = res
            if is_pending(op_):
                # a consumed pending is a no-op, so keeping the successor is
                # safe — and necessary: e.g. _Sleep fixes its end time in its
                # successor, which must not be recomputed every poll
                out[i] = gen2
                if pend is None or (op_.time or 0) < (pend.time or 0):
                    pend = op_
                continue
            t = op_.get("time") or 0
            if best is None or t < best[0]:
                best = (t, i, op_, gen2)
        if best is not None:
            _, i, op_, gen2 = best
            # build on `out`, not self.gens: pending successors recorded in
            # out (e.g. _Sleep's fixed end time) must survive this poll
            chosen = list(out)
            chosen[i] = gen2
            return (op_, _Any(chosen))
        if alive:
            return (pend or PENDING, _Any(out))
        return None

    def update(self, test, ctx, event):
        return _Any([gen_update(g, test, ctx, event) for g in self.gens])


def any_gen(*gens: Any) -> Generator:
    return _Any(gens)


class _FlipFlop(Generator):
    """Alternates ops between two generators (reference `flip-flop`);
    exhausted when either side is."""

    def __init__(self, a: Any, b: Any, turn: int = 0):
        self.sides = [lift(a), lift(b)]
        self.turn = turn

    def op(self, test, ctx):
        g = self.sides[self.turn]
        res = next_op(g, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        sides = list(self.sides)
        sides[self.turn] = gen2
        turn = self.turn if is_pending(op_) else 1 - self.turn
        return (op_, _FlipFlop(sides[0], sides[1], turn))

    def update(self, test, ctx, event):
        return _FlipFlop(gen_update(self.sides[0], test, ctx, event),
                         gen_update(self.sides[1], test, ctx, event),
                         self.turn)


def flip_flop(a: Any, b: Any) -> Generator:
    return _FlipFlop(a, b)


# ---------------------------------------------------------------------------
# Predicates & transforms


class _Filter(Generator):
    def __init__(self, pred: Callable[[dict], bool], gen: Any):
        self.pred = pred
        self.gen = lift(gen)

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = next_op(gen, test, ctx)
            if res is None:
                return None
            op_, gen2 = res
            if is_pending(op_) or self.pred(op_):
                return (op_, _Filter(self.pred, gen2))
            gen = gen2

    def update(self, test, ctx, event):
        return _Filter(self.pred, gen_update(self.gen, test, ctx, event))


def filter_gen(pred: Callable[[dict], bool], gen: Any) -> Generator:
    return _Filter(pred, gen)


class _FMap(Generator):
    """Transforms emitted ops with f (reference `map`/`f-map`)."""

    def __init__(self, f: Callable[[dict], dict], gen: Any):
        self.f = f
        self.gen = lift(gen)

    def op(self, test, ctx):
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        if not is_pending(op_):
            op_ = self.f(dict(op_))
        return (op_, _FMap(self.f, gen2))

    def update(self, test, ctx, event):
        return _FMap(self.f, gen_update(self.gen, test, ctx, event))


def f_map(f: Callable[[dict], dict], gen: Any) -> Generator:
    return _FMap(f, gen)


class _UntilOk(Generator):
    """Runs gen until an :ok completion is observed (reference `until-ok`)."""

    def __init__(self, gen: Any, done: bool = False):
        self.gen = lift(gen)
        self.done = done

    def op(self, test, ctx):
        if self.done:
            return None
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        return (op_, _UntilOk(gen2, False))

    def update(self, test, ctx, event):
        done = self.done or event.get("type") == "ok"
        return _UntilOk(gen_update(self.gen, test, ctx, event), done)


def until_ok(gen: Any) -> Generator:
    return _UntilOk(gen)


# ---------------------------------------------------------------------------
# Thread-restriction combinators


class _OnThreads(Generator):
    """Restricts a generator to the threads matching pred; both ops and
    updates see (and only see) the restricted context (reference
    `on-threads`)."""

    def __init__(self, pred: Callable[[Any], bool], gen: Any):
        self.pred = pred
        self.gen = lift(gen)

    def op(self, test, ctx):
        sub = ctx.restrict(self.pred)
        if not sub.workers:
            return None
        res = next_op(self.gen, test, sub)
        if res is None:
            return None
        op_, gen2 = res
        return (op_, _OnThreads(self.pred, gen2))

    def update(self, test, ctx, event):
        p = event.get("process")
        try:
            t = ctx.thread_for_process(p)
        except KeyError:
            return self
        if not self.pred(t):
            return self
        sub = ctx.restrict(self.pred)
        return _OnThreads(self.pred, gen_update(self.gen, test, sub, event))


def on_threads(pred: Callable[[Any], bool], gen: Any) -> Generator:
    return _OnThreads(pred, gen)


def clients(gen: Any) -> Generator:
    """Restrict to client (integer) threads (reference `clients`)."""
    return _OnThreads(lambda t: isinstance(t, int), gen)


def nemesis(gen: Any) -> Generator:
    """Restrict to the nemesis thread (reference `nemesis`)."""
    return _OnThreads(lambda t: t == NEMESIS_THREAD, gen)


def reserve(*args: Any) -> Generator:
    """reserve(n1, gen1, n2, gen2, ..., default): the first n1 client
    threads run gen1, the next n2 run gen2, ..., remaining client threads
    run the default (reference `reserve`)."""
    if len(args) % 2 != 1:
        raise ValueError("reserve needs (n, gen)* pairs plus a default")
    pairs = list(zip(args[:-1:2], args[1:-1:2]))
    default = args[-1]
    gens = []
    lo = 0
    for n, g in pairs:
        hi = lo + n
        gens.append(_OnThreads(
            (lambda lo=lo, hi=hi: lambda t: isinstance(t, int) and lo <= t < hi)(),
            g))
        lo = hi
    cut = lo
    gens.append(_OnThreads(lambda t: isinstance(t, int) and t >= cut, default))
    return _Any(gens)


class _Synchronize(Generator):
    """Barriers the start of gen until every thread in ctx is free
    (reference `synchronize`)."""

    def __init__(self, gen: Any, started: bool = False):
        self.gen = lift(gen)
        self.started = started

    def op(self, test, ctx):
        if not self.started and ctx.free_count() < len(ctx.workers):
            return (PENDING, self)
        res = next_op(self.gen, test, ctx)
        if res is None:
            return None
        op_, gen2 = res
        return (op_, _Synchronize(gen2, True))

    def update(self, test, ctx, event):
        return _Synchronize(gen_update(self.gen, test, ctx, event),
                            self.started)


def synchronize(gen: Any) -> Generator:
    return _Synchronize(gen)


def phases(*gens: Any) -> Generator:
    """Each phase starts only after all threads finish the previous one
    (reference `phases`)."""
    return _SeqGen([_Synchronize(g) for g in gens])


class _EachThread(Generator):
    """Every thread runs its own fresh copy of the spec (reference
    `each-thread`)."""

    def __init__(self, spec: Any, copies: Optional[dict] = None):
        self.spec = spec
        self.copies = copies  # thread -> Generator|None; None once exhausted

    def _copies_for(self, ctx) -> dict:
        if self.copies is not None:
            return self.copies
        return {t: lift(self.spec) for t in ctx.all_threads()}

    def op(self, test, ctx):
        copies = dict(self._copies_for(ctx))
        alive = False
        pend = None
        for t in ctx._sorted_free():
            g = copies.get(t, "missing")
            if g == "missing":
                g = copies[t] = lift(self.spec)
            if g is None:
                continue
            sub = ctx.restrict(lambda x, t=t: x == t)
            res = g.op(test, sub)
            if res is None:
                copies[t] = None
                continue
            op_, gen2 = res
            if is_pending(op_):
                # keep the pending successor: e.g. _Sleep fixes its end time
                # there, and it must not be recomputed on the next poll
                copies[t] = gen2
                alive = True
                if pend is None or (op_.time or 0) < (pend.time or 0):
                    pend = op_
                continue
            copies[t] = gen2
            return (op_, _EachThread(self.spec, copies))
        if any(g is not None for g in copies.values()) and (
                alive or ctx.free_count() < len(ctx.workers)):
            return (pend or PENDING, _EachThread(self.spec, copies))
        return None

    def update(self, test, ctx, event):
        if self.copies is None:
            return self
        p = event.get("process")
        try:
            t = ctx.thread_for_process(p)
        except KeyError:
            return self
        g = self.copies.get(t)
        if g is None:
            return self
        copies = dict(self.copies)
        sub = ctx.restrict(lambda x: x == t)
        copies[t] = g.update(test, sub, event)
        return _EachThread(self.spec, copies)


def each_thread(spec: Any) -> Generator:
    return _EachThread(spec)


# ---------------------------------------------------------------------------
# Observability


class _Log(Generator):
    """Logs a message when asked for an op, then is exhausted (reference
    `log`)."""

    def __init__(self, msg: str):
        self.msg = msg

    def op(self, test, ctx):
        logger.info(self.msg)
        return None


def log(msg: str) -> Generator:
    return _Log(msg)


class _Trace(Generator):
    """Logs every op/update flowing through (reference `trace`)."""

    def __init__(self, name: str, gen: Any):
        self.name = name
        self.gen = lift(gen)

    def op(self, test, ctx):
        res = next_op(self.gen, test, ctx)
        logger.debug("trace %s op -> %r", self.name,
                     None if res is None else res[0])
        if res is None:
            return None
        op_, gen2 = res
        return (op_, _Trace(self.name, gen2))

    def update(self, test, ctx, event):
        logger.debug("trace %s update <- %r", self.name, event)
        return _Trace(self.name, gen_update(self.gen, test, ctx, event))


def trace(name: str, gen: Any) -> Generator:
    return _Trace(name, gen)
