"""The concurrency engine.

Equivalent of the reference's `jepsen/generator/interpreter.clj` (SURVEY.md
§2.1, §3.4): one OS thread per client worker plus a nemesis worker; a
central loop polls the pure generator for ops, dispatches them to per-worker
queues, and feeds invocations and completions back through `gen.update`,
building the history.

Semantics mirrored exactly from the reference:
- op :time is the relative test clock (nanoseconds since run start);
- an op whose :time is in the future is held until then;
- a client exception or :info completion means the op's effect is unknown;
  the worker's process is considered crashed, its thread gets process
  p + concurrency, and its client is re-opened for the new process;
- the nemesis is driven as one more worker, never crashes, ops complete
  :info;
- workers survive client exceptions: the run always produces a history.

The pure simulator in `generator/sim.py` implements the same dispatch rules
with a virtual clock; the two are differentially tested.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu import telemetry
from jepsen_tpu.client import Client, invoke_with_errors
from jepsen_tpu.generator import core as g
from jepsen_tpu.generator.context import NEMESIS_THREAD, Context, context
from jepsen_tpu.history.ops import History, Op, history
from jepsen_tpu.utils.core import init_time_origin, relative_time_nanos

logger = logging.getLogger("jepsen.interpreter")

_STOP = object()
_TICK_S = 0.001  # poll granularity when pending with no wake time

#: the client-side chaos seam (ISSUE 4 satellite): a FaultPlan that
#: EXPLICITLY names this site (``sites`` or ``persistent``) injects
#: stalls (op latency) and crash-kind faults (``info`` completions —
#: the op's effect is unknown, the process is re-opened) into every
#: worker's invoke path.  Strictly opt-in: checker-chaos plans without
#: the site never touch the workload.
FAULT_SITE = "interpreter"

#: index-stream stride per worker: fire_at decisions hash the supplied
#: index, so giving each worker a disjoint arithmetic stream makes
#: injection deterministic per (seed, worker, local op) regardless of
#: thread interleaving
_FAULT_STRIDE = 1_000_003


class _ClientWorker:
    """Owns one thread + queue; opens a client per process incarnation."""

    def __init__(self, thread_id: int, test: dict, completions: queue.Queue,
                 plan=None):
        self.thread_id = thread_id
        self.test = test
        self.completions = completions
        self.plan = plan  # a FaultPlan targeting FAULT_SITE, or None
        self._n_ops = 0
        self.q: "queue.Queue" = queue.Queue()
        self.process: Optional[int] = None
        self.client: Optional[Client] = None
        self.thread = threading.Thread(
            target=self._run, name=f"jepsen-worker-{thread_id}", daemon=True)
        self.thread.start()

    def _node_for(self, process: int) -> Optional[str]:
        nodes = self.test.get("nodes") or []
        return nodes[process % len(nodes)] if nodes else None

    def _ensure_client(self, process: int) -> Client:
        if self.client is not None and self.process == process:
            return self.client
        if self.client is not None:
            try:
                self.client.close(self.test)
            except Exception as e:  # noqa: BLE001
                logger.warning("client close failed: %s", e)
        base: Client = self.test["client"]
        self.client = base.open(self.test, self._node_for(process))
        self.process = process
        return self.client

    def _run(self):
        while True:
            msg = self.q.get()
            if msg is _STOP:
                if self.client is not None:
                    try:
                        self.client.close(self.test)
                    except Exception as e:  # noqa: BLE001
                        logger.warning("client close failed: %s", e)
                return
            op: dict = msg
            comp = None
            if self.plan is not None:
                # stalls sleep here (client latency), crash kinds turn
                # the op into an :info completion without invoking the
                # client — indistinguishable from a client that died
                # mid-call, which is exactly what checkers must absorb
                from jepsen_tpu.resilience.faults import FaultInjected

                idx = self.thread_id * _FAULT_STRIDE + self._n_ops
                self._n_ops += 1
                try:
                    self.plan.fire_at(FAULT_SITE, idx)
                except FaultInjected as e:
                    comp = dict(op, type="info",
                                error=f"fault-injected: {e.kind}")
            if comp is None:
                try:
                    client = self._ensure_client(op["process"])
                    comp = invoke_with_errors(client, self.test, op)
                except Exception as e:  # noqa: BLE001 — open() failed
                    comp = dict(op, type="info",
                                error=f"open failed: "
                                      f"{type(e).__name__}: {e}")
            self.completions.put((self.thread_id, comp))


class _NemesisWorker:
    """The nemesis is one more worker; its ops complete :info."""

    def __init__(self, test: dict, completions: queue.Queue):
        self.test = test
        self.completions = completions
        self.q: "queue.Queue" = queue.Queue()
        self.thread = threading.Thread(
            target=self._run, name="jepsen-nemesis", daemon=True)
        self.thread.start()

    def _run(self):
        nemesis = self.test.get("nemesis")
        while True:
            msg = self.q.get()
            if msg is _STOP:
                return
            op: dict = msg
            if nemesis is None:
                comp = dict(op, type="info", value=None)
            else:
                try:
                    comp = nemesis.invoke(self.test, op)
                    if not isinstance(comp, dict):
                        comp = dict(op, type="info", value=comp)
                except Exception as e:  # noqa: BLE001
                    comp = dict(op, type="info",
                                error=f"{type(e).__name__}: {e}")
            if comp.get("type") == "invoke":
                comp = dict(comp, type="info")
            self.completions.put((NEMESIS_THREAD, comp))


def run(test: dict) -> History:
    """Run the test's generator against its client and nemesis, returning
    the completed history (reference `generator.interpreter/run!`)."""
    concurrency = int(test.get("concurrency", 1))
    gen = g.lift(test.get("generator"))
    ctx = context(test)
    init_time_origin()

    from jepsen_tpu.resilience import faults as faults_mod

    plan = faults_mod.plan_for(test)
    if plan is not None and not plan.targets_site(FAULT_SITE):
        plan = None

    completions: "queue.Queue" = queue.Queue()
    workers = {t: _ClientWorker(t, test, completions, plan=plan)
               for t in range(concurrency)}
    nemesis_worker = _NemesisWorker(test, completions)
    events: List[dict] = []
    in_flight = 0

    # the live-check op sink (ISSUE 13): every appended history event
    # (invokes AND completions, in history order) is offered to
    # test["op-sink"] — `verifier.client.LiveCheck.feed`, which only
    # buffers under a lock (its own sender thread does the I/O).  A
    # sink that raises is disarmed: live checking is an accelerant and
    # must never break the workload.
    sink = test.get("op-sink")

    def offer(ev: dict) -> None:
        nonlocal sink
        if sink is None:
            return
        try:
            sink(ev)
        except Exception as e:  # noqa: BLE001
            logger.warning("op-sink failed (%s); live feed disarmed", e)
            sink = None

    # telemetry (ISSUE 1): per-worker op counts accumulate in a local
    # dict on the (single-threaded) dispatch loop and flush to the
    # process registry once at the end — zero locking on the op path,
    # zero work when disabled
    telemetric = telemetry.enabled()
    op_counts: Dict[Tuple[Any, str], int] = {}
    stall_ns = 0

    def now() -> int:
        return relative_time_nanos()

    def apply_completion(thread, comp) -> None:
        nonlocal ctx, gen, in_flight
        comp = dict(comp, time=now())
        events.append(comp)
        offer(comp)
        if telemetric:
            k = (thread, comp.get("type"))
            op_counts[k] = op_counts.get(k, 0) + 1
        ctx = ctx.with_time(comp["time"]).free_thread(thread)
        if comp.get("type") == "info" and isinstance(comp.get("process"), int):
            ctx = ctx.with_next_process(thread, concurrency)
        gen = g.gen_update(gen, test, ctx, comp)
        in_flight -= 1

    def wait_for_completion(timeout_s: Optional[float]) -> bool:
        nonlocal ctx
        try:
            thread, comp = completions.get(timeout=timeout_s)
        except queue.Empty:
            ctx = ctx.with_time(now())
            return False
        apply_completion(thread, comp)
        return True

    try:
        while True:
            ctx = ctx.with_time(now())
            res = g.next_op(gen, test, ctx)
            if res is None:
                if in_flight > 0:
                    wait_for_completion(None)
                    continue
                break
            op_, gen2 = res
            if g.is_pending(op_):
                gen = gen2
                wake = ((op_.time - ctx.time) / 1e9
                        if op_.time is not None else _TICK_S)
                if telemetric:
                    t_stall = time.perf_counter_ns()
                    wait_for_completion(
                        min(max(wake, _TICK_S / 10), 10.0))
                    stall_ns += time.perf_counter_ns() - t_stall
                else:
                    wait_for_completion(min(max(wake, _TICK_S / 10), 10.0))
                continue
            t_op = op_.get("time") or ctx.time
            if t_op > ctx.time:
                # future op: completions arriving first must update the
                # generator before dispatch time
                if wait_for_completion((t_op - ctx.time) / 1e9):
                    continue
                ctx = ctx.with_time(now())
            gen = gen2
            invoke = dict(op_, type="invoke", time=ctx.time)
            events.append(invoke)
            offer(invoke)
            thread = ctx.thread_for_process(invoke["process"])
            if telemetric:
                k = (thread, "invoke")
                op_counts[k] = op_counts.get(k, 0) + 1
            ctx = ctx.busy_thread(thread)
            gen = g.gen_update(gen, test, ctx, invoke)
            in_flight += 1
            if thread == NEMESIS_THREAD:
                nemesis_worker.q.put(invoke)
            else:
                workers[thread].q.put(invoke)
    finally:
        for w in workers.values():
            w.q.put(_STOP)
        nemesis_worker.q.put(_STOP)
        for w in workers.values():
            w.thread.join(timeout=10)
        nemesis_worker.thread.join(timeout=10)
        if telemetric:
            _flush_metrics(concurrency, op_counts, stall_ns)

    ops = [Op.from_dict(e) for e in events]
    return history(ops)


def _flush_metrics(concurrency: int,
                   op_counts: Dict[Tuple[Any, str], int],
                   stall_ns: int) -> None:
    """Flush the dispatch loop's local tallies into the process-wide
    registry: ops invoked/ok/fail/info per worker + generator stall."""
    reg = telemetry.registry()
    for (thread, typ), n in sorted(op_counts.items(), key=lambda kv:
                                   (str(kv[0][0]), str(kv[0][1]))):
        worker = "nemesis" if thread == NEMESIS_THREAD else str(thread)
        reg.counter("interpreter-ops", worker=worker, type=typ).inc(n)
    reg.counter("generator-stall-ns").inc(stall_ns)
    reg.gauge("interpreter-concurrency").set(concurrency)
