"""Pure generator simulation.

Equivalent of the reference's generator test harness (SURVEY.md §4: drives
generators with a fake context and a perfect simulated clock, asserting on
exact op sequences).  No threads: every dispatched invoke completes after a
fixed simulated latency, and the whole run is deterministic.

Also serves as the reference semantics for the real interpreter
(`generator/interpreter.py`): both follow the same dispatch/update rules,
so interpreter behavior can be differentially tested against this.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, List, Optional

from jepsen_tpu.generator import core as g
from jepsen_tpu.generator.context import Context, context


def simulate(gen: Any, test: Optional[dict] = None, *,
             latency_ns: int = 10_000_000,
             complete: Optional[Callable[[dict], dict]] = None,
             max_ops: int = 100_000) -> List[dict]:
    """Run a generator to exhaustion under a simulated perfect cluster.

    Returns the full event list (invokes and completions, time-ordered).
    `complete` maps an invoke op to its completion (default: same op with
    type "ok")."""
    test = test or {"concurrency": 2}
    gen = g.lift(gen)
    ctx = context(test)
    concurrency = int(test.get("concurrency", 1))
    events: List[dict] = []
    in_flight: list = []  # heap of (time, seq, thread, completion-op)
    seq = 0
    steps = 0

    def apply_completion() -> None:
        nonlocal ctx, gen
        t, _, thread, comp = heapq.heappop(in_flight)
        ctx = ctx.with_time(max(ctx.time, t))
        comp = dict(comp, time=ctx.time)
        events.append(comp)
        ctx = ctx.free_thread(thread)
        if comp.get("type") == "info" and isinstance(comp.get("process"), int):
            ctx = ctx.with_next_process(thread, concurrency)
        gen = g.gen_update(gen, test, ctx, comp)

    while len(events) < max_ops:
        steps += 1
        if steps > 10 * max_ops + 1000:
            raise RuntimeError(
                f"simulation stuck: {steps} steps for {len(events)} events")
        res = g.next_op(gen, test, ctx)
        if res is None:
            if in_flight:
                apply_completion()
                continue
            break
        op_, gen2 = res
        if g.is_pending(op_):
            if in_flight and (op_.time is None
                              or in_flight[0][0] <= op_.time):
                gen = gen2
                apply_completion()
                continue
            if op_.time is not None:
                ctx = ctx.with_time(max(ctx.time + 1, op_.time))
                gen = gen2
                continue
            if in_flight:
                gen = gen2
                apply_completion()
                continue
            break  # deadlocked: pending forever with nothing in flight
        # completions due before this op's scheduled time go first
        t_op = op_.get("time") or ctx.time
        if in_flight and in_flight[0][0] <= t_op:
            apply_completion()
            continue
        gen = gen2
        ctx = ctx.with_time(max(ctx.time, t_op))
        invoke = dict(op_, type="invoke", time=ctx.time)
        events.append(invoke)
        thread = ctx.thread_for_process(invoke["process"])
        ctx = ctx.busy_thread(thread)
        gen = g.gen_update(gen, test, ctx, invoke)
        comp = complete(invoke) if complete else dict(invoke, type="ok")
        seq += 1
        heapq.heappush(in_flight,
                       (ctx.time + latency_ns, seq, thread, comp))
    else:
        raise RuntimeError(f"simulation exceeded {max_ops} events")
    return events


def invokes(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("type") == "invoke"]


def completions(events: List[dict]) -> List[dict]:
    return [e for e in events if e.get("type") != "invoke"]
