"""Generator context.

Equivalent of the reference's `jepsen/generator/context.clj` (SURVEY.md
§2.1): an immutable context tracking the logical test time, the set of free
threads, and the thread<->process translation table.  Client threads are
ints 0..concurrency-1; the nemesis thread is the string "nemesis".  A
client process starts equal to its thread id and, when it crashes (an
:info completion), is replaced by process + concurrency — so processes are
unique forever while threads are a fixed pool, exactly the reference's
scheme.

Contexts are persistent values: every mutator returns a new Context.  The
reference uses bifurcan sets for O(log n) updates; at Python workload scale
(10^2 threads, 10^5 ops host-side) frozenset/dict copies are fine, and the
device-side checkers never see contexts at all.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, FrozenSet, Iterable, Optional, Tuple

NEMESIS_THREAD = "nemesis"


@dataclasses.dataclass(frozen=True)
class Context:
    time: int                        # logical test time, nanoseconds
    free_threads: FrozenSet[Any]     # threads with no op in flight
    workers: Tuple[Tuple[Any, Any], ...]  # sorted (thread, process) pairs

    # -- construction ------------------------------------------------------

    @staticmethod
    def make(concurrency: int, *, with_nemesis: bool = True) -> "Context":
        threads = list(range(concurrency)) + (
            [NEMESIS_THREAD] if with_nemesis else [])
        return Context(
            time=0,
            free_threads=frozenset(threads),
            workers=tuple((t, t) for t in threads),
        )

    # -- lookups -----------------------------------------------------------

    def _worker_map(self) -> dict:
        return dict(self.workers)

    def all_threads(self) -> list:
        return [t for t, _ in self.workers]

    def all_processes(self) -> list:
        return [p for _, p in self.workers]

    def process_for_thread(self, thread) -> Any:
        return self._worker_map()[thread]

    def thread_for_process(self, process) -> Any:
        for t, p in self.workers:
            if p == process:
                return t
        raise KeyError(process)

    def free_processes(self) -> list:
        wm = self._worker_map()
        return [wm[t] for t in self._sorted_free()]

    def _sorted_free(self) -> list:
        # ints first in order, then nemesis — deterministic dispatch order
        ints = sorted(t for t in self.free_threads if isinstance(t, int))
        other = [t for t in self.free_threads if not isinstance(t, int)]
        return ints + other

    def some_free_process(self) -> Optional[Any]:
        free = self.free_processes()
        return free[0] if free else None

    def free_count(self) -> int:
        return len(self.free_threads)

    # -- transitions -------------------------------------------------------

    def with_time(self, t: int) -> "Context":
        return dataclasses.replace(self, time=t)

    def busy_thread(self, thread) -> "Context":
        return dataclasses.replace(
            self, free_threads=self.free_threads - {thread})

    def free_thread(self, thread) -> "Context":
        return dataclasses.replace(
            self, free_threads=self.free_threads | {thread})

    def with_next_process(self, thread, concurrency: int) -> "Context":
        """Replace thread's crashed process with a fresh one (p + n)."""
        workers = tuple(
            (t, p + concurrency if t == thread and isinstance(p, int) else p)
            for t, p in self.workers)
        return dataclasses.replace(self, workers=workers)

    # -- restricted views (reference: thread filters with precompiled
    # translation; used by on-threads / clients / nemesis / reserve) -------

    def restrict(self, thread_pred: Callable[[Any], bool]) -> "Context":
        """A view containing only threads satisfying the predicate."""
        workers = tuple((t, p) for t, p in self.workers if thread_pred(t))
        keep = {t for t, _ in workers}
        return Context(
            time=self.time,
            free_threads=frozenset(t for t in self.free_threads if t in keep),
            workers=workers,
        )


def context(test: dict) -> Context:
    """Build the initial context for a test map (reference
    `generator.context/context`)."""
    return Context.make(int(test.get("concurrency", 1)))
