"""Control-host file cache + node deploy.

Equivalent of the reference's `jepsen/src/jepsen/fs_cache.clj` (SURVEY.md
§2.1): a local cache directory on the control host for downloaded
artifacts (db tarballs, binaries), with `deploy_remote` to push a cached
file to the current node — so N nodes don't each re-download a release.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.request
from typing import Optional

from . import control

CACHE_DIR = os.path.expanduser("~/.cache/jepsen-tpu")


def _key_path(key: str) -> str:
    h = hashlib.sha256(key.encode()).hexdigest()[:24]
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in os.path.basename(key))[:64]
    return os.path.join(CACHE_DIR, f"{h}-{safe}")


def cached(key: str) -> Optional[str]:
    """The cached local path for key, or None (reference `cache/file`)."""
    p = _key_path(key)
    return p if os.path.exists(p) else None


def save(key: str, src_path: str) -> str:
    """Copy a local file into the cache under key."""
    os.makedirs(CACHE_DIR, exist_ok=True)
    p = _key_path(key)
    shutil.copyfile(src_path, p + ".tmp")
    os.replace(p + ".tmp", p)
    return p


def fetch(url: str, *, force: bool = False) -> str:
    """Download url into the cache (once) and return the local path
    (reference `cache/locking-fetch!`-style).  Concurrent fetchers each
    write a private temp file and publish atomically, so parallel node
    setups can never observe a torn artifact."""
    import tempfile

    p = _key_path(url)
    if not force and os.path.exists(p):
        return p
    os.makedirs(CACHE_DIR, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=CACHE_DIR, suffix=".tmp")
    try:
        with urllib.request.urlopen(url) as r, os.fdopen(fd, "wb") as f:
            shutil.copyfileobj(r, f)
        os.replace(tmp, p)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return p


def deploy_remote(key_or_url: str, remote_path: str, *,
                  mode: Optional[str] = None) -> None:
    """Upload the cached artifact to the current node (reference
    `cache/deploy-remote!`); fetches first if it's a URL and uncached."""
    local = cached(key_or_url)
    if local is None:
        if "://" in key_or_url:
            local = fetch(key_or_url)
        else:
            raise FileNotFoundError(f"not cached: {key_or_url}")
    parent = os.path.dirname(remote_path)
    if parent:
        control.exec_("mkdir", "-p", parent)
    control.upload(local, remote_path)
    if mode:
        control.exec_("chmod", mode, remote_path)
