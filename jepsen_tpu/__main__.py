"""`python -m jepsen_tpu` — the framework's own CLI.

Ships a self-contained demo suite over the in-process sim cluster (so the
zero-to-aha path needs no real nodes), plus `serve` and `analyze`
(SURVEY.md §2.1 L7).  A real db suite builds its own CLI with
`jepsen_tpu.cli.single_test_cmd`.
"""

from __future__ import annotations

import random
from typing import Any, Dict

from . import cli
from .generator import core as g


def _wl(name: str, opts: Dict[str, Any]):
    from .workloads import (append, bank, causal, linearizable_register,
                            long_fork, queue, session, sets, wr,
                            write_skew)
    from .workloads.mem import MemClient, MemStore

    rng = random.Random(opts.get("seed"))
    # per-op client latency (seconds): campaign specs use it to pace
    # the unbounded in-memory cluster so nemesis windows actually
    # overlap a bounded op count
    lat = float(opts.get("client-latency") or 0.0)
    if name == "append":
        return append.workload(rng=rng), MemClient(latency=lat)
    if name == "wr":
        return wr.workload(rng=rng), MemClient(txn_kind="rw-register",
                                               latency=lat)
    if name == "lin-register":
        return (linearizable_register.workload(rng=rng),
                MemClient(latency=lat))
    if name == "bank":
        wl = bank.workload(rng=rng)
        s = MemStore()
        s.accounts = dict(wl["accounts"])
        return wl, MemClient(s, latency=lat)
    if name == "long-fork":
        return (long_fork.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    if name == "set":
        return sets.workload(rng=rng), MemClient(latency=lat)
    if name == "queue":
        return queue.workload(rng=rng), MemClient(latency=lat)
    if name == "causal":
        return (causal.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    if name == "write-skew":
        return (write_skew.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    if name == "session":
        return (session.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    raise ValueError(f"unknown workload {name!r}")


def _demo_test(name: str):
    def test_fn(opts: Dict[str, Any]) -> Dict[str, Any]:
        wl, client = _wl(name, opts)
        nodes = opts.get("nodes") or ["n1", "n2", "n3"]
        # re-derive concurrency from the raw spec against the *defaulted*
        # node list, so "1n" with no -n flags means 3 workers, not 1
        spec = opts.get("concurrency-spec")
        concurrency = (cli.parse_concurrency(spec, len(nodes)) if spec
                       else opts.get("concurrency") or 5)
        t = dict(opts)
        t.update({
            "name": f"demo-{name}",
            "nodes": nodes,
            "concurrency": concurrency,
            "client": client,
            **{k: v for k, v in wl.items()
               if k not in ("generator", "checker", "final-generator")},
            "generator": g.clients(wl["generator"]),
            "checker": wl["checker"],
        })
        if "final-generator" in wl:
            t["final-generator"] = wl["final-generator"]
        return t

    return test_fn


DEMOS = {n: _demo_test(n) for n in
         ("append", "wr", "lin-register", "bank", "long-fork", "set",
          "queue", "causal", "write-skew", "session")}

if __name__ == "__main__":
    cli.main(cli.test_all_cmd(DEMOS, prog="python -m jepsen_tpu"))
