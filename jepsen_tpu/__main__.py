"""`python -m jepsen_tpu` — the framework's own CLI.

Ships a self-contained demo suite over the in-process sim cluster (so the
zero-to-aha path needs no real nodes), plus `serve` and `analyze`
(SURVEY.md §2.1 L7).  A real db suite builds its own CLI with
`jepsen_tpu.cli.single_test_cmd`.
"""

from __future__ import annotations

import random
from typing import Any, Dict

from . import cli
from .generator import core as g


def _wl(name: str, opts: Dict[str, Any]):
    from .workloads import (append, bank, causal, linearizable_register,
                            long_fork, queue, session, sets, wr,
                            write_skew)
    from .workloads.mem import MemClient, MemStore

    rng = random.Random(opts.get("seed"))
    # per-op client latency (seconds): campaign specs use it to pace
    # the unbounded in-memory cluster so nemesis windows actually
    # overlap a bounded op count
    lat = float(opts.get("client-latency") or 0.0)
    if name == "append":
        return append.workload(rng=rng), MemClient(latency=lat)
    if name == "wr":
        return wr.workload(rng=rng), MemClient(txn_kind="rw-register",
                                               latency=lat)
    if name == "lin-register":
        return (linearizable_register.workload(rng=rng),
                MemClient(latency=lat))
    if name == "bank":
        wl = bank.workload(rng=rng)
        s = MemStore()
        s.accounts = dict(wl["accounts"])
        return wl, MemClient(s, latency=lat)
    if name == "long-fork":
        return (long_fork.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    if name == "set":
        return sets.workload(rng=rng), MemClient(latency=lat)
    if name == "queue":
        adv = opts.get("queue-adversary") or {}
        return (queue.workload(rng=rng, fifo=bool(adv.get("fifo"))),
                MemClient(
                    latency=lat, rng=random.Random(opts.get("seed")),
                    dup_enqueue_p=float(adv.get("dup-enqueue-p") or 0.0),
                    lose_enqueue_p=float(adv.get("lose-enqueue-p") or 0.0),
                    reorder_dequeue_p=float(
                        adv.get("reorder-dequeue-p") or 0.0)))
    if name == "kafka":
        from .workloads import kafka as kafka_wl

        adv = opts.get("queue-adversary") or {}
        store = kafka_wl.KafkaStore()
        store.freeze_commits = bool(adv.get("freeze-commits"))
        client = kafka_wl.KafkaClient(
            store, rng=random.Random(opts.get("seed")),
            lose_tail_p=float(adv.get("lose-tail-p") or 0.0),
            dup_p=float(adv.get("dup-p") or 0.0),
            dup_send_p=float(adv.get("dup-send-p") or 0.0),
            reorder_p=float(adv.get("reorder-p") or 0.0),
            zombie_p=float(adv.get("zombie-p") or 0.0),
            torn_p=float(adv.get("torn-p") or 0.0))
        return (kafka_wl.workload(
            key_count=int(opts.get("kafka-key-count") or 4),
            subscribe_frac=float(opts.get("kafka-subscribe-frac", 0.2)),
            txn_frac=float(opts.get("kafka-txn-frac", 0.3)),
            crash_frac=float(opts.get("kafka-crash-frac", 0.05)),
            rng=rng), client)
    if name == "causal":
        return (causal.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    if name == "write-skew":
        return (write_skew.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    if name == "session":
        return (session.workload(rng=rng),
                MemClient(txn_kind="rw-register", latency=lat))
    raise ValueError(f"unknown workload {name!r}")


def _demo_test(name: str):
    def test_fn(opts: Dict[str, Any]) -> Dict[str, Any]:
        wl, client = _wl(name, opts)
        nodes = opts.get("nodes") or ["n1", "n2", "n3"]
        # re-derive concurrency from the raw spec against the *defaulted*
        # node list, so "1n" with no -n flags means 3 workers, not 1
        spec = opts.get("concurrency-spec")
        concurrency = (cli.parse_concurrency(spec, len(nodes)) if spec
                       else opts.get("concurrency") or 5)
        t = dict(opts)
        t.update({
            "name": f"demo-{name}",
            "nodes": nodes,
            "concurrency": concurrency,
            "client": client,
            **{k: v for k, v in wl.items()
               if k not in ("generator", "checker", "final-generator")},
            "generator": g.clients(wl["generator"]),
            "checker": wl["checker"],
        })
        if "final-generator" in wl:
            t["final-generator"] = wl["final-generator"]
        return t

    return test_fn


DEMOS = {n: _demo_test(n) for n in
         ("append", "wr", "lin-register", "bank", "long-fork", "set",
          "queue", "kafka", "causal", "write-skew", "session")}

if __name__ == "__main__":
    cli.main(cli.test_all_cmd(DEMOS, prog="python -m jepsen_tpu"))
