"""The fleet worker: claim → execute → renew → complete, over HTTP.

``cli fleet work --coordinator URL`` runs one of these until the
coordinator reports the campaign finished.  Execution is exactly
`campaign.core.execute_run` — shrink-on-invalid, telemetry streaming,
crash→attributable-record semantics all included — so a distributed
cell's index record is indistinguishable from a single-process one
(modulo the ``fleet-worker`` stamp the coordinator adds).

Resilience contract:

- every control-plane call goes through `resilience.device_call` with
  a seeded `RetryPolicy` and the :func:`~.policy.is_transient_http`
  classifier — connection refusals (a coordinator restarting),
  timeouts, 502/503/504, and injected `FaultInjected` transients are
  ridden out with bounded backoff; 4xx protocol errors propagate.
  The call sites are the ``fleet.*`` fault-plan family, so a plan
  installed in the worker process (``JEPSEN_FAULTS`` env in the chaos
  soak) drops/stalls the client side of the same seams the
  coordinator guards server-side.
- a renewer thread heartbeats + renews the lease at ``lease/3`` while
  a cell runs; a LOST lease (the coordinator expired it — e.g. after a
  partition) is noted but execution continues: the completion is then
  either the first verdict (accepted) or a zombie duplicate the
  coordinator discards.  Renewer failures never kill the run.
- SIGTERM (``cli fleet work`` installs the handler) drains gracefully:
  the in-flight cell finishes and uploads, a claimed-but-unstarted
  cell is released back to the queue, and the loop exits.
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from jepsen_tpu import resilience, store
from jepsen_tpu.campaign.plan import RunSpec
from jepsen_tpu.campaign.scheduler import crash_record
from jepsen_tpu.resilience import RetryPolicy
from jepsen_tpu.resilience.policy import is_transient_http
from jepsen_tpu.telemetry import spans as spans_mod

logger = logging.getLogger("jepsen.fleet")

__all__ = ["FleetWorker"]

#: cap on the metric rows one heartbeat pushes (ISSUE 14 tentpole b) —
#: must stay under the coordinator's MAX_FEDERATED_SERIES so nothing
#: is silently dropped server-side
MAX_PUSHED_SERIES = 48


class FleetWorker:
    """One remote executor against a fleet coordinator."""

    def __init__(self, coordinator: str, base: Optional[str] = None, *,
                 name: Optional[str] = None, device_slots: int = 1,
                 backend: Optional[str] = None, mesh: Any = None,
                 poll_s: float = 0.5,
                 lease_s: float = 15.0,
                 retry: Optional[RetryPolicy] = None,
                 timeout_s: float = 10.0,
                 claim_budget_s: float = 120.0,
                 upload: bool = False,
                 version: Optional[str] = None):
        self.url = coordinator.rstrip("/")
        self.base = base or store.BASE
        self.name = name or f"{socket.gethostname()}-{os.getpid()}"
        #: rolling-upgrade identity (ISSUE 17): stamped at register
        #: and every heartbeat so the coordinator (and the autopilot's
        #: upgrade tick) can tell which build each worker runs
        self.version = str(
            version or os.environ.get("JEPSEN_WORKER_VERSION")
            or "dev")
        self.device_slots = int(device_slots)
        self.backend = backend
        self.mesh = mesh
        self.poll_s = float(poll_s)
        self.lease_s = float(lease_s)  # server value adopted at register
        self.timeout_s = float(timeout_s)
        #: how long claim outages are ridden out before giving up —
        #: spent in seeded-jittered backoff sleeps (ISSUE 11 satellite:
        #: each worker's delay stream is seeded from its own name, so a
        #: fleet recovering from a coordinator outage doesn't
        #: synchronize its re-poll storm)
        self.claim_budget_s = float(claim_budget_s)
        self._backoff_rng = random.Random(f"{self.name}|claim-backoff")
        # generous by default: the retry window must cover a
        # coordinator kill -9 + restart (a few seconds of ECONNREFUSED)
        self.retry = retry or RetryPolicy(
            max_attempts=8, base_delay_s=0.2, multiplier=2.0,
            max_delay_s=2.0, classify=is_transient_http)
        #: SIGTERM drain flag (cli fleet work sets it from the handler)
        self.stop = threading.Event()
        #: store federation (ISSUE 13): upload run dirs to the
        #: coordinator's artifact endpoint after each cell — no shared
        #: store filesystem needed.  Forced per cell by opts
        #: ``"artifact-upload": true`` even when the flag is off.
        self.upload = bool(upload)
        self.uploads_done = 0
        self.cells_done = 0
        self.duplicates = 0
        #: the last installed window set (digest + descriptors) — what
        #: heartbeat ticks report while a scheduled cell runs
        self.installed_windows: Optional[Dict[str, Any]] = None
        #: the in-flight cell's trace context (ISSUE 14): every
        #: control-plane POST made while a cell runs carries it in the
        #: Jepsen-Trace header — heartbeat/renew, artifact chunks,
        #: complete all stitch onto the run's one trace
        self._trace: Optional[spans_mod.TraceContext] = None
        # compile-cache adoption (docs/COMPILECACHE.md): the worker's
        # persistent AOT store follows its store base, so entries
        # pulled from the coordinator land exactly where the dispatch
        # seam (`compilecache.call`) looks
        try:
            from jepsen_tpu import compilecache

            compilecache.adopt_base(self.base)
        except Exception:  # noqa: BLE001 — the cache is optional
            pass

    # -- transport -----------------------------------------------------------

    def _post(self, site: str, path: str,
              doc: Dict[str, Any]) -> Dict[str, Any]:
        """One guarded control-plane POST: the active fault plan fires
        at `site` (client-side chaos), transients retry per the
        policy."""
        return self._post_raw(site, path, json.dumps(doc).encode(),
                              ctype="application/json")

    def _post_raw(self, site: str, path: str, body: bytes, *,
                  ctype: str = "application/octet-stream",
                  accept_conflict: bool = False) -> Dict[str, Any]:
        """One guarded POST.  With ``accept_conflict``, protocol 409s
        are ANSWERS, not failures — their JSON body carries the
        server's cursor, so they parse (stamped ``_conflict``) instead
        of raising."""
        def send() -> Dict[str, Any]:
            headers = {"Content-Type": ctype}
            tr = self._trace
            if tr is not None:
                headers[spans_mod.TRACE_HEADER] = tr.header()
            req = urllib.request.Request(
                self.url + path, data=body,
                headers=headers, method="POST")
            try:
                with urllib.request.urlopen(
                        req, timeout=self.timeout_s) as r:
                    return json.loads(r.read().decode() or "{}")
            except urllib.error.HTTPError as e:
                if accept_conflict and e.code == 409:
                    doc = json.loads(e.read().decode() or "{}")
                    doc["_conflict"] = True
                    return doc
                raise

        return resilience.device_call(site, send, policy=self.retry)

    # -- store federation (ISSUE 13) -----------------------------------------

    CHUNK_BYTES = 256 * 1024

    def _artifact_post(self, run_id: str, params: Dict[str, Any],
                       body: bytes) -> Dict[str, Any]:
        from urllib.parse import quote, urlencode

        q = urlencode({k: v for k, v in params.items()
                       if v is not None})
        path = f"/fleet/artifact/{quote(run_id)}" + (f"?{q}" if q
                                                     else "")
        return self._post_raw("fleet.artifact", path, body,
                              accept_conflict=True)

    #: how long an upload rides out a coordinator outage (a kill -9 +
    #: restart window) before giving up — each re-contact resumes from
    #: the server's durable cursor, so patience costs no re-sent bytes
    UPLOAD_BUDGET_S = 30.0

    def upload_artifact(self, run_id: str, rel: str) -> bool:
        """Stream one run dir to the coordinator's artifact endpoint
        (docs/FLEET.md federation): tar + sha256, chunked from the
        server's resume cursor — a coordinator kill -9 mid-upload
        leaves a resumable partial this loop picks back up after the
        restart; a digest mismatch restarts the upload from byte 0.
        Transport outages outlasting the retry policy re-probe under
        :data:`UPLOAD_BUDGET_S` instead of failing the upload."""
        import tempfile

        from .artifacts import pack_run_dir_file

        d = os.path.join(self.base, rel)
        if not os.path.isdir(d):
            logger.warning("fleet worker %s: no run dir %s to upload",
                           self.name, d)
            return False
        with tempfile.TemporaryFile(prefix="jepsen-artifact-") as spool:
            total, digest = pack_run_dir_file(d, spool)
            return self._upload_spooled(run_id, rel, spool, total,
                                        digest)

    def _upload_spooled(self, run_id: str, rel: str, spool: Any,
                        total: int, digest: str) -> bool:
        # budget anchors the CONTINUOUS outage, not total upload time:
        # every successful request pushes the deadline back out
        deadline = time.monotonic() + self.UPLOAD_BUDGET_S

        def patient(params: Dict[str, Any], body: bytes
                    ) -> Optional[Dict[str, Any]]:
            nonlocal deadline
            while True:
                try:
                    r = self._artifact_post(run_id, params, body)
                    deadline = time.monotonic() + self.UPLOAD_BUDGET_S
                    return r
                except urllib.error.HTTPError as e:
                    if e.code < 500:
                        # deterministic protocol rejection (oversized
                        # artifact, bad rel): no retry can land it —
                        # fail fast instead of burning the outage
                        # budget on a non-outage
                        logger.warning(
                            "fleet worker %s: artifact upload of %s "
                            "rejected (%s); giving up", self.name,
                            run_id, e)
                        return None
                    if time.monotonic() > deadline:
                        logger.warning(
                            "fleet worker %s: artifact upload of %s "
                            "gave up after %.0fs of outage (%s)",
                            self.name, run_id, self.UPLOAD_BUDGET_S, e)
                        return None
                    time.sleep(0.5)
                except Exception as e:  # noqa: BLE001 — outage window
                    if time.monotonic() > deadline:
                        logger.warning(
                            "fleet worker %s: artifact upload of %s "
                            "gave up after %.0fs of outage (%s)",
                            self.name, run_id, self.UPLOAD_BUDGET_S, e)
                        return None
                    time.sleep(0.5)

        probe = patient({}, b"")
        if probe is None:
            return False
        if probe.get("landed") and probe.get("rel", rel) == rel:
            return True
        if probe.get("rel", rel) != rel:
            # the marker/partial is another execution's dir (lease-
            # lapse re-run, new timestamp): upload ours from scratch —
            # the server discards the stale state on the first chunk
            probe = {"received": 0}
        offset = int(probe.get("received", 0))
        restarts = 0
        while True:
            spool.seek(offset)
            chunk = spool.read(self.CHUNK_BYTES)
            r = patient(
                {"offset": offset, "total": total,
                 "digest": digest, "rel": rel}, chunk)
            if r is None:
                return False
            if r.get("landed"):
                self.uploads_done += 1
                return True
            if r.get("_conflict"):
                got = int(r.get("received", 0))
                if got == 0:
                    # a discard-class answer (digest mismatch, unpack
                    # failure), not a resume gap — gaps always carry a
                    # positive cursor.  Counted regardless of offset:
                    # a single-chunk upload conflicts AT offset 0, and
                    # without the count it would re-POST forever while
                    # the kept-alive lease pins the cell to this worker
                    restarts += 1  # retry from 0 once, then give up
                    if restarts > 1:
                        logger.warning(
                            "fleet worker %s: artifact %s rejected "
                            "twice (%s); giving up", self.name,
                            run_id, r.get("error"))
                        return False
                offset = got
                continue
            new_off = int(r.get("received", offset + len(chunk)))
            if new_off <= offset and chunk:
                logger.warning(
                    "fleet worker %s: artifact upload of %s stuck at "
                    "%d", self.name, run_id, offset)
                return False
            offset = new_off

    # -- protocol ------------------------------------------------------------

    def register(self) -> Dict[str, Any]:
        r = self._post("fleet.register", "/fleet/register", {
            "worker": self.name, "host": socket.gethostname(),
            "backend": self.backend, "mesh": self.mesh,
            "device-slots": self.device_slots,
            "version": self.version})
        if isinstance(r.get("lease-s"), (int, float)):
            self.lease_s = float(r["lease-s"])
        logger.info("fleet worker %s registered with %s (campaign %s, "
                    "lease %.1fs)", self.name, self.url,
                    r.get("campaign"), self.lease_s)
        return r

    def _claim_backoff(self, fails: int) -> float:
        """One seeded-jittered backoff delay for the `fails`-th
        consecutive claim outage: exponential from `poll_s`, capped,
        each draw scaled by a per-worker random factor — two workers
        with the same poll settings still desynchronize their re-poll
        storms against a recovering coordinator."""
        base = min(self.poll_s * (2.0 ** max(0, fails - 1)), 5.0)
        return base * self._backoff_rng.uniform(0.5, 1.5)

    def run(self) -> int:
        """Claim-execute until the campaign finishes (or SIGTERM
        drains); returns the number of cells this worker completed."""
        self.register()
        claim_fails = 0
        claim_waited = 0.0
        while not self.stop.is_set():
            try:
                r = self._post("fleet.claim", "/fleet/claim",
                               {"worker": self.name})
            except Exception as e:  # noqa: BLE001 — outage outlasting
                # the retry budget: keep polling under seeded jittered
                # backoff (a daemon rides out long partitions), give up
                # only once the configured budget is spent
                claim_fails += 1
                delay = self._claim_backoff(claim_fails)
                if claim_waited + delay > self.claim_budget_s:
                    logger.error(
                        "fleet worker %s: claim outage outlasted the "
                        "%.1fs budget (%d attempts); giving up",
                        self.name, self.claim_budget_s, claim_fails)
                    raise
                claim_waited += delay
                logger.warning("fleet worker %s: claim failed (%s); "
                               "re-polling in %.2fs", self.name, e,
                               delay)
                time.sleep(delay)
                continue
            claim_fails = 0
            claim_waited = 0.0
            spec = r.get("spec")
            if not spec:
                if r.get("finished"):
                    break
                time.sleep(self.poll_s)
                continue
            if self.stop.is_set():
                # drained between claim and start: give the cell back
                # instead of sitting on the lease until it lapses
                self._post("fleet.release", "/fleet/release",
                           {"worker": self.name, "run": spec["run_id"]})
                break
            self._run_cell(spec, r.get("windows"), r.get("trace"),
                           r.get("compilecache"))
        logger.info("fleet worker %s done: %d cells completed "
                    "(%d duplicates discarded upstream)",
                    self.name, self.cells_done, self.duplicates)
        return self.cells_done

    def _install_windows(self, rs: RunSpec,
                         windows: Optional[Dict[str, Any]]) -> None:
        """Install the claim response's synchronized window set before
        `execute_run` (ISSUE 11 tentpole).  The claim broadcast is
        authoritative: it overrides whatever the ledger's serialized
        spec carried (a cell enqueued before the schedule existed, or
        by an older coordinator), so every host's cell for generation
        *g* runs the same seeded windows at the same schedule
        positions.  The worker's name rides along as the executing
        host, the attribution the cross-host fault-window ddmin
        surfaces."""
        from jepsen_tpu.campaign.plan import windows_digest

        rs.opts["_fleet-host"] = self.name
        wins = (windows or {}).get("set")
        if wins is not None:
            rs.opts["nemesis-windows"] = wins
        wins = rs.opts.get("nemesis-windows")
        if wins:
            self.installed_windows = {
                "gen": int(rs.seed),
                "digest": windows_digest(wins),
                "set": wins,
            }
            # wall-clock t0 alignment (ISSUE 13): the claim carries the
            # coordinator's absolute window anchor plus its "now";
            # delta converts the anchor into THIS host's clock domain,
            # so every host's windows fire at the same absolute time
            # instead of `at_s` past whenever each workload happened
            # to start.  The aligned anchor rides opts["nemesis-t0"]
            # into `combined.schedule_package`.
            t0 = (windows or {}).get("t0")
            now = (windows or {}).get("now")
            if isinstance(t0, (int, float)) \
                    and isinstance(now, (int, float)):
                delta = time.time() - float(now)
                t0_local = float(t0) + delta
                self.installed_windows["t0"] = round(t0_local, 3)
                rs.opts["nemesis-t0"] = t0_local
            want = (windows or {}).get("digest")
            if want and want != self.installed_windows["digest"]:
                logger.warning(
                    "fleet worker %s: installed window digest %s != "
                    "coordinator's %s for gen %s", self.name,
                    self.installed_windows["digest"], want, rs.seed)
        else:
            self.installed_windows = None

    def _window_ticks(self, t0: float) -> Optional[Dict[str, Any]]:
        """The heartbeat's chaos-clock payload: installed digest plus
        which schedule positions are open right now (derived from the
        deterministic window offsets and the cell's elapsed wall
        clock) — lease renewal doubles as window open/close tick
        sync."""
        iw = self.installed_windows
        if not iw:
            return None
        # one read: the cell thread may pop "t0" (stale-anchor path)
        # between a has-key check and a lookup on this renewer thread
        t0v = iw.get("t0")
        if t0v is not None:
            # aligned mode: elapsed runs from the shared wall-clock
            # anchor, so two hosts' open/closed reports agree even when
            # their workloads started at different times
            elapsed = time.time() - float(t0v)
        else:
            elapsed = time.monotonic() - t0
        open_: List[Dict[str, Any]] = [
            {"pos": w.get("pos"), "fault": w.get("fault")}
            for w in iw["set"]
            if w["at_s"] <= elapsed < w["at_s"] + w["dur_s"]]
        out = {"gen": iw["gen"], "digest": iw["digest"],
               "n": len(iw["set"]), "open": open_,
               "elapsed": round(elapsed, 3)}
        if t0v is not None:
            out["t0"] = t0v
        return out

    def metrics_snapshot(self) -> List[Dict[str, Any]]:
        """The heartbeat's metrics payload (ISSUE 14 tentpole b): this
        worker's own progress counters, process RSS, the jit
        compile-cache stats, and a bounded slice of the process-wide
        registry — what the coordinator re-exposes with ``host=``
        labels so one scrape sees the whole fleet."""
        rows: List[Dict[str, Any]] = [
            {"name": "worker-cells-done", "kind": "counter",
             "labels": {}, "value": self.cells_done},
            {"name": "worker-uploads-done", "kind": "counter",
             "labels": {}, "value": self.uploads_done},
            {"name": "worker-duplicate-completions", "kind": "counter",
             "labels": {}, "value": self.duplicates},
        ]
        try:
            from jepsen_tpu.telemetry.stream import _hwm_bytes, _rss_bytes

            rss = _rss_bytes()
            if rss:
                rows.append({"name": "worker-rss-bytes",
                             "kind": "gauge", "labels": {},
                             "value": rss})
                # the kernel high watermark federates the worker's PEAK
                # footprint (ISSUE 16): visible fleet-wide even when no
                # scrape coincided with the spike, and retired with the
                # worker's liveness like every host-attributed series
                hwm = _hwm_bytes()
                rows.append({"name": "worker-rss-peak-bytes",
                             "kind": "gauge", "labels": {},
                             "value": max(rss, hwm or 0)})
        except Exception:  # noqa: BLE001 — observability only
            pass
        try:
            # compile-cost groundwork (ISSUE 14 satellite): the AOT
            # cache PR's baseline, visible fleet-wide on one scrape
            from jepsen_tpu.resilience.guard import compile_cache_stats

            st = compile_cache_stats()
            rows.append({"name": "jit-cache-entries", "kind": "gauge",
                         "labels": {}, "value": st["entries"]})
            rows.append({"name": "compile-cache-miss",
                         "kind": "counter", "labels": {},
                         "value": st["misses"]})
        except Exception:  # noqa: BLE001
            pass
        try:
            from jepsen_tpu.telemetry import metrics as metrics_mod

            snap = metrics_mod.registry().snapshot()
            extra = [
                dict(name=m["name"], kind=kind, labels=m["labels"],
                     value=float(m["value"]))
                for kind, group in (("counter", snap["counters"]),
                                    ("gauge", snap["gauges"]))
                for m in sorted(group, key=lambda m: (
                    m["name"], str(sorted(m["labels"].items()))))
                if isinstance(m.get("value"), (int, float))]
            rows.extend(extra[:max(0, MAX_PUSHED_SERIES - len(rows))])
        except Exception:  # noqa: BLE001
            pass
        return rows[:MAX_PUSHED_SERIES]

    def _run_cell(self, spec: Dict[str, Any],
                  windows: Optional[Dict[str, Any]] = None,
                  trace: Optional[Dict[str, Any]] = None,
                  cc_advert: Optional[Any] = None) -> None:
        from jepsen_tpu.campaign.core import execute_run

        rs = RunSpec.from_dict(spec)
        rs.opts["_base"] = self.base
        self._install_windows(rs, windows)
        run_id = rs.run_id
        # compile-cache federation (docs/COMPILECACHE.md): pull the
        # claim's advertised AOT entries before executing, so this
        # worker's first cell of a known shape class dispatches a
        # pre-built executable instead of compiling; snapshot the
        # store so freshly minted entries can be pushed back after.
        # The baseline snapshot is its own guarded step BEFORE the
        # pull — a failed pull must not void it, or the post-cell push
        # would re-upload the entire local store every cell.
        cc_dir: Optional[str] = None
        cc_pre: set = set()
        cc_secret: Optional[bytes] = None
        try:
            from jepsen_tpu import compilecache
            from jepsen_tpu.compilecache import fleet as cc_fleet

            cc_dir = compilecache.cache_dir()
            cc_pre = cc_fleet.entry_names(cc_dir)
            cc_secret = cc_fleet.shared_secret(self.base)
        except Exception:  # noqa: BLE001 — never fail a cell on cache
            logger.warning("fleet worker %s: compile-cache snapshot "
                           "failed", self.name, exc_info=True)
        if cc_dir and cc_advert:
            try:
                cc_fleet.pull_missing(self.url, cc_advert, cc_dir,
                                      cc_secret,
                                      timeout_s=self.timeout_s)
                # pulled entries are not "minted here": fold them into
                # the baseline so the push sends only what this cell
                # compiles
                cc_pre = cc_fleet.entry_names(cc_dir)
            except Exception:  # noqa: BLE001
                logger.warning("fleet worker %s: compile-cache pull "
                               "failed", self.name, exc_info=True)
        # distributed trace (ISSUE 14): adopt the claim's trace id —
        # equal to the locally derivable one (both are pure functions
        # of the run id), so a claim from an older coordinator still
        # traces.  The worker's own control-plane segment parents on
        # the claim segment the coordinator handed out.
        trace_id = str((trace or {}).get("trace-id")
                       or spans_mod.trace_id_for(run_id))
        rs.opts["trace-id"] = trace_id
        self._trace = spans_mod.trace_context(trace_id,
                                              f"fleet:worker:{self.name}")
        t_claim = time.monotonic()
        state = {"run": run_id, "workload": rs.workload_label,
                 "fault": rs.fault_label, "seed": rs.seed,
                 "slot": None, "worker-host": socket.gethostname()}
        if self.installed_windows:
            state["windows-digest"] = self.installed_windows["digest"]
        stop_renew = threading.Event()
        lease_lost = threading.Event()
        t0 = time.monotonic()

        def renew_loop() -> None:
            # heartbeat + renew at lease/3; failures are logged, never
            # fatal — a lapsed lease just makes the completion racy,
            # which the coordinator's at-most-once rule resolves
            while not stop_renew.wait(max(0.2, self.lease_s / 3.0)):
                try:
                    r = self._post("fleet.heartbeat", "/fleet/heartbeat",
                                   {"worker": self.name, "state": state,
                                    "version": self.version,
                                    "windows": self._window_ticks(t0),
                                    "metrics": self.metrics_snapshot(),
                                    "renew": [run_id]})
                    if run_id in (r.get("lost") or []):
                        lease_lost.set()
                        logger.warning(
                            "fleet worker %s: lease on %s LOST "
                            "(requeued elsewhere); finishing anyway",
                            self.name, run_id)
                    want = r.get("windows-digest")
                    if want and self.installed_windows and \
                            want != self.installed_windows["digest"]:
                        logger.warning(
                            "fleet worker %s: window desync on %s "
                            "(installed %s, coordinator %s); will "
                            "reinstall at next claim", self.name,
                            run_id, self.installed_windows["digest"],
                            want)
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.warning("fleet worker %s: heartbeat failed "
                                   "(%s)", self.name, e)

        # announce the claim before execution so the live dashboard
        # names the in-flight cell even if the run wedges instantly
        try:
            self._post("fleet.heartbeat", "/fleet/heartbeat",
                       {"worker": self.name, "state": state,
                        "version": self.version,
                        "windows": self._window_ticks(t0),
                        "metrics": self.metrics_snapshot(),
                        "renew": [run_id]})
        except Exception:  # noqa: BLE001
            pass
        renewer = threading.Thread(target=renew_loop, daemon=True,
                                   name=f"fleet-renew-{self.name}")
        renewer.start()
        # wall-clock t0 alignment: hold the workload until the
        # generation's (clock-offset-corrected) anchor so every host
        # starts — and therefore fires its windows — at the same
        # absolute time, while the offsets stay RELATIVE to workload
        # start (chaos-equivalent with the single-process expansion of
        # the same spec, the PR 10 pin).  A stale anchor (claimed late,
        # or a clock jumped) is skipped, bounded by the claim lead.
        iw = self.installed_windows
        if iw and iw.get("t0") is not None:
            wait = float(iw["t0"]) - time.time()
            if 0.0 < wait <= 5.0:
                time.sleep(wait)
            else:
                if wait > 5.0:
                    logger.warning(
                        "fleet worker %s: window anchor %.3fs ahead "
                        "(clock skew?); starting unaligned", self.name,
                        wait)
                # drop the anchor entirely, for a far-future anchor
                # (clock skew — leaving nemesis-t0 set would shift
                # every window by the full skew, silently diverging
                # from the single-process schedule) AND for a stale
                # one (claimed after t0, e.g. a later cell of the
                # same generation — schedule_package clamps the shift
                # to 0, so anchor-based ticks would report windows
                # closed that actually fire relative to workload
                # start).  Unaligned means RELATIVE offsets from
                # workload start — the PR 10 behavior — and the tick
                # clock must agree with where the faults really fire.
                iw.pop("t0", None)
                rs.opts.pop("nemesis-t0", None)
        t0 = time.monotonic()  # the window tick clock: workload start
        # the claim→workload-start gap (ISSUE 14): claim transport,
        # window install, and anchor wait — stamped as a gateable span
        # on the index record next to the coordinator's enqueue-wait
        claim_to_start_s = time.monotonic() - t_claim
        # mesh capability -> default-mesh shard count (PR 10 follow-on,
        # ISSUE 12 satellite): a cell pinning opts["mesh"] — or a worker
        # advertising one — runs its device checks sharded over exactly
        # that many devices.  The pin is THREAD-LOCAL
        # (slots.set_forced_shards): several workers may share one
        # process, and a process-global env pin would leak across their
        # concurrently executing cells
        import math

        from jepsen_tpu.fleet.queue import _norm_mesh
        from jepsen_tpu.parallel import slots as slots_mod

        want_mesh = _norm_mesh(rs.opts.get("mesh")) or \
            _norm_mesh(self.mesh)
        if want_mesh:
            slots_mod.set_forced_shards(math.prod(want_mesh))
        try:
            rec = execute_run(rs, self.base)
        except Exception as e:  # noqa: BLE001 — same contract as the
            # scheduler: whatever escapes execute_run becomes an
            # attributable unknown record, never a worker crash
            rec = crash_record(rs, f"{type(e).__name__}: {e}", 1,
                               time.monotonic() - t0)
        finally:
            if want_mesh:
                slots_mod.set_forced_shards(None)
        # store federation: ship the run dir BEFORE the verdict record,
        # so the record's "dir" is browsable on the coordinator the
        # moment the verdict lands.  Best-effort with retries — an
        # upload outage never loses the verdict (the record carries
        # it), and the idempotent protocol makes a re-upload after a
        # lease-lapse re-execution harmless.  The renewer stays alive
        # through upload AND complete: an outage-ridden upload
        # (UPLOAD_BUDGET_S) can outlast the lease, and without
        # renewals the cell would spuriously requeue and re-execute
        # while this attempt is seconds from landing.
        try:
            rec.setdefault("trace", trace_id)
            sp = rec.setdefault("spans", {})
            if isinstance(sp, dict):
                sp.setdefault("fleet:claim-to-start",
                              round(claim_to_start_s, 6))
            if (self.upload or rs.opts.get("artifact-upload")) \
                    and isinstance(rec.get("dir"), str):
                t_up = time.monotonic()
                try:
                    if not self.upload_artifact(run_id, rec["dir"]):
                        logger.warning(
                            "fleet worker %s: artifact upload of %s "
                            "did not land", self.name, run_id)
                except Exception as e:  # noqa: BLE001 — verdict >
                    # artifact
                    logger.warning("fleet worker %s: artifact upload "
                                   "of %s failed (%s)", self.name,
                                   run_id, e)
                finally:
                    if isinstance(sp, dict):
                        sp.setdefault(
                            "fleet:upload",
                            round(time.monotonic() - t_up, 6))
            try:
                r = self._post("fleet.complete", "/fleet/complete",
                               {"worker": self.name, "run": run_id,
                                "record": rec})
                if r.get("duplicate"):
                    self.duplicates += 1
                    logger.warning(
                        "fleet worker %s: completion of %s was a "
                        "duplicate (cell finished elsewhere)",
                        self.name, run_id)
                else:
                    self.cells_done += 1
            except Exception as e:  # noqa: BLE001 — an outage
                # outlasting the retries loses THIS attempt, not the
                # cell: the lease lapses, the cell requeues, and
                # another worker (or this one, next claim) re-executes
                # it — exactly-once still holds because this record
                # never landed
                logger.warning("fleet worker %s: complete(%s) failed "
                               "beyond retries (%s); cell will "
                               "requeue on lease expiry", self.name,
                               run_id, e)
            # push entries this cell minted so the NEXT claim's advert
            # carries them fleet-wide (best-effort; own batch rel, so
            # no lease dependency)
            try:
                if cc_dir:
                    from jepsen_tpu.compilecache import fleet as \
                        cc_fleet

                    new = cc_fleet.entry_names(cc_dir) - cc_pre
                    if new:
                        cc_fleet.push_new(self, new, cc_dir,
                                          cc_secret)
            except Exception:  # noqa: BLE001 — push is an optimization
                logger.warning("fleet worker %s: compile-cache push "
                               "failed", self.name, exc_info=True)
        finally:
            stop_renew.set()
            renewer.join(timeout=5)
            self.installed_windows = None
            try:
                self._post("fleet.heartbeat", "/fleet/heartbeat",
                           {"worker": self.name, "state": None,
                            "version": self.version,
                            "metrics": self.metrics_snapshot(),
                            "windows": None})
            except Exception:  # noqa: BLE001
                pass
            self._trace = None
